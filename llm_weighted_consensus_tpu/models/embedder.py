"""Host<->device embedding pipeline exposing the OpenAI wire contract.

The serve-path boundary (SURVEY §3.1 note: "the trained-weight path crosses
host<->device (PJRT) instead" of HTTP): texts are tokenized on host, padded
to bucketed static shapes (bounding jit specializations), embedded by the
jitted BERT forward, and returned both as arrays (device consumers) and as
``CreateEmbeddingResponse`` JSON (wire consumers + usage accounting that
seeds ``weight_data`` cost, score client.rs:330-337).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types.chat_response import Usage
from ..types.embeddings import CreateEmbeddingResponse, Embedding
from . import bert
from .configs import PRESETS, BertConfig
from .dispatch_seam import (
    PendingDispatch,
    StagingPool,
    active_sink,
    wait_device_ready,
)
from .tokenizer import BaseTokenizer, load_tokenizer


DEFAULT_VOTE_TEMPERATURE = 0.05


@partial(
    jax.jit, static_argnames=("n", "config", "pooling", "use_fused")
)
def _embed_and_vote(
    params, ids, mask, temperature, n, config, pooling, use_fused
):
    """Single-dispatch self-consistency: encoder forward + cosine consensus
    vote fused under one jit so nothing round-trips the host between them
    (the serving hot path: one upload, one tiny download).  At the default
    temperature the vote runs in the fused Pallas kernel (VMEM-resident
    normalize+cosine+softmax, which bakes the temperature in); any other
    temperature takes the jnp composition with temperature TRACED, so
    user-supplied values never trigger recompiles.  Rows past ``n`` are
    dp-alignment padding (sliced off before the vote so they cannot
    perturb the softmax)."""
    from ..ops.kernels import fused_cosine_vote
    from ..ops.similarity import dyn_cosine_vote

    emb = bert.embed(params, ids, mask, config, pooling=pooling)
    with jax.named_scope("consensus_vote"):
        if use_fused:
            return fused_cosine_vote(
                emb[:n], temperature=DEFAULT_VOTE_TEMPERATURE
            )
        return dyn_cosine_vote(emb[:n], temperature)


@partial(
    jax.jit, static_argnames=("n", "config", "pooling", "mesh")
)
def _mesh_embed_and_vote(
    params, ids, mask, temperature, n, config, pooling, mesh
):
    """Mesh-serving twin of ``_embed_and_vote``: encoder forward (params
    Megatron-split over ``tp``, batch rows over ``dp`` via the input
    shardings the caller staged) + the dp-sharded consensus reduction
    (parallel/collectives.py) fused under ONE jit-with-shardings
    dispatch, so embed and vote never round-trip the host and the vote's
    all-gather/psum ride ICI.  Temperature is always TRACED here — the
    fused Pallas vote is a single-device kernel (interpret-mode on CPU)
    and never runs under SPMD — so user-supplied values cannot trigger
    recompiles.  Rows at and past ``n`` are dp-alignment padding, masked
    inside the sharded vote (``n_valid``) rather than sliced pre-vote: a
    pre-vote slice would break the even dp row split."""
    from ..parallel.collectives import sharded_cosine_vote

    emb = bert.embed(params, ids, mask, config, pooling=pooling)
    with jax.named_scope("consensus_vote"):
        return sharded_cosine_vote(emb, mesh, temperature, n_valid=n)


@partial(
    jax.jit, static_argnames=("r", "n", "config", "pooling")
)
def _embed_and_vote_many(
    params, ids, mask, temperature, r, n, config, pooling
):
    """Batched self-consistency: ids/mask[>=R*N, S] -> confidence[R, N].

    R concurrent requests share ONE device dispatch (dynamic batching —
    the encoder sees one [R*N, S] batch), amortizing the host<->device
    round-trip that dominates single-request latency on tunneled links.
    The vote is one R-batched einsum + softmax (same numerics as
    ``ops.similarity.cosine_consensus_vote``) rather than R unrolled
    kernel calls — compile time stays flat in R, and the caller buckets R
    to a power of two so only log2 specializations ever compile.  Rows
    past ``r*n`` are bucket/dp-alignment padding, sliced off pre-vote."""
    from ..ops.similarity import dyn_cosine_vote

    emb = bert.embed(params, ids, mask, config, pooling=pooling)
    emb = emb[: r * n].reshape(r, n, -1)
    with jax.named_scope("consensus_vote_many"):
        return dyn_cosine_vote(emb, temperature)


@partial(
    jax.jit,
    static_argnames=("config", "pooling"),
    # buf/valid are the canonical donation case: same-shape state in, new
    # state out — XLA aliases them in place, so the steady-state stream
    # loop allocates nothing (SURVEY §7's last hard-part; VERDICT r3
    # item 1a).  Callers MUST rebind to the returned buffers (they all
    # do: the old ones are dead after this call).  ids/mask are NOT
    # donated: int32 inputs alias no f32 output, so XLA ignores the
    # donation and warns — measured no-op.
    donate_argnums=(3, 4),
)
def _stream_vote_update(
    params, ids, mask, buf, valid, position, config, pooling, temperature
):
    """One streaming-consensus step on device: embed ids[1, S], write the
    vector into buf[position], set valid[position], masked revote.  The
    capacity (buf.shape[0]) is the only streaming-dependent shape, so the
    jit specializes per capacity bucket, not per candidate count."""
    from ..ops.similarity import masked_cosine_vote

    vec = bert.embed(params, ids, mask, config, pooling=pooling)[0]
    buf = buf.at[position].set(vec.astype(buf.dtype))
    valid = valid.at[position].set(1.0)
    with jax.named_scope("stream_masked_vote"):
        conf = masked_cosine_vote(buf, valid, temperature)
    return buf, valid, conf


@partial(
    jax.jit,
    static_argnames=("config", "pooling"),
    donate_argnums=(3, 4),  # see _stream_vote_update
)
def _stream_vote_update_many(
    params, ids, mask, bufs, valids, positions, config, pooling, temperature
):
    """R concurrent streaming-consensus steps in ONE dispatch: embed
    ids[R, S] as one encoder batch, then vmap the scatter + masked revote
    over the R per-stream buffers (same capacity bucket).  The serving
    micro-batcher (serve/batcher.py) groups live streams' updates into
    this; R=1 callers use ``_stream_vote_update``.  Rows past R (batch
    bucketing) are sliced off before the vmap."""
    from ..ops.similarity import masked_cosine_vote

    r = bufs.shape[0]
    vecs = bert.embed(params, ids, mask, config, pooling=pooling)[:r]

    def update(buf, valid, vec, position):
        buf = buf.at[position].set(vec.astype(buf.dtype))
        valid = valid.at[position].set(1.0)
        with jax.named_scope("stream_masked_vote"):
            conf = masked_cosine_vote(buf, valid, temperature)
        return buf, valid, conf

    return jax.vmap(update)(bufs, valids, vecs, positions)


def _bucket(n: int, cap: int) -> int:
    """Next power of two >= n (min 16), capped."""
    size = 16
    while size < n:
        size *= 2
    return min(size, cap)


# Sequence-length buckets: multiples of 16 up to 128 (XLA tiles cleanly at
# /8 boundaries; the fused-attention block constraint needs /8 too), then
# sparse above, doubling past 512 so long-context presets (bge-m3 8k)
# keep a bounded jit-specialization count.  Finer than the power-of-two
# batch buckets on purpose: a ~100-token corpus padding to 128 pays 23%
# padding FLOPs (VERDICT r3 item 1b), while a 112 bucket recovers most of
# it, and the bucket count stays small enough that lazy jit
# specialization is cheap (compile is per-bucket, once).
_SEQ_BUCKETS = (
    16, 32, 48, 64, 80, 96, 112, 128, 192, 256, 384, 512,
    1024, 2048, 4096, 8192,
)


def _seq_bucket(n: int, cap: int) -> int:
    for size in _SEQ_BUCKETS:
        if size >= n:
            return min(size, cap)
    return min(n, cap)


class TpuEmbedder:
    """A BGE-class encoder ready to embed batches on device.

    ``params=None`` random-inits (tests / no local checkpoint); pass a
    pytree from ``bert.from_hf_weights`` for real bge weights.
    ``parallel.shard_embedder_mesh`` flips the instance into first-class
    mesh serving (params partitioned by the rule tables, per-(mesh-shape,
    bucket) AOT executables); the legacy ``parallel.shard_embedder`` hook
    path still works but forgoes AOT + packing; single-device otherwise.
    """

    def __init__(
        self,
        model: str = "bge-small-en",
        *,
        params: Optional[dict] = None,
        config: Optional[BertConfig] = None,
        tokenizer: Optional[BaseTokenizer] = None,
        dtype=None,
        max_tokens: int = 512,
        pooling: Optional[str] = None,
        seed: int = 0,
        quantize: str = "none",
    ) -> None:
        from .configs import usable_positions

        self.model_name = model
        self.config = config or PRESETS[model]
        self.max_tokens = min(max_tokens, usable_positions(self.config))
        # family default from the config (bge: CLS, e5/gte: masked mean)
        # unless the caller overrides
        self.pooling = pooling if pooling is not None else self.config.pooling
        if dtype is None:
            dtype = (
                jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
            )
        self.dtype = dtype
        self.tokenizer = tokenizer or load_tokenizer(
            vocab_size=self.config.vocab_size
        )
        if params is None:
            params = bert.init_params(
                jax.random.PRNGKey(seed), self.config, dtype=dtype
            )
        # validate + stamp the quantize mode and (once, at load) quantize
        # full-precision params — the shared entry point with TpuReranker
        from .quant import resolve_quantize

        self.config, params = resolve_quantize(self.config, params, quantize)
        self.params = params
        put_batch = lambda ids, mask: (ids, mask)  # mesh hook
        # marks the hook as the identity default: AOT executables bake
        # input shardings at lowering time, so replacing the hook (mesh
        # sharding) disables the AOT fast path (_aot_ready)
        put_batch._lwc_default = True
        self.put_batch = put_batch
        # AOT-compiled executables (aot_warmup) keyed by call signature;
        # the dispatch methods consult this FIRST, so warmed buckets
        # never touch the jit dispatch cache (zero new specializations
        # after startup — see jit_stats)
        self._aot = {}
        # fleet-shared serialized-executable store (models/aot_store.py,
        # AOT_CACHE_DIR; serve/__main__ attaches it before warmup): when
        # set, _aot_compile deserializes a peer's executable instead of
        # compiling, and persists anything it does compile
        self.aot_store = None
        self._aot_restored = 0
        # batches are padded up to a multiple of this before dispatch so
        # the dp split divides evenly (shard_embedder sets it to dp)
        self.batch_multiple = 1
        # forward-override hook: a shard function (e.g.
        # parallel.ring.shard_embedder_sp) may replace the whole embedding
        # forward — (padded ids, mask) -> embeddings — keeping this module
        # parallelism-agnostic
        self.embed_override = None
        # introspection: the sequence-parallel mesh when sp-sharded
        self.sp_mesh = None
        # first-class mesh serving (parallel.shard_embedder_mesh /
        # MESH_ENABLED): params placed by the partition-rule tables and
        # dispatches staged with real input shardings.  Unlike the
        # legacy put_batch hook above, mesh mode KEEPS the AOT fast path
        # (executables lower with sharded avals, keyed per mesh shape)
        # and the packed dispatch.
        self.mesh_mode = False
        self.mesh = None
        self.mesh_shape = None
        self.batch_sharding = None
        self.repl_sharding = None
        # long-context ring dispatch (MESH_SHAPE=dp,tp,sp;
        # parallel.shard_embedder_mesh sets these when the mesh carries
        # an sp axis).  mesh_sp == 1 means no ring path: every dense
        # dispatch above stays byte-identical to the 2-axis mesh.
        self.mesh_sp = 1
        self.ring_sharding = None
        self.ring_max_tokens = None
        self._ring_config = None
        # per-(mesh-shape, bucket) device timing at the dispatch seam
        # (obs/phases.py; METRICS_DEVICE_TIMING=0 clears it).  Direct
        # callers pay a block-until-ready bracket; under the batcher's
        # deferred-readiness sink (dispatch_seam.py) the dispatch thread
        # returns at enqueue and the waiter records the same numbers
        self.device_timing = True
        # host staging-buffer pool for the padded dispatch paths
        # (STAGING_BUFFERS; serve/__main__ re-sizes it): buffers recycle
        # via the batcher's waiter once the consuming dispatch is ready
        self.staging_pool = StagingPool(per_bucket=2)
        # device-resident vote-temperature scalars per value (satellite:
        # every consensus dispatch used to re-transfer the same float);
        # entries pin the sharding they were placed with, so a mesh
        # re-shard (new repl_sharding object) can never serve stale
        # placements
        self._temp_cache: dict = {}

    # -- AOT bucket precompile ------------------------------------------------

    def _aot_ready(self) -> bool:
        """Whether the AOT fast path is usable: single-device dispatch,
        or the first-class mesh mode.  Mesh mode lowers with sharded
        ShapeDtypeStructs, so its executables carry the input shardings
        a plain-aval lowering doesn't.  The legacy hook paths (put_batch
        replaced without mesh_mode, embed_override set, or a manual dp
        batch_multiple) still keep the lazy-jit path."""
        if self.mesh_mode:
            return self.embed_override is None
        return (
            self.embed_override is None
            and getattr(self.put_batch, "_lwc_default", False)
            and self.batch_multiple == 1
        )

    def _aot_key(self, key: tuple) -> tuple:
        """AOT table key, namespaced per mesh shape in mesh mode: the
        same bucket compiles to a DIFFERENT executable per (dp, tp) —
        input shardings and the vote's collectives are baked in — so the
        table holds per-(mesh-shape, bucket) entries that can never be
        confused with single-device ones."""
        if self.mesh_mode:
            return ("mesh",) + tuple(self.mesh_shape) + key
        return key

    def _ring_aot_key(self, key: tuple) -> tuple:
        """AOT key for the ring (sequence-parallel) dispatch: namespaced
        by the FULL (dp, tp, sp) shape, so ring executables — which bake
        the (dp, sp) input sharding and the ring collectives — can never
        collide with the dense ("mesh", dp, tp, ...) entries even when
        the bucket tuple matches."""
        return ("mesh",) + tuple(self.mesh_shape) + (self.mesh_sp,) + key

    def ring_available(self) -> bool:
        """Whether the long-context ring dispatch is wired: first-class
        mesh mode on a mesh with an sp axis (shard_embedder_mesh set
        ``mesh_sp``/``ring_sharding``/``_ring_config``).  The batcher
        routes over-length requests here only when this holds; otherwise
        they truncate at ``max_tokens`` exactly as before."""
        return (
            self.mesh_mode
            and self.mesh_sp > 1
            and self.embed_override is None
        )

    def _stage_batch(self, *arrays):
        """Stage host int32 arrays for an AOT executable call: mesh mode
        device_puts with the baked batch sharding (rows split over dp);
        single-device is the plain transfer the executable expects."""
        if self.mesh_mode:
            return tuple(
                jax.device_put(np.asarray(a), self.batch_sharding)
                for a in arrays
            )
        return tuple(jnp.asarray(a) for a in arrays)

    def _timed_dispatch(self, label: str, fn):
        """Run one device dispatch under its canonical bucket label —
        the SAME label the mesh audit measures and ``roofline.json``
        keys, suffixed ``@dp{dp}xtp{tp}`` in mesh mode so the fault
        ladder's rungs report separately — and account its device wall
        time into the global phase aggregator (the ``device_dispatch``
        phase + the roofline gauge's per-bucket p50).

        Two readiness modes (dispatch_seam.py): under the batcher's
        deferred-readiness sink the PJRT call is merely ENQUEUED here —
        a PendingDispatch record hands (label, t0, output) to the
        waiter, which blocks, records the identical numbers, and frees
        this thread to stage the next group.  Without a sink (direct
        and bench callers) the old block-until-ready bracket runs
        inline, now also feeding the overlap gauge's interval union."""
        sink = active_sink()
        if sink is None and not self.device_timing:
            return fn()
        t0 = time.perf_counter()
        out = fn()
        if self.mesh_mode:
            dp, tp = self.mesh_shape
            label = f"{label}@dp{dp}xtp{tp}"
            if self.mesh_sp > 1:
                label = f"{label}xsp{self.mesh_sp}"
        if sink is not None:
            sink.add(
                PendingDispatch(label, t0, out, timed=self.device_timing)
            )
            return out
        wait_device_ready(out)
        t1 = time.perf_counter()
        from ..obs import phases as _phases

        _phases.observe_device(label, (t1 - t0) * 1e3)
        _phases.observe_device_interval(t0, t1)
        return out

    def _finish(self, out):
        """Materialize a dispatch output for host consumers — unless a
        deferred-readiness sink is active, in which case the device
        array is returned as-is (slices stay lazy) and the batcher's
        waiter converts after readiness."""
        if active_sink() is not None:
            return out
        return np.asarray(out)

    def _stage_temp(self, temperature):
        """The vote temperature as a device scalar (replicated over the
        mesh in mesh mode — the executable baked that sharding), cached
        per value: the serving paths send the same default temperature
        on every consensus dispatch, and the fresh host->device scalar
        transfer it used to pay is pure per-dispatch overhead.  Each
        entry pins the sharding object it was placed with; a re-shard
        replaces ``repl_sharding``, so stale placements miss."""
        key = float(temperature)
        expected = self.repl_sharding if self.mesh_mode else None
        hit = self._temp_cache.get(key)
        if hit is not None and hit[0] is expected:
            return hit[1]
        t = jnp.asarray(key, jnp.float32)
        if self.mesh_mode:
            t = jax.device_put(t, self.repl_sharding)
        if len(self._temp_cache) >= 64:
            self._temp_cache.clear()
        self._temp_cache[key] = (expected, t)
        return t

    def _stage_pad(self, ids, mask, pad_b: int, pad_attend: bool = False):
        """Pad the batch dim to ``pad_b`` rows.  Under a deferred-
        readiness sink the rows land in reusable per-bucket staging
        buffers (StagingPool) instead of fresh ``np.pad`` copies — the
        buffers recycle via the waiter once the consuming dispatch is
        ready, because an async ``device_put`` may still be reading
        them before that.  ``pad_attend`` makes pad rows attend to one
        [PAD] token (the consensus contract; callers slice them off
        pre-vote)."""
        b = ids.shape[0]
        sink = active_sink()
        pool = self.staging_pool
        if (
            sink is not None
            and pool is not None
            and pool.enabled
            and ids.dtype == np.int32
            and mask.dtype == np.int32
        ):
            pids = pool.acquire((pad_b, ids.shape[1]), np.int32)
            pmask = pool.acquire((pad_b, mask.shape[1]), np.int32)
            pids[:b] = ids
            pids[b:] = 0
            pmask[:b] = mask
            pmask[b:] = 0
            if pad_attend:
                pmask[b:, 0] = 1
            sink.staged.extend((pids, pmask))
            return pids, pmask
        ids = np.pad(np.asarray(ids), ((0, pad_b - b), (0, 0)))
        mask = np.pad(np.asarray(mask), ((0, pad_b - b), (0, 0)))
        if pad_attend:
            mask[b:, 0] = 1
        return ids, mask

    def _aot_lookup(self, key, ids, mask):
        if not self._aot or not self._aot_ready():
            return None
        # executables were lowered for int32 ids/mask (the tokenizer's
        # dtype); anything else falls back to the jit path rather than
        # tripping the compiled call's aval check
        if ids.dtype != np.int32 or mask.dtype != np.int32:
            return None
        return self._aot.get(key)

    def aot_cache_meta(self) -> dict:
        """The environment digest preimage for the shared executable
        store (models/aot_store.py): everything that makes a serialized
        executable non-portable.  Any difference between the compiling
        and restoring replica lands them in different store namespaces,
        so an incompatible artifact is never even opened."""
        dev = jax.devices()[0]
        return {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": dev.device_kind,
            "device_count": jax.device_count(),
            "config": repr(self.config),
            "pooling": self.pooling,
            "max_tokens": self.max_tokens,
        }

    def _aot_compile(self, timings, key, label, lower) -> None:
        """Fill ``self._aot[key]``: from the shared artifact store when
        a compatible serialized executable exists (AOT_CACHE_DIR), else
        by lowering and compiling — then persisting the result so the
        next replica (or this one after a restart) deserializes in
        milliseconds instead.  ``lower`` is a thunk returning the
        Lowered, deferred so a store hit skips tracing entirely."""
        import time as _time

        if key in self._aot:
            return
        store = self.aot_store
        if store is not None:
            t0 = _time.perf_counter()
            compiled = store.load(key)
            if compiled is not None:
                self._aot[key] = compiled
                self._aot_restored += 1
                timings.append((
                    f"{label} [deserialized]", _time.perf_counter() - t0
                ))
                return
        t0 = _time.perf_counter()
        compiled = lower().compile()
        self._aot[key] = compiled
        timings.append((label, _time.perf_counter() - t0))
        if store is not None:
            store.save(key, compiled)

    def aot_warmup(
        self,
        specs: list,
        r_buckets: list = (),
        packed_buckets: list = (),
        ring_buckets: list = (),
    ) -> list:
        """AOT-lower-and-compile (``.lower().compile()``) every serving
        bucket up front: for each (N, S) spec the single-request consensus
        dispatch (both vote variants — the fused-kernel default and the
        traced-temperature fallback), the plain embed forward at its
        padded batch bucket, and (per ``r_buckets`` entry >= 2) the
        batcher's grouped dispatch.

        The executables land in ``self._aot`` and the dispatch methods
        call them directly, bypassing jit dispatch entirely — post-warmup
        traffic at warmed buckets creates ZERO new jit specializations
        (``.lower().compile()`` alone does not populate the jit dispatch
        cache on jax 0.4.x, so caching the executables ourselves is what
        makes the warmup stick).  With ``COMPILE_CACHE_DIR`` set the
        lowering also lands in the persistent XLA cache, so restarts
        deserialize instead of recompiling.

        In mesh mode (``shard_embedder_mesh``) the same buckets lower
        with SHARDED avals — batch rows split over ``dp``, params
        already carrying their Megatron placement — producing one
        executable per (mesh-shape, bucket) with the input shardings
        and the vote's collectives baked in; see ``_aot_warmup_mesh``.

        Returns [(label, seconds)] for startup logging."""
        if not self._aot_ready():
            raise RuntimeError(
                "AOT warmup needs the single-device embedder or the "
                "first-class mesh mode (shard_embedder_mesh); legacy "
                "hook-sharded embedders warm via real dispatches "
                "(serve/__main__.py)"
            )
        if self.mesh_mode:
            return self._aot_warmup_mesh(
                specs, r_buckets, packed_buckets, ring_buckets
            )
        # ring buckets need an sp mesh; single-device warmup ignores them
        sds = jax.ShapeDtypeStruct
        temp_av = sds((), jnp.float32)
        timings = []
        for n, s in specs:
            s = _seq_bucket(s, self.max_tokens)
            ids_av = sds((n, s), jnp.int32)
            for use_fused in (True, False):
                self._aot_compile(
                    timings,
                    ("vote1", n, s, use_fused),
                    f"consensus {n}x{s} fused={use_fused}",
                    lambda a=ids_av, n=n, f=use_fused: _embed_and_vote.lower(
                        self.params, a, a, temp_av,
                        n, self.config, self.pooling, f,
                    ),
                )
            pad_b = _bucket(n, self.MAX_DEVICE_BATCH)
            b_av = sds((pad_b, s), jnp.int32)
            self._aot_compile(
                timings,
                ("embed", pad_b, s),
                f"embed {pad_b}x{s}",
                lambda a=b_av: bert.embed.lower(
                    self.params, a, a, self.config,
                    pooling=self.pooling, normalize=True,
                ),
            )
            for r in r_buckets:
                if r < 2:
                    continue  # R=1 groups dispatch the single-request path
                flat_av = sds((r * n, s), jnp.int32)
                self._aot_compile(
                    timings,
                    ("many", r, n, s),
                    f"grouped R={r} {n}x{s}",
                    lambda a=flat_av, r=r, n=n: _embed_and_vote_many.lower(
                        self.params, a, a, temp_av,
                        r, n, self.config, self.pooling,
                    ),
                )
        # packed-capacity buckets (continuous batching, serve/packing.py):
        # (rows, row_tokens, max_segments) triples — the small fixed set
        # replacing the (R, N, S) lattice on the packed dispatch path
        for b_rows, l_tokens, k_segs in packed_buckets:
            row_av = sds((b_rows, l_tokens), jnp.int32)
            starts_av = sds((b_rows, k_segs), jnp.int32)
            self._aot_compile(
                timings,
                ("packed", b_rows, l_tokens, k_segs),
                f"packed {b_rows}x{l_tokens}/k{k_segs}",
                lambda a=row_av, st=starts_av: bert.embed_packed.lower(
                    self.params, a, a, a, st,
                    self.config, pooling=self.pooling, normalize=True,
                ),
            )
        return timings

    def _aot_warmup_mesh(
        self,
        specs: list,
        r_buckets: list = (),
        packed_buckets: list = (),
        ring_buckets: list = (),
    ) -> list:
        """The mesh-mode half of ``aot_warmup``: lower every serving
        bucket with SHARDED avals (batch rows over ``dp`` via the
        NamedSharding-carrying ShapeDtypeStructs, params concrete and
        already placed) so ``.lower().compile()`` bakes the input
        shardings and the vote collectives into each executable.  Keys
        are namespaced per mesh shape (``_aot_key``); batch dims are
        padded to the dp multiple exactly like the dispatch methods pad,
        so lookup keys always line up.  One consensus executable per
        (N, S) — the mesh vote always traces its temperature (the fused
        Pallas variant is single-device-only), so there is no
        ``use_fused`` split here."""
        sds = jax.ShapeDtypeStruct
        bm = self.batch_multiple
        dp, tp = self.mesh_shape
        tag = f"mesh {dp}x{tp}"

        def iav(rows, cols):
            return sds((rows, cols), jnp.int32, sharding=self.batch_sharding)

        temp_av = sds((), jnp.float32, sharding=self.repl_sharding)
        timings = []
        for n, s in specs:
            s = _seq_bucket(s, self.max_tokens)
            pad_n = n + (-n) % bm
            self._aot_compile(
                timings,
                self._aot_key(("vote1", n, s)),
                f"{tag} consensus {n}x{s}",
                lambda a=iav(pad_n, s), n=n: _mesh_embed_and_vote.lower(
                    self.params, a, a, temp_av,
                    n, self.config, self.pooling, self.mesh,
                ),
            )
            pad_b = _bucket(n, self.MAX_DEVICE_BATCH)
            pad_b += (-pad_b) % bm
            self._aot_compile(
                timings,
                self._aot_key(("embed", pad_b, s)),
                f"{tag} embed {pad_b}x{s}",
                lambda a=iav(pad_b, s): bert.embed.lower(
                    self.params, a, a, self.config,
                    pooling=self.pooling, normalize=True,
                ),
            )
            for r in r_buckets:
                if r < 2:
                    continue  # R=1 groups dispatch the single-request path
                flat_n = r * n + (-(r * n)) % bm
                self._aot_compile(
                    timings,
                    self._aot_key(("many", r, n, s)),
                    f"{tag} grouped R={r} {n}x{s}",
                    lambda a=iav(flat_n, s), r=r, n=n: (
                        _embed_and_vote_many.lower(
                            self.params, a, a, temp_av,
                            r, n, self.config, self.pooling,
                        )
                    ),
                )
        for b_rows, l_tokens, k_segs in packed_buckets:
            # the packed dispatch pads its row dim to the dp multiple
            # (all-zero rows: segment id 0 is the fully-masked pad slot,
            # which forwards cleanly), so warm the padded bucket
            pb = b_rows + (-b_rows) % bm
            starts_av = sds(
                (pb, k_segs), jnp.int32, sharding=self.batch_sharding
            )
            self._aot_compile(
                timings,
                self._aot_key(("packed", pb, l_tokens, k_segs)),
                f"{tag} packed {pb}x{l_tokens}/k{k_segs}",
                lambda a=iav(pb, l_tokens), st=starts_av: (
                    bert.embed_packed.lower(
                        self.params, a, a, a, st,
                        self.config, pooling=self.pooling, normalize=True,
                    )
                ),
            )
        # long-context ring buckets (N, S): only meaningful with an sp
        # mesh axis — without one the ring shard_map has no axis to ring
        # over, and warming nothing here keeps the 2-axis AOT table
        # byte-identical to the pre-sp serving path
        if ring_buckets and self.ring_available():
            from ..parallel.ring import _ring_embed_and_vote, _ring_embed_jit

            sp = self.mesh_sp
            rtag = f"mesh {dp}x{tp}x{sp}"

            def rav(rows, cols):
                return sds(
                    (rows, cols), jnp.int32, sharding=self.ring_sharding
                )

            for n, s in ring_buckets:
                s = _seq_bucket(s, self.ring_max_tokens)
                s = min(s + (-s) % sp, self.ring_max_tokens)
                pad_n = n + (-n) % bm
                self._aot_compile(
                    timings,
                    self._ring_aot_key(("ring_vote", n, s)),
                    f"{rtag} ring consensus {n}x{s}",
                    lambda a=rav(pad_n, s), n=n: _ring_embed_and_vote.lower(
                        self.params, a, a, temp_av,
                        n, self._ring_config, self.mesh, "sp", "dp",
                        self.pooling,
                    ),
                )
                pad_b = _bucket(n, self.MAX_DEVICE_BATCH)
                pad_b += (-pad_b) % bm
                self._aot_compile(
                    timings,
                    self._ring_aot_key(("ring", pad_b, s)),
                    f"{rtag} ring embed {pad_b}x{s}",
                    lambda a=rav(pad_b, s): _ring_embed_jit.lower(
                        self.params, a, a,
                        self._ring_config, self.mesh, "sp", "dp",
                        self.pooling, True,
                    ),
                )
        return timings

    def aot_mesh_shapes(self) -> list:
        """The (dp, tp) shapes with warmed mesh-mode AOT executables,
        sorted largest-first — the fault-domain ladder audit
        (analysis/mesh_audit.py JXA012) and the ``meshfault`` /metrics
        section read this to prove every fallback rung was prewarmed."""
        shapes = {
            (key[1], key[2])
            for key in self._aot
            if key and key[0] == "mesh"
        }
        return sorted(shapes, reverse=True)

    def jit_stats(self) -> dict:
        """Jit-cache introspection: AOT bucket count + per-entry-point
        specialization counts (serve /metrics "jit" section; the warmup
        test asserts the counts stay flat under post-warmup load)."""
        from ..parallel.ring import _ring_embed_and_vote, _ring_embed_jit

        return {
            "aot_buckets": len(self._aot),
            "aot_restored": self._aot_restored,
            "specializations": {
                "embed_and_vote": _embed_and_vote._cache_size(),
                "embed_and_vote_many": _embed_and_vote_many._cache_size(),
                "mesh_embed_and_vote": _mesh_embed_and_vote._cache_size(),
                "embed": bert.embed._cache_size(),
                "stream_vote_update": _stream_vote_update._cache_size(),
                "stream_vote_update_many": (
                    _stream_vote_update_many._cache_size()
                ),
                "embed_packed": bert.embed_packed._cache_size(),
                "ring_embed": _ring_embed_jit._cache_size(),
                "ring_embed_and_vote": _ring_embed_and_vote._cache_size(),
            },
        }

    # -- core ----------------------------------------------------------------

    def tokenize(self, texts: Iterable[str], max_tokens: Optional[int] = None):
        cap = min(max_tokens or self.max_tokens, self.max_tokens)
        ids, mask = self.tokenizer.encode_batch(list(texts), cap)
        seq = _seq_bucket(int(mask.sum(axis=1).max(initial=1)), cap)
        return ids[:, :seq], mask[:, :seq]

    def embed_texts(
        self, texts: list, max_tokens: Optional[int] = None
    ) -> np.ndarray:
        """texts -> embeddings[B, H] (f32, l2-normalized)."""
        ids, mask = self.tokenize(texts, max_tokens)
        return self.embed_tokens(ids, mask)

    MAX_DEVICE_BATCH = 4096

    def embed_tokens(self, ids: np.ndarray, mask: np.ndarray) -> np.ndarray:
        b = ids.shape[0]
        if b > self.MAX_DEVICE_BATCH:
            # chunk oversized batches; each chunk reuses the same jit
            # specialization (fixed bucketed shapes)
            chunks = [
                self.embed_tokens(
                    ids[i : i + self.MAX_DEVICE_BATCH],
                    mask[i : i + self.MAX_DEVICE_BATCH],
                )
                for i in range(0, b, self.MAX_DEVICE_BATCH)
            ]
            return np.concatenate(chunks, axis=0)
        pad_b = _bucket(b, self.MAX_DEVICE_BATCH)
        pad_b += (-pad_b) % self.batch_multiple  # keep the dp split divisible
        if pad_b != b:
            ids, mask = self._stage_pad(ids, mask, pad_b)
        if self.embed_override is not None:
            return np.asarray(self.embed_override(ids, mask)[:b])
        label = f"embed(b={pad_b},s={ids.shape[1]})"
        exe = self._aot_lookup(
            self._aot_key(("embed", pad_b, ids.shape[1])), ids, mask
        )
        if exe is not None:
            dev_ids, dev_mask = self._stage_batch(ids, mask)
            emb = self._timed_dispatch(
                label, lambda: exe(self.params, dev_ids, dev_mask)
            )
            return self._finish(emb[:b])
        dev_ids, dev_mask = self.put_batch(jnp.asarray(ids), jnp.asarray(mask))
        emb = self._timed_dispatch(
            label,
            lambda: bert.embed(
                self.params,
                dev_ids,
                dev_mask,
                self.config,
                pooling=self.pooling,
                normalize=True,
            ),
        )
        return self._finish(emb[:b])

    # -- long-context ring (sequence-parallel) path ---------------------------

    def _stage_ring(self, *arrays):
        """Stage host int32 arrays for a ring dispatch: rows over ``dp``
        AND the sequence axis over ``sp`` (the sharding the ring
        executables baked).  Callers pad batch to the dp multiple and
        sequence to an sp multiple first, so the split always divides."""
        return tuple(
            jax.device_put(np.asarray(a), self.ring_sharding)
            for a in arrays
        )

    def _require_ring(self):
        if not self.ring_available():
            raise RuntimeError(
                "ring dispatch needs first-class mesh mode on a mesh "
                "with an sp axis (MESH_SHAPE=dp,tp,sp + "
                "shard_embedder_mesh)"
            )

    def _ring_pad_seq(self, ids, mask):
        """Pad the sequence axis to an sp multiple (pads are masked
        keys; ring attention ignores them)."""
        pad_s = (-ids.shape[1]) % self.mesh_sp
        if pad_s:
            ids = np.pad(np.asarray(ids), ((0, 0), (0, pad_s)))
            mask = np.pad(np.asarray(mask), ((0, 0), (0, pad_s)))
        return ids, mask

    def tokenize_ring(
        self, texts: Iterable[str], max_tokens: Optional[int] = None
    ):
        """``tokenize`` for the ring path: caps at ``ring_max_tokens``
        (the position window rounded down to an sp multiple) instead of
        the dense ``max_tokens``, and rounds the trimmed sequence bucket
        UP to an sp multiple so the dispatch never re-pads."""
        self._require_ring()
        sp = self.mesh_sp
        cap = min(max_tokens or self.ring_max_tokens, self.ring_max_tokens)
        cap = max((cap // sp) * sp, sp)
        ids, mask = self.tokenizer.encode_batch(list(texts), cap)
        seq = _seq_bucket(int(mask.sum(axis=1).max(initial=1)), cap)
        seq = min(seq + (-seq) % sp, cap)
        return ids[:, :seq], mask[:, :seq]

    def embed_tokens_ring(self, ids: np.ndarray, mask: np.ndarray):
        """Long-context twin of ``embed_tokens``: the sequence axis is
        sharded over ``sp`` and attention runs as a ring
        (parallel/ring.py), so sequences up to ``ring_max_tokens`` —
        beyond what one device's attention memory can serve — dispatch
        as a single executable.  Same AOT-first contract, keyed under
        the ("mesh", dp, tp, sp, "ring", b, s) namespace."""
        self._require_ring()
        b = ids.shape[0]
        ids, mask = self._ring_pad_seq(ids, mask)
        pad_b = _bucket(b, self.MAX_DEVICE_BATCH)
        pad_b += (-pad_b) % self.batch_multiple
        if pad_b != b:
            ids, mask = self._stage_pad(ids, mask, pad_b)
        s = ids.shape[1]
        label = f"ring(b={pad_b},s={s})"
        exe = self._aot_lookup(
            self._ring_aot_key(("ring", pad_b, s)), ids, mask
        )
        dev_ids, dev_mask = self._stage_ring(ids, mask)
        if exe is not None:
            emb = self._timed_dispatch(
                label, lambda: exe(self.params, dev_ids, dev_mask)
            )
            return self._finish(emb[:b])
        from ..parallel.ring import _ring_embed_jit

        emb = self._timed_dispatch(
            label,
            lambda: _ring_embed_jit(
                self.params, dev_ids, dev_mask, self._ring_config,
                self.mesh, "sp", "dp", self.pooling, True,
            ),
        )
        return self._finish(emb[:b])

    def consensus_confidence_tokens_ring(
        self, ids: np.ndarray, mask: np.ndarray, temperature: float = 0.05
    ):
        """Long-context twin of ``consensus_confidence_tokens``:
        sequence-sharded encoder + the dp-sharded consensus vote in one
        dispatch (parallel.ring._ring_embed_and_vote).  Temperature is
        always traced, pad rows masked via n_valid — the same contract
        as the dense mesh vote."""
        self._require_ring()
        n = ids.shape[0]
        ids, mask = self._ring_pad_seq(ids, mask)
        ids, mask = self._pad_rows(ids, mask)
        s = ids.shape[1]
        label = f"ring_vote(n={n},s={s})"
        exe = self._aot_lookup(
            self._ring_aot_key(("ring_vote", n, s)), ids, mask
        )
        temp = self._stage_temp(temperature)
        dev_ids, dev_mask = self._stage_ring(ids, mask)
        if exe is not None:
            return self._timed_dispatch(
                label, lambda: exe(self.params, dev_ids, dev_mask, temp)
            )
        from ..parallel.ring import _ring_embed_and_vote

        return self._timed_dispatch(
            label,
            lambda: _ring_embed_and_vote(
                self.params, dev_ids, dev_mask, temp, n,
                self._ring_config, self.mesh, "sp", "dp", self.pooling,
            ),
        )

    # -- packed (continuous-batching) path ------------------------------------

    def supports_packing(self) -> bool:
        """Whether the ragged packed dispatch is usable.  Same gate as
        the AOT fast path: the packed entry bypasses ``put_batch`` /
        ``embed_override`` (its layout is not the [B, S] the legacy
        hooks were built for), so hook-sharded embedders keep the
        padded paths.  First-class mesh mode packs fine — its dispatch
        pads the packed row dim to the dp multiple and shards rows like
        any other batch."""
        return self._aot_ready()

    def tokenize_ragged(
        self, texts: Iterable[str], max_tokens: Optional[int] = None
    ) -> list:
        """texts -> list of 1-D int32 token rows, padding stripped.  The
        packing planner consumes these as segments; each row is exactly
        what ``tokenize`` would produce for that text before padding, so
        a packed segment embeds the same token stream as its padded
        twin."""
        cap = min(max_tokens or self.max_tokens, self.max_tokens)
        ids, mask = self.tokenizer.encode_batch(list(texts), cap)
        lens = mask.sum(axis=1)
        return [ids[i, : int(lens[i])] for i in range(ids.shape[0])]

    def embed_packed(
        self,
        ids: np.ndarray,
        segment_ids: np.ndarray,
        positions: np.ndarray,
        seg_starts: np.ndarray,
    ) -> np.ndarray:
        """Packed layout [B, L] (+ seg_starts[B, K]) -> per-segment-slot
        embeddings [B, K, H] (f32, l2-normalized).  One device dispatch;
        consults the AOT table at the ("packed", B, L, K) bucket first so
        warmed packed traffic creates zero jit specializations."""
        b, l = ids.shape
        k = seg_starts.shape[1]
        if self.mesh_mode:
            # pad the row dim to the dp multiple with all-zero rows —
            # segment id 0 is the fully-masked pad slot, so they forward
            # cleanly — then slice the pad slots back off
            pad = (-b) % self.batch_multiple
            if pad:
                ids = np.pad(np.asarray(ids), ((0, pad), (0, 0)))
                segment_ids = np.pad(
                    np.asarray(segment_ids), ((0, pad), (0, 0))
                )
                positions = np.pad(np.asarray(positions), ((0, pad), (0, 0)))
                seg_starts = np.pad(
                    np.asarray(seg_starts), ((0, pad), (0, 0))
                )
        pb = ids.shape[0]
        label = f"packed(b={pb},l={l},k={k})"
        exe = self._aot_lookup(
            self._aot_key(("packed", pb, l, k)), ids, segment_ids
        )
        if exe is not None and (
            positions.dtype == np.int32 and seg_starts.dtype == np.int32
        ):
            dev_ids, dev_segs, dev_pos, dev_starts = self._stage_batch(
                ids, segment_ids, positions, seg_starts
            )
            out = self._timed_dispatch(
                label,
                lambda: exe(
                    self.params, dev_ids, dev_segs, dev_pos, dev_starts
                ),
            )
            return self._finish(out)[:b]
        dev_ids, dev_segs, dev_pos, dev_starts = self._stage_batch(
            ids, segment_ids, positions, seg_starts
        )
        out = self._timed_dispatch(
            label,
            lambda: bert.embed_packed(
                self.params,
                dev_ids,
                dev_segs,
                dev_pos,
                dev_starts,
                self.config,
                pooling=self.pooling,
                normalize=True,
            ),
        )
        return self._finish(out)[:b]

    def consensus_confidence(
        self,
        texts: list,
        max_tokens: Optional[int] = None,
        temperature: float = 0.05,
    ) -> np.ndarray:
        """texts (N candidates) -> confidence[N]: the whole embed + cosine
        self-consistency vote in ONE device dispatch."""
        ids, mask = self.tokenize(texts, max_tokens)
        return self.consensus_confidence_tokens(ids, mask, temperature)

    def _pad_rows(self, ids: np.ndarray, mask: np.ndarray):
        """Pad the batch dim to a multiple of ``batch_multiple`` so the dp
        sharding divides evenly.  Pad rows attend to one [PAD] token (a
        clean forward, no 0/0 pooling); callers slice them off pre-vote.
        Batched callers ride the staging pool via ``_stage_pad``."""
        pad = (-ids.shape[0]) % self.batch_multiple
        if pad:
            ids, mask = self._stage_pad(
                ids, mask, ids.shape[0] + pad, pad_attend=True
            )
        return ids, mask

    def consensus_confidence_tokens(
        self, ids: np.ndarray, mask: np.ndarray, temperature: float = 0.05
    ):
        n = ids.shape[0]
        ids, mask = self._pad_rows(ids, mask)
        label = f"vote1(n={n},s={ids.shape[1]})"
        if self.mesh_mode:
            # one jit-with-shardings dispatch: encoder + the dp-sharded
            # vote reduction; temperature always traced (the fused
            # Pallas vote never runs under SPMD), pad rows masked via
            # n_valid inside the sharded vote
            exe = self._aot_lookup(
                self._aot_key(("vote1", n, ids.shape[1])), ids, mask
            )
            temp = self._stage_temp(temperature)
            dev_ids, dev_mask = self._stage_batch(ids, mask)
            if exe is not None:
                return self._timed_dispatch(
                    label, lambda: exe(self.params, dev_ids, dev_mask, temp)
                )
            return self._timed_dispatch(
                label,
                lambda: _mesh_embed_and_vote(
                    self.params, dev_ids, dev_mask, temp,
                    n, self.config, self.pooling, self.mesh,
                ),
            )
        # the Pallas fast path bakes its temperature in; any other
        # value rides the traced-jnp vote (no per-value recompiles)
        use_fused = float(temperature) == DEFAULT_VOTE_TEMPERATURE
        exe = self._aot_lookup(
            ("vote1", ids.shape[0], ids.shape[1], use_fused), ids, mask
        )
        if exe is not None:
            temp = self._stage_temp(temperature)
            return self._timed_dispatch(
                label,
                lambda: exe(
                    self.params,
                    jnp.asarray(ids),
                    jnp.asarray(mask),
                    temp,
                ),
            )
        dev_ids, dev_mask = self.put_batch(jnp.asarray(ids), jnp.asarray(mask))
        return self._timed_dispatch(
            label,
            lambda: _embed_and_vote(
                self.params,
                dev_ids,
                dev_mask,
                float(temperature),
                n,
                self.config,
                self.pooling,
                use_fused=use_fused,
            ),
        )

    def consensus_confidence_tokens_many(
        self, ids: np.ndarray, mask: np.ndarray, temperature: float = 0.05
    ):
        """ids/mask[R, N, S] (R concurrent requests) -> confidence[R, N] in
        ONE device dispatch (dynamic batching for the serving loop).

        R buckets to the next power of two before the jit: the batcher's
        group size varies with load, and an exact-R specialization would
        recompile the full encoder per distinct concurrency level (tens
        of seconds each for bge-large).  Pad request slots attend to one
        [PAD] token; their confidences are sliced off."""
        from ..utils import next_pow2

        r, n, s = ids.shape
        r_bucket = next_pow2(r)
        if r_bucket != r:
            pad = (r_bucket - r) * n
            ids = np.concatenate(
                [ids.reshape(r * n, s), np.zeros((pad, s), ids.dtype)]
            )
            mask = np.concatenate(
                [mask.reshape(r * n, s), np.zeros((pad, s), mask.dtype)]
            )
            mask[r * n :, 0] = 1
        else:
            ids = ids.reshape(r * n, s)
            mask = mask.reshape(r * n, s)
        flat_ids, flat_mask = self._pad_rows(ids, mask)
        label = f"many(r={r_bucket},n={n},s={s})"
        exe = self._aot_lookup(
            self._aot_key(("many", r_bucket, n, s)), flat_ids, flat_mask
        )
        if exe is not None:
            dev_ids, dev_mask = self._stage_batch(flat_ids, flat_mask)
            conf = self._timed_dispatch(
                label,
                lambda: exe(
                    self.params,
                    dev_ids,
                    dev_mask,
                    self._stage_temp(temperature),
                ),
            )
            return conf[:r]
        dev_ids, dev_mask = self.put_batch(
            jnp.asarray(flat_ids), jnp.asarray(flat_mask)
        )
        conf = self._timed_dispatch(
            label,
            lambda: _embed_and_vote_many(
                self.params, dev_ids, dev_mask, float(temperature), r_bucket,
                n, self.config, self.pooling,
            ),
        )
        return conf[:r]

    def token_count(self, texts: list, max_tokens: Optional[int] = None) -> int:
        _, mask = self.tokenize(texts, max_tokens)
        return int(mask.sum())

    def stream_vote_update(
        self,
        text: str,
        buf,
        valid,
        position: int,
        temperature: float = 0.05,
    ):
        """Streaming-consensus step: embed ONE new candidate into slot
        ``position`` of the device-resident buffer and recompute the
        masked consensus vote — embed + revote fused in ONE dispatch, so a
        live stream pays one link round-trip per finished candidate
        instead of two.  Returns (buf, valid, confidence[CAP]); buf/valid
        stay on device, fetch only the confidence."""
        ids, mask = self.tokenize([text])
        return _stream_vote_update(
            self.params,
            jnp.asarray(ids),
            jnp.asarray(mask),
            buf,
            valid,
            position,
            self.config,
            self.pooling,
            temperature,
        )

    def stream_vote_update_many(
        self,
        texts: list,
        bufs: list,
        valids: list,
        positions: list,
        temperature: float = 0.05,
    ):
        """R streaming-consensus steps (one per live stream, same capacity
        bucket) in ONE dispatch -> (bufs[R, CAP, H], valids[R, CAP],
        confidences[R, CAP]).  The batch dim is bucketed so the jit
        specializes per (R-bucket, CAP, S-bucket), not per exact R; pad
        rows attend to one [PAD] token and their outputs are sliced off."""
        r = len(texts)
        ids, mask = self.tokenize(texts)
        pad = _bucket(r, self.MAX_DEVICE_BATCH) - r
        dev_bufs = jnp.stack(bufs)
        dev_valids = jnp.stack(valids)
        pos = np.zeros((r + pad,), dtype=np.int32)
        pos[:r] = positions
        if pad:
            ids = np.pad(ids, ((0, pad), (0, 0)))
            mask = np.pad(mask, ((0, pad), (0, 0)))
            mask[-pad:, 0] = 1
            dev_bufs = jnp.pad(dev_bufs, ((0, pad), (0, 0), (0, 0)))
            dev_valids = jnp.pad(dev_valids, ((0, pad), (0, 0)))
        out_bufs, out_valids, confs = _stream_vote_update_many(
            self.params,
            jnp.asarray(ids),
            jnp.asarray(mask),
            dev_bufs,
            dev_valids,
            jnp.asarray(pos),
            self.config,
            self.pooling,
            temperature,
        )
        return out_bufs[:r], out_valids[:r], confs[:r]

    # -- wire contract --------------------------------------------------------

    def wire_response(
        self, emb: np.ndarray, tokens: int
    ) -> CreateEmbeddingResponse:
        """Wrap already-computed embeddings as the OpenAI response
        (types/embeddings.py) with usage = real token counts for cost
        accounting — the assembly half of ``embeddings_response``, split
        out so batched callers (serve/batcher.py) can reuse it.

        Row assembly is one bulk ``tolist()`` — a single C-level
        device-to-host conversion — instead of a Python ``float(v)``
        call per element; values are identical (``tolist`` applies the
        same per-element widening ``item()`` conversion).  Before/after
        numbers live in BENCH_host.json ("embed_assembly")."""
        rows = np.asarray(emb).tolist()
        return CreateEmbeddingResponse(
            object="list",
            data=[
                Embedding(
                    object="embedding",
                    index=i,
                    embedding=row,
                )
                for i, row in enumerate(rows)
            ],
            model=self.model_name,
            usage=Usage(
                prompt_tokens=tokens, completion_tokens=0, total_tokens=tokens
            ),
        )

    def embeddings_response(
        self, texts: list, max_tokens: Optional[int] = None
    ) -> CreateEmbeddingResponse:
        """The OpenAI embeddings response.  Tokenizes once."""
        ids, mask = self.tokenize(texts, max_tokens)
        emb = self.embed_tokens(ids, mask)
        return self.wire_response(emb, int(mask.sum()))
