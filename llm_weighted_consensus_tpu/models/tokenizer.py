"""Host-side tokenization for the on-TPU encoders.

Tokenization stays on host (SURVEY §7 phase 5: "tokenize host-side"); the
device sees only int arrays with static shapes.  Two implementations behind
one interface:

* ``WordPieceTokenizer`` — the real BERT algorithm (basic whitespace +
  punctuation split, greedy longest-match with ``##`` continuations) given
  a ``vocab.txt``; loads bge vocabularies from local files (no network);
* ``HashTokenizer``     — deterministic hashing into a fixed vocab so the
  whole pipeline (tests, CPU mesh, benches without downloaded assets) runs
  with identical shapes and padding behavior.

Both pad/truncate to a fixed ``max_length`` and return numpy int32 arrays
(ids, attention_mask).
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, Optional

import numpy as np

CLS, SEP, PAD, UNK = "[CLS]", "[SEP]", "[PAD]", "[UNK]"


class BaseTokenizer:
    pad_id: int = 0

    def encode_batch(self, texts: Iterable[str], max_length: int = 512):
        rows = [self._encode(t, max_length) for t in texts]
        n = len(rows)
        ids = np.full((n, max_length), self.pad_id, dtype=np.int32)
        mask = np.zeros((n, max_length), dtype=np.int32)
        for i, row in enumerate(rows):
            ids[i, : len(row)] = row
            mask[i, : len(row)] = 1
        return ids, mask

    def _encode(self, text: str, max_length: int):
        raise NotImplementedError


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (
        33 <= cp <= 47
        or 58 <= cp <= 64
        or 91 <= cp <= 96
        or 123 <= cp <= 126
    ):
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str) -> list:
    """Lowercase, strip accents, split on whitespace and punctuation."""
    text = unicodedata.normalize("NFD", text.lower())
    out = []
    word = []
    for ch in text:
        if unicodedata.category(ch) == "Mn":
            continue  # strip accents
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif _is_punctuation(ch):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class WordPieceTokenizer(BaseTokenizer):
    """WordPiece with a native (C++) ASCII fast path.

    Pure-ASCII texts — the English serving hot case — encode through
    ``native/wordpiece.cpp`` when the native library loads (byte-for-byte
    parity, tests/test_native.py); non-ASCII texts take the Python path,
    which owns the Unicode NFD + combining-mark handling.  ``use_native=
    False`` forces Python everywhere.
    """

    def __init__(
        self,
        vocab: dict,
        max_chars_per_word: int = 100,
        use_native: bool = True,
    ):
        self.vocab = vocab
        self.max_chars_per_word = max_chars_per_word
        self.pad_id = vocab[PAD]
        self.cls_id = vocab[CLS]
        self.sep_id = vocab[SEP]
        self.unk_id = vocab[UNK]
        # the C++ path hardcodes the default word-length cap; any custom
        # cap keeps the Python path
        self._native = (
            _native_wordpiece(vocab)
            if use_native and max_chars_per_word == 100
            else None
        )

    @classmethod
    def from_vocab_file(cls, path: str, **kwargs) -> "WordPieceTokenizer":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\r\n")] = i
        return cls(vocab, **kwargs)

    def _wordpiece(self, word: str) -> list:
        if len(word) > self.max_chars_per_word:
            return [self.unk_id]
        pieces = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                pid = self.vocab.get(piece)
                if pid is not None:
                    piece_id = pid
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            pieces.append(piece_id)
            start = end
        return pieces

    def _encode(self, text: str, max_length: int):
        if self._native is not None and text.isascii():
            out = self._native.encode(text, max_length)
            if out is not None:
                return out
        ids = [self.cls_id]
        for word in basic_tokenize(text):
            ids.extend(self._wordpiece(word))
            if len(ids) >= max_length - 1:
                break
        ids = ids[: max_length - 1]
        ids.append(self.sep_id)
        return ids


class NativeTokenizerBridge:
    """ctypes bridge to one native tokenizer (``<prefix>_new`` /
    ``<prefix>_encode`` / ``<prefix>_free`` in liblwc_native.so) — shared
    by the WordPiece ('wp') and unigram ('spm') fast paths, which expose
    the identical C ABI."""

    def __init__(self, lib, prefix: str, blob: bytes):
        import ctypes

        new = getattr(lib, f"{prefix}_new")
        new.restype = ctypes.c_void_p
        new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        getattr(lib, f"{prefix}_free").argtypes = [ctypes.c_void_p]
        encode = getattr(lib, f"{prefix}_encode")
        encode.restype = ctypes.c_int64
        encode.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        self._ctypes = ctypes
        self._encode = encode
        self._free = getattr(lib, f"{prefix}_free")
        self._handle = new(blob, len(blob))
        if not self._handle:
            raise ValueError(f"native {prefix} tokenizer rejected the blob")

    def encode(self, text: str, max_length: int):
        # fresh output buffer per call: the native encode releases the
        # GIL, and the gateway encodes on executor threads — a shared
        # buffer would race under concurrent requests
        buf = (self._ctypes.c_int32 * max_length)()
        raw = text.encode("ascii")
        n = self._encode(self._handle, raw, len(raw), max_length, buf)
        if n < 0:
            return None
        return list(buf[: int(n)])

    def __del__(self):
        try:
            if self._handle:
                self._free(self._handle)
                self._handle = None
        except Exception:
            pass


def _native_wordpiece(vocab: dict):
    """A ``_NativeWordPiece`` for this vocab, or None when the native
    library is unavailable or the vocab can't be serialized (ids must be
    exactly 0..n-1, tokens newline-free, specials present)."""
    try:
        from ..utils.native import load_library

        lib = load_library()
        if lib is None:
            return None
        n = len(vocab)
        lines = [None] * n
        for token, i in vocab.items():
            if (
                not isinstance(i, int)
                or not 0 <= i < n
                or lines[i] is not None
                or "\n" in token
                or "\r" in token
            ):
                return None
            lines[i] = token
        if any(line is None for line in lines):
            return None
        for special in (CLS, SEP, UNK):
            if special not in vocab:
                return None
        blob = ("\n".join(lines) + "\n").encode("utf-8")
        return NativeTokenizerBridge(lib, "wp", blob)
    except Exception:
        return None


class HashTokenizer(BaseTokenizer):
    """Deterministic hash tokenization: same text -> same ids, same-shaped
    pipeline as WordPiece.  Special ids mirror BERT (0=PAD, 101=CLS,
    102=SEP)."""

    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size
        self.pad_id = 0
        self.cls_id = min(101, vocab_size - 3)
        self.sep_id = min(102, vocab_size - 2)
        self._reserved = {self.pad_id, self.cls_id, self.sep_id}

    def _token_id(self, token: str) -> int:
        import hashlib

        h = int.from_bytes(
            hashlib.blake2s(token.encode("utf-8"), digest_size=4).digest(),
            "big",
        )
        tid = h % self.vocab_size
        while tid in self._reserved:
            tid = (tid + 1) % self.vocab_size
        return tid

    def _encode(self, text: str, max_length: int):
        ids = [self.cls_id]
        for word in basic_tokenize(text):
            ids.append(self._token_id(word))
            if len(ids) >= max_length - 1:
                break
        ids = ids[: max_length - 1]
        ids.append(self.sep_id)
        return ids


def load_tokenizer(
    vocab_path: Optional[str] = None,
    vocab_size: int = 30522,
    scheme: Optional[str] = None,
) -> BaseTokenizer:
    """Real tokenizer for ``vocab_path``, hash fallback when none given.

    ``*.txt`` -> WordPiece; ``*.model`` / ``*.spm`` -> SentencePiece
    unigram (``scheme`` picks the xlmr/deberta id convention, default
    xlmr — see models/spm.py).  A configured path that does not exist is
    an error, not a silent hash fallback — a typo'd EMBEDDER_VOCAB must
    not serve garbage tokenization that looks valid.
    """
    if vocab_path:
        import os

        if not os.path.exists(vocab_path):
            raise FileNotFoundError(
                f"tokenizer vocab {vocab_path!r} does not exist"
            )
        if vocab_path.endswith((".model", ".spm")):
            from .spm import UnigramTokenizer

            return UnigramTokenizer.from_model_file(
                vocab_path, scheme or "xlmr"
            )
        return WordPieceTokenizer.from_vocab_file(vocab_path)
    return HashTokenizer(vocab_size)
