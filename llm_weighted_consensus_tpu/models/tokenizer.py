"""Host-side tokenization for the on-TPU encoders.

Tokenization stays on host (SURVEY §7 phase 5: "tokenize host-side"); the
device sees only int arrays with static shapes.  Two implementations behind
one interface:

* ``WordPieceTokenizer`` — the real BERT algorithm (basic whitespace +
  punctuation split, greedy longest-match with ``##`` continuations) given
  a ``vocab.txt``; loads bge vocabularies from local files (no network);
* ``HashTokenizer``     — deterministic hashing into a fixed vocab so the
  whole pipeline (tests, CPU mesh, benches without downloaded assets) runs
  with identical shapes and padding behavior.

Both pad/truncate to a fixed ``max_length`` and return numpy int32 arrays
(ids, attention_mask).
"""

from __future__ import annotations

import unicodedata
from typing import Iterable, Optional

import numpy as np

CLS, SEP, PAD, UNK = "[CLS]", "[SEP]", "[PAD]", "[UNK]"


class BaseTokenizer:
    pad_id: int = 0

    def encode_batch(self, texts: Iterable[str], max_length: int = 512):
        rows = [self._encode(t, max_length) for t in texts]
        n = len(rows)
        ids = np.full((n, max_length), self.pad_id, dtype=np.int32)
        mask = np.zeros((n, max_length), dtype=np.int32)
        for i, row in enumerate(rows):
            ids[i, : len(row)] = row
            mask[i, : len(row)] = 1
        return ids, mask

    def _encode(self, text: str, max_length: int):
        raise NotImplementedError


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (
        33 <= cp <= 47
        or 58 <= cp <= 64
        or 91 <= cp <= 96
        or 123 <= cp <= 126
    ):
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str) -> list:
    """Lowercase, strip accents, split on whitespace and punctuation."""
    text = unicodedata.normalize("NFD", text.lower())
    out = []
    word = []
    for ch in text:
        if unicodedata.category(ch) == "Mn":
            continue  # strip accents
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif _is_punctuation(ch):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class WordPieceTokenizer(BaseTokenizer):
    def __init__(self, vocab: dict, max_chars_per_word: int = 100):
        self.vocab = vocab
        self.max_chars_per_word = max_chars_per_word
        self.pad_id = vocab[PAD]
        self.cls_id = vocab[CLS]
        self.sep_id = vocab[SEP]
        self.unk_id = vocab[UNK]

    @classmethod
    def from_vocab_file(cls, path: str) -> "WordPieceTokenizer":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\r\n")] = i
        return cls(vocab)

    def _wordpiece(self, word: str) -> list:
        if len(word) > self.max_chars_per_word:
            return [self.unk_id]
        pieces = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                pid = self.vocab.get(piece)
                if pid is not None:
                    piece_id = pid
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            pieces.append(piece_id)
            start = end
        return pieces

    def _encode(self, text: str, max_length: int):
        ids = [self.cls_id]
        for word in basic_tokenize(text):
            ids.extend(self._wordpiece(word))
            if len(ids) >= max_length - 1:
                break
        ids = ids[: max_length - 1]
        ids.append(self.sep_id)
        return ids


class HashTokenizer(BaseTokenizer):
    """Deterministic hash tokenization: same text -> same ids, same-shaped
    pipeline as WordPiece.  Special ids mirror BERT (0=PAD, 101=CLS,
    102=SEP)."""

    def __init__(self, vocab_size: int = 30522):
        self.vocab_size = vocab_size
        self.pad_id = 0
        self.cls_id = min(101, vocab_size - 3)
        self.sep_id = min(102, vocab_size - 2)
        self._reserved = {self.pad_id, self.cls_id, self.sep_id}

    def _token_id(self, token: str) -> int:
        import hashlib

        h = int.from_bytes(
            hashlib.blake2s(token.encode("utf-8"), digest_size=4).digest(),
            "big",
        )
        tid = h % self.vocab_size
        while tid in self._reserved:
            tid = (tid + 1) % self.vocab_size
        return tid

    def _encode(self, text: str, max_length: int):
        ids = [self.cls_id]
        for word in basic_tokenize(text):
            ids.append(self._token_id(word))
            if len(ids) >= max_length - 1:
                break
        ids = ids[: max_length - 1]
        ids.append(self.sep_id)
        return ids


def load_tokenizer(
    vocab_path: Optional[str] = None, vocab_size: int = 30522
) -> BaseTokenizer:
    """WordPiece when a local vocab exists, hash fallback otherwise."""
    if vocab_path:
        import os

        if os.path.exists(vocab_path):
            return WordPieceTokenizer.from_vocab_file(vocab_path)
    return HashTokenizer(vocab_size)
