"""Encoder configurations (BGE family + DeBERTa-v3 reward model)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 1536
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    # "auto" routes to the fused Pallas attention kernel on TPU when the
    # (s, s) tile fits VMEM; "fused" / "einsum" force one path; "ring"
    # selects sequence-parallel ring attention (only valid inside
    # parallel/ring.py's shard_map over ``ring_axis``).
    attention_impl: str = "auto"
    ring_axis: str = "sp"
    # family-default pooling: bge uses CLS, e5/gte use masked mean
    # (both + l2-normalize); TpuEmbedder reads this unless overridden
    pooling: str = "cls"
    # "absolute": positions 0..s-1 (BERT).  "roberta": positions start at
    # pad_token_id+1 (XLM-R/RoBERTa checkpoints, e.g. bge-m3) — with the
    # framework's left-aligned masks that is an arange offset, so the
    # usable window is max_position_embeddings - pad_token_id - 1.
    position_style: str = "absolute"
    # "none" (default) runs dense matmuls in the param dtype; "int8"
    # expects params transformed by models.quant.quantize_bert_params and
    # runs them W8A8 on the MXU's int8 path (2x bf16 peak on v5e) —
    # opt-in serving mode, accuracy pinned in tests/test_quant.py
    quantize: str = "none"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


# BGE family (BAAI/bge-*-en-v1.5 shapes; CLS pooling)
BGE_SMALL = BertConfig(
    hidden_size=384, num_layers=12, num_heads=12, intermediate_size=1536
)
BGE_BASE = BertConfig(
    hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072
)
BGE_LARGE = BertConfig(
    hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096
)

# E5 family (intfloat/e5-*-v2: BERT arch, masked-MEAN pooling, "query:"/
# "passage:" input prefixes are the caller's concern)
E5_SMALL = BertConfig(
    hidden_size=384, num_layers=12, num_heads=12, intermediate_size=1536,
    pooling="mean",
)
E5_BASE = BertConfig(
    hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072,
    pooling="mean",
)
E5_LARGE = BertConfig(
    hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096,
    pooling="mean",
)

# GTE family (thenlper/gte-*: BERT arch, masked-MEAN pooling)
GTE_SMALL = BertConfig(
    hidden_size=384, num_layers=12, num_heads=12, intermediate_size=1536,
    pooling="mean",
)
GTE_BASE = BertConfig(
    hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072,
    pooling="mean",
)
GTE_LARGE = BertConfig(
    hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096,
    pooling="mean",
)

# BGE-M3 (BAAI/bge-m3 dense retrieval: XLM-RoBERTa-large arch, 8192-token
# context, CLS pooling).  Positions are roberta-style; the 8194-row table
# minus pad_token_id+1 gives the advertised 8192-token window.  Serve long
# inputs with MESH_SP (ring attention).  The checkpoint's
# sentencepiece.bpe.model tokenizes via models/spm.py (xlmr id scheme,
# auto-discovered next to EMBEDDER_WEIGHTS or set EMBEDDER_VOCAB).
BGE_M3 = BertConfig(
    vocab_size=250002,
    hidden_size=1024,
    num_layers=24,
    num_heads=16,
    intermediate_size=4096,
    max_position_embeddings=8194,
    type_vocab_size=1,
    pad_token_id=1,
    position_style="roberta",
)

# Long-context encoder (bge-large dims, 8192-position table): serve with
# MESH_SP so attention runs as a sequence-parallel ring — a single device
# would need the full (s, s) score matrix.  No public checkpoint ships
# with these positions; load fine-tuned weights via EMBEDDER_WEIGHTS or
# train with train/ (position_embed rows beyond 512 train from scratch).
BERT_LONG_8K = BertConfig(
    hidden_size=1024,
    num_layers=24,
    num_heads=16,
    intermediate_size=4096,
    max_position_embeddings=8192,
)

# tiny config for tests: fast init/compile on the CPU mesh
TEST_TINY = BertConfig(
    vocab_size=512,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    intermediate_size=128,
    max_position_embeddings=64,
)

PRESETS = {
    "bge-small-en": BGE_SMALL,
    "bge-base-en": BGE_BASE,
    "bge-large-en": BGE_LARGE,
    "e5-small-v2": E5_SMALL,
    "e5-base-v2": E5_BASE,
    "e5-large-v2": E5_LARGE,
    "gte-small": GTE_SMALL,
    "gte-base": GTE_BASE,
    "gte-large": GTE_LARGE,
    "bge-m3": BGE_M3,
    "bert-long-8k": BERT_LONG_8K,
    "test-tiny": TEST_TINY,
}


def position_base(config: BertConfig) -> int:
    """First position id of a left-aligned sequence (0 for BERT,
    pad_token_id+1 for roberta-style checkpoints)."""
    return (
        config.pad_token_id + 1
        if config.position_style == "roberta"
        else 0
    )


def usable_positions(config: BertConfig) -> int:
    """Longest sequence the position table supports."""
    return config.max_position_embeddings - position_base(config)


@dataclass(frozen=True)
class DebertaConfig:
    """DeBERTa-style encoder with disentangled relative attention.

    ``position_buckets > 0`` selects DeBERTa-v3's LOG-bucketed relative
    positions (HF ``make_log_bucket_position``: exact within ±buckets/2,
    log-spaced beyond, out to ``max_relative_positions``) — the scheme
    every released v3 checkpoint is trained with (rel table rows =
    2 x buckets).  ``position_buckets = 0`` is the plain clamp scheme
    (rel table rows = 2 x max_relative_positions).
    """

    vocab_size: int = 128100
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_relative_positions: int = 512  # furthest distinguishable distance
    position_buckets: int = 256  # 0 = clamp scheme
    layer_norm_eps: float = 1e-7
    pad_token_id: int = 0
    # "int8": W8A8 content/MLP matmuls (models/quant.py twin for the RM;
    # the tiny positional projections and the reward head stay full
    # precision).  Opt-in, accuracy pinned in tests/test_quant.py.
    quantize: str = "none"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def att_span(self) -> int:
        """Half the relative-position table (HF ``pos_ebd_size``)."""
        return (
            self.position_buckets
            if self.position_buckets > 0
            else self.max_relative_positions
        )


# microsoft/deberta-v3-base shapes: max_relative_positions=-1 in HF
# resolves to max_position_embeddings (512); position_buckets=256
DEBERTA_V3_BASE = DebertaConfig()
DEBERTA_TEST_TINY = DebertaConfig(
    vocab_size=512,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    intermediate_size=128,
    max_relative_positions=16,
    position_buckets=0,
)
