"""Opt-in int8 quantization for the encoder's dense matmuls.

The v5e MXU runs int8 x int8 -> int32 at twice the bf16 rate (394 vs 197
TOPS), and the headline consensus forward is dense-matmul-bound (~25 of
its ~32 ms, DESIGN.md r4 breakdown) — so a W8A8 path roughly halves the
FLOP term on the serving hot path.  No reference analog (the reference
delegates model compute to upstream HTTP APIs); this is a TPU-native
serving optimization, OFF by default, selected per embedder
(``TpuEmbedder(..., quantize="int8")`` / ``EMBEDDER_QUANTIZE=int8``).

Scheme — the standard symmetric W8A8 recipe:

* weights: per-OUTPUT-channel symmetric int8 (scale[out] = max|W[:,o]|/127,
  quantized ONCE at load time);
* activations: per-ROW dynamic symmetric int8 (scale[row] = max|x[row]|/127,
  quantized at trace time inside the jit — XLA fuses the quant pass into
  the surrounding elementwise work);
* matmul: int8 x int8 with int32 accumulation on the MXU
  (``preferred_element_type=int32`` — exact), dequantized by the rank-1
  outer product of the two scales, bias added in the activation dtype.

What stays un-quantized, deliberately: attention QK^T/PV (bf16, already
cheap and softmax-sensitive), layernorm/softmax (f32 module contract),
GELU (f32/A&S), embeddings/pooling.  Accuracy is pinned in
tests/test_quant.py: per-matmul error bounds, end-to-end embedding cosine
vs the bf16 path, and consensus-vote top-1 agreement on the committed
golden checkpoint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_weight(kernel: jax.Array):
    """kernel[..., in, out] (f32/bf16) -> (int8 kernel, f32 scale[..., out]).

    Per-output-channel symmetric: preserves each output feature's dynamic
    range independently, which matters for LN-adjacent projections whose
    channel magnitudes vary by orders of magnitude."""
    k32 = kernel.astype(jnp.float32)
    scale = jnp.max(jnp.abs(k32), axis=-2) / 127.0  # [..., out]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(k32 / scale[..., None, :])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_rows(x: jax.Array):
    """x[..., rows, in] -> (int8 x, f32 scale[..., rows]) per-row dynamic."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=-1) / 127.0  # [..., rows]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(x32 / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def dense_int8(x: jax.Array, p: dict) -> jax.Array:
    """W8A8 dense: x[..., in] @ p["kernel_q"][in, out] -> [..., out].

    int32 accumulation on the MXU (exact), dequantized by
    act_scale x weight_scale, bias in the activation dtype — the
    quantized twin of layers.dense."""
    xq, sx = _quantize_rows(x)
    acc = jax.lax.dot_general(
        xq,
        p["kernel_q"],
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sx[..., None] * p["scale"]
    return out.astype(x.dtype) + p["bias"]


_QUANT_LAYER_KERNELS = (
    "attn_q", "attn_k", "attn_v", "attn_out", "mlp_in", "mlp_out"
)


def is_quantized(params: dict) -> bool:
    """Whether a bert param pytree carries the int8 layout — the ONE
    structural probe (callers must not re-invent it: layout changes then
    surface here, not as a silent misdetection at a second site)."""
    return "kernel_q" in params.get("layers", {}).get("attn_q", {})


def quantize_bert_params(params: dict) -> dict:
    """bert param pytree -> its int8 twin (layer dense kernels quantized,
    everything else untouched).  Pair with a config carrying
    ``quantize="int8"`` so the forward takes the dense_int8 path."""
    layers = dict(params["layers"])
    for name in _QUANT_LAYER_KERNELS:
        leaf = layers[name]
        kq, scale = quantize_weight(leaf["kernel"])
        layers[name] = {
            "kernel_q": kq,
            "scale": scale,
            "bias": leaf["bias"],
        }
    out = dict(params)
    out["layers"] = layers
    return out


# DeBERTa RM: the same six content/MLP kernels quantize; the positional
# projections (pos_q/pos_k — one tiny [2k, h] matmul per forward, and the
# disentangled scores are position-sensitive) and the reward head (two
# small matmuls whose scalar output IS the product) stay full precision.
quantize_deberta_params = quantize_bert_params


def resolve_quantize(config, params: dict, quantize: str):
    """The ONE quantize-mode entry point for model constructors
    (TpuEmbedder, TpuReranker): validates the mode, stamps it on the
    (frozen dataclass) config, and quantizes full-precision params once
    at load — pre-quantized pytrees pass through.  Returns
    (config, params)."""
    if quantize not in ("none", "int8"):
        raise ValueError(
            f"quantize={quantize!r}: expected 'none' or 'int8'"
        )
    if quantize == "none":
        return config, params
    import dataclasses

    config = dataclasses.replace(config, quantize=quantize)
    if not is_quantized(params):
        params = quantize_bert_params(params)
    return config, params
