"""Opt-in int8 quantization for the encoder's dense matmuls.

The v5e MXU runs int8 x int8 -> int32 at twice the bf16 rate (394 vs 197
TOPS), and the headline consensus forward is dense-matmul-bound (~25 of
its ~32 ms, DESIGN.md r4 breakdown) — so a W8A8 path roughly halves the
FLOP term on the serving hot path.  No reference analog (the reference
delegates model compute to upstream HTTP APIs); this is a TPU-native
serving optimization, OFF by default, selected per embedder
(``TpuEmbedder(..., quantize="int8")`` / ``EMBEDDER_QUANTIZE=int8``).

Scheme — the standard symmetric W8A8 recipe:

* weights: per-OUTPUT-channel symmetric int8 (scale[out] = max|W[:,o]|/127,
  quantized ONCE at load time);
* activations: per-ROW dynamic symmetric int8 (scale[row] = max|x[row]|/127);
* matmul: int8 x int8 with int32 accumulation on the MXU
  (``preferred_element_type=int32`` — exact), dequantized by the rank-1
  outer product of the two scales, bias added.

Two implementations of the same math, selected by ``impl_for``:

* ``pallas`` (TPU default) — ops/kernels.w8a8_matmul: activation quant +
  int8 matmul + dequant/bias(+GELU) epilogue fused in ONE kernel, so the
  quantized activations, the int32 accumulator, and any dequantized
  weight copy stay in VMEM — nothing but the input and the finished
  output touches HBM;
* ``xla`` (non-TPU default / VMEM-overflow fallback) — the jnp
  composition below with ``preferred_element_type=int32``; XLA fuses the
  quant pass into surrounding elementwise work but stages the int8
  activations through HBM.

The mode strings ``int8-pallas`` / ``int8-xla`` pin an implementation
(tests, debugging); plain ``int8`` auto-selects per backend.

What stays un-quantized, deliberately: attention QK^T/PV (bf16, already
cheap and softmax-sensitive), layernorm/softmax (f32 module contract),
GELU (f32/A&S), embeddings/pooling.  Accuracy is pinned in
tests/test_quant.py: per-matmul error bounds, end-to-end embedding cosine
vs the bf16 path, and consensus-vote top-1 agreement on the committed
golden checkpoint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_weight(kernel: jax.Array):
    """kernel[..., in, out] (f32/bf16) -> (int8 kernel, f32 scale[..., out]).

    Per-output-channel symmetric: preserves each output feature's dynamic
    range independently, which matters for LN-adjacent projections whose
    channel magnitudes vary by orders of magnitude."""
    k32 = kernel.astype(jnp.float32)
    scale = jnp.max(jnp.abs(k32), axis=-2) / 127.0  # [..., out]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(k32 / scale[..., None, :])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_rows(x: jax.Array):
    """x[..., rows, in] -> (int8 x, f32 scale[..., rows]) per-row dynamic."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=-1) / 127.0  # [..., rows]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(x32 / scale[..., None])
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return q, scale


QUANT_MODES = ("none", "int8", "int8-pallas", "int8-xla", "int4-pallas")


def impl_for(mode: str) -> str:
    """Quantize mode string -> dense implementation name.

    Called at trace time, so the backend probe is a compile-time constant
    — the jit sees exactly one path."""
    if mode == "int8":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if mode in ("int8-pallas", "int8-xla"):
        return mode[len("int8-"):]
    if mode == "int4-pallas":
        # one spelling: the packed layout exists FOR the fused kernel
        # (non-TPU backends run it in interpret mode; the only XLA
        # composition is the in-graph VMEM-overflow fallback)
        return "pallas"
    raise ValueError(f"quantize={mode!r} is not a quantized mode")


def dense_int8(
    x: jax.Array, p: dict, *, gelu: bool = False, impl: str = None
) -> jax.Array:
    """W8A8 dense: x[..., in] @ p["kernel_q"][in, out] -> [..., out].

    int32 accumulation on the MXU (exact), dequantized by
    act_scale x weight_scale, plus bias — the quantized twin of
    layers.dense.  ``gelu=True`` appends the exact-profile GELU
    (layers.gelu_erf semantics), fused into the kernel epilogue on the
    pallas impl.  ``impl`` pins "pallas"/"xla"; None auto-selects
    (pallas on TPU, xla elsewhere)."""
    if impl is None:
        impl = impl_for("int8")
    if impl == "pallas":
        from ..ops.kernels import w8a8_matmul, w8a8_shape_fits

        m = 1
        for d in x.shape[:-1]:
            m *= d
        k = x.shape[-1]
        n = p["kernel_q"].shape[-1]
        if w8a8_shape_fits(m, k, n, jnp.dtype(x.dtype).itemsize):
            return w8a8_matmul(
                x, p["kernel_q"], p["scale"], p["bias"], gelu=gelu
            )
        # weight block too big for VMEM: the XLA composition below
    xq, sx = _quantize_rows(x)
    acc = jax.lax.dot_general(
        xq,
        p["kernel_q"],
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sx[..., None] * p["scale"]
    out = out.astype(x.dtype) + p["bias"]
    if gelu:
        from .layers import gelu_erf

        out = gelu_erf(out)
    return out


def quantize_weight_int4(kernel: jax.Array):
    """kernel[..., in, out] (f32/bf16) -> (packed uint8 kernel,
    f32 scale[..., out]).

    Per-output-channel symmetric int4: scale = max|W[:,o]|/7, values
    clipped to [-7, 7], packed two-per-byte along K in the split-K
    biased-nibble layout of ops/kernels.pack_int4_weights (which is also
    the layout ``w4a8_matmul`` unpacks in-kernel).  2x less HBM/VMEM
    than int8 — the headroom the long-context ring path spends on
    activations."""
    from ..ops.kernels import pack_int4_weights

    k32 = kernel.astype(jnp.float32)
    scale = jnp.max(jnp.abs(k32), axis=-2) / 7.0  # [..., out]
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(k32 / scale[..., None, :])
    q = jnp.clip(q, -7, 7).astype(jnp.int8)
    return pack_int4_weights(q), scale


def _unpack_int4(wq4: jax.Array, k: int) -> jax.Array:
    """Packed uint8 [Kp/2, N] -> int8 [k, N] (the XLA-composition twin of
    the in-kernel unpack; XLA constant-folds it over the frozen weights)."""
    w32 = wq4.astype(jnp.int32)
    lo = ((w32 & 0xF) - 8).astype(jnp.int8)
    hi = ((w32 >> 4) - 8).astype(jnp.int8)
    return jnp.concatenate([lo, hi], axis=0)[:k]


def dense_int4(
    x: jax.Array, p: dict, *, gelu: bool = False, impl: str = None
) -> jax.Array:
    """W4A8 dense: x[..., in] @ unpack(p["kernel_q"])[in, out] -> [..., out].

    The packed-int4 twin of ``dense_int8``: same per-row dynamic int8
    activations, same int32 MXU accumulation, same rank-1 dequant — the
    weight block just decodes from nibbles.  The pallas impl unpacks
    IN-KERNEL (ops/kernels.w4a8_matmul) so the int8 weight copy never
    materializes; shapes past the shared VMEM gate fall back to the XLA
    composition over an unpacked weight."""
    if impl is None:
        impl = impl_for("int4-pallas")
    k = x.shape[-1]
    n = p["kernel_q"].shape[-1]
    if impl == "pallas":
        from ..ops.kernels import w4a8_matmul, w8a8_shape_fits

        m = 1
        for d in x.shape[:-1]:
            m *= d
        if w8a8_shape_fits(
            m, k, n, jnp.dtype(x.dtype).itemsize, w_bytes=0.5
        ):
            return w4a8_matmul(
                x, p["kernel_q"], p["scale"], p["bias"], gelu=gelu
            )
        # weight block too big for VMEM: the XLA composition below
    wq = _unpack_int4(p["kernel_q"], k)
    xq, sx = _quantize_rows(x)
    acc = jax.lax.dot_general(
        xq,
        wq,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sx[..., None] * p["scale"]
    out = out.astype(x.dtype) + p["bias"]
    if gelu:
        from .layers import gelu_erf

        out = gelu_erf(out)
    return out


_QUANT_LAYER_KERNELS = (
    "attn_q", "attn_k", "attn_v", "attn_out", "mlp_in", "mlp_out"
)


def is_quantized(params: dict) -> bool:
    """Whether a bert param pytree carries a quantized layout — the ONE
    structural probe (callers must not re-invent it: layout changes then
    surface here, not as a silent misdetection at a second site)."""
    return "kernel_q" in params.get("layers", {}).get("attn_q", {})


def is_int4(params: dict) -> bool:
    """Whether a quantized pytree carries the PACKED int4 layout (uint8
    nibbles) rather than int8 — same single-probe contract as
    ``is_quantized``."""
    leaf = params.get("layers", {}).get("attn_q", {})
    return (
        "kernel_q" in leaf and leaf["kernel_q"].dtype == jnp.uint8
    )


def quantize_bert_params(params: dict) -> dict:
    """bert param pytree -> its int8 twin (layer dense kernels quantized,
    everything else untouched).  Pair with a config carrying
    ``quantize="int8"`` so the forward takes the dense_int8 path."""
    layers = dict(params["layers"])
    for name in _QUANT_LAYER_KERNELS:
        leaf = layers[name]
        kq, scale = quantize_weight(leaf["kernel"])
        layers[name] = {
            "kernel_q": kq,
            "scale": scale,
            "bias": leaf["bias"],
        }
    out = dict(params)
    out["layers"] = layers
    return out


# DeBERTa RM: the same six content/MLP kernels quantize; the positional
# projections (pos_q/pos_k — one tiny [2k, h] matmul per forward, and the
# disentangled scores are position-sensitive) and the reward head (two
# small matmuls whose scalar output IS the product) stay full precision.
quantize_deberta_params = quantize_bert_params


def quantize_bert_params_int4(params: dict) -> dict:
    """bert param pytree -> its packed-int4 twin (same six layer dense
    kernels as the int8 path; same leaf names, so the partition rules
    and the JXA006 coverage audit apply unchanged)."""
    layers = dict(params["layers"])
    for name in _QUANT_LAYER_KERNELS:
        leaf = layers[name]
        kq4, scale = quantize_weight_int4(leaf["kernel"])
        layers[name] = {
            "kernel_q": kq4,
            "scale": scale,
            "bias": leaf["bias"],
        }
    out = dict(params)
    out["layers"] = layers
    return out


def resolve_quantize(config, params: dict, quantize: str):
    """The ONE quantize-mode entry point for model constructors
    (TpuEmbedder, TpuReranker): validates the mode, stamps it on the
    (frozen dataclass) config, and quantizes full-precision params once
    at load — pre-quantized pytrees pass through.  Returns
    (config, params)."""
    if quantize not in QUANT_MODES:
        raise ValueError(
            f"quantize={quantize!r}: expected one of {QUANT_MODES}"
        )
    if quantize == "none":
        return config, params
    import dataclasses

    config = dataclasses.replace(config, quantize=quantize)
    want_int4 = quantize.startswith("int4")
    if is_quantized(params):
        if is_int4(params) != want_int4:
            raise ValueError(
                f"quantize={quantize!r} but params carry the "
                f"{'int4' if is_int4(params) else 'int8'} layout — "
                "re-load full-precision params to switch schemes"
            )
        return config, params
    params = (
        quantize_bert_params_int4(params)
        if want_int4
        else quantize_bert_params(params)
    )
    return config, params
