"""Offline encoder weight loading (no network — local files only).

One entry point, three accepted layouts:

* an HF snapshot directory (``model.safetensors`` or ``pytorch_model.bin``
  + usually ``vocab.txt``) — the layout ``huggingface_hub`` snapshots use
  and the one tests/test_hf_parity.py documents for golden checks;
* a single weights file (``.safetensors`` / ``.bin`` / ``.pt``);
* an orbax checkpoint directory written by ``train.save_checkpoint``.

HF state dicts may carry a ``bert.`` prefix (BertForSequenceClassification
etc.); it is stripped so plain ``BertModel`` and task-head checkpoints both
load.  Reference note: the reference delegates inference upstream
(src/chat/completions/client.rs:308-332) and ships no weight loading at
all — local weights are this framework's whole point.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .configs import BertConfig

_HF_FILES = ("model.safetensors", "pytorch_model.bin")


def _strip_prefix(state: dict) -> dict:
    if any(key.startswith("bert.") for key in state):
        return {
            (key[len("bert."):] if key.startswith("bert.") else key): value
            for key, value in state.items()
        }
    return state


def _load_state_dict(path: str) -> dict:
    """weights file -> {name: np.ndarray}."""
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(path))
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.numpy() for k, v in state.items()}


def _is_orbax_dir(path: str) -> bool:
    if not os.path.isdir(path):
        return False
    entries = set(os.listdir(path))
    return bool(
        entries
        & {"_METADATA", "manifest.ocdbt", "_CHECKPOINT_METADATA", "d"}
    )


def load_params(path: str, config: BertConfig, dtype=None) -> dict:
    """Encoder params pytree from a local checkpoint (see module doc)."""
    import jax
    import jax.numpy as jnp

    from . import bert

    if dtype is None:
        dtype = (
            jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        )
    if os.path.isdir(path):
        for name in _HF_FILES:
            candidate = os.path.join(path, name)
            if os.path.exists(candidate):
                state = _strip_prefix(_load_state_dict(candidate))
                return bert.from_hf_weights(state, config, dtype=dtype)
        if _is_orbax_dir(path):
            from .. import train

            like = bert.init_params(
                jax.random.PRNGKey(0), config, dtype=dtype
            )
            return train.load_checkpoint(path, like=like)
        raise FileNotFoundError(
            f"{path!r} is a directory but contains neither an HF weights "
            f"file ({'/'.join(_HF_FILES)}) nor an orbax checkpoint"
        )
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    state = _strip_prefix(_load_state_dict(path))
    return bert.from_hf_weights(state, config, dtype=dtype)


def find_vocab(weights_path: str) -> Optional[str]:
    """Tokenizer asset sitting next to the weights, if any (HF snapshot
    layout): WordPiece ``vocab.txt`` or a SentencePiece model proto
    (XLM-R/bge-m3 ship ``sentencepiece.bpe.model``, DeBERTa ``spm.model``)."""
    from .spm import SPM_FILES

    root = (
        weights_path
        if os.path.isdir(weights_path)
        else os.path.dirname(weights_path)
    )
    for name in ("vocab.txt",) + SPM_FILES:
        candidate = os.path.join(root, name)
        if os.path.exists(candidate):
            return candidate
    return None
