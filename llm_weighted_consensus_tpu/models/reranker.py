"""Served reward-model re-ranking (BASELINE config 3 as a service).

``TpuReranker`` mirrors ``TpuEmbedder``'s host<->device contract for the
DeBERTa reward model: tokenize candidates on host (unigram spm for real
checkpoints, hash fallback for shape work), run the disentangled-attention
encoder + reward head on device, and return softmax(reward/temperature) —
RM re-ranking as a drop-in consensus vote.  Wired into ``POST /consensus``
via ``{"scorer": "rm"}`` when the gateway has ``RM_MODEL`` configured.

Unlike the embedder there is no request micro-batching (the reward
softmax normalizes over exactly the request's candidates, so requests
cannot share a softmax) — concurrent RM requests ride the executor and
the device queue.  Temperature is traced (no recompile per user value);
the candidate count is a static shape, bounded by the gateway's
MAX_CONSENSUS_CANDIDATES.
"""

from __future__ import annotations

from functools import partial
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import deberta
from .configs import DEBERTA_TEST_TINY, DEBERTA_V3_BASE, DebertaConfig
from .tokenizer import BaseTokenizer, load_tokenizer

RM_PRESETS = {
    "deberta-v3-base": DEBERTA_V3_BASE,
    "deberta-test-tiny": DEBERTA_TEST_TINY,
}


@partial(jax.jit, static_argnames=("config",))
def _reward_and_vote(params, ids, mask, temperature, config):
    """Fused reward + softmax vote: one dispatch per request."""
    rewards = deberta.reward(params, ids, mask, config)
    with jax.named_scope("rm_vote"):
        return jax.nn.softmax(rewards.astype(jnp.float32) / temperature)


class TpuReranker:
    """A DeBERTa reward model ready to re-rank candidate batches."""

    def __init__(
        self,
        model: str = "deberta-v3-base",
        *,
        params: Optional[dict] = None,
        config: Optional[DebertaConfig] = None,
        tokenizer: Optional[BaseTokenizer] = None,
        dtype=None,
        max_tokens: int = 512,
        seed: int = 0,
        quantize: str = "none",
    ) -> None:
        self.model_name = model
        self.config = config or RM_PRESETS[model]
        self.max_tokens = max_tokens
        if dtype is None:
            dtype = (
                jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
            )
        self.dtype = dtype
        self.tokenizer = tokenizer or load_tokenizer(
            vocab_size=self.config.vocab_size
        )
        if params is None:
            params = deberta.init_params(
                jax.random.PRNGKey(seed), self.config, dtype=dtype
            )
        # shared quantize entry point with TpuEmbedder (models/quant.py)
        from .quant import resolve_quantize

        self.config, params = resolve_quantize(self.config, params, quantize)
        self.params = params

    def tokenize(self, texts: Iterable[str]):
        from .embedder import _seq_bucket

        ids, mask = self.tokenizer.encode_batch(
            list(texts), self.max_tokens
        )
        # shrink to the content bucket like the embedder (bounds jit
        # specializations per (N, S-bucket)); multiples-of-16 buckets so
        # ~100-token (prompt + candidate) pairs stop paying pow2 padding
        # (the r4 serving-path cut, embedder._SEQ_BUCKETS)
        seq = _seq_bucket(int(mask.sum(axis=1).max(initial=1)), self.max_tokens)
        return ids[:, :seq], mask[:, :seq]

    def rerank_confidence(
        self,
        texts: list,
        prompt: Optional[str] = None,
        temperature: float = 1.0,
    ):
        """N candidate texts -> (confidence[N], token_count), confidence
        = softmax(reward / T) in one fused dispatch.

        ``prompt`` (the question being answered) is prepended to every
        candidate — reward models score (prompt, candidate) pairs."""
        if prompt:
            texts = [f"{prompt}\n{text}" for text in texts]
        ids, mask = self.tokenize(texts)
        conf = np.asarray(
            _reward_and_vote(
                self.params,
                jnp.asarray(ids),
                jnp.asarray(mask),
                float(temperature),
                self.config,
            )
        )
        return conf, int(mask.sum())


def load_rm_params(path: str, config: DebertaConfig, dtype=None):
    """RM params from a local checkpoint: HF DeBERTa-v3 snapshot dir or
    weights file (``deberta.from_hf_weights``), or an orbax dir.

    Returns ``(params, head_loaded)`` — ``head_loaded`` is False when the
    checkpoint was encoder-only and the reward head had to random-init
    (serving such params is gated like any other synthetic state)."""
    import os

    from .loading import _HF_FILES, _is_orbax_dir, _load_state_dict

    if dtype is None:
        dtype = (
            jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        )

    def from_state(state_path):
        state = _strip_deberta_prefix(_load_state_dict(state_path))
        return (
            deberta.from_hf_weights(state, config, dtype=dtype),
            "pooler.dense.weight" in state,
        )

    if os.path.isdir(path):
        for name in _HF_FILES:
            candidate = os.path.join(path, name)
            if os.path.exists(candidate):
                return from_state(candidate)
        if _is_orbax_dir(path):
            from .. import train

            like = deberta.init_params(
                jax.random.PRNGKey(0), config, dtype=dtype
            )
            # orbax checkpoints are written by train/ with the head
            # included — head_loaded by construction
            return train.load_checkpoint(path, like=like), True
        raise FileNotFoundError(
            f"{path!r} contains neither an HF weights file nor an orbax "
            "checkpoint"
        )
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return from_state(path)


def _strip_deberta_prefix(state: dict) -> dict:
    """HF task-model checkpoints prefix the backbone with ``deberta.``;
    head weights (pooler/classifier) stay unprefixed."""
    return {
        (key[len("deberta."):] if key.startswith("deberta.") else key): value
        for key, value in state.items()
    }
