"""Functional JAX BERT encoder (BGE-class).

TPU-first design decisions (SURVEY §2.8 note: pure DP suffices for
bge-small/large — no TP needed; the batch axis is the sharded axis):

* params are a plain nested-dict pytree — shard/checkpoint/donate freely;
* all matmuls run in the param dtype (bf16 on TPU) with f32 accumulation on
  the MXU (``preferred_element_type``); layernorm and softmax always f32;
* static shapes only: (batch, seq) fixed per jit specialization, attention
  mask handles padding — no data-dependent control flow;
* one fused forward: embeddings -> N transformer layers (lax.scan over
  stacked layer params so XLA compiles ONE layer body regardless of depth)
  -> pooled embedding.

Weight layout matches HuggingFace BERT so real bge checkpoints load via
``from_hf_weights`` when available offline; random init otherwise.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .configs import BertConfig
from .layers import (
    dense as _dense,
    dense_cfg as _dense_cfg,
    dense_init as _dense_init,
    gelu_erf as _gelu_erf,
    layer_norm as _layer_norm,
    ln_init as _ln_init,
    mlp_cfg as _mlp_cfg,
)


def init_params(rng: jax.Array, config: BertConfig, dtype=jnp.float32) -> dict:
    """Random-init parameters; layer params are stacked on a leading axis
    for the scanned layer body."""
    keys = jax.random.split(rng, 8)
    h, i = config.hidden_size, config.intermediate_size

    def layer_params(layer_rng):
        ks = jax.random.split(layer_rng, 6)
        return {
            "attn_q": _dense_init(ks[0], h, h, dtype),
            "attn_k": _dense_init(ks[1], h, h, dtype),
            "attn_v": _dense_init(ks[2], h, h, dtype),
            "attn_out": _dense_init(ks[3], h, h, dtype),
            "attn_ln": _ln_init(h, dtype),
            "mlp_in": _dense_init(ks[4], h, i, dtype),
            "mlp_out": _dense_init(ks[5], i, h, dtype),
            "mlp_ln": _ln_init(h, dtype),
        }

    layer_keys = jax.random.split(keys[0], config.num_layers)
    layers = jax.vmap(layer_params)(layer_keys)

    return {
        "token_embed": (
            jax.random.normal(
                keys[1], (config.vocab_size, h), jnp.float32
            )
            * 0.02
        ).astype(dtype),
        "position_embed": (
            jax.random.normal(
                keys[2], (config.max_position_embeddings, h), jnp.float32
            )
            * 0.02
        ).astype(dtype),
        "type_embed": (
            jax.random.normal(keys[3], (config.type_vocab_size, h), jnp.float32)
            * 0.02
        ).astype(dtype),
        "embed_ln": _ln_init(h, dtype),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _attention(x, p, mask_bias, config: BertConfig):
    b, s, h = x.shape
    nh, hd = config.num_heads, config.head_dim

    def heads(t):
        return t.reshape(b, s, nh, hd)

    with jax.named_scope("qkv_proj"):
        q = heads(_dense_cfg(x, p["attn_q"], config))
        k = heads(_dense_cfg(x, p["attn_k"], config))
        v = heads(_dense_cfg(x, p["attn_v"], config))
    scale = 1.0 / float(hd) ** 0.5
    if config.attention_impl == "ring":
        # sequence-parallel ring attention: only valid inside a shard_map
        # over config.ring_axis (parallel/ring.py::ring_encode sets it up)
        from ..parallel.ring import ring_attention

        with jax.named_scope("ring_attention"):
            ctx = ring_attention(
                q, k, v, mask_bias[:, 0, 0, :], scale, config.ring_axis
            )
    elif _use_fused_attention(config, b, s, hd, q.dtype):
        from ..ops.attention import best_heads_per_step, fused_attention_tiled

        with jax.named_scope("fused_attention"):
            # mask_bias is [b, 1, 1, s]; the kernel wants the [b, s] key bias
            ctx = fused_attention_tiled(
                q,
                k,
                v,
                mask_bias[:, 0, 0, :],
                scale,
                # forced mode may arrive with best==0 (caller takes the
                # VMEM responsibility); run the minimal 1-tile step then
                heads_per_step=max(
                    best_heads_per_step(b, s, nh, hd, q.dtype.itemsize), 1
                ),
            )
    else:
        with jax.named_scope("einsum_attention"):
            # [b, nh, s, s] logits: f32 accumulation on the MXU, stored in
            # the activation dtype like every other matmul in this module
            # (bf16 storage halves the attention HBM traffic — the one
            # materialized intermediate XLA cannot fuse away); softmax
            # itself stays f32 per the module contract
            logits = (
                jnp.einsum(
                    "bqnd,bknd->bnqk", q, k,
                    preferred_element_type=x.dtype,
                )
                * scale
            )
            logits = logits + mask_bias.astype(x.dtype)  # [b, 1, 1, s]
            probs = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1
            ).astype(x.dtype)
            ctx = jnp.einsum(
                "bnqk,bknd->bqnd", probs, v,
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
    with jax.named_scope("attn_out"):
        return _dense_cfg(ctx.reshape(b, s, h), p["attn_out"], config)


def _use_fused_attention(
    config: BertConfig, b: int, s: int, hd: int, dtype
) -> bool:
    from ..ops.attention import attention_fits, best_heads_per_step

    impl = config.attention_impl
    if impl == "einsum":
        return False
    if impl == "fused":
        # forced: the caller takes responsibility for the VMEM budget
        # (Mosaic fails loudly if one (s, s) tile cannot fit)
        return True
    if not attention_fits(s, hd):
        return False
    if best_heads_per_step(b, s, config.num_heads, hd, dtype.itemsize) < 1:
        # the kernel's own cost model says no tile fits (e.g. f32
        # activations at s=1024): einsum, not a thrashing kernel
        return False
    # "auto": measured IN CONTEXT on the real v5e chip (bge-large, bf16,
    # full forward, bench_fwd.py r4): einsum with bf16-stored logits wins
    # at s=128/256/384 (31.97 vs 35.54; 36.46 vs 39.56; 30.50 vs 30.87
    # ms/fwd) because XLA fuses the head transposes into the projection
    # matmuls, while the Pallas kernel pays them as HBM passes; the
    # VMEM-resident kernel wins at s=512 (42.79 vs 47.65) where the
    # [b, nh, s, s] intermediates dominate.  Isolated-op numbers (where
    # the kernel matches einsum at 128 and wins from 256) are in
    # ops/attention.py — the in-context crossover is what serving pays.
    return jax.default_backend() == "tpu" and s >= 512


def _layer(x, p, mask_bias, config: BertConfig):
    attn = _attention(x, p, mask_bias, config)
    x = _layer_norm(x + attn, p["attn_ln"], config.layer_norm_eps)
    # GELU fuses into the mlp_in epilogue on the int8 path (layers.mlp_cfg)
    mlp = _mlp_cfg(x, p["mlp_in"], p["mlp_out"], config)
    return _layer_norm(x + mlp, p["mlp_ln"], config.layer_norm_eps)


def encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
    position_offset=0,
) -> jax.Array:
    """input_ids[b, s], attention_mask[b, s] -> hidden[b, s, h].

    ``position_offset`` shifts the position embeddings — used by the
    sequence-parallel forward (parallel/ring.py) where each shard holds a
    slice of the global sequence."""
    from .configs import position_base

    b, s = input_ids.shape
    base = position_base(config)
    if (
        isinstance(position_offset, int)
        and base + s + position_offset > config.max_position_embeddings
    ):
        # gathers clamp out-of-range indices — fail loudly instead of
        # silently reusing the last position embedding
        raise ValueError(
            f"sequence {s} (+offset {position_offset}, position base "
            f"{base}) exceeds max_position_embeddings="
            f"{config.max_position_embeddings}"
        )
    with jax.named_scope("embeddings"):
        x = params["token_embed"][input_ids]
        # left-aligned masks make roberta's cumsum positions an arange
        # with a base offset (pad positions get wrong embeddings but their
        # hidden states are masked out of attention and pooling)
        x = x + params["position_embed"][
            jnp.arange(s) + position_offset + base
        ][None, :, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + params["type_embed"][token_type_ids]
        x = _layer_norm(x, params["embed_ln"], config.layer_norm_eps)

    mask_bias = jnp.where(
        attention_mask[:, None, None, :] > 0, 0.0, -1e9
    ).astype(jnp.float32)

    # scan over stacked layers: ONE compiled layer body for any depth
    def body(carry, layer_p):
        return _layer(carry, layer_p, mask_bias, config), None

    with jax.named_scope("encoder_layers"):
        x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def pool(
    hidden: jax.Array,
    attention_mask: jax.Array,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """hidden[b, s, h] -> embedding[b, h] (bge uses CLS + l2-normalize)."""
    if pooling == "cls":
        emb = hidden[:, 0, :]
    elif pooling == "mean":
        # f32 reductions regardless of activation dtype (module contract):
        # bf16 cannot even represent token counts > 256 exactly
        mask = attention_mask[:, :, None].astype(jnp.float32)
        emb = jnp.sum(hidden.astype(jnp.float32) * mask, axis=1) / jnp.maximum(
            jnp.sum(mask, axis=1), 1
        )
    else:
        raise ValueError(f"unknown pooling {pooling!r}")
    emb = emb.astype(jnp.float32)
    if normalize:
        norm = jnp.sqrt(jnp.sum(emb * emb, axis=-1, keepdims=True))
        emb = emb / jnp.maximum(norm, 1e-12)
    return emb


@partial(jax.jit, static_argnames=("config", "pooling", "normalize"))
def embed(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """The jitted end-to-end embedding forward: ids -> pooled vectors."""
    hidden = encode(params, input_ids, attention_mask, config)
    return pool(hidden, attention_mask, pooling, normalize)


# ---------------------------------------------------------------------------
# HF checkpoint import (offline)
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attn_q": "attention.self.query",
    "attn_k": "attention.self.key",
    "attn_v": "attention.self.value",
    "attn_out": "attention.output.dense",
    "mlp_in": "intermediate.dense",
    "mlp_out": "output.dense",
}
_HF_LN_MAP = {
    "attn_ln": "attention.output.LayerNorm",
    "mlp_ln": "output.LayerNorm",
}


def from_hf_weights(state_dict: dict, config: BertConfig, dtype=jnp.float32) -> dict:
    """Map a HuggingFace BERT state dict (numpy arrays) into our pytree.

    Works with any BERT-architecture checkpoint (bge-*-en-v1.5 included)
    loaded from local files — no network access is assumed here.
    """

    def get(name):
        arr = state_dict[name]
        return jnp.asarray(arr, dtype=dtype)

    def dense(prefix):
        return {
            # torch Linear stores [out, in]; ours is [in, out]
            "kernel": get(f"{prefix}.weight").T,
            "bias": get(f"{prefix}.bias"),
        }

    def ln(prefix):
        return {"scale": get(f"{prefix}.weight"), "bias": get(f"{prefix}.bias")}

    layers = []
    for i in range(config.num_layers):
        base = f"encoder.layer.{i}"
        layer = {
            name: dense(f"{base}.{hf}") for name, hf in _HF_LAYER_MAP.items()
        }
        layer.update(
            {name: ln(f"{base}.{hf}") for name, hf in _HF_LN_MAP.items()}
        )
        layers.append(layer)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)

    return {
        "token_embed": get("embeddings.word_embeddings.weight"),
        "position_embed": get("embeddings.position_embeddings.weight"),
        "type_embed": get("embeddings.token_type_embeddings.weight"),
        "embed_ln": ln("embeddings.LayerNorm"),
        "layers": stacked,
    }
