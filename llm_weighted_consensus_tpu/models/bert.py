"""Functional JAX BERT encoder (BGE-class).

TPU-first design decisions (SURVEY §2.8 note: pure DP suffices for
bge-small/large — no TP needed; the batch axis is the sharded axis):

* params are a plain nested-dict pytree — shard/checkpoint/donate freely;
* all matmuls run in the param dtype (bf16 on TPU) with f32 accumulation on
  the MXU (``preferred_element_type``); layernorm and softmax always f32;
* static shapes only: (batch, seq) fixed per jit specialization, attention
  mask handles padding — no data-dependent control flow;
* one fused forward: embeddings -> N transformer layers (lax.scan over
  stacked layer params so XLA compiles ONE layer body regardless of depth)
  -> pooled embedding.

Weight layout matches HuggingFace BERT so real bge checkpoints load via
``from_hf_weights`` when available offline; random init otherwise.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .configs import BertConfig
from .layers import (
    dense as _dense,
    dense_cfg as _dense_cfg,
    dense_init as _dense_init,
    gelu_erf as _gelu_erf,
    layer_norm as _layer_norm,
    ln_init as _ln_init,
    mlp_cfg as _mlp_cfg,
)


def init_params(rng: jax.Array, config: BertConfig, dtype=jnp.float32) -> dict:
    """Random-init parameters; layer params are stacked on a leading axis
    for the scanned layer body."""
    keys = jax.random.split(rng, 8)
    h, i = config.hidden_size, config.intermediate_size

    def layer_params(layer_rng):
        ks = jax.random.split(layer_rng, 6)
        return {
            "attn_q": _dense_init(ks[0], h, h, dtype),
            "attn_k": _dense_init(ks[1], h, h, dtype),
            "attn_v": _dense_init(ks[2], h, h, dtype),
            "attn_out": _dense_init(ks[3], h, h, dtype),
            "attn_ln": _ln_init(h, dtype),
            "mlp_in": _dense_init(ks[4], h, i, dtype),
            "mlp_out": _dense_init(ks[5], i, h, dtype),
            "mlp_ln": _ln_init(h, dtype),
        }

    layer_keys = jax.random.split(keys[0], config.num_layers)
    layers = jax.vmap(layer_params)(layer_keys)

    return {
        "token_embed": (
            jax.random.normal(
                keys[1], (config.vocab_size, h), jnp.float32
            )
            * 0.02
        ).astype(dtype),
        "position_embed": (
            jax.random.normal(
                keys[2], (config.max_position_embeddings, h), jnp.float32
            )
            * 0.02
        ).astype(dtype),
        "type_embed": (
            jax.random.normal(keys[3], (config.type_vocab_size, h), jnp.float32)
            * 0.02
        ).astype(dtype),
        "embed_ln": _ln_init(h, dtype),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _attention(x, p, mask_bias, config: BertConfig, segment_ids=None):
    b, s, h = x.shape
    nh, hd = config.num_heads, config.head_dim

    def heads(t):
        return t.reshape(b, s, nh, hd)

    with jax.named_scope("qkv_proj"):
        q = heads(_dense_cfg(x, p["attn_q"], config))
        k = heads(_dense_cfg(x, p["attn_k"], config))
        v = heads(_dense_cfg(x, p["attn_v"], config))
    scale = 1.0 / float(hd) ** 0.5
    if config.attention_impl == "ring":
        if segment_ids is not None:
            # the ring rotates k/v shards across chips; a same-segment
            # mask would need the GLOBAL segment row on every chip —
            # packed serving stays on the dense paths
            raise ValueError(
                "ring attention does not support packed segment_ids"
            )
        # sequence-parallel ring attention: only valid inside a shard_map
        # over config.ring_axis (parallel/ring.py::ring_encode sets it up)
        from ..parallel.ring import ring_attention

        with jax.named_scope("ring_attention"):
            ctx = ring_attention(
                q, k, v, mask_bias[:, 0, 0, :], scale, config.ring_axis
            )
    elif _use_fused_attention(config, b, s, hd, q.dtype):
        from ..ops.attention import (
            best_heads_per_step,
            fused_attention_tiled,
            fused_attention_tiled_seg,
        )

        # forced mode may arrive with best==0 (caller takes the
        # VMEM responsibility); run the minimal 1-tile step then
        kk = max(best_heads_per_step(b, s, nh, hd, q.dtype.itemsize), 1)
        if segment_ids is not None:
            with jax.named_scope("fused_attention_seg"):
                # packed layout: the kernel builds the same-segment mask
                # in VMEM from the int32 segment row
                ctx = fused_attention_tiled_seg(
                    q, k, v, segment_ids, scale, heads_per_step=kk
                )
        else:
            with jax.named_scope("fused_attention"):
                # mask_bias is [b, 1, 1, s]; the kernel wants the
                # [b, s] key bias
                ctx = fused_attention_tiled(
                    q, k, v, mask_bias[:, 0, 0, :], scale, heads_per_step=kk
                )
    else:
        with jax.named_scope("einsum_attention"):
            # [b, nh, s, s] logits: f32 accumulation on the MXU, stored in
            # the activation dtype like every other matmul in this module
            # (bf16 storage halves the attention HBM traffic — the one
            # materialized intermediate XLA cannot fuse away); softmax
            # itself stays f32 per the module contract
            logits = (
                jnp.einsum(
                    "bqnd,bknd->bnqk", q, k,
                    preferred_element_type=x.dtype,
                )
                * scale
            )
            # [b, 1, 1, s] key-padding bias, or [b, 1, s, s] same-segment
            # bias on the packed path — both broadcast over heads
            logits = logits + mask_bias.astype(x.dtype)
            probs = jax.nn.softmax(
                logits.astype(jnp.float32), axis=-1
            ).astype(x.dtype)
            ctx = jnp.einsum(
                "bnqk,bknd->bqnd", probs, v,
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
    with jax.named_scope("attn_out"):
        return _dense_cfg(ctx.reshape(b, s, h), p["attn_out"], config)


def _use_fused_attention(
    config: BertConfig, b: int, s: int, hd: int, dtype
) -> bool:
    from ..ops.attention import attention_fits, best_heads_per_step

    impl = config.attention_impl
    if impl == "einsum":
        return False
    if impl == "fused":
        # forced: the caller takes responsibility for the VMEM budget
        # (Mosaic fails loudly if one (s, s) tile cannot fit)
        return True
    if not attention_fits(s, hd):
        return False
    if best_heads_per_step(b, s, config.num_heads, hd, dtype.itemsize) < 1:
        # the kernel's own cost model says no tile fits (e.g. f32
        # activations at s=1024): einsum, not a thrashing kernel
        return False
    # "auto": measured IN CONTEXT on the real v5e chip (bge-large, bf16,
    # full forward, bench_fwd.py r4): einsum with bf16-stored logits wins
    # at s=128/256/384 (31.97 vs 35.54; 36.46 vs 39.56; 30.50 vs 30.87
    # ms/fwd) because XLA fuses the head transposes into the projection
    # matmuls, while the Pallas kernel pays them as HBM passes; the
    # VMEM-resident kernel wins at s=512 (42.79 vs 47.65) where the
    # [b, nh, s, s] intermediates dominate.  Isolated-op numbers (where
    # the kernel matches einsum at 128 and wins from 256) are in
    # ops/attention.py — the in-context crossover is what serving pays.
    return jax.default_backend() == "tpu" and s >= 512


def _layer(x, p, mask_bias, config: BertConfig, segment_ids=None):
    attn = _attention(x, p, mask_bias, config, segment_ids)
    x = _layer_norm(x + attn, p["attn_ln"], config.layer_norm_eps)
    # GELU fuses into the mlp_in epilogue on the int8 path (layers.mlp_cfg)
    mlp = _mlp_cfg(x, p["mlp_in"], p["mlp_out"], config)
    return _layer_norm(x + mlp, p["mlp_ln"], config.layer_norm_eps)


def encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
    position_offset=0,
    segment_ids: Optional[jax.Array] = None,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """input_ids[b, s], attention_mask[b, s] -> hidden[b, s, h].

    ``position_offset`` shifts the position embeddings — used by the
    sequence-parallel forward (parallel/ring.py) where each shard holds a
    slice of the global sequence.

    The packed (continuous-batching) layout passes ``segment_ids[b, s]``
    (int32, 0 = pad slot, >=1 = packed sequence id) and ``positions[b, s]``
    (within-segment offsets, each segment restarting at 0): attention is
    confined to same-segment tokens and every segment sees exactly the
    position embeddings its padded twin would, so a packed row reproduces
    the per-row forward bit-for-bit up to float reduction order.  Because
    positions are per-segment, a packed row may be LONGER than the
    model's position table — only each segment is bounded by it
    (serve/packing.py enforces that at plan time)."""
    from .configs import position_base

    b, s = input_ids.shape
    base = position_base(config)
    if (
        positions is None
        and isinstance(position_offset, int)
        and base + s + position_offset > config.max_position_embeddings
    ):
        # gathers clamp out-of-range indices — fail loudly instead of
        # silently reusing the last position embedding
        raise ValueError(
            f"sequence {s} (+offset {position_offset}, position base "
            f"{base}) exceeds max_position_embeddings="
            f"{config.max_position_embeddings}"
        )
    with jax.named_scope("embeddings"):
        x = params["token_embed"][input_ids]
        # left-aligned masks make roberta's cumsum positions an arange
        # with a base offset (pad positions get wrong embeddings but their
        # hidden states are masked out of attention and pooling)
        if positions is None:
            pos_index = (jnp.arange(s) + position_offset + base)[None, :]
        else:
            pos_index = positions + base
        x = x + params["position_embed"][pos_index]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + params["type_embed"][token_type_ids]
        x = _layer_norm(x, params["embed_ln"], config.layer_norm_eps)

    if segment_ids is None:
        mask_bias = jnp.where(
            attention_mask[:, None, None, :] > 0, 0.0, -1e9
        ).astype(jnp.float32)
    else:
        # same-segment visibility, pad slots (seg 0) see and are seen by
        # nothing; fully-masked pad QUERY rows softmax to uniform (all
        # logits equal), never 0/0 — their hidden states are dropped by
        # pool_segments.  The einsum path consumes this [b, 1, s, s]
        # bias; the fused path rebuilds the mask in-kernel from the raw
        # segment row instead of paying the [b, s, s] HBM materialization
        seg = segment_ids.astype(jnp.int32)
        same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, None, :] > 0)
        mask_bias = jnp.where(same, 0.0, -1e9).astype(jnp.float32)[:, None]

    # scan over stacked layers: ONE compiled layer body for any depth
    def body(carry, layer_p):
        return _layer(carry, layer_p, mask_bias, config, segment_ids), None

    with jax.named_scope("encoder_layers"):
        x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def pool(
    hidden: jax.Array,
    attention_mask: jax.Array,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """hidden[b, s, h] -> embedding[b, h] (bge uses CLS + l2-normalize)."""
    if pooling == "cls":
        emb = hidden[:, 0, :]
    elif pooling == "mean":
        # f32 reductions regardless of activation dtype (module contract):
        # bf16 cannot even represent token counts > 256 exactly
        mask = attention_mask[:, :, None].astype(jnp.float32)
        emb = jnp.sum(hidden.astype(jnp.float32) * mask, axis=1) / jnp.maximum(
            jnp.sum(mask, axis=1), 1
        )
    else:
        raise ValueError(f"unknown pooling {pooling!r}")
    emb = emb.astype(jnp.float32)
    if normalize:
        norm = jnp.sqrt(jnp.sum(emb * emb, axis=-1, keepdims=True))
        emb = emb / jnp.maximum(norm, 1e-12)
    return emb


def pool_segments(
    hidden: jax.Array,
    segment_ids: jax.Array,
    seg_starts: jax.Array,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """hidden[b, s, h], segment_ids[b, s], seg_starts[b, k] -> emb[b, k, h].

    Per-segment pooling for the packed layout: slot j of row b pools the
    tokens with ``segment_ids == j + 1``.  ``seg_starts[b, j]`` is the row
    offset of that segment's first token ([CLS] — the padded path's
    ``hidden[:, 0]``).  Unused slots (no tokens at that segment id) pool
    to the zero vector under ``mean`` and to whatever token sits at
    offset 0 under ``cls`` — the host-side unpack only reads slots the
    planner filled, so both are fine."""
    if pooling == "cls":
        emb = jnp.take_along_axis(hidden, seg_starts[:, :, None], axis=1)
    elif pooling == "mean":
        # f32 reductions regardless of activation dtype (module contract)
        k = seg_starts.shape[1]
        one_hot = (
            segment_ids[:, :, None] == (jnp.arange(k) + 1)[None, None, :]
        ).astype(jnp.float32)  # [b, s, k]
        emb = jnp.einsum(
            "bsh,bsk->bkh",
            hidden.astype(jnp.float32),
            one_hot,
            preferred_element_type=jnp.float32,
        ) / jnp.maximum(jnp.sum(one_hot, axis=1), 1.0)[:, :, None]
    else:
        raise ValueError(f"unknown pooling {pooling!r}")
    emb = emb.astype(jnp.float32)
    if normalize:
        norm = jnp.sqrt(jnp.sum(emb * emb, axis=-1, keepdims=True))
        emb = emb / jnp.maximum(norm, 1e-12)
    return emb


@partial(jax.jit, static_argnames=("config", "pooling", "normalize"))
def embed(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """The jitted end-to-end embedding forward: ids -> pooled vectors."""
    hidden = encode(params, input_ids, attention_mask, config)
    return pool(hidden, attention_mask, pooling, normalize)


@partial(jax.jit, static_argnames=("config", "pooling", "normalize"))
def embed_packed(
    params: dict,
    input_ids: jax.Array,
    segment_ids: jax.Array,
    positions: jax.Array,
    seg_starts: jax.Array,
    config: BertConfig,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """The packed twin of ``embed``: many variable-length sequences per
    dense row -> one pooled vector per segment slot [b, k, h].

    ids/segment_ids/positions are [b, s] int32 (segment id 0 = pad slot,
    positions restart at 0 per segment); seg_starts[b, k] int32 indexes
    each slot's first token.  Specializes per (b, s, k) packed-capacity
    bucket — the small fixed set that replaces the (R, N, S) lattice."""
    attention_mask = (segment_ids > 0).astype(jnp.int32)
    hidden = encode(
        params,
        input_ids,
        attention_mask,
        config,
        segment_ids=segment_ids,
        positions=positions,
    )
    return pool_segments(hidden, segment_ids, seg_starts, pooling, normalize)


# ---------------------------------------------------------------------------
# HF checkpoint import (offline)
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attn_q": "attention.self.query",
    "attn_k": "attention.self.key",
    "attn_v": "attention.self.value",
    "attn_out": "attention.output.dense",
    "mlp_in": "intermediate.dense",
    "mlp_out": "output.dense",
}
_HF_LN_MAP = {
    "attn_ln": "attention.output.LayerNorm",
    "mlp_ln": "output.LayerNorm",
}


def from_hf_weights(state_dict: dict, config: BertConfig, dtype=jnp.float32) -> dict:
    """Map a HuggingFace BERT state dict (numpy arrays) into our pytree.

    Works with any BERT-architecture checkpoint (bge-*-en-v1.5 included)
    loaded from local files — no network access is assumed here.
    """

    def get(name):
        arr = state_dict[name]
        return jnp.asarray(arr, dtype=dtype)

    def dense(prefix):
        return {
            # torch Linear stores [out, in]; ours is [in, out]
            "kernel": get(f"{prefix}.weight").T,
            "bias": get(f"{prefix}.bias"),
        }

    def ln(prefix):
        return {"scale": get(f"{prefix}.weight"), "bias": get(f"{prefix}.bias")}

    layers = []
    for i in range(config.num_layers):
        base = f"encoder.layer.{i}"
        layer = {
            name: dense(f"{base}.{hf}") for name, hf in _HF_LAYER_MAP.items()
        }
        layer.update(
            {name: ln(f"{base}.{hf}") for name, hf in _HF_LN_MAP.items()}
        )
        layers.append(layer)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)

    return {
        "token_embed": get("embeddings.word_embeddings.weight"),
        "position_embed": get("embeddings.position_embeddings.weight"),
        "type_embed": get("embeddings.token_type_embeddings.weight"),
        "embed_ln": ln("embeddings.LayerNorm"),
        "layers": stacked,
    }
