"""SentencePiece unigram-LM tokenization (host-side, offline).

The XLM-R family (bge-m3) and DeBERTa-v3 ship ``sentencepiece.bpe.model`` /
``spm.model`` protos instead of a WordPiece ``vocab.txt``; without this
module those presets cannot tokenize real inputs.  Two pieces:

* ``parse_model_proto`` — a minimal protobuf wire-format reader for the
  SentencePiece ``ModelProto`` (field 1: repeated ``SentencePiece { piece,
  score, type }``).  No protobuf runtime dependency; the three fields this
  framework needs are decoded directly from the wire bytes.
* ``UnigramTokenizer`` — Viterbi (max-sum) segmentation over the piece
  scores, matching the semantics of HF ``tokenizers``' ``Unigram`` model
  (the engine behind ``XLMRobertaTokenizerFast``): metaspace
  pre-tokenization (whitespace split, every chunk prefixed with ``▁``),
  per-chunk Viterbi, unknown characters scored at ``min_score - 10`` and
  consecutive unknowns fused (tokenizers' ``fuse_unk``).  Parity is pinned
  by tests/test_spm.py against ``tokenizers.models.Unigram`` on shared
  vocabularies.

Id schemes (how spm piece ids become model input ids):

* ``xlmr``  — fairseq convention used by XLM-RoBERTa / bge-m3 checkpoints:
  ``<s>=0, <pad>=1, </s>=2, <unk>=3``, every spm piece id shifted by +1
  (transformers ``XLMRobertaTokenizer._convert_token_to_id`` semantics);
  sequences are ``<s> … </s>``.
* ``deberta`` — DeBERTa-v2/v3 convention: spm ids used directly (the
  checkpoint's proto reserves ``[PAD]=0, [CLS]=1, [SEP]=2, [UNK]=3`` as
  control pieces); sequences are ``[CLS] … [SEP]``.

Normalization is NFKC plus control-char removal — the documented
approximation of sentencepiece's ``nmt_nfkc`` without the precompiled
charsmap (the charsmap's extra rules cover rare codepoints; divergences are
confined to those).  Reference note: the reference delegates all inference
upstream (src/chat/completions/client.rs:308-332) and needs no tokenizer;
local encoders are this framework's point, so this closes the last
un-servable encoder families (VERDICT r2 item 2).
"""

from __future__ import annotations

import struct
import unicodedata
from typing import Dict, List, Optional, Tuple

from .tokenizer import BaseTokenizer

# SentencePiece.Type values (sentencepiece_model.proto)
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6

SPACE = "▁"  # ▁ metaspace marker
_UNK_PENALTY = 10.0  # sentencepiece kUnkPenalty; tokenizers uses the same


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _check_bounds(data: bytes, pos: int, size: int) -> None:
    if pos + size > len(data):
        raise ValueError(
            "truncated ModelProto: field of "
            f"{size} bytes at offset {pos} runs past end "
            f"({len(data)} bytes) — partial download?"
        )


def _skip_field(data: bytes, pos: int, wire_type: int) -> int:
    if wire_type == 0:
        _, pos = _read_varint(data, pos)
        return pos
    if wire_type == 1:
        _check_bounds(data, pos, 8)
        return pos + 8
    if wire_type == 2:
        size, pos = _read_varint(data, pos)
        _check_bounds(data, pos, size)
        return pos + size
    if wire_type == 5:
        _check_bounds(data, pos, 4)
        return pos + 4
    raise ValueError(f"unsupported wire type {wire_type}")


def _parse_piece(data: bytes) -> Tuple[str, float, int]:
    """One ``SentencePiece`` message: piece(1)=string, score(2)=float,
    type(3)=enum (default NORMAL)."""
    piece, score, ptype = "", 0.0, NORMAL
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            size, pos = _read_varint(data, pos)
            _check_bounds(data, pos, size)
            piece = data[pos : pos + size].decode("utf-8")
            pos += size
        elif field == 2 and wire == 5:
            _check_bounds(data, pos, 4)
            (score,) = struct.unpack("<f", data[pos : pos + 4])
            pos += 4
        elif field == 3 and wire == 0:
            ptype, pos = _read_varint(data, pos)
        else:
            pos = _skip_field(data, pos, wire)
    return piece, score, ptype


def parse_model_proto(data: bytes) -> List[Tuple[str, float, int]]:
    """``ModelProto`` bytes -> ordered [(piece, score, type)].

    Only field 1 (the pieces) is decoded; trainer/normalizer specs are
    skipped by wire type.  Piece order IS the spm id space.
    """
    pieces: List[Tuple[str, float, int]] = []
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            size, pos = _read_varint(data, pos)
            _check_bounds(data, pos, size)
            pieces.append(_parse_piece(data[pos : pos + size]))
            pos += size
        else:
            pos = _skip_field(data, pos, wire)
    if not pieces:
        raise ValueError("no pieces found: not a SentencePiece ModelProto?")
    return pieces


def normalize(text: str) -> str:
    """NFKC + control-character removal (approximation of nmt_nfkc; see
    module doc)."""
    text = unicodedata.normalize("NFKC", text)
    return "".join(
        " " if ch in "\t\n\r\v\f" else ch
        for ch in text
        if unicodedata.category(ch) != "Cc" or ch in "\t\n\r\v\f"
    )


class _Viterbi:
    """Max-sum segmentation over a piece->score table."""

    def __init__(self, scores: Dict[str, float], unk_score: float):
        self.scores = scores
        self.unk_score = unk_score
        self.max_len = max((len(p) for p in scores), default=1)

    def segment(self, chunk: str) -> List[Tuple[str, bool]]:
        """chunk -> [(token_text, known)]; unknown chars surface as their
        raw text with known=False, consecutive unknowns fused into one
        token (tokenizers ``fuse_unk=True`` semantics)."""
        n = len(chunk)
        NEG = float("-inf")
        best_score = [NEG] * (n + 1)
        best_prev = [0] * (n + 1)
        best_known = [False] * (n + 1)
        best_score[0] = 0.0
        for i in range(n):
            si = best_score[i]
            if si == NEG:
                continue
            # known pieces starting at i
            hi = min(n, i + self.max_len)
            for j in range(i + 1, hi + 1):
                score = self.scores.get(chunk[i:j])
                if score is not None and si + score > best_score[j]:
                    best_score[j] = si + score
                    best_prev[j] = i
                    best_known[j] = True
            # single unknown char fallback
            j = i + 1
            if si + self.unk_score > best_score[j]:
                best_score[j] = si + self.unk_score
                best_prev[j] = i
                best_known[j] = False
        spans: List[Tuple[int, int, bool]] = []
        j = n
        while j > 0:
            i = best_prev[j]
            spans.append((i, j, best_known[j]))
            j = i
        spans.reverse()
        out: List[Tuple[str, bool]] = []
        for i, j, known in spans:
            if not known and out and not out[-1][1]:
                prev_text, _ = out[-1]
                out[-1] = (prev_text + chunk[i:j], False)
            else:
                out.append((chunk[i:j], known))
        return out


class UnigramTokenizer(BaseTokenizer):
    """Unigram-LM tokenizer over a SentencePiece vocabulary.

    ``scheme`` picks the piece-id -> input-id mapping ("xlmr" or
    "deberta", module doc).  Construct from a proto via
    ``from_model_file`` / ``from_model_bytes``, or directly from
    [(piece, score, type)] rows (tests).
    """

    def __init__(
        self,
        pieces: List[Tuple[str, float, int]],
        scheme: str = "xlmr",
        use_native: bool = True,
    ):
        if scheme not in ("xlmr", "deberta"):
            raise ValueError(
                f"unknown spm scheme {scheme!r}: expected 'xlmr' or 'deberta'"
            )
        self.scheme = scheme
        self.pieces = pieces
        by_name = {piece: i for i, (piece, _, _) in enumerate(pieces)}
        scores = {
            piece: score
            for piece, score, ptype in pieces
            if ptype in (NORMAL, USER_DEFINED)
        }
        min_score = min(scores.values(), default=0.0)
        self._viterbi = _Viterbi(scores, min_score - _UNK_PENALTY)
        self._spm_id = by_name

        unk_spm = next(
            (i for i, (_, _, t) in enumerate(pieces) if t == UNKNOWN), 0
        )
        if scheme == "xlmr":
            # fairseq: <s>=0 <pad>=1 </s>=2 <unk>=3, pieces shifted +1,
            # <mask> appended last
            self._offset = 1
            self.cls_id = 0
            self.pad_id = 1
            self.sep_id = 2
            self.unk_id = 3
            self._unk_spm = unk_spm
            self.vocab_size = len(pieces) + self._offset + 1  # +<mask>
        else:
            # deberta: proto reserves [PAD]=0 [CLS]=1 [SEP]=2 [UNK]=3
            self._offset = 0
            self.pad_id = by_name.get("[PAD]", 0)
            self.cls_id = by_name.get("[CLS]", 1)
            self.sep_id = by_name.get("[SEP]", 2)
            self.unk_id = by_name.get("[UNK]", unk_spm)
            self._unk_spm = self.unk_id
            self.vocab_size = len(pieces)
        # C++ Viterbi fast path for pure-ASCII texts (native/unigram.cpp;
        # NFKC is the identity there) — parity-tested, Python fallback
        # owns all real Unicode
        self._native = _native_unigram(self) if use_native else None

    @classmethod
    def from_model_bytes(
        cls, data: bytes, scheme: str = "xlmr"
    ) -> "UnigramTokenizer":
        return cls(parse_model_proto(data), scheme)

    @classmethod
    def from_model_file(
        cls, path: str, scheme: str = "xlmr"
    ) -> "UnigramTokenizer":
        with open(path, "rb") as f:
            return cls.from_model_bytes(f.read(), scheme)

    # -- segmentation --------------------------------------------------------

    def tokenize_text(self, text: str) -> List[str]:
        """text -> token strings (no specials); unknown runs surface as
        their raw text (id = unk), matching ``tokenizers`` ``.tokens``."""
        return [
            token
            for word in normalize(text).split()
            for token, _ in self._viterbi.segment(SPACE + word)
        ]

    def _token_to_id(self, token: str, known: bool) -> int:
        if not known:
            return self.unk_id
        spm_id = self._spm_id.get(token)
        if spm_id is None or spm_id == self._unk_spm:
            return self.unk_id
        return spm_id + self._offset

    def _encode(self, text: str, max_length: int):
        if self._native is not None and text.isascii():
            out = self._native.encode(text, max_length)
            if out is not None:
                return out
        ids = [self.cls_id]
        done = False
        for word in normalize(text).split():
            if done:
                break
            for token, known in self._viterbi.segment(SPACE + word):
                ids.append(self._token_to_id(token, known))
                if len(ids) >= max_length - 1:
                    done = True
                    break
        ids = ids[: max_length - 1]
        ids.append(self.sep_id)
        return ids


def _native_unigram(tok: "UnigramTokenizer"):
    """A native bridge for this tokenizer, or None when the native
    library is unavailable.  Blob: header "cls sep unk offset unk_spm",
    then one "score\\tmatchable\\tpiece" line per piece in spm-id order
    (see native/unigram.cpp).

    Matchability mirrors the Python Viterbi exactly — NORMAL and
    USER_DEFINED pieces, INCLUDING the one at the unk index (its matches
    remap to unk on emit, which is what ``unk_spm`` in the header is
    for).  Pieces containing framing bytes (newline/tab) can never match
    an ASCII chunk anyway (whitespace splits before segmentation), so
    they are written unmatchable with an EMPTY text field — embedding
    their raw bytes would corrupt the line framing and silently shift
    every later piece id."""
    try:
        from ..utils.native import load_library
        from .tokenizer import NativeTokenizerBridge

        lib = load_library()
        if lib is None:
            return None
        lines = [
            f"{tok.cls_id} {tok.sep_id} {tok.unk_id} {tok._offset} "
            f"{tok._unk_spm}"
        ]
        for piece, score, ptype in tok.pieces:
            matchable = ptype in (NORMAL, USER_DEFINED) and not any(
                c in piece for c in "\n\r\t"
            )
            lines.append(
                f"{score!r}\t{1 if matchable else 0}\t"
                f"{piece if matchable else ''}"
            )
        blob = ("\n".join(lines) + "\n").encode("utf-8")
        return NativeTokenizerBridge(lib, "spm", blob)
    except Exception:
        return None


# filenames probed (in order) next to checkpoint weights
SPM_FILES = ("sentencepiece.bpe.model", "spm.model", "spiece.model")


def scheme_for_model(model_name: str) -> str:
    """Preset name -> id scheme ('deberta' for the RM family, else
    'xlmr')."""
    return "deberta" if "deberta" in model_name else "xlmr"
