"""Deferred-readiness dispatch seam (ISSUE 13 tentpole piece 1).

The old device-timing bracket (`jax.block_until_ready` inside
``TpuEmbedder._timed_dispatch``) held the dispatch thread for the full
device time of every timed call, so the executor thread that should
have been staging group k+1 sat parked on group k's readiness.  This
module is the replacement contract:

* the **dispatch thread** enters ``deferred_readiness(sink)``, calls
  the embedder, and returns as soon as every PJRT call is ENQUEUED —
  each timed dispatch appends a :class:`PendingDispatch` record
  (label, enqueue timestamp, output handle, waiter callable) to the
  sink instead of blocking;
* the batcher's **waiter thread** later runs :func:`drain_sink`, which
  blocks on each output, records the per-(mesh-shape, bucket) device
  time and the (enqueue, ready) interval — the ``overlap`` gauge in
  the ``phases`` metrics section is the union of those intervals over
  wall time — and recycles any host staging buffers the dispatch
  checked out of the :class:`StagingPool`.

Device faults therefore surface at the waiter (readiness is where XLA
reports them), and the batcher's meshfault triage handles waiter-hop
exceptions exactly like dispatch-hop ones.

Deliberately jax-free at import time: ``bench_host.py
--overlap-overhead`` measures the seam's pure-Python bookkeeping cost
against the host p50 budget without pulling jax into the process, and
test fakes implement a "device" by passing their own ``wait``
callable.  :func:`wait_device_ready` is the ONE sanctioned blocking
readiness call on the dispatch path (lint LWC013 allowlists it by
symbol); everything else must defer.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def wait_device_ready(out) -> None:
    """Default readiness waiter: block until ``out``'s device buffers
    are materialized.  Runs on the batcher's waiter thread — never on
    the dispatch thread (LWC013 enforces that split statically)."""
    import jax

    jax.block_until_ready(out)


class PendingDispatch:
    """One enqueued-but-not-ready device dispatch."""

    __slots__ = ("label", "t0", "out", "wait", "timed")

    def __init__(
        self,
        label: str,
        t0: float,
        out,
        wait: Callable = wait_device_ready,
        timed: bool = True,
    ) -> None:
        self.label = label
        self.t0 = t0
        self.out = out
        self.wait = wait
        # False when METRICS_DEVICE_TIMING=0: the waiter still blocks
        # (finalize would anyway) but records nothing
        self.timed = timed


class DispatchSink:
    """Per-group collector the dispatch thread fills under
    ``deferred_readiness``: the pending device dispatches plus the host
    staging buffers checked out for them (returned to the pool only
    after readiness — a ``device_put`` may still be reading the host
    buffer asynchronously before that)."""

    __slots__ = ("pending", "staged")

    def __init__(self) -> None:
        self.pending: List[PendingDispatch] = []
        self.staged: list = []

    def add(self, record: PendingDispatch) -> PendingDispatch:
        self.pending.append(record)
        return record


_TLS = threading.local()


def active_sink() -> Optional[DispatchSink]:
    """The calling thread's deferred-readiness sink, or None when
    dispatches should block inline (direct/bench callers)."""
    return getattr(_TLS, "sink", None)


class deferred_readiness:
    """Context manager scoping a :class:`DispatchSink` to the calling
    thread.  ``deferred_readiness(None)`` suspends an outer scope (the
    packed fallback path uses this to run a padded dispatch inline)."""

    __slots__ = ("sink", "_prev")

    def __init__(self, sink: Optional[DispatchSink]) -> None:
        self.sink = sink
        self._prev = None

    def __enter__(self) -> Optional[DispatchSink]:
        self._prev = getattr(_TLS, "sink", None)
        _TLS.sink = self.sink
        return self.sink

    def __exit__(self, *exc) -> bool:
        _TLS.sink = self._prev
        return False


def drain_sink(
    sink: DispatchSink,
    observe_device: Optional[Callable[[str, float], None]] = None,
    observe_interval: Optional[Callable[[float, float], None]] = None,
    release: Optional[Callable] = None,
    clock: Callable[[], float] = time.perf_counter,
) -> None:
    """The waiter hop: block on every pending dispatch in enqueue
    order, recording each timed one's device ms (same label contract as
    the old bracket) and its (enqueue, ready) interval for the overlap
    gauge.  Staging buffers recycle only on a clean drain — a raising
    ``wait`` (device fault) propagates to the caller's triage and the
    buffers are dropped for the GC instead."""
    for record in sink.pending:
        record.wait(record.out)
        t1 = clock()
        if record.timed:
            if observe_device is not None:
                observe_device(record.label, (t1 - record.t0) * 1e3)
            if observe_interval is not None:
                observe_interval(record.t0, t1)
    if release is not None:
        for buf in sink.staged:
            release(buf)
        sink.staged = []


class StagingPool:
    """Per-(shape, dtype) reusable host staging buffers for the padded
    dispatch paths (ISSUE 13 tentpole piece 3): every padded dispatch
    used to allocate fresh ``np.pad`` copies of ids/mask per call; the
    pool hands back the previous group's buffer once its transfer is
    confirmed ready.  Device-side aliasing stays where it is legal —
    the ``_stream_vote_update`` donation of same-shape f32 state —
    because int32 ids/mask alias no f32 output (a measured no-op, see
    models/embedder.py); host-side reuse is the generalization that IS
    safe, provided recycling waits for readiness (the waiter's
    ``release``)."""

    def __init__(self, per_bucket: int = 2) -> None:
        self.per_bucket = max(0, int(per_bucket))
        self._lock = threading.Lock()
        self._free: Dict[Tuple[tuple, str], list] = {}
        self.hits = 0
        self.misses = 0

    @property
    def enabled(self) -> bool:
        return self.per_bucket > 0

    def acquire(self, shape, dtype) -> np.ndarray:
        """A writable buffer of exactly ``shape``/``dtype`` — recycled
        (contents stale: the caller overwrites every row) or fresh."""
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                self.hits += 1
                return free.pop()
            self.misses += 1
        return np.empty(shape, dtype)

    def release(self, buf: np.ndarray) -> None:
        key = (buf.shape, buf.dtype.str)
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) < self.per_bucket:
                free.append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {
                "per_bucket": self.per_bucket,
                "hits": self.hits,
                "misses": self.misses,
                "buckets": len(self._free),
            }
