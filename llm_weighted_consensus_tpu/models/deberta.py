"""DeBERTa-style encoder + scalar reward head (reward-model re-ranking).

BASELINE config 3 replaces the cosine self-consistency vote with a trained
reward model (deberta-v3 class).  The architectural difference from BERT is
**disentangled attention**: no absolute position embeddings; instead every
layer adds content->position and position->content terms computed against a
shared relative-position embedding table:

    score(i, j) = q_c[i]·k_c[j] + q_c[i]·k_r[d(i,j)] + k_c[j]·q_r[d(i,j)]

scaled by 1/sqrt(3*head_dim), with d(i, j) the bucketed relative distance
(log-bucketed for v3 checkpoints, clamped otherwise — configs.py).  This
keeps shapes static (the relative index matrix is precomputed per seq
length) and every contraction on the MXU.

The reward head follows HF ``ContextPooler`` + 1-logit classifier: CLS
pooled state -> dense -> exact-erf GELU -> dense(1) -> scalar reward per
(prompt, candidate) sequence — numerics-pinned to
``transformers.DebertaV2Model``/``ForSequenceClassification`` in
tests/test_hf_parity.py, so real v3 RM checkpoints reproduce their
trained rewards.  ``reward_consensus_vote`` turns N candidate rewards
into a confidence distribution, slotting into the same tally as ballot
votes.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from .configs import DebertaConfig
from .layers import (
    dense as _dense,
    dense_cfg as _dense_cfg,
    dense_init as _dense_init,
    gelu_erf as _gelu_erf,
    layer_norm as _layer_norm,
    ln_init as _ln_init,
    mlp_cfg as _mlp_cfg,
)


def init_params(rng, config: DebertaConfig, dtype=jnp.float32) -> dict:
    keys = jax.random.split(rng, 6)
    h, i, k = config.hidden_size, config.intermediate_size, config.att_span

    def layer_params(layer_rng):
        ks = jax.random.split(layer_rng, 8)
        return {
            "attn_q": _dense_init(ks[0], h, h, dtype),
            "attn_k": _dense_init(ks[1], h, h, dtype),
            "attn_v": _dense_init(ks[2], h, h, dtype),
            "pos_q": _dense_init(ks[3], h, h, dtype),
            "pos_k": _dense_init(ks[4], h, h, dtype),
            "attn_out": _dense_init(ks[5], h, h, dtype),
            "attn_ln": _ln_init(h, dtype),
            "mlp_in": _dense_init(ks[6], h, i, dtype),
            "mlp_out": _dense_init(ks[7], i, h, dtype),
            "mlp_ln": _ln_init(h, dtype),
        }

    layers = jax.vmap(layer_params)(
        jax.random.split(keys[0], config.num_layers)
    )
    return {
        "token_embed": (
            jax.random.normal(keys[1], (config.vocab_size, h), jnp.float32)
            * 0.02
        ).astype(dtype),
        "embed_ln": _ln_init(h, dtype),
        # shared relative position table covering [-k, k)
        "rel_embed": (
            jax.random.normal(keys[2], (2 * k, h), jnp.float32) * 0.02
        ).astype(dtype),
        "rel_ln": _ln_init(h, dtype),
        "layers": layers,
        "head_dense": _dense_init(keys[3], h, h, dtype),
        "head_out": _dense_init(keys[4], h, 1, dtype),
    }


def _rel_index(seq: int, config: DebertaConfig) -> jax.Array:
    """[seq, seq] relative-position table indices in [0, 2*att_span).

    ``position_buckets > 0``: HF ``make_log_bucket_position`` — positions
    within ±buckets/2 index exactly, farther ones land in log-spaced
    buckets out to ``max_relative_positions`` (how every released v3
    checkpoint was trained).  Otherwise plain clamp."""
    span = config.att_span
    pos = jnp.arange(seq)
    rel = pos[:, None] - pos[None, :]
    if config.position_buckets > 0:
        mid = config.position_buckets // 2
        abs_pos = jnp.where(
            (rel < mid) & (rel > -mid), mid - 1, jnp.abs(rel)
        ).astype(jnp.float32)
        log_pos = (
            jnp.ceil(
                jnp.log(abs_pos / mid)
                / jnp.log((config.max_relative_positions - 1) / mid)
                * (mid - 1)
            )
            + mid
        )
        rel = jnp.where(
            jnp.abs(rel) <= mid,
            rel,
            (log_pos * jnp.sign(rel)).astype(rel.dtype),
        )
    return jnp.clip(rel, -span, span - 1) + span


def _disentangled_attention(x, rel, p, mask_bias, config: DebertaConfig):
    b, s, h = x.shape
    nh, hd = config.num_heads, config.head_dim
    k = config.att_span

    q_c = _dense_cfg(x, p["attn_q"], config).reshape(b, s, nh, hd)
    k_c = _dense_cfg(x, p["attn_k"], config).reshape(b, s, nh, hd)
    v = _dense_cfg(x, p["attn_v"], config).reshape(b, s, nh, hd)
    # relative projections of the shared table: [2k, nh, hd] — always
    # full precision: one tiny matmul per forward, position-sensitive
    q_r = _dense(rel, p["pos_q"]).reshape(2 * k, nh, hd)
    k_r = _dense(rel, p["pos_k"]).reshape(2 * k, nh, hd)

    rel_idx = _rel_index(s, config)  # [s, s]

    # The three disentangled score tensors store in the activation dtype
    # (f32 MXU accumulation unchanged) like every other matmul in the
    # model family — on the bf16 TPU path that halves the HBM traffic of
    # THREE [b, nh, s, s] intermediates + two bucket gathers (the same
    # r4 cut measured on bert.py's single logits tensor); the f32 parity
    # path is byte-identical.  Softmax stays f32 per the module contract.
    # content -> content
    c2c = jnp.einsum(
        "bqnd,bknd->bnqk", q_c, k_c, preferred_element_type=x.dtype
    )
    # content -> position: q_c against every bucket, then gather per (i, j)
    c2p_all = jnp.einsum(
        "bqnd,rnd->bnqr", q_c, k_r, preferred_element_type=x.dtype
    )  # [b, nh, s, 2k]
    c2p = jnp.take_along_axis(
        c2p_all, rel_idx[None, None, :, :], axis=-1
    )  # [b, nh, s, s]
    # position -> content: k_c against every bucket, transposed gather
    p2c_all = jnp.einsum(
        "bknd,rnd->bnkr", k_c, q_r, preferred_element_type=x.dtype
    )  # [b, nh, s, 2k]
    p2c = jnp.take_along_axis(
        p2c_all, rel_idx.T[None, None, :, :], axis=-1
    )  # [b, nh, k_pos=s, q_pos=s] -> transpose to [b, nh, q, k]
    p2c = jnp.swapaxes(p2c, -1, -2)

    # python-float scale + same-dtype bias keep the sum in x.dtype (an
    # f32 scalar would silently promote all three tensors back to f32)
    scale = 1.0 / float(3 * hd) ** 0.5
    logits = (c2c + c2p + p2c) * scale + mask_bias.astype(x.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum(
        "bnqk,bknd->bqnd", probs, v, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    return _dense_cfg(ctx.reshape(b, s, h), p["attn_out"], config)


def encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: DebertaConfig,
    segment_ids: jax.Array = None,
) -> jax.Array:
    """``segment_ids[b, s]`` (int32, 0 = pad slot) switches the mask to
    the packed same-segment form (the ragged continuous-batching layout,
    serve/packing.py).  Disentangled attention has NO absolute positions —
    only bucketed relative distances — and packed segments are contiguous
    row spans, so within-segment relative distances are exactly those of
    the padded forward; the segment mask removes every cross-segment
    (wrong-distance) term.  No position plumbing needed, unlike bert.py."""
    x = params["token_embed"][input_ids]
    x = _layer_norm(x, params["embed_ln"], config.layer_norm_eps)
    rel = _layer_norm(
        params["rel_embed"], params["rel_ln"], config.layer_norm_eps
    )
    if segment_ids is None:
        mask_bias = jnp.where(
            attention_mask[:, None, None, :] > 0, 0.0, -1e9
        ).astype(jnp.float32)
    else:
        seg = segment_ids.astype(jnp.int32)
        same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, None, :] > 0)
        mask_bias = jnp.where(same, 0.0, -1e9).astype(jnp.float32)[:, None]

    def body(carry, layer_p):
        attn = _disentangled_attention(carry, rel, layer_p, mask_bias, config)
        y = _layer_norm(carry + attn, layer_p["attn_ln"], config.layer_norm_eps)
        # exact-erf GELU (layers.gelu_erf: exact for f32, A&S for bf16):
        # HF deberta-v2's hidden_act is "gelu" = erf — jax.nn.gelu's
        # default tanh approximation silently diverged here (r4 fix; the
        # head below already used approximate=False).  On the int8 path
        # mlp_cfg folds the GELU into the mlp_in kernel epilogue.
        mlp = _mlp_cfg(y, layer_p["mlp_in"], layer_p["mlp_out"], config)
        return _layer_norm(y + mlp, layer_p["mlp_ln"], config.layer_norm_eps), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


@partial(jax.jit, static_argnames=("config",))
def reward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: DebertaConfig,
) -> jax.Array:
    """(prompt ++ candidate) token batch -> scalar reward per row [b].

    Head = HF ``ContextPooler`` semantics (exact-erf GELU over a dense of
    the CLS state — transformers' default ``pooler_hidden_act="gelu"``)
    followed by the 1-logit classifier, so
    ``DebertaV2ForSequenceClassification`` RM checkpoints reproduce their
    trained rewards (tests/test_hf_parity.py)."""
    hidden = encode(params, input_ids, attention_mask, config)
    cls = hidden[:, 0, :].astype(jnp.float32)
    z = _dense(cls, params["head_dense"]).astype(jnp.float32)
    z = jax.nn.gelu(z, approximate=False)
    return _dense(z, params["head_out"]).astype(jnp.float32)[:, 0]


@partial(jax.jit, static_argnames=("config",))
def reward_packed(
    params: dict,
    input_ids: jax.Array,
    segment_ids: jax.Array,
    seg_starts: jax.Array,
    config: DebertaConfig,
) -> jax.Array:
    """Packed twin of ``reward``: ids/segment_ids[b, s] + seg_starts[b, k]
    -> one scalar reward per segment slot [b, k].  Each slot's "CLS" is
    its segment's first token, gathered where the padded path reads
    ``hidden[:, 0]``; unused slots produce garbage rewards the host-side
    unpack never reads."""
    attention_mask = (segment_ids > 0).astype(jnp.int32)
    hidden = encode(
        params, input_ids, attention_mask, config, segment_ids=segment_ids
    )
    cls = jnp.take_along_axis(
        hidden, seg_starts[:, :, None], axis=1
    ).astype(jnp.float32)  # [b, k, h]
    z = _dense(cls, params["head_dense"]).astype(jnp.float32)
    z = jax.nn.gelu(z, approximate=False)
    return _dense(z, params["head_out"]).astype(jnp.float32)[:, :, 0]


@partial(jax.jit, static_argnames=("temperature",))
def reward_consensus_vote(
    rewards: jax.Array, temperature: float = 1.0
) -> jax.Array:
    """rewards[N] -> confidence[N]: RM re-ranking as a consensus vote
    (drop-in for ops.similarity.cosine_consensus_vote)."""
    return jax.nn.softmax(rewards.astype(jnp.float32) / temperature)


def from_hf_weights(
    state_dict: dict, config: DebertaConfig, dtype=jnp.float32
) -> dict:
    """Map a HuggingFace DeBERTa-v2/v3 state dict into our pytree.

    Accepts ``DebertaV2ForSequenceClassification`` reward models (e.g.
    the OpenAssistant deberta-v3 RM family, BASELINE config 3): the
    ``pooler.dense`` + ``classifier`` head maps onto ``head_dense`` /
    ``head_out``; encoder-only checkpoints load with a random-init head
    (fine-tune via train/).  v3 shares the content projections for the
    disentangled position attention (HF ``share_att_key=True``, so the
    checkpoint has no separate position projections) — our ``pos_q`` /
    ``pos_k`` load the content ``query_proj`` / ``key_proj`` weights,
    reproducing exactly that sharing.
    """

    def get(name):
        return jnp.asarray(state_dict[name], dtype=dtype)

    def dense(prefix):
        # torch Linear stores [out, in]; ours is [in, out]
        return {
            "kernel": get(f"{prefix}.weight").T,
            "bias": get(f"{prefix}.bias"),
        }

    def ln(prefix):
        return {"scale": get(f"{prefix}.weight"), "bias": get(f"{prefix}.bias")}

    def maybe_head(dense_prefix, fallback_shape_rng):
        if f"{dense_prefix}.weight" in state_dict:
            return dense(dense_prefix)
        rng, in_dim, out_dim = fallback_shape_rng
        from .layers import dense_init

        return dense_init(rng, in_dim, out_dim, dtype)

    layers = []
    for i in range(config.num_layers):
        base = f"encoder.layer.{i}"
        q = dense(f"{base}.attention.self.query_proj")
        k = dense(f"{base}.attention.self.key_proj")
        layers.append(
            {
                "attn_q": q,
                "attn_k": k,
                "attn_v": dense(f"{base}.attention.self.value_proj"),
                # share_att_key: position attention reuses content q/k
                "pos_q": q,
                "pos_k": k,
                "attn_out": dense(f"{base}.attention.output.dense"),
                "attn_ln": ln(f"{base}.attention.output.LayerNorm"),
                "mlp_in": dense(f"{base}.intermediate.dense"),
                "mlp_out": dense(f"{base}.output.dense"),
                "mlp_ln": ln(f"{base}.output.LayerNorm"),
            }
        )
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)

    h = config.hidden_size
    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    rel = get("encoder.rel_embeddings.weight")
    want_rows = 2 * config.att_span
    if rel.shape[0] != want_rows:
        raise ValueError(
            f"rel_embeddings has {rel.shape[0]} rows; config expects "
            f"{want_rows} (2 x att_span) — set position_buckets="
            f"{rel.shape[0] // 2} (v3 log-bucketed checkpoints) or "
            f"max_relative_positions={rel.shape[0] // 2} with "
            "position_buckets=0 (clamp scheme)"
        )
    return {
        "token_embed": get("embeddings.word_embeddings.weight"),
        "embed_ln": ln("embeddings.LayerNorm"),
        "rel_embed": rel,
        "rel_ln": ln("encoder.LayerNorm"),
        "layers": stacked,
        "head_dense": maybe_head("pooler.dense", (rngs[0], h, h)),
        "head_out": maybe_head("classifier", (rngs[1], h, 1)),
    }
