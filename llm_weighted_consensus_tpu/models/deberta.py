"""DeBERTa-style encoder + scalar reward head (reward-model re-ranking).

BASELINE config 3 replaces the cosine self-consistency vote with a trained
reward model (deberta-v3 class).  The architectural difference from BERT is
**disentangled attention**: no absolute position embeddings; instead every
layer adds content->position and position->content terms computed against a
shared relative-position embedding table:

    score(i, j) = q_c[i]·k_c[j] + q_c[i]·k_r[d(i,j)] + k_c[j]·q_r[d(i,j)]

scaled by 1/sqrt(3*head_dim), with d(i, j) the clamped relative distance.
This keeps shapes static (the relative index matrix is precomputed per seq
length) and every contraction on the MXU.

The reward head is the standard RM recipe: CLS pooled state -> dense ->
tanh -> dense(1) -> scalar reward per (prompt, candidate) sequence;
``reward_consensus_vote`` turns N candidate rewards into a confidence
distribution, slotting into the same tally as ballot votes.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from .configs import DebertaConfig
from .layers import dense as _dense, dense_init as _dense_init, layer_norm as _layer_norm, ln_init as _ln_init


def init_params(rng, config: DebertaConfig, dtype=jnp.float32) -> dict:
    keys = jax.random.split(rng, 6)
    h, i, k = config.hidden_size, config.intermediate_size, config.max_relative_positions

    def layer_params(layer_rng):
        ks = jax.random.split(layer_rng, 8)
        return {
            "attn_q": _dense_init(ks[0], h, h, dtype),
            "attn_k": _dense_init(ks[1], h, h, dtype),
            "attn_v": _dense_init(ks[2], h, h, dtype),
            "pos_q": _dense_init(ks[3], h, h, dtype),
            "pos_k": _dense_init(ks[4], h, h, dtype),
            "attn_out": _dense_init(ks[5], h, h, dtype),
            "attn_ln": _ln_init(h, dtype),
            "mlp_in": _dense_init(ks[6], h, i, dtype),
            "mlp_out": _dense_init(ks[7], i, h, dtype),
            "mlp_ln": _ln_init(h, dtype),
        }

    layers = jax.vmap(layer_params)(
        jax.random.split(keys[0], config.num_layers)
    )
    return {
        "token_embed": (
            jax.random.normal(keys[1], (config.vocab_size, h), jnp.float32)
            * 0.02
        ).astype(dtype),
        "embed_ln": _ln_init(h, dtype),
        # shared relative position table covering [-k, k)
        "rel_embed": (
            jax.random.normal(keys[2], (2 * k, h), jnp.float32) * 0.02
        ).astype(dtype),
        "rel_ln": _ln_init(h, dtype),
        "layers": layers,
        "head_dense": _dense_init(keys[3], h, h, dtype),
        "head_out": _dense_init(keys[4], h, 1, dtype),
    }


def _rel_index(seq: int, k: int) -> jax.Array:
    """[seq, seq] bucket indices: clamp(i - j, -k, k-1) + k."""
    pos = jnp.arange(seq)
    rel = pos[:, None] - pos[None, :]
    return jnp.clip(rel, -k, k - 1) + k


def _disentangled_attention(x, rel, p, mask_bias, config: DebertaConfig):
    b, s, h = x.shape
    nh, hd = config.num_heads, config.head_dim
    k = config.max_relative_positions

    q_c = _dense(x, p["attn_q"]).reshape(b, s, nh, hd)
    k_c = _dense(x, p["attn_k"]).reshape(b, s, nh, hd)
    v = _dense(x, p["attn_v"]).reshape(b, s, nh, hd)
    # relative projections of the shared table: [2k, nh, hd]
    q_r = _dense(rel, p["pos_q"]).reshape(2 * k, nh, hd)
    k_r = _dense(rel, p["pos_k"]).reshape(2 * k, nh, hd)

    rel_idx = _rel_index(s, k)  # [s, s]

    # content -> content
    c2c = jnp.einsum(
        "bqnd,bknd->bnqk", q_c, k_c, preferred_element_type=jnp.float32
    )
    # content -> position: q_c against every bucket, then gather per (i, j)
    c2p_all = jnp.einsum(
        "bqnd,rnd->bnqr", q_c, k_r, preferred_element_type=jnp.float32
    )  # [b, nh, s, 2k]
    c2p = jnp.take_along_axis(
        c2p_all, rel_idx[None, None, :, :], axis=-1
    )  # [b, nh, s, s]
    # position -> content: k_c against every bucket, transposed gather
    p2c_all = jnp.einsum(
        "bknd,rnd->bnkr", k_c, q_r, preferred_element_type=jnp.float32
    )  # [b, nh, s, 2k]
    p2c = jnp.take_along_axis(
        p2c_all, rel_idx.T[None, None, :, :], axis=-1
    )  # [b, nh, k_pos=s, q_pos=s] -> transpose to [b, nh, q, k]
    p2c = jnp.swapaxes(p2c, -1, -2)

    scale = 1.0 / jnp.sqrt(jnp.float32(3 * hd))
    logits = (c2c + c2p + p2c) * scale + mask_bias
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    ctx = jnp.einsum(
        "bnqk,bknd->bqnd", probs, v, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    return _dense(ctx.reshape(b, s, h), p["attn_out"])


def encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: DebertaConfig,
) -> jax.Array:
    x = params["token_embed"][input_ids]
    x = _layer_norm(x, params["embed_ln"], config.layer_norm_eps)
    rel = _layer_norm(
        params["rel_embed"], params["rel_ln"], config.layer_norm_eps
    )
    mask_bias = jnp.where(
        attention_mask[:, None, None, :] > 0, 0.0, -1e9
    ).astype(jnp.float32)

    def body(carry, layer_p):
        attn = _disentangled_attention(carry, rel, layer_p, mask_bias, config)
        y = _layer_norm(carry + attn, layer_p["attn_ln"], config.layer_norm_eps)
        mlp = _dense(jax.nn.gelu(_dense(y, layer_p["mlp_in"])), layer_p["mlp_out"])
        return _layer_norm(y + mlp, layer_p["mlp_ln"], config.layer_norm_eps), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


@partial(jax.jit, static_argnames=("config",))
def reward(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: DebertaConfig,
) -> jax.Array:
    """(prompt ++ candidate) token batch -> scalar reward per row [b]."""
    hidden = encode(params, input_ids, attention_mask, config)
    cls = hidden[:, 0, :].astype(jnp.float32)
    z = jnp.tanh(_dense(cls, params["head_dense"]).astype(jnp.float32))
    return _dense(z, params["head_out"]).astype(jnp.float32)[:, 0]


@partial(jax.jit, static_argnames=("temperature",))
def reward_consensus_vote(
    rewards: jax.Array, temperature: float = 1.0
) -> jax.Array:
    """rewards[N] -> confidence[N]: RM re-ranking as a consensus vote
    (drop-in for ops.similarity.cosine_consensus_vote)."""
    return jax.nn.softmax(rewards.astype(jnp.float32) / temperature)
