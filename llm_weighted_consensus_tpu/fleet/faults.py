"""Deterministic peer-to-peer fault injection: the fleet's failure seam.

``resilience/faults.py`` breaks the judge transport and
``DEVICE_FAULT_PLAN`` breaks the mesh; this module breaks the *fleet
wire* — the peer legs ``FleetClient`` drives (entry fetch, lease claim,
publish, handoff, liveness probe).  Six kinds, the failure modes a
replica actually sees from a sick or partitioned peer:

* ``blackhole`` — the peer is unreachable and packets vanish: the leg
  burns its full clamped budget, then times out.  The building block of
  partition schedules (a cut is a set of blackholed pairs).
* ``slow``      — the leg stalls ``slow_ms`` before the request leaves
  (a congested or GC-pausing peer).
* ``connect``   — connection refused immediately (the peer's port is
  closed: crash, restart, drain).
* ``5xx``       — the peer answers 503 (overloaded or draining).
* ``corrupt``   — the peer's *payload* arrives mangled: the chunk
  record is garbled so the wire guard (fleet/wire.py) must refuse it.
* ``flap``      — the pair's health TOGGLES: a seeded coin flips the
  pair between healthy and blackholed-at-connect, producing the
  up/down/up pattern that drives peer quarantine.

Determinism does not depend on request interleaving: every decision for
the ordered pair ``(src, dst)`` is drawn from
``random.Random(xxh3(seed, src, dst, ordinal))`` where ``ordinal`` is
that pair's own call counter — the same pair sees the same fault
sequence no matter how the event loop schedules other pairs.  Scripted
control is per-pair too: ``set_pair``/``partition`` install explicit
rules (the split-brain drill's schedule), and ``script=`` in the env
spec replays a fault list per pair by ordinal.

Selectable in production-shaped runs via ``FLEET_FAULT_PLAN``, e.g.
``seed=7,blackhole=0.05,slow=0.1,slow_ms=150`` or
``blackhole=1.0,to=http://10.0.0.2:5000`` (faults only on legs toward
the listed peers — how a bench carves a partition out of env config).

Unset ⇒ ``FleetClient`` never consults this module: the seam is one
``is None`` check, byte-identical to the pre-fault-plan fleet.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

import xxhash

# fault kinds, in the fixed order the sampler walks (order is part of
# the determinism contract — do not reorder)
BLACKHOLE = "blackhole"
SLOW = "slow"
CONNECT = "connect"
BAD_STATUS = "5xx"
CORRUPT = "corrupt"
FLAP = "flap"

KINDS = (BLACKHOLE, SLOW, CONNECT, BAD_STATUS, CORRUPT, FLAP)


def _pair_rng(seed: int, src: str, dst: str, ordinal: int) -> random.Random:
    label = f"{seed}:{src}>{dst}:{ordinal}"
    return random.Random(xxhash.xxh3_64_intdigest(label.encode("utf-8")))


class FleetFaultPlan:
    """Per-peer-pair fault schedule: seeded sampling, a per-pair script,
    or explicit rules (``set_pair``/``partition``)."""

    def __init__(
        self,
        seed: int = 0,
        probabilities: Optional[Dict[str, float]] = None,
        slow_ms: float = 100.0,
        script: Optional[List[Optional[str]]] = None,
        to: Optional[List[str]] = None,
    ) -> None:
        self.seed = int(seed)
        self.probabilities = {
            kind: float((probabilities or {}).get(kind, 0.0)) for kind in KINDS
        }
        self.slow_ms = float(slow_ms)
        self._script = list(script) if script is not None else None
        self._to = {u.rstrip("/") for u in to} if to else None
        # explicit rules installed by drills: (src, dst) -> [kind, count]
        # (count None = until cleared)
        self._rules: Dict[Tuple[str, str], list] = {}
        self._ordinals: Dict[Tuple[str, str], int] = {}
        self._flapped: set = set()
        self.requests = 0
        self.injected: Dict[str, int] = {kind: 0 for kind in KINDS}

    # -- explicit schedules (the drill API) -----------------------------------

    def set_pair(
        self, src: str, dst: str, kind: str, count: Optional[int] = None
    ) -> None:
        """Install an explicit fault rule for the ordered pair: every
        leg ``src -> dst`` gets ``kind`` (for ``count`` legs, or until
        cleared)."""
        if kind not in KINDS:
            raise ValueError(f"unknown fleet fault {kind!r}")
        self._rules[(src.rstrip("/"), dst.rstrip("/"))] = [kind, count]

    def clear_pair(self, src: str, dst: str) -> None:
        self._rules.pop((src.rstrip("/"), dst.rstrip("/")), None)

    def partition(self, groups: List[List[str]], kind: str = BLACKHOLE) -> None:
        """Install a partition: every ordered pair whose endpoints lie
        in different groups gets ``kind``.  ``heal()`` removes it."""
        for i, a_group in enumerate(groups):
            for j, b_group in enumerate(groups):
                if i == j:
                    continue
                for a in a_group:
                    for b in b_group:
                        self.set_pair(a, b, kind)

    def heal(self) -> None:
        """Remove every explicit rule and reset flap state (seeded
        probabilities keep sampling; a pure-scripted plan goes fully
        healthy)."""
        self._rules.clear()
        self._flapped.clear()

    # -- the sampling seam ----------------------------------------------------

    def next_fault(self, src: str, dst: str) -> Optional[str]:
        """The fault for the next ``src -> dst`` leg (None = healthy)."""
        self.requests += 1
        src = src.rstrip("/")
        dst = dst.rstrip("/")
        pair = (src, dst)
        ordinal = self._ordinals.get(pair, 0)
        self._ordinals[pair] = ordinal + 1
        rule = self._rules.get(pair)
        if rule is not None:
            kind, count = rule
            if count is not None:
                if count <= 1:
                    del self._rules[pair]
                else:
                    rule[1] = count - 1
            self.injected[kind] += 1
            return kind
        if self._to is not None and dst not in self._to:
            return None
        if self._script is not None:
            if ordinal >= len(self._script):
                return None
            fault = self._script[ordinal]
            if fault is not None:
                self.injected[fault] += 1
            return fault
        rng = _pair_rng(self.seed, src, dst, ordinal)
        # flap is a TOGGLE draw, independent of the per-leg kind draw: a
        # flapped pair stays down (connect-refused) until the next toggle
        if rng.random() < self.probabilities[FLAP]:
            if pair in self._flapped:
                self._flapped.discard(pair)
            else:
                self._flapped.add(pair)
        if pair in self._flapped:
            self.injected[FLAP] += 1
            return FLAP
        draw = rng.random()
        edge = 0.0
        for kind in KINDS:
            if kind == FLAP:
                continue
            edge += self.probabilities[kind]
            if draw < edge:
                self.injected[kind] += 1
                return kind
        return None

    @classmethod
    def parse(cls, spec: str) -> "FleetFaultPlan":
        """Parse a ``FLEET_FAULT_PLAN`` env spec.

        Comma-separated ``key=value``: ``seed``, ``slow_ms``, one key
        per fault kind with its probability, ``script=a|b|ok`` (per-pair
        replay, ``ok``/empty = healthy leg), or ``to=url|url`` limiting
        sampled faults to legs toward the listed peers.
        """
        from ..resilience.faults import iter_plan_spec

        seed = 0
        slow_ms = 100.0
        probs: Dict[str, float] = {}
        script: Optional[List[Optional[str]]] = None
        to: Optional[List[str]] = None
        for key, value in iter_plan_spec(spec, "FLEET_FAULT_PLAN"):
            if key == "seed":
                seed = int(value)
            elif key == "slow_ms":
                slow_ms = float(value)
            elif key == "script":
                script = [
                    None if slot in ("", "ok") else slot
                    for slot in value.split("|")
                ]
                for slot in script:
                    if slot is not None and slot not in KINDS:
                        raise ValueError(
                            f"FLEET_FAULT_PLAN: unknown fault {slot!r}"
                        )
            elif key == "to":
                to = [u for u in value.split("|") if u]
            elif key in KINDS:
                probs[key] = float(value)
            else:
                raise ValueError(f"FLEET_FAULT_PLAN: unknown key {key!r}")
        return cls(
            seed=seed,
            probabilities=probs,
            slow_ms=slow_ms,
            script=script,
            to=to,
        )

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "injected": {k: v for k, v in self.injected.items() if v},
            "flapped_pairs": sorted(f"{a}>{b}" for a, b in self._flapped),
            "rules": len(self._rules),
        }


def corrupt_payload(payload):
    """Mangle a peer response the way a buggy or truncating peer would:
    the chunk record loses its tail frame and grows a frame the typed
    decode must refuse, so ``clean_chunk_objs`` rejects the record and
    the receiver degrades instead of serving garbage.  Non-record
    payloads pass through (corruption targets the data plane)."""
    if (
        isinstance(payload, dict)
        and isinstance(payload.get("chunks"), list)
        and payload["chunks"]
    ):
        payload = dict(payload)
        payload["chunks"] = payload["chunks"][:-1] + [
            {"corrupt": "fleet-fault-injected"}
        ]
    return payload
