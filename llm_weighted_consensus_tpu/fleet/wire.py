"""Wire-side record validation: the replay admission guard, cross-replica.

``cache/replay.py::record_stream`` guarantees a replica's OWN cache only
ever holds clean, complete, error-free, non-degraded streams.  A record
arriving over the fleet wire (publish after a granted lease, drain-time
handoff) carries no such guarantee — the sender could be buggy, stale,
or malicious — so the receiving side re-derives it: decode every frame
through the same typed schema the replay path uses and reject anything
``record_stream`` would have refused to cache.  A peer can therefore
never be served a degraded or errored consensus, exactly as PR 2's
guard promises for local entries.
"""

from __future__ import annotations

from typing import List, Optional

import xxhash

from ..cache.replay import chunks_from_record


def clean_chunk_objs(chunk_objs) -> Optional[List[dict]]:
    """Validate a wire-received chunk record; the (plain-JSON) list on
    success, None on anything record_stream would not have cached."""
    if not isinstance(chunk_objs, list) or not chunk_objs:
        return None
    if not all(isinstance(obj, dict) for obj in chunk_objs):
        return None
    chunks = chunks_from_record(chunk_objs)
    if chunks is None:
        return None
    for chunk in chunks:
        if getattr(chunk, "degraded", None):
            return None
        if any(c.error is not None for c in chunk.choices):
            return None
    return chunk_objs


def record_digest(chunk_objs: List[dict]) -> str:
    """A stable xxh3 fingerprint of a chunk record's canonical JSON —
    what the partition drill compares to assert two replicas converged
    on byte-identical content (and that a replay from the same seed
    reproduced it)."""
    from ..utils import jsonutil

    return xxhash.xxh3_64_hexdigest(
        jsonutil.dumps(chunk_objs).encode("utf-8")
    )
