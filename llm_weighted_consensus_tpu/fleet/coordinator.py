"""The fleet coordinator: what the rest of the service talks to.

The score client's front door calls ``begin(fp)`` on a local cache miss
(only the in-process single-flight LEADER ever gets here — in-process
collapse still happens first, so one replica contributes at most one
fleet participant per fingerprint).  The answer is one of:

* ``("hit", chunks)``  — the owner had the entry; replay it locally.
* ``("lease", None)``  — this replica holds the fleet-wide lease: go
  upstream exactly as before the fleet existed, then ``publish`` (or
  ``abandon`` on failure).
* ``("local", None)``  — the fleet cannot help (no roster, owner dead,
  breaker open, deadline nearly spent, lease wait timed out, rosters
  diverged): behave exactly as today.  Every failure path funnels here —
  a broken fleet degrades to N independent replicas, never worse.

Routing is PINNED per request: ``begin`` resolves one ``OwnershipView``
and the matching ``publish``/``abandon`` route through that same view,
so a roster reload between begin and publish cannot send the publish to
a different "owner" than the one holding the lease (which would both
drop the record and strand the real owner's waiters until TTL).

An owner-side waiter does not ride out ``FLEET_LEASE_MILLIS`` when the
remote holder is dead: the wait runs in probe-interval slices, and a
holder whose breaker is open — or that fails a liveness probe — loses
the lease to the waiter (early takeover).  The dead holder's publish,
if it ever arrives, is a LATE publish: the record is still cached, the
current claimant's lease is untouched, and a counter says it happened.

The drain path calls ``handoff(cache)``: the departing replica's
hottest live entries are pushed to the peers that will own them once it
leaves the ring, before ``/readyz`` flips (serve/lifecycle.py).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Tuple

from ..resilience.breaker import OPEN
from .client import FleetClient
from .health import PeerHealth
from .leases import LeaseTable
from .membership import FleetConfig, FleetMembership, OwnershipView

# drain-time handoff: at most this many MRU entries leave with us
HANDOFF_MAX_ENTRIES = 256

# drain-time handoff: per-target pushes in flight at once (one dead
# target must not serialize the rest of the drain budget behind it)
HANDOFF_CONCURRENCY = 4


class FleetCoordinator:
    def __init__(
        self,
        config: FleetConfig,
        *,
        membership: Optional[FleetMembership] = None,
        client: Optional[FleetClient] = None,
        leases: Optional[LeaseTable] = None,
        clock=time.monotonic,
    ) -> None:
        self.config = config
        self.membership = membership or FleetMembership(config, clock=clock)
        if client is None:
            fault_plan = None
            if config.fault_plan_spec:
                from .faults import FleetFaultPlan

                fault_plan = FleetFaultPlan.parse(config.fault_plan_spec)
            client = FleetClient(
                self.membership.self_url,
                fetch_timeout_ms=config.fetch_timeout_millis,
                fault_plan=fault_plan,
            )
        self.client = client
        self.leases = leases or LeaseTable(config.lease_millis, clock=clock)
        self.health = PeerHealth(
            config.quarantine_failures,
            config.probe_millis,
            clock=clock,
        )
        if self.health.enabled:
            self.client.health = self.health
        self.clock = clock
        # attached by build_service: the owner-side score cache the
        # /fleet/v1 handlers serve from and publish into
        self.cache = None
        self.peer_hits = 0
        self.peer_misses = 0
        self.peer_errors = 0
        self.local_fallbacks = 0
        self.publishes = 0
        self.abandons = 0
        self.rejected_publishes = 0
        self.ring_divergences = 0
        self.ring_rejects = 0
        self.early_takeovers = 0
        self.handoff_sent = 0
        self.handoff_accepted = 0
        self.handoff_received = 0
        self.handoff_rejected = 0
        # fp -> the OwnershipView its begin() routed on; publish/abandon
        # must route on the SAME ring (the pinning contract)
        self._pinned: Dict[str, OwnershipView] = {}
        # publish/release tasks in flight (kept so GC can't cancel them)
        self._tasks: set = set()

    # -- the front door -------------------------------------------------------

    async def begin(self, fp: str) -> Tuple[str, Optional[list]]:
        try:
            return await self._begin(fp)
        except Exception:
            # the fleet must never break a request
            self.local_fallbacks += 1
            return "local", None

    async def _begin(self, fp: str) -> Tuple[str, Optional[list]]:
        self._apply_quarantine()
        view = self.membership.view()
        owner = view.owner(fp)
        if owner is None:
            self.local_fallbacks += 1
            return "local", None
        if owner == view.self_url:
            return await self._begin_as_owner(fp, view)
        return await self._begin_as_peer(fp, owner, view)

    async def _begin_as_owner(
        self, fp: str, view: OwnershipView
    ) -> Tuple[str, Optional[list]]:
        """We own ``fp``: claim the lease locally; if a remote replica
        holds it, wait for its publish (bounded by the lease TTL and the
        deadline share) — in probe-interval slices, stealing the lease
        early when the holder is provably gone."""
        self_url = view.self_url
        granted, future = self.leases.acquire(fp, self_url)
        if granted:
            self._pinned[fp] = view
            return "lease", None
        deadline = self.clock() + min(
            self.leases.remaining_sec(fp) or self.leases.ttl_sec,
            self._wait_budget_sec(),
        )
        probe_sec = max(0.001, self.config.probe_millis / 1000.0)
        while not future.done():
            remaining = deadline - self.clock()
            if remaining <= 0:
                break
            result = await self.leases.wait(
                future, min(remaining, probe_sec)
            )
            if result is not None or future.done():
                break
            # wait slice timed out with the lease still in flight: is
            # the holder even alive?  Its breaker open (recent transport
            # failures) is verdict enough; otherwise one liveness probe
            # decides.  A dead holder loses the lease NOW instead of at
            # TTL expiry.
            holder = self.leases.holder(fp)
            if holder is None or holder == self_url:
                break
            dead = self.client.breakers.peek(holder, "fleet") == OPEN
            if not dead:
                dead = not await self.client.probe(holder)
            if dead and self.leases.steal(fp, self_url):
                self.early_takeovers += 1
                self._pinned[fp] = view
                return "lease", None
        chunks = self.cache.get(fp) if self.cache is not None else None
        if chunks is not None:
            self.peer_hits += 1
            return "hit", chunks
        # holder abandoned, expired, or we ran out of patience: take the
        # lease ourselves if free, else compute locally without one
        granted, _ = self.leases.acquire(fp, self_url)
        if granted:
            self._pinned[fp] = view
            return "lease", None
        self.local_fallbacks += 1
        return "local", None

    async def _begin_as_peer(
        self, fp: str, owner: str, view: OwnershipView
    ) -> Tuple[str, Optional[list]]:
        status, chunks = await self.client.fetch_entry(
            owner, fp, ring=view.digest
        )
        if status == "hit":
            self.peer_hits += 1
            return "hit", chunks
        if status == "divergent":
            return self._diverged(owner)
        if status == "error":
            self.peer_errors += 1
            self.local_fallbacks += 1
            return "local", None
        self.peer_misses += 1
        lease = await self.client.request_lease(owner, fp, ring=view.digest)
        if lease == "granted":
            self._pinned[fp] = view
            return "lease", None
        if lease == "divergent":
            return self._diverged(owner)
        if lease == "wait":
            status, chunks = await self.client.fetch_entry(
                owner, fp, wait_ms=self.config.lease_millis,
                ring=view.digest,
            )
            if status == "hit":
                self.peer_hits += 1
                return "hit", chunks
            if status == "divergent":
                return self._diverged(owner)
        self.local_fallbacks += 1
        return "local", None

    def _diverged(self, owner: str) -> Tuple[str, Optional[list]]:
        """The peer rejected our ring digest: it routes on a different
        roster (split-brain from staggered peers-file reads).  Degrade
        to local — a duplicate local compute is bounded and correct,
        while trusting a divergent owner's lease table is neither."""
        self.ring_divergences += 1
        self.local_fallbacks += 1
        return "local", None

    def _wait_budget_sec(self) -> float:
        """How long an owner-side waiter may block on a remote holder:
        the lease TTL, clamped to the deadline share the client applies
        to peer legs."""
        from ..resilience.deadline import current_deadline

        from .client import DEADLINE_SHARE

        budget = self.leases.ttl_sec
        deadline = current_deadline()
        if deadline is not None:
            budget = min(budget, deadline.remaining() * DEADLINE_SHARE)
        return max(0.001, budget)

    # -- quarantine -----------------------------------------------------------

    def _apply_quarantine(self) -> None:
        """Fold the health table's verdict into the routing ring and
        kick liveness probes for quarantined peers whose interval is
        up.  Synchronous except for the spawned probes — ``begin`` pays
        a set compare in the steady state."""
        if not self.health.enabled:
            return
        self.membership.set_quarantined(self.health.quarantined())
        for peer in self.health.probes_due():
            self._spawn(self._probe(peer))

    async def _probe(self, peer: str) -> None:
        ok = await self.client.probe(peer)
        self.health.record_probe(peer, ok)
        if ok:
            # the probe is direct evidence of recovery: re-admit the
            # peer to the ring AND close its breaker, or the re-homed
            # keys keep shedding until the cooldown expires anyway
            self.client.breakers.get(peer, "fleet").force_close()
            self.membership.set_quarantined(self.health.quarantined())

    # -- completion -----------------------------------------------------------

    def publish(self, fp: str, chunk_objs: list) -> None:
        """The lease holder's clean result landed in its local cache:
        retire the lease (owner) or push the record to the owner (peer).
        Fire-and-forget — the response stream must not wait on it.
        Routes on the view pinned at ``begin``, never the live ring."""
        self.publishes += 1
        view = self._pinned.pop(fp, None)
        if view is None:
            view = self.membership.view()
        owner = view.owner(fp)
        if owner is None:
            return
        if owner == view.self_url:
            self.leases.publish(fp, view.self_url)
            return
        self._spawn(self.client.publish_entry(owner, fp, chunk_objs))

    def abandon(self, fp: str) -> None:
        """The lease holder failed without a result: release so waiters
        fall back to local compute instead of riding out the TTL."""
        self.abandons += 1
        view = self._pinned.pop(fp, None)
        if view is None:
            view = self.membership.view()
        owner = view.owner(fp)
        if owner is None:
            return
        if owner == view.self_url:
            self.leases.release(fp, view.self_url)
            return
        self._spawn(self.client.release_lease(owner, fp))

    def _spawn(self, coro) -> None:
        try:
            task = asyncio.get_running_loop().create_task(coro)
        except RuntimeError:
            # no running loop (teardown, sync test context): close the
            # coroutine instead of leaking a never-awaited warning
            coro.close()
            return
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    # -- drain ----------------------------------------------------------------

    async def handoff(self, cache) -> int:
        """Push this replica's hottest live entries to their post-drain
        owners.  Returns the number of entries peers accepted; any
        failure is skipped (the fleet re-computes what it must)."""
        if cache is None or not getattr(cache, "enabled", False):
            return 0
        # one reduced post-departure ring for the whole hot set, not an
        # O(peers x vnodes) scan per entry
        view = self.membership.departure_view()
        by_target: dict = {}
        for fp, chunk_objs, ttl_sec in cache.hot_entries(
            HANDOFF_MAX_ENTRIES
        ):
            target = view.owner(fp)
            if target is None or target == self.membership.self_url:
                continue
            by_target.setdefault(target, []).append(
                {"fp": fp, "chunks": chunk_objs, "ttl_sec": round(ttl_sec, 3)}
            )
        if not by_target:
            return 0
        semaphore = asyncio.Semaphore(HANDOFF_CONCURRENCY)

        async def push(target: str, entries: list) -> int:
            async with semaphore:
                self.handoff_sent += len(entries)
                try:
                    return await self.client.handoff(target, entries)
                except Exception:
                    return 0

        results = await asyncio.gather(
            *(push(t, e) for t, e in by_target.items())
        )
        accepted = sum(results)
        self.handoff_accepted += accepted
        return accepted

    async def close(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        await self.client.close()

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "membership": self.membership.snapshot(),
            "peer_fetch": {
                "hits": self.peer_hits,
                "misses": self.peer_misses,
                "errors": self.peer_errors,
            },
            "leases": self.leases.stats(),
            "local_fallbacks": self.local_fallbacks,
            "publishes": self.publishes,
            "abandons": self.abandons,
            "rejected_publishes": self.rejected_publishes,
            "ring_divergences": self.ring_divergences,
            "ring_rejects": self.ring_rejects,
            "early_takeovers": self.early_takeovers,
            "health": self.health.stats(),
            "handoff": {
                "sent": self.handoff_sent,
                "accepted": self.handoff_accepted,
                "received": self.handoff_received,
                "rejected": self.handoff_rejected,
            },
            "client": self.client.stats(),
        }
