"""The fleet coordinator: what the rest of the service talks to.

The score client's front door calls ``begin(fp)`` on a local cache miss
(only the in-process single-flight LEADER ever gets here — in-process
collapse still happens first, so one replica contributes at most one
fleet participant per fingerprint).  The answer is one of:

* ``("hit", chunks)``  — the owner had the entry; replay it locally.
* ``("lease", None)``  — this replica holds the fleet-wide lease: go
  upstream exactly as before the fleet existed, then ``publish`` (or
  ``abandon`` on failure).
* ``("local", None)``  — the fleet cannot help (no roster, owner dead,
  breaker open, deadline nearly spent, lease wait timed out): behave
  exactly as today.  Every failure path funnels here — a broken fleet
  degrades to N independent replicas, never worse.

The drain path calls ``handoff(cache)``: the departing replica's
hottest live entries are pushed to the peers that will own them once it
leaves the ring, before ``/readyz`` flips (serve/lifecycle.py).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Tuple

from .client import FleetClient
from .leases import LeaseTable
from .membership import FleetConfig, FleetMembership

# drain-time handoff: at most this many MRU entries leave with us
HANDOFF_MAX_ENTRIES = 256


class FleetCoordinator:
    def __init__(
        self,
        config: FleetConfig,
        *,
        membership: Optional[FleetMembership] = None,
        client: Optional[FleetClient] = None,
        leases: Optional[LeaseTable] = None,
        clock=time.monotonic,
    ) -> None:
        self.config = config
        self.membership = membership or FleetMembership(config, clock=clock)
        self.client = client or FleetClient(
            self.membership.self_url,
            fetch_timeout_ms=config.fetch_timeout_millis,
        )
        self.leases = leases or LeaseTable(config.lease_millis, clock=clock)
        self.clock = clock
        # attached by build_service: the owner-side score cache the
        # /fleet/v1 handlers serve from and publish into
        self.cache = None
        self.peer_hits = 0
        self.peer_misses = 0
        self.peer_errors = 0
        self.local_fallbacks = 0
        self.publishes = 0
        self.abandons = 0
        self.rejected_publishes = 0
        self.handoff_sent = 0
        self.handoff_accepted = 0
        self.handoff_received = 0
        self.handoff_rejected = 0
        # publish/release tasks in flight (kept so GC can't cancel them)
        self._tasks: set = set()

    # -- the front door -------------------------------------------------------

    async def begin(self, fp: str) -> Tuple[str, Optional[list]]:
        try:
            return await self._begin(fp)
        except Exception:
            # the fleet must never break a request
            self.local_fallbacks += 1
            return "local", None

    async def _begin(self, fp: str) -> Tuple[str, Optional[list]]:
        owner = self.membership.owner(fp)
        if owner is None:
            self.local_fallbacks += 1
            return "local", None
        if owner == self.membership.self_url:
            return await self._begin_as_owner(fp)
        return await self._begin_as_peer(fp, owner)

    async def _begin_as_owner(self, fp: str) -> Tuple[str, Optional[list]]:
        """We own ``fp``: claim the lease locally; if a remote replica
        holds it, wait for its publish (bounded by the lease TTL and the
        deadline share) and re-check the cache."""
        granted, future = self.leases.acquire(fp, self.membership.self_url)
        if granted:
            return "lease", None
        timeout = min(
            self.leases.remaining_sec(fp) or self.leases.ttl_sec,
            self._wait_budget_sec(),
        )
        await self.leases.wait(future, timeout)
        chunks = self.cache.get(fp) if self.cache is not None else None
        if chunks is not None:
            self.peer_hits += 1
            return "hit", chunks
        # holder abandoned, expired, or we ran out of patience: take the
        # lease ourselves if free, else compute locally without one
        granted, _ = self.leases.acquire(fp, self.membership.self_url)
        if granted:
            return "lease", None
        self.local_fallbacks += 1
        return "local", None

    async def _begin_as_peer(
        self, fp: str, owner: str
    ) -> Tuple[str, Optional[list]]:
        status, chunks = await self.client.fetch_entry(owner, fp)
        if status == "hit":
            self.peer_hits += 1
            return "hit", chunks
        if status == "error":
            self.peer_errors += 1
            self.local_fallbacks += 1
            return "local", None
        self.peer_misses += 1
        lease = await self.client.request_lease(owner, fp)
        if lease == "granted":
            return "lease", None
        if lease == "wait":
            status, chunks = await self.client.fetch_entry(
                owner, fp, wait_ms=self.config.lease_millis
            )
            if status == "hit":
                self.peer_hits += 1
                return "hit", chunks
        self.local_fallbacks += 1
        return "local", None

    def _wait_budget_sec(self) -> float:
        """How long an owner-side waiter may block on a remote holder:
        the lease TTL, clamped to the deadline share the client applies
        to peer legs."""
        from ..resilience.deadline import current_deadline

        from .client import DEADLINE_SHARE

        budget = self.leases.ttl_sec
        deadline = current_deadline()
        if deadline is not None:
            budget = min(budget, deadline.remaining() * DEADLINE_SHARE)
        return max(0.001, budget)

    # -- completion -----------------------------------------------------------

    def publish(self, fp: str, chunk_objs: list) -> None:
        """The lease holder's clean result landed in its local cache:
        retire the lease (owner) or push the record to the owner (peer).
        Fire-and-forget — the response stream must not wait on it."""
        self.publishes += 1
        owner = self.membership.owner(fp)
        if owner is None:
            return
        if owner == self.membership.self_url:
            self.leases.publish(fp)
            return
        self._spawn(self.client.publish_entry(owner, fp, chunk_objs))

    def abandon(self, fp: str) -> None:
        """The lease holder failed without a result: release so waiters
        fall back to local compute instead of riding out the TTL."""
        self.abandons += 1
        owner = self.membership.owner(fp)
        if owner is None:
            return
        if owner == self.membership.self_url:
            self.leases.release(fp, self.membership.self_url)
            return
        self._spawn(self.client.release_lease(owner, fp))

    def _spawn(self, coro) -> None:
        try:
            task = asyncio.get_event_loop().create_task(coro)
        except RuntimeError:
            coro.close()
            return
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    # -- drain ----------------------------------------------------------------

    async def handoff(self, cache) -> int:
        """Push this replica's hottest live entries to their post-drain
        owners.  Returns the number of entries peers accepted; any
        failure is skipped (the fleet re-computes what it must)."""
        if cache is None or not getattr(cache, "enabled", False):
            return 0
        by_target: dict = {}
        for fp, chunk_objs, ttl_sec in cache.hot_entries(
            HANDOFF_MAX_ENTRIES
        ):
            target = self.membership.owner_excluding_self(fp)
            if target is None or target == self.membership.self_url:
                continue
            by_target.setdefault(target, []).append(
                {"fp": fp, "chunks": chunk_objs, "ttl_sec": round(ttl_sec, 3)}
            )
        accepted = 0
        for target, entries in by_target.items():
            self.handoff_sent += len(entries)
            got = await self.client.handoff(target, entries)
            accepted += got
        self.handoff_accepted += accepted
        return accepted

    async def close(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        await self.client.close()

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "membership": self.membership.snapshot(),
            "peer_fetch": {
                "hits": self.peer_hits,
                "misses": self.peer_misses,
                "errors": self.peer_errors,
            },
            "leases": self.leases.stats(),
            "local_fallbacks": self.local_fallbacks,
            "publishes": self.publishes,
            "abandons": self.abandons,
            "rejected_publishes": self.rejected_publishes,
            "handoff": {
                "sent": self.handoff_sent,
                "accepted": self.handoff_accepted,
                "received": self.handoff_received,
                "rejected": self.handoff_rejected,
            },
            "client": self.client.stats(),
        }
