"""The peer-to-peer HTTP surface, guarded by the resilience stack.

Every call a replica makes to a peer (entry fetch, lease claim/release,
publish, drain handoff) goes through here, and every call:

* injects the ambient ``traceparent`` (obs/propagate.py) so the
  cross-replica hop appears as one stitched span tree;
* clamps its timeout to the propagated request deadline
  (resilience/deadline.py) — a peer leg may spend at most HALF the
  remaining budget, so the local-compute fallback always has time left
  to actually run (the "sheds instead of blocking" contract);
* forwards the clamped budget as ``x-deadline-ms`` so the peer's own
  deadline middleware bounds any server-side long-poll;
* rides a per-peer circuit breaker (resilience/breaker.py): a dead or
  flapping owner stops costing a connect timeout per request within a
  few attempts, and the fleet degrades to N independent replicas.

Failures never propagate: every method returns a "treat the fleet as
absent" value and the caller falls back to today's local behavior.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..obs import propagate
from ..resilience.breaker import BreakerConfig, BreakerRegistry
from ..resilience.deadline import current_deadline
from ..utils import jsonutil
from . import faults as fleet_faults
from .wire import clean_chunk_objs

# a peer leg may consume at most this fraction of the remaining request
# deadline (the rest is reserved for the local-compute fallback)
DEADLINE_SHARE = 0.5

# per-peer breaker: open after repeated transport failures, probe again
# a few seconds later; deliberately NOT configurable per knob — the
# fleet either reaches a peer or routes around it
_BREAKER = BreakerConfig(
    threshold=0.5, window=10, min_samples=3, cooldown_ms=3000.0
)


class FleetClient:
    def __init__(
        self,
        self_url: str,
        *,
        fetch_timeout_ms: float = 2000.0,
        fault_plan=None,
    ) -> None:
        self.self_url = self_url
        self.fetch_timeout_ms = fetch_timeout_ms
        self.breakers = BreakerRegistry(_BREAKER)
        # FLEET_FAULT_PLAN seam (fleet/faults.py); None — the default —
        # costs one identity check per request
        self.fault_plan = fault_plan
        # transport-outcome listener (fleet/health.py PeerHealth),
        # installed by the coordinator when quarantine is enabled
        self.health = None
        self._session = None
        self.peer_errors = 0
        self.deadline_sheds = 0
        self.peer_5xx = 0

    # -- plumbing -------------------------------------------------------------

    def _ensure_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    def _budget_ms(self, extra_ms: float = 0.0) -> Optional[float]:
        """The timeout for one peer leg: the fetch timeout (+ any
        explicit long-poll extension), clamped to DEADLINE_SHARE of the
        propagated deadline.  None means the deadline is already spent —
        shed the peer leg entirely."""
        budget = self.fetch_timeout_ms + extra_ms
        deadline = current_deadline()
        if deadline is not None:
            remaining_ms = deadline.remaining() * 1000.0
            if remaining_ms <= 1.0:
                return None
            budget = min(budget, remaining_ms * DEADLINE_SHARE)
        return max(1.0, budget)

    def _health(self, peer: str, ok: bool) -> None:
        if self.health is not None:
            self.health.record(peer, ok)

    async def _request(
        self,
        method: str,
        peer: str,
        path: str,
        *,
        body: Optional[dict] = None,
        extra_ms: float = 0.0,
        ring: Optional[str] = None,
    ):
        """(status, json_obj) or None on any transport-level failure
        (breaker open, deadline spent, connect/read error, timeout)."""
        breaker = self.breakers.get(peer, "fleet")
        if not breaker.allow():
            return None
        resolved = False
        try:
            budget_ms = self._budget_ms(extra_ms)
            if budget_ms is None:
                # deadline already spent: the peer's health was never
                # probed — neither success nor failure
                self.deadline_sheds += 1
                return None
            import aiohttp
            import asyncio

            fault = None
            if self.fault_plan is not None:
                fault = self.fault_plan.next_fault(self.self_url, peer)
            if fault in (fleet_faults.CONNECT, fleet_faults.FLAP):
                # connection refused — immediate, no budget burned
                self.peer_errors += 1
                breaker.record_failure()
                self._health(peer, False)
                resolved = True
                return None
            if fault == fleet_faults.BLACKHOLE:
                # packets vanish: the leg rides its whole clamped
                # budget before timing out, like a real partition
                await asyncio.sleep(budget_ms / 1000.0)
                self.peer_errors += 1
                breaker.record_failure()
                self._health(peer, False)
                resolved = True
                return None
            if fault == fleet_faults.BAD_STATUS:
                # the peer answered (transport healthy) but with a 503
                self.peer_5xx += 1
                breaker.record_failure()
                self._health(peer, True)
                resolved = True
                return 503, {
                    "error": {
                        "kind": "fault_injected",
                        "message": "fleet fault-injected 503",
                    }
                }
            if fault == fleet_faults.SLOW:
                await asyncio.sleep(
                    min(self.fault_plan.slow_ms, budget_ms) / 1000.0
                )

            headers = {
                "content-type": "application/json",
                "x-deadline-ms": str(int(budget_ms)),
            }
            if ring is not None:
                headers["x-fleet-ring"] = ring
            propagate.inject(headers)
            session = self._ensure_session()
            try:
                async with session.request(
                    method,
                    peer + path,
                    headers=headers,
                    data=(
                        jsonutil.dumps(body) if body is not None else None
                    ),
                    timeout=aiohttp.ClientTimeout(
                        total=budget_ms / 1000.0
                    ),
                ) as resp:
                    payload = None
                    if resp.content_type == "application/json":
                        payload = jsonutil.loads(await resp.read())
                    else:
                        await resp.read()
                    if fault == fleet_faults.CORRUPT:
                        payload = fleet_faults.corrupt_payload(payload)
                    if resp.status >= 500:
                        # a peer stuck returning 5xx is as unavailable
                        # as a dead one: the breaker must open
                        self.peer_5xx += 1
                        breaker.record_failure()
                    else:
                        breaker.record_success()
                    self._health(peer, True)
                    resolved = True
                    return resp.status, payload
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                self.peer_errors += 1
                breaker.record_failure()
                self._health(peer, False)
                resolved = True
                return None
        finally:
            if not resolved:
                breaker.release_probe()

    # -- the peer protocol ----------------------------------------------------

    async def fetch_entry(
        self, owner: str, fp: str, *, wait_ms: float = 0.0,
        ring: Optional[str] = None,
    ) -> Tuple[str, Optional[list]]:
        """("hit", chunks) | ("miss", None) | ("divergent", None) |
        ("error", None).  With ``wait_ms`` the owner long-polls its
        lease table before answering, so a waiter gets the published
        entry in one trip.  "divergent" means the peer rejected our
        ``x-fleet-ring`` digest: it is routing on a different roster."""
        path = f"/fleet/v1/entry/{fp}"
        if wait_ms > 0:
            path += f"?wait_ms={int(wait_ms)}"
        result = await self._request(
            "GET", owner, path, extra_ms=wait_ms, ring=ring
        )
        if result is None:
            return "error", None
        status, payload = result
        if status == 200 and isinstance(payload, dict):
            chunks = clean_chunk_objs(payload.get("chunks"))
            if chunks is not None:
                return "hit", chunks
            return "error", None
        if status == 404:
            return "miss", None
        if status == 409:
            return "divergent", None
        return "error", None

    async def request_lease(
        self, owner: str, fp: str, *, ring: Optional[str] = None
    ) -> str:
        """"granted" | "wait" | "divergent" | "error"."""
        result = await self._request(
            "POST", owner, f"/fleet/v1/lease/{fp}",
            body={"holder": self.self_url},
            ring=ring,
        )
        if result is None:
            return "error"
        status, payload = result
        if status == 200 and isinstance(payload, dict):
            return "granted" if payload.get("granted") else "wait"
        if status == 409:
            return "divergent"
        return "error"

    async def release_lease(self, owner: str, fp: str) -> None:
        await self._request(
            "DELETE", owner, f"/fleet/v1/lease/{fp}",
            body={"holder": self.self_url},
        )

    async def publish_entry(
        self, owner: str, fp: str, chunk_objs: list
    ) -> bool:
        result = await self._request(
            "PUT", owner, f"/fleet/v1/entry/{fp}",
            body={"holder": self.self_url, "chunks": chunk_objs},
        )
        return result is not None and result[0] in (200, 204)

    async def handoff(self, target: str, entries: List[dict]) -> int:
        """Drain-time hot-set transfer; the count the target accepted."""
        result = await self._request(
            "POST", target, "/fleet/v1/handoff",
            body={"from": self.self_url, "entries": entries},
        )
        if result is None or result[0] != 200:
            return 0
        payload = result[1]
        if isinstance(payload, dict):
            return int(payload.get("accepted", 0))
        return 0

    async def probe(self, peer: str) -> bool:
        """One liveness GET against a quarantined peer.  Deliberately
        BYPASSES the breaker gate (a quarantined peer's breaker is
        usually open — that is exactly why traffic stopped and a probe
        must go instead) but still rides the fault seam, so a scripted
        partition fails probes deterministically too."""
        if self.fault_plan is not None:
            fault = self.fault_plan.next_fault(self.self_url, peer)
            if fault in (
                fleet_faults.CONNECT,
                fleet_faults.FLAP,
                fleet_faults.BLACKHOLE,
                fleet_faults.BAD_STATUS,
            ):
                return False
        import aiohttp
        import asyncio

        try:
            async with self._ensure_session().get(
                peer + "/fleet/v1/ping",
                timeout=aiohttp.ClientTimeout(
                    total=self.fetch_timeout_ms / 1000.0
                ),
            ) as resp:
                await resp.read()
                return resp.status < 500
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    def stats(self) -> dict:
        out = {
            "peer_errors": self.peer_errors,
            "peer_5xx": self.peer_5xx,
            "deadline_sheds": self.deadline_sheds,
            "breakers": self.breakers.snapshot(),
        }
        if self.fault_plan is not None:
            out["fault_plan"] = self.fault_plan.snapshot()
        return out
