"""Peer health scoring: quarantine a flapping replica, re-admit by probe.

The per-peer circuit breaker (fleet/client.py) already stops *calls*
to a dead peer, but a breaker-open peer still OWNS its ring segment —
every request for its keys pays a shed-and-fallback round trip, and a
flapping peer (up just long enough to half-open the breaker, down
again by the next call) is worse: it oscillates between costing a
timeout and costing nothing.  This module decides *membership*, not
admission: a peer whose recent outcome window shows either a run of
consecutive failures or too many up/down transitions is QUARANTINED —
``FleetMembership.set_quarantined`` removes it from the active ring,
so its keys re-home to healthy replicas and the sick peer costs
nothing per request.

Re-admission is by probe, not by traffic: while quarantined, the peer
receives a liveness GET at most once per ``FLEET_PROBE_MILLIS``; one
success re-admits it (consistent hashing moves only its own keys
back).  Probes are the coordinator's job — this table only says who is
due (``probes_due``) and scores the result (``record_probe``).

Disabled (``FLEET_QUARANTINE_FAILURES=0``) the table is inert: nothing
is ever quarantined and the ring never shrinks, which is exactly the
pre-quarantine fleet.

Single event loop, no locks: every method is synchronous bookkeeping.
"""

from __future__ import annotations

import time
from typing import Dict, List

# outcome window per peer: transitions are counted over this many
# most-recent call outcomes
WINDOW = 16

# ok<->fail transitions within the window at or above which the peer is
# declared flapping (even if it never hits the consecutive-failure bar)
FLAP_TRANSITIONS = 6


class PeerHealth:
    def __init__(
        self,
        fail_threshold: int = 3,
        probe_interval_ms: float = 1000.0,
        *,
        clock=time.monotonic,
    ) -> None:
        self.fail_threshold = max(0, int(fail_threshold))
        self.probe_interval_sec = max(0.001, probe_interval_ms / 1000.0)
        self.clock = clock
        self._window: Dict[str, List[bool]] = {}
        self._consecutive_failures: Dict[str, int] = {}
        self._quarantined: Dict[str, float] = {}  # peer -> last probe time
        self.quarantines = 0
        self.readmissions = 0
        self.probes = 0

    @property
    def enabled(self) -> bool:
        return self.fail_threshold > 0

    def record(self, peer: str, ok: bool) -> None:
        """Score one call outcome for ``peer`` (transport-level: did the
        peer answer at all — HTTP status is the breaker's business)."""
        if not self.enabled or peer in self._quarantined:
            return
        window = self._window.setdefault(peer, [])
        window.append(ok)
        if len(window) > WINDOW:
            del window[: len(window) - WINDOW]
        if ok:
            self._consecutive_failures[peer] = 0
        else:
            self._consecutive_failures[peer] = (
                self._consecutive_failures.get(peer, 0) + 1
            )
        transitions = sum(
            1 for a, b in zip(window, window[1:]) if a != b
        )
        if (
            self._consecutive_failures[peer] >= self.fail_threshold
            or transitions >= FLAP_TRANSITIONS
        ):
            # quarantine; first probe is due one full interval later
            self._quarantined[peer] = self.clock()
            self._window.pop(peer, None)
            self._consecutive_failures.pop(peer, None)
            self.quarantines += 1

    def quarantined(self) -> List[str]:
        return sorted(self._quarantined)

    def probes_due(self) -> List[str]:
        """Quarantined peers whose probe interval has elapsed.  Stamps
        them probed NOW, so concurrent callers never double-probe."""
        now = self.clock()
        due = []
        for peer, last in self._quarantined.items():
            if now - last >= self.probe_interval_sec:
                self._quarantined[peer] = now
                due.append(peer)
        return due

    def record_probe(self, peer: str, ok: bool) -> None:
        """Score a liveness probe: one success re-admits the peer (its
        ring segment moves back); a failure leaves it quarantined until
        the next interval."""
        self.probes += 1
        if ok and peer in self._quarantined:
            del self._quarantined[peer]
            self.readmissions += 1

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "quarantined": self.quarantined(),
            "quarantines": self.quarantines,
            "readmissions": self.readmissions,
            "probes": self.probes,
        }
