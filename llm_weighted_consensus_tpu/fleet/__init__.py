"""Fleet tier: N gateway replicas serving as one coherent cache tier.

Through PR 15 every replica was an island: a private result LRU
(cache/store.py) and a per-process single-flight (cache/singleflight.py)
meant a fleet-wide hot score request stampeded every replica's upstream
judges independently.  This package adds the cross-replica half of the
cache design (cf. Nishtala et al., *Scaling Memcache at Facebook*,
NSDI'13):

* ``membership``  — static-or-file-watched peer roster + consistent-hash
  ownership of cache fingerprints (score/v1 + embed/v1 key space);
* ``leases``      — the owner-side cross-replica single-flight table:
  one lease per in-flight fingerprint, TTL-bounded so a dead holder
  degrades to local compute, never a hang;
* ``client``      — the peer HTTP surface (entry fetch / lease / publish
  / handoff) with traceparent + deadline propagation and per-peer
  circuit breakers;
* ``coordinator`` — the glue the score client's front door and the
  drain path call; every fleet failure collapses to ("local", None),
  i.e. exactly the pre-fleet behavior;
* ``handlers``    — the ``/fleet/v1/*`` aiohttp handlers, including the
  wire-side replay admission guard (a peer can never be served a
  degraded or errored record);
* ``wire``        — record validation shared by publish and handoff;
* ``faults``      — the ``FLEET_FAULT_PLAN`` seam: deterministic
  per-peer-pair fault injection (partitions, flaps, corruption) so
  every failure path above is drillable from a seed;
* ``health``      — peer quarantine: a flapping peer is ejected from
  the routing ring behind a health score and re-admitted by probe.

Everything here is single-event-loop asyncio: no threading primitives,
so the concurrency-model registry (analysis/concurrency_model.py) gains
no rows and the lockdep witness has nothing new to watch.
"""

from .client import FleetClient
from .coordinator import FleetCoordinator
from .faults import FleetFaultPlan
from .handlers import register_fleet_routes
from .health import PeerHealth
from .leases import LeaseTable
from .membership import FleetConfig, FleetMembership, OwnershipView
from .wire import clean_chunk_objs, record_digest

__all__ = [
    "FleetClient",
    "FleetConfig",
    "FleetCoordinator",
    "FleetFaultPlan",
    "FleetMembership",
    "LeaseTable",
    "OwnershipView",
    "PeerHealth",
    "clean_chunk_objs",
    "record_digest",
    "register_fleet_routes",
]
