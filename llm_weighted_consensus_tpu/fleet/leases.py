"""Owner-side cross-replica single-flight leases.

The per-process ``SingleFlight`` (cache/singleflight.py) collapses
identical concurrent requests *inside* one replica; this table is the
same idea at the fleet level, held by the fingerprint's OWNER: the
first claimant — local or a remote replica over ``POST
/fleet/v1/lease/{fp}`` — is granted the lease and goes upstream, every
later claimant waits for the publish instead of fanning out its own
judge calls.  One upstream fan-out per hot fingerprint, fleet-wide.

Unlike the in-process table, a lease here is TTL-bounded: the holder
may be another process that dies mid-flight, and nothing can ``finally:``
on its behalf.  An expired lease is simply re-grantable — the next
claimant computes locally, which is exactly the pre-fleet behavior.
The failure direction is deliberate: a lost lease costs one duplicate
upstream fan-out, never a stuck request.

Single event loop, no locks (the gateway's asyncio discipline): every
method is synchronous bookkeeping except ``wait``, which awaits a
future with a timeout.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Tuple


class LeaseTable:
    def __init__(self, ttl_ms: float, *, clock=time.monotonic) -> None:
        self.ttl_sec = max(0.001, ttl_ms / 1000.0)
        self.clock = clock
        # fp -> [holder, expires_at, future]; the future resolves True on
        # publish, None on release/expiry (waiters re-check and fall back)
        self._leases: dict = {}
        self.granted = 0
        self.waits = 0
        self.published = 0
        self.released = 0
        self.expirations = 0
        self.takeovers = 0
        self.late_publishes = 0

    def _expire(self, fp: str, lease: list) -> None:
        del self._leases[fp]
        self.expirations += 1
        if not lease[2].done():
            lease[2].set_result(None)

    def acquire(
        self, fp: str, holder: str
    ) -> Tuple[bool, Optional[asyncio.Future]]:
        """(granted, wait_future): granted means ``holder`` now owns the
        in-flight slot for ``fp`` and must publish or release; otherwise
        the future resolves when the current holder publishes (True) or
        abandons/expires (None)."""
        now = self.clock()
        lease = self._leases.get(fp)
        if lease is not None and lease[1] <= now:
            self._expire(fp, lease)
            lease = None
        if lease is not None:
            if lease[0] == holder:
                # re-claim by the same holder (a retry): extend, keep it
                lease[1] = now + self.ttl_sec
                return True, None
            self.waits += 1
            return False, lease[2]
        future = asyncio.get_running_loop().create_future()
        self._leases[fp] = [holder, now + self.ttl_sec, future]
        self.granted += 1
        return True, None

    def holder(self, fp: str) -> Optional[str]:
        """The live lease's holder URL (lazily expiring), or None."""
        lease = self._leases.get(fp)
        if lease is None:
            return None
        if lease[1] <= self.clock():
            self._expire(fp, lease)
            return None
        return lease[0]

    def steal(self, fp: str, new_holder: str) -> bool:
        """Early takeover: the current holder is believed dead (its
        breaker is open or a liveness probe failed), so the lease moves
        to ``new_holder`` without waiting out the TTL.  Old waiters wake
        to None (fall back to local compute — the safe direction); the
        thief gets a fresh TTL.  False when no live lease exists or
        ``new_holder`` already holds it."""
        lease = self._leases.get(fp)
        now = self.clock()
        if lease is not None and lease[1] <= now:
            self._expire(fp, lease)
            lease = None
        if lease is None or lease[0] == new_holder:
            return False
        if not lease[2].done():
            lease[2].set_result(None)
        future = asyncio.get_running_loop().create_future()
        self._leases[fp] = [new_holder, now + self.ttl_sec, future]
        self.takeovers += 1
        return True

    def holder_future(self, fp: str) -> Optional[asyncio.Future]:
        """The active lease's publish future (long-poll handlers wait on
        it), or None when nothing is in flight."""
        lease = self._leases.get(fp)
        if lease is None:
            return None
        if lease[1] <= self.clock():
            self._expire(fp, lease)
            return None
        return lease[2]

    def remaining_sec(self, fp: str) -> float:
        lease = self._leases.get(fp)
        if lease is None:
            return 0.0
        return max(0.0, lease[1] - self.clock())

    def publish(self, fp: str, holder: Optional[str] = None) -> bool:
        """The holder's result landed (in the owner's cache): wake every
        waiter with success and retire the lease.

        With ``holder`` given, the retire is holder-checked: a LATE
        publish — the lease already expired (or was stolen and
        re-granted to someone else) — must not tear down the *current*
        claimant's lease.  The late result is still worth caching (the
        caller already put it), so it is counted as a reconciliation
        rather than dropped; returns False so the caller can tell.
        ``holder=None`` keeps the legacy unconditional retire (the
        local-owner path, where the process itself held the slot)."""
        lease = self._leases.get(fp)
        if lease is not None and lease[1] <= self.clock():
            self._expire(fp, lease)
            lease = None
        if holder is not None and (lease is None or lease[0] != holder):
            self.late_publishes += 1
            return False
        self._leases.pop(fp, None)
        self.published += 1
        if lease is not None and not lease[2].done():
            lease[2].set_result(True)
        return True

    def release(self, fp: str, holder: str) -> None:
        """The holder abandons without a result (its upstream fan-out
        failed): waiters wake to None and fall back to local compute."""
        lease = self._leases.get(fp)
        if lease is None or lease[0] != holder:
            return
        del self._leases[fp]
        self.released += 1
        if not lease[2].done():
            lease[2].set_result(None)

    async def wait(
        self, future: asyncio.Future, timeout_sec: float
    ) -> Optional[bool]:
        """Await a lease future for at most ``timeout_sec``; None on
        timeout.  Shielded: one waiter's cancellation must not kill the
        shared future other waiters hold."""
        try:
            return await asyncio.wait_for(
                asyncio.shield(future), timeout=max(0.001, timeout_sec)
            )
        except asyncio.TimeoutError:
            return None

    def active(self) -> int:
        now = self.clock()
        return sum(1 for lease in self._leases.values() if lease[1] > now)

    def stats(self) -> dict:
        return {
            "active": self.active(),
            "granted": self.granted,
            "waits": self.waits,
            "published": self.published,
            "released": self.released,
            "expirations": self.expirations,
            "takeovers": self.takeovers,
            "late_publishes": self.late_publishes,
            "ttl_sec": self.ttl_sec,
        }
