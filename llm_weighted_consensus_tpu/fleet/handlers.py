"""``/fleet/v1/*``: the owner-side HTTP surface.

Routes (all replica-to-replica; admission-exempt like the probe
endpoints — each call is bounded, cheap, and sheds itself via the
propagated deadline rather than occupying an admission slot):

* ``GET    /fleet/v1/entry/{fp}``  — the cached record, or 404.  With
  ``?wait_ms=N`` the owner long-polls its lease table first, so a
  waiter picks up the publish in one round trip.
* ``PUT    /fleet/v1/entry/{fp}``  — a lease holder publishes its
  result: the record is re-validated by the wire-side replay admission
  guard (fleet/wire.py) before it may enter the owner's cache; a dirty
  record is refused with 422 and the lease is released so waiters fall
  back to local compute.
* ``POST   /fleet/v1/lease/{fp}``  — claim the cross-replica
  single-flight lease; ``{"granted": bool}``.
* ``DELETE /fleet/v1/lease/{fp}``  — abandon a held lease.
* ``POST   /fleet/v1/handoff``     — a draining replica pushes its hot
  set; every entry passes the same wire guard, and the remaining TTL
  rides along so a handed-off entry can never outlive its original
  lifetime.
* ``GET    /fleet/v1/ping``        — liveness, for quarantine
  re-admission probes.

Entry GET and lease POST additionally check the caller's
``x-fleet-ring`` digest against the local roster and answer 409 on a
mismatch — a split-brain caller must degrade to local, not be handed a
lease decision made on a different ring.
"""

from __future__ import annotations

from ..resilience.deadline import current_deadline
from ..utils import jsonutil
from .wire import clean_chunk_objs

# a long-poll may hold the connection at most this long regardless of
# what the caller asked for (connection hygiene, not a semantic bound)
MAX_WAIT_MS = 30000.0


def _json(obj, status: int = 200):
    from aiohttp import web

    return web.Response(
        text=jsonutil.dumps(obj),
        status=status,
        content_type="application/json",
    )


async def _read_body(request) -> dict:
    try:
        obj = jsonutil.loads(await request.read())
    except ValueError:
        return {}
    return obj if isinstance(obj, dict) else {}


def register_fleet_routes(app, fleet) -> None:
    """Wire the fleet peer endpoints onto the gateway app."""

    def _ring_mismatch(request):
        """A 409 when the caller's ``x-fleet-ring`` digest disagrees
        with ours — the two replicas are routing on different rosters.
        Enforced only on the calls that CAUSE duplicate upstream work
        when misrouted (entry fetch, lease claim); publish, release and
        handoff stay digest-blind on purpose: a draining or lagging
        replica's results and abandons are still valid, and rejecting an
        abandon would strand waiters until TTL."""
        claimed = request.headers.get("x-fleet-ring")
        if claimed is None:
            return None
        ours = fleet.membership.ring_digest()
        if claimed == ours:
            return None
        fleet.ring_rejects += 1
        return _json(
            {
                "kind": "ring_divergence",
                "ring": ours,
                "epoch": fleet.membership.epoch,
            },
            status=409,
        )

    async def ping(request):
        # the quarantine re-admission probe target: answering at all is
        # the signal, the body is a courtesy
        return _json({"ok": True, "self": fleet.membership.self_url})

    async def entry_get(request):
        mismatch = _ring_mismatch(request)
        if mismatch is not None:
            return mismatch
        fp = request.match_info["fp"]
        cache = fleet.cache
        record = cache.get(fp) if cache is not None else None
        if record is None:
            wait_ms = 0.0
            try:
                wait_ms = float(request.query.get("wait_ms", 0))
            except ValueError:
                pass
            wait_ms = min(max(0.0, wait_ms), MAX_WAIT_MS)
            future = fleet.leases.holder_future(fp) if wait_ms else None
            if future is not None:
                timeout = min(
                    wait_ms / 1000.0,
                    fleet.leases.remaining_sec(fp) or wait_ms / 1000.0,
                )
                deadline = current_deadline()
                if deadline is not None:
                    timeout = min(timeout, max(0.0, deadline.remaining()))
                await fleet.leases.wait(future, timeout)
                record = cache.get(fp) if cache is not None else None
        if record is None:
            return _json({"found": False}, status=404)
        return _json({"found": True, "chunks": record})

    async def entry_put(request):
        fp = request.match_info["fp"]
        body = await _read_body(request)
        holder = str(body.get("holder", ""))
        chunks = clean_chunk_objs(body.get("chunks"))
        if chunks is None:
            # dirty or corrupt record: never enters the cache, and the
            # lease is released so waiters stop hoping for it
            fleet.rejected_publishes += 1
            fleet.leases.release(fp, holder)
            return _json({"accepted": False}, status=422)
        if fleet.cache is not None:
            fleet.cache.put_chunks(fp, chunks)
        # holder-aware retire: a LATE publish (lease expired or stolen
        # while the holder was partitioned away) still lands in the
        # cache — the work is done, wasting it helps no one — but must
        # not tear down the CURRENT claimant's lease
        retired = fleet.leases.publish(fp, holder if holder else None)
        return _json({"accepted": True, "retired": retired})

    async def lease_post(request):
        mismatch = _ring_mismatch(request)
        if mismatch is not None:
            return mismatch
        fp = request.match_info["fp"]
        body = await _read_body(request)
        holder = str(body.get("holder", "")) or "unknown-peer"
        granted, _ = fleet.leases.acquire(fp, holder)
        return _json(
            {
                "granted": granted,
                "ttl_ms": fleet.leases.ttl_sec * 1000.0,
            }
        )

    async def lease_delete(request):
        fp = request.match_info["fp"]
        body = await _read_body(request)
        holder = str(body.get("holder", ""))
        fleet.leases.release(fp, holder)
        return _json({"released": True})

    async def handoff_post(request):
        body = await _read_body(request)
        entries = body.get("entries")
        if not isinstance(entries, list):
            return _json({"accepted": 0}, status=400)
        accepted = 0
        for entry in entries:
            if not isinstance(entry, dict):
                continue
            fp = entry.get("fp")
            chunks = clean_chunk_objs(entry.get("chunks"))
            try:
                ttl_sec = float(entry.get("ttl_sec", 0))
            except (TypeError, ValueError):
                ttl_sec = 0.0
            if not isinstance(fp, str) or chunks is None or ttl_sec <= 0:
                fleet.handoff_rejected += 1
                continue
            if fleet.cache is not None:
                # the remaining lifetime travels with the entry: a
                # handed-off record expires exactly when the original
                # would have
                fleet.cache.put_chunks(fp, chunks, ttl_sec=ttl_sec)
            fleet.leases.publish(fp)
            accepted += 1
        fleet.handoff_received += accepted
        return _json({"accepted": accepted})

    app.router.add_get("/fleet/v1/ping", ping)
    app.router.add_get("/fleet/v1/entry/{fp}", entry_get)
    app.router.add_put("/fleet/v1/entry/{fp}", entry_put)
    app.router.add_post("/fleet/v1/lease/{fp}", lease_post)
    app.router.add_delete("/fleet/v1/lease/{fp}", lease_delete)
    app.router.add_post("/fleet/v1/handoff", handoff_post)
