"""Fleet membership and consistent-hash ownership.

The roster is a list of replica base URLs (including this replica's
own ``FLEET_SELF``), either static (``FLEET_PEERS``) or read from a
file re-checked on mtime change (``FLEET_PEERS_FILE`` — one URL per
line, ``#`` comments).  Ownership of a cache fingerprint is decided by
a classic consistent-hash ring: each peer contributes ``vnodes``
points (xxh3-64 over ``"url#i"``), a key maps to the first point
clockwise from its own hash, and adding or removing one peer moves
only the keys that peer owned — the property that makes elastic
scale-out and the drain-time hot-set handoff cheap.

Ownership is consumed through immutable ``OwnershipView`` snapshots,
stamped with a **ring epoch** (monotonic per process, bumped on every
ring rebuild) and a **ring digest** (xxh3 over the sorted roster — the
cross-process fingerprint two replicas can compare).  A request pins
ONE view at ``begin`` and routes every later leg (lease, publish,
abandon) through it, so a roster reload mid-request cannot misroute
the publish to a different "owner" than the one holding the lease.
The digest rides every peer call as ``x-fleet-ring``; a mismatch means
the two replicas are operating on different rosters (split-brain from
staggered peers-file reads) and the caller degrades to local instead
of silently double-hitting upstream.

Quarantine (fleet/health.py decides *who*) removes a sick peer from
the ACTIVE ring — ownership is recomputed without it, so a flapping
replica costs one probe instead of a timeout per request.  The digest
deliberately covers only the configured roster, never the quarantine
set: quarantine is local knowledge, and folding it into the digest
would make every replica's ring look divergent the moment one of them
noticed a sick peer.

Hashing is xxh3 (the identity layer's function family), NOT Python's
``hash()``: ring positions must agree across processes, and ``hash()``
is salted per process by PYTHONHASHSEED.
"""

from __future__ import annotations

import bisect
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import xxhash


def _point(label: str) -> int:
    return xxhash.xxh3_64_intdigest(label.encode("utf-8"))


@dataclass
class FleetConfig:
    """Parsed FLEET_* knobs (serve/config.py ``fleet_config()``)."""

    self_url: str
    peers: List[str] = field(default_factory=list)
    peers_file: Optional[str] = None
    vnodes: int = 64
    lease_millis: float = 10000.0
    fetch_timeout_millis: float = 2000.0
    fault_plan_spec: Optional[str] = None
    quarantine_failures: int = 3
    probe_millis: float = 1000.0


class OwnershipView:
    """One immutable ring snapshot: every routing decision a single
    request makes (owner at begin, publish target, abandon target) must
    come from the SAME view, so membership churn between those calls
    cannot split them across two rosters."""

    __slots__ = ("self_url", "peers", "epoch", "digest", "_points", "_owners")

    def __init__(
        self,
        self_url: str,
        peers: Tuple[str, ...],
        epoch: int,
        digest: str,
        vnodes: int,
    ) -> None:
        self.self_url = self_url
        self.peers = peers
        self.epoch = epoch
        self.digest = digest
        points = []
        for peer in peers:
            for i in range(max(1, vnodes)):
                points.append((_point(f"{peer}#{i}"), peer))
        points.sort()
        self._points = [h for h, _ in points]
        self._owners = [p for _, p in points]

    def owner(self, fp: str) -> Optional[str]:
        """The replica owning ``fp`` in this snapshot, or None with an
        empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, _point(fp))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def owns(self, fp: str) -> bool:
        return self.owner(fp) == self.self_url


class FleetMembership:
    """The roster + ring.  Pure computation plus an optional lazy file
    re-read; safe to call from any task on the event loop."""

    # peers-file mtime is re-checked at most this often (seconds)
    RELOAD_INTERVAL_SEC = 1.0

    def __init__(
        self,
        config: FleetConfig,
        *,
        clock=time.monotonic,
    ) -> None:
        self.config = config
        self.self_url = config.self_url.rstrip("/")
        self.clock = clock
        self.reloads = 0
        self.epoch = 0
        self._file_mtime: Optional[float] = None
        self._last_check = 0.0
        self._quarantined: set = set()
        self._view: Optional[OwnershipView] = None
        self._departure_view: Optional[OwnershipView] = None
        peers = list(config.peers)
        if config.peers_file:
            loaded = self._read_peers_file()
            if loaded is not None:
                peers = loaded
        self._set_peers(peers)

    # -- roster ---------------------------------------------------------------

    def _read_peers_file(self) -> Optional[List[str]]:
        """The peers file's roster, or None when unreadable (the caller
        keeps the previous roster: a transiently missing file must not
        empty the fleet)."""
        path = self.config.peers_file
        try:
            mtime = os.stat(path).st_mtime
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return None
        self._file_mtime = mtime
        peers = []
        for line in lines:
            line = line.strip()
            if line and not line.startswith("#"):
                peers.append(line)
        return peers

    def _set_peers(self, peers: List[str]) -> None:
        self._peers = sorted({p.rstrip("/") for p in peers if p})
        self._rebuild()

    def _rebuild(self) -> None:
        """Recompute the active ring (roster minus quarantined peers —
        never this replica itself) and bump the epoch.  Views are
        rebuilt lazily: a rebuild between two requests costs nothing
        until someone routes."""
        self.epoch += 1
        self._view = None
        self._departure_view = None

    def ring_digest(self) -> str:
        """The cross-process roster fingerprint: two replicas reading
        the same peers file agree on it byte-for-byte.  Covers the
        CONFIGURED roster only (see module docstring on quarantine)."""
        label = "|".join(self._peers) + f"#v{self.config.vnodes}"
        return xxhash.xxh3_64_hexdigest(label.encode("utf-8"))

    def _active_peers(self) -> Tuple[str, ...]:
        return tuple(
            p
            for p in self._peers
            if p == self.self_url or p not in self._quarantined
        )

    def view(self) -> OwnershipView:
        """The current pinned-ownership snapshot (cached until the next
        ring rebuild)."""
        self._maybe_reload()
        if self._view is None:
            self._view = OwnershipView(
                self.self_url,
                self._active_peers(),
                self.epoch,
                self.ring_digest(),
                self.config.vnodes,
            )
        return self._view

    def departure_view(self) -> OwnershipView:
        """The ring as it looks once this replica leaves — the
        drain-time handoff router, built ONCE per rebuild instead of
        the old O(peers×vnodes) scan per entry."""
        self._maybe_reload()
        if self._departure_view is None:
            peers = tuple(
                p for p in self._active_peers() if p != self.self_url
            )
            self._departure_view = OwnershipView(
                self.self_url,
                peers,
                self.epoch,
                self.ring_digest(),
                self.config.vnodes,
            )
        return self._departure_view

    def _maybe_reload(self) -> None:
        if not self.config.peers_file:
            return
        now = self.clock()
        if now - self._last_check < self.RELOAD_INTERVAL_SEC:
            return
        self._last_check = now
        try:
            mtime = os.stat(self.config.peers_file).st_mtime
        except OSError:
            return
        if mtime == self._file_mtime:
            return
        loaded = self._read_peers_file()
        if loaded is not None:
            self._set_peers(loaded)
            self.reloads += 1

    @property
    def peers(self) -> List[str]:
        self._maybe_reload()
        return list(self._peers)

    # -- quarantine -----------------------------------------------------------

    def set_quarantined(self, sick) -> None:
        """Install the health layer's verdict.  Only a CHANGE rebuilds
        the ring (and bumps the epoch) — steady state is a set compare."""
        sick = {s.rstrip("/") for s in sick}
        sick.discard(self.self_url)
        if sick == self._quarantined:
            return
        self._quarantined = sick
        self._rebuild()

    def quarantined(self) -> List[str]:
        return sorted(self._quarantined)

    # -- ownership ------------------------------------------------------------

    def owner(self, fp: str) -> Optional[str]:
        """The replica owning ``fp``, or None with an empty ring."""
        return self.view().owner(fp)

    def owns(self, fp: str) -> bool:
        return self.owner(fp) == self.self_url

    def owner_excluding_self(self, fp: str) -> Optional[str]:
        """Where ``fp`` lands once this replica leaves the ring — the
        drain-time handoff target.  None when no other peer exists."""
        return self.departure_view().owner(fp)

    def owned_share(self, samples: int = 256) -> float:
        """Estimated fraction of the key space this replica owns
        (deterministic probe points; surfaced in /readyz + metrics)."""
        view = self.view()
        if not view._points:
            return 0.0
        owned = sum(
            1
            for i in range(samples)
            if view.owner(f"fleet-share-probe:{i}") == self.self_url
        )
        return owned / float(samples)

    def snapshot(self) -> dict:
        return {
            "self": self.self_url,
            "peers": self.peers,
            "owned_share": round(self.owned_share(), 4),
            "vnodes": self.config.vnodes,
            "roster_reloads": self.reloads,
            "epoch": self.epoch,
            "ring": self.ring_digest(),
            "quarantined": self.quarantined(),
        }
