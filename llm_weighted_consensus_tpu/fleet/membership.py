"""Fleet membership and consistent-hash ownership.

The roster is a list of replica base URLs (including this replica's
own ``FLEET_SELF``), either static (``FLEET_PEERS``) or read from a
file re-checked on mtime change (``FLEET_PEERS_FILE`` — one URL per
line, ``#`` comments).  Ownership of a cache fingerprint is decided by
a classic consistent-hash ring: each peer contributes ``vnodes``
points (xxh3-64 over ``"url#i"``), a key maps to the first point
clockwise from its own hash, and adding or removing one peer moves
only the keys that peer owned — the property that makes elastic
scale-out and the drain-time hot-set handoff cheap.

Hashing is xxh3 (the identity layer's function family), NOT Python's
``hash()``: ring positions must agree across processes, and ``hash()``
is salted per process by PYTHONHASHSEED.
"""

from __future__ import annotations

import bisect
import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

import xxhash


def _point(label: str) -> int:
    return xxhash.xxh3_64_intdigest(label.encode("utf-8"))


@dataclass
class FleetConfig:
    """Parsed FLEET_* knobs (serve/config.py ``fleet_config()``)."""

    self_url: str
    peers: List[str] = field(default_factory=list)
    peers_file: Optional[str] = None
    vnodes: int = 64
    lease_millis: float = 10000.0
    fetch_timeout_millis: float = 2000.0


class FleetMembership:
    """The roster + ring.  Pure computation plus an optional lazy file
    re-read; safe to call from any task on the event loop."""

    # peers-file mtime is re-checked at most this often (seconds)
    RELOAD_INTERVAL_SEC = 1.0

    def __init__(
        self,
        config: FleetConfig,
        *,
        clock=time.monotonic,
    ) -> None:
        self.config = config
        self.self_url = config.self_url.rstrip("/")
        self.clock = clock
        self.reloads = 0
        self._file_mtime: Optional[float] = None
        self._last_check = 0.0
        peers = list(config.peers)
        if config.peers_file:
            loaded = self._read_peers_file()
            if loaded is not None:
                peers = loaded
        self._set_peers(peers)

    # -- roster ---------------------------------------------------------------

    def _read_peers_file(self) -> Optional[List[str]]:
        """The peers file's roster, or None when unreadable (the caller
        keeps the previous roster: a transiently missing file must not
        empty the fleet)."""
        path = self.config.peers_file
        try:
            mtime = os.stat(path).st_mtime
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            return None
        self._file_mtime = mtime
        peers = []
        for line in lines:
            line = line.strip()
            if line and not line.startswith("#"):
                peers.append(line)
        return peers

    def _set_peers(self, peers: List[str]) -> None:
        self._peers = sorted({p.rstrip("/") for p in peers if p})
        points = []
        for peer in self._peers:
            for i in range(max(1, self.config.vnodes)):
                points.append((_point(f"{peer}#{i}"), peer))
        points.sort()
        self._ring_points = [h for h, _ in points]
        self._ring_peers = [p for _, p in points]

    def _maybe_reload(self) -> None:
        if not self.config.peers_file:
            return
        now = self.clock()
        if now - self._last_check < self.RELOAD_INTERVAL_SEC:
            return
        self._last_check = now
        try:
            mtime = os.stat(self.config.peers_file).st_mtime
        except OSError:
            return
        if mtime == self._file_mtime:
            return
        loaded = self._read_peers_file()
        if loaded is not None:
            self._set_peers(loaded)
            self.reloads += 1

    @property
    def peers(self) -> List[str]:
        self._maybe_reload()
        return list(self._peers)

    # -- ownership ------------------------------------------------------------

    def owner(self, fp: str) -> Optional[str]:
        """The replica owning ``fp``, or None with an empty ring."""
        self._maybe_reload()
        if not self._ring_points:
            return None
        i = bisect.bisect_right(self._ring_points, _point(fp))
        if i == len(self._ring_points):
            i = 0
        return self._ring_peers[i]

    def owns(self, fp: str) -> bool:
        return self.owner(fp) == self.self_url

    def owner_excluding_self(self, fp: str) -> Optional[str]:
        """Where ``fp`` lands once this replica leaves the ring — the
        drain-time handoff target.  None when no other peer exists."""
        self._maybe_reload()
        others = [p for p in self._peers if p != self.self_url]
        if not others:
            return None
        if len(others) == len(self._peers):
            return self.owner(fp)
        h = _point(fp)
        best = None
        for peer in others:
            for i in range(max(1, self.config.vnodes)):
                ph = _point(f"{peer}#{i}")
                d = (ph - h) % (1 << 64)
                if best is None or d < best[0]:
                    best = (d, peer)
        return best[1]

    def owned_share(self, samples: int = 256) -> float:
        """Estimated fraction of the key space this replica owns
        (deterministic probe points; surfaced in /readyz + metrics)."""
        if not self._ring_points:
            return 0.0
        owned = sum(
            1
            for i in range(samples)
            if self.owner(f"fleet-share-probe:{i}") == self.self_url
        )
        return owned / float(samples)

    def snapshot(self) -> dict:
        return {
            "self": self.self_url,
            "peers": self.peers,
            "owned_share": round(self.owned_share(), 4),
            "vnodes": self.config.vnodes,
            "roster_reloads": self.reloads,
        }
