"""Offline feed: stream ledger shards / archive records into the
offline priority class (ISSUE 20 tentpole piece b).

Two sources, one driver:

* ``LedgerFeed`` streams ``LEDGER_DIR`` shard by shard — the rotated
  generations plus the active file (``obs/ledger.py``) — keeping the
  torn-tail skip-and-count contract per shard, so a multi-gigabyte
  ledger never needs to fit in memory at once;
* ``candidate_texts``/``archive_groups`` lift a stored score
  completion's candidate set (the same candidate definition as
  ``archive/rescore.py::vote_matrix``: choices with no
  ``model_index``) into a re-embeddable text group.

``OfflineFeed.drive`` pumps the groups through
``DeviceBatcher.consensus(..., priority="offline")`` with a bounded
number of awaited futures in flight.  Keeping ``inflight >= 2`` groups
pending is what sustains near-100% device occupancy on an idle mesh:
the batcher always has a ready offline group the moment a pipeline
slot frees — while a latency arrival still preempts at the next
dispatch boundary, because the planner drains the latency queue first.
"""

from __future__ import annotations

import asyncio
import time
from typing import Iterable, Optional

from ..obs.ledger import ledger_shard_paths, read_shard_records


class LedgerFeed:
    """Shard-streaming reader over a ledger directory."""

    def __init__(self, disk_dir: str) -> None:
        self.disk_dir = disk_dir
        self.shards_read = 0
        self.torn = 0

    def paths(self) -> list:
        return ledger_shard_paths(self.disk_dir)

    def records(self) -> Iterable[dict]:
        """Yield every record across every shard, one shard resident at
        a time; torn lines accumulate on ``self.torn``."""
        for path in self.paths():
            records, torn = read_shard_records(path)
            self.shards_read += 1
            self.torn += torn
            yield from records


def candidate_texts(completion) -> list:
    """A stored score completion's candidate texts, in index order —
    the rows an offline re-embed dispatches.  Candidates are the
    choices without a ``model_index`` (vote_matrix's definition);
    judge choices never re-embed."""
    rows = []
    for choice in completion.choices:
        if choice.model_index is not None:
            continue
        content = getattr(choice.message, "content", None)
        if isinstance(content, str) and content:
            rows.append((choice.index, content))
    return [text for _, text in sorted(rows)]


def archive_groups(store, ids: Optional[list] = None) -> Iterable[list]:
    """Candidate text groups from the archive, skipping records too
    small to vote on (the consensus dispatch needs >= 2 candidates)."""
    for cid in list(ids if ids is not None else store.score_ids()):
        completion = store.score_completion(cid)
        if completion is None:
            continue
        texts = candidate_texts(completion)
        if len(texts) >= 2:
            yield texts


def synthetic_groups(n_groups: int, n_choices: int, seed: int = 0) -> list:
    """Deterministic word-salad candidate groups for drills/benches:
    saturating the offline lane must not depend on a populated archive."""
    words = (
        "alpha bravo charlie delta echo foxtrot golf hotel india juliet "
        "kilo lima mike november oscar papa quebec romeo sierra tango"
    ).split()
    state = seed * 2654435761 % (2**32) or 1
    groups = []
    for g in range(n_groups):
        group = []
        for c in range(n_choices):
            picks = []
            for _ in range(12):
                state = (state * 1103515245 + 12345) % (2**31)
                picks.append(words[state % len(words)])
            group.append(f"candidate {g}-{c}: " + " ".join(picks))
        groups.append(group)
    return groups


class OfflineFeed:
    """Drive candidate groups through the batcher's offline class."""

    def __init__(self, batcher, inflight: int = 4) -> None:
        self.batcher = batcher
        # awaited futures in flight: the feeder's only backpressure —
        # offline submissions bypass the latency lane's queue-depth
        # shed, so this bound is what keeps the offline queue from
        # swallowing a whole archive at once
        self.inflight = max(1, int(inflight))
        self.groups = 0
        self.items = 0
        self.errors = 0

    async def drive(self, groups: Iterable[list], temperature: float = 0.05):
        """Pump every group through ``consensus(priority="offline")``;
        returns ``(results, occupancy)`` where occupancy is the merged
        fraction of the drive window the offline lane held the device
        (``DeviceBatcher.lane_occupancy``).  Failed groups count in
        ``errors`` and return None in their slot — an offline feed
        outlives individual dispatch faults."""
        sem = asyncio.Semaphore(self.inflight)
        results: list = []
        tasks: list = []

        async def one(slot: int, texts: list):
            try:
                results[slot] = await self.batcher.consensus(
                    texts, temperature, priority="offline"
                )
                self.items += len(texts)
            except Exception:
                self.errors += 1
            finally:
                sem.release()

        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        for texts in groups:
            await sem.acquire()
            results.append(None)
            self.groups += 1
            tasks.append(loop.create_task(one(len(results) - 1, texts)))
        if tasks:
            await asyncio.gather(*tasks)
        occupancy = self.batcher.lane_occupancy("offline", t0)
        return results, occupancy
