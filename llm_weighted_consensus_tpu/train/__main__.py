"""CLI for the offline lane: ``python -m llm_weighted_consensus_tpu.train``.

Two subcommands (ISSUE 20 tentpole piece b):

``fit``
    Stream the ledger shards under ``--ledger-dir`` (default:
    ``LEDGER_DIR``) through the batched JAX learner (``train/fit.py``)
    and print the versioned weights report.  ``--out`` writes the
    table in the ``lwc.weights.v1`` format ``WEIGHTS_PATH`` loads at
    startup; ``--put`` hot-swaps it into a RUNNING server via
    PUT /v1/weights (zero-restart promotion).

``rescore``
    Saturate the offline priority class: build the env-configured
    embedder (the same ``build_embedder`` the server uses), assemble
    candidate groups from the ``ARCHIVE_PATH`` snapshot — or
    ``--synthetic N`` deterministic groups — and drive them through
    ``DeviceBatcher.consensus(priority="offline")``.  Prints the
    groups/items pushed and the merged offline device occupancy, the
    near-100%-on-an-idle-mesh acceptance gauge.
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ..utils import jsonutil


def _cmd_fit(args) -> int:
    from .fit import fit_from_ledger

    if not args.ledger_dir:
        # knobs enter through Config.from_env (LWC008) — the CLI default
        # is the server's own LEDGER_DIR so fit trains on what serve wrote
        from ..serve.config import Config

        args.ledger_dir = Config.from_env().ledger_dir
    if not args.ledger_dir:
        print(
            "fit: no ledger directory (--ledger-dir or LEDGER_DIR)",
            file=sys.stderr,
        )
        return 2
    labels = None
    if args.labels:
        with open(args.labels, encoding="utf-8") as f:
            labels = {
                str(k): int(v) for k, v in jsonutil.loads(f.read()).items()
            }
    report = fit_from_ledger(
        args.ledger_dir,
        labels=labels,
        steps=args.steps,
        lr=args.lr,
        holdout_every=args.holdout_every,
    )
    if report is None:
        print("fit: no trainable records in the ledger", file=sys.stderr)
        return 1
    if args.out:
        from ..utils.io import atomic_write

        doc = {
            "schema": "lwc.weights.v1",
            "active": {
                "version": report["version"],
                "weights": {
                    k: str(v) for k, v in report["weights"].items()
                },
            },
            "shadow": None,
        }
        payload = jsonutil.dumps(doc).encode("utf-8")
        atomic_write(args.out, lambda f: f.write(payload))
    if args.put:
        import urllib.request

        body = jsonutil.dumps(
            {
                "version": report["version"],
                "weights": report["weights"],
                "mode": args.put_mode,
            }
        ).encode("utf-8")
        req = urllib.request.Request(
            args.put.rstrip("/") + "/v1/weights",
            data=body,
            method="PUT",
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            report["put"] = jsonutil.loads(resp.read().decode("utf-8"))
    print(jsonutil.dumps(report))
    return 0


async def _rescore_async(args) -> dict:
    from ..serve.__main__ import build_embedder
    from ..serve.batcher import DeviceBatcher
    from ..serve.config import Config
    from .feed import OfflineFeed, archive_groups, synthetic_groups

    config = Config.from_env()
    embedder = build_embedder(config, allow_synthetic=True)
    if embedder is None:
        raise SystemExit("rescore: no embedder configured (EMBED_MODEL)")
    batcher = DeviceBatcher(
        embedder,
        None,
        window_ms=config.batch_window_ms,
        max_batch=config.batch_max,
        pipeline_depth=config.batch_pipeline,
        max_rows=config.batch_max_rows,
        packing=config.packing_enabled,
        packing_row_tokens=config.packing_row_tokens,
        packing_max_rows=config.packing_max_rows,
        packing_max_segments=config.packing_max_segments,
        host_tokenizer_workers=config.host_tokenizer_workers,
        staging_buffers=config.staging_buffers,
    )
    try:
        if args.synthetic:
            groups = synthetic_groups(args.synthetic, args.n, seed=args.seed)
        else:
            import os

            from .. import archive

            if not config.archive_path or not os.path.exists(
                config.archive_path
            ):
                raise SystemExit(
                    "rescore: no archive snapshot (ARCHIVE_PATH) — "
                    "use --synthetic N for a synthetic feed"
                )
            store = archive.InMemoryArchive.load(config.archive_path)
            groups = list(archive_groups(store))
        feed = OfflineFeed(batcher, inflight=args.inflight)
        import time

        t0 = time.perf_counter()
        _results, occupancy = await feed.drive(groups)
        wall = time.perf_counter() - t0
        util = batcher.utilization()
        return {
            "groups": feed.groups,
            "items": feed.items,
            "errors": feed.errors,
            "wall_sec": round(wall, 3),
            "offline_occupancy": occupancy,
            "lanes": util["lanes"],
        }
    finally:
        batcher.close()


def _cmd_rescore(args) -> int:
    stats = asyncio.run(_rescore_async(args))
    print(jsonutil.dumps(stats))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llm_weighted_consensus_tpu.train"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    fit = sub.add_parser("fit", help="fit per-judge weights from the ledger")
    fit.add_argument(
        "--ledger-dir", default=None, help="default: the server's LEDGER_DIR"
    )
    fit.add_argument(
        "--labels",
        default=None,
        help="JSON file mapping record id -> candidate label (supervised); "
        "without it, records score self-consistently against their winner",
    )
    fit.add_argument("--steps", type=int, default=300)
    fit.add_argument("--lr", type=float, default=0.1)
    fit.add_argument("--holdout-every", type=int, default=4)
    fit.add_argument(
        "--out", default=None, help="write the lwc.weights.v1 table here"
    )
    fit.add_argument(
        "--put",
        default=None,
        help="base URL of a running server to hot-swap via PUT /v1/weights",
    )
    fit.add_argument("--put-mode", choices=("active", "shadow"), default="active")
    fit.set_defaults(run=_cmd_fit)

    rescore = sub.add_parser(
        "rescore", help="drive archive/synthetic groups through the offline lane"
    )
    rescore.add_argument(
        "--synthetic",
        type=int,
        default=0,
        help="drive N deterministic synthetic groups instead of the archive",
    )
    rescore.add_argument("--n", type=int, default=8, help="candidates per group")
    rescore.add_argument("--seed", type=int, default=0)
    rescore.add_argument("--inflight", type=int, default=4)
    rescore.set_defaults(run=_cmd_rescore)

    args = parser.parse_args(argv)
    return args.run(args)


if __name__ == "__main__":
    sys.exit(main())
