"""The offline training subsystem (ISSUE 20): lane feed, weight
learner, and the model trainers.

The serving engine closes its own loop here — serve -> ledger ->
learn -> serve with better weights:

* ``train.feed``  — streams ``LEDGER_DIR`` shards and archive records
  into the batcher's **offline priority class** (``priority="offline"``
  on ``DeviceBatcher.embed/consensus``): full-width dispatches that
  only run when the latency lane has no ready group;
* ``train.fit``   — fits per-judge consensus weights as one batched
  JAX softmax optimization over every ledger record (dp-sharded on the
  serving mesh as an offline-lane tenant), emitting the versioned
  table ``weights/live.py`` hot-swaps via PUT /v1/weights;
* ``__main__``    — ``python -m llm_weighted_consensus_tpu.train
  rescore|fit``, the operator CLI for both.

This module keeps the lower-level model trainers the trained-weight
path needs:

* ``contrastive_train_step`` — bge-style InfoNCE over (query, positive)
  pairs with in-batch negatives: the recipe that produces the embedding
  tables behind training-table weights;
* ``reward_train_step``      — pairwise Bradley-Terry loss on
  (chosen, rejected) candidate pairs for the DeBERTa RM (config 3).

Both are single jitted steps over a mesh: batch sharded over ``dp``,
gradients all-reduced by XLA (replicated params => psum on the backward
pass), optional encoder TP via parallel.sharding.  Checkpointing is orbax
on the param pytree (see ``save_checkpoint``/``load_checkpoint``).
"""

from __future__ import annotations

__all__ = [
    "make_optimizer",
    "contrastive_loss",
    "contrastive_train_step",
    "reward_pairwise_loss",
    "reward_train_step",
    "save_checkpoint",
    "load_checkpoint",
    "save_train_state",
    "load_train_state",
]

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from ..models import bert
from ..models.configs import BertConfig, DebertaConfig


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, weight_decay=weight_decay)


# ---------------------------------------------------------------------------
# Contrastive encoder training (InfoNCE, in-batch negatives)
# ---------------------------------------------------------------------------


def contrastive_loss(
    params, q_ids, q_mask, p_ids, p_mask, config: BertConfig, temperature=0.05
):
    q = bert.embed(params, q_ids, q_mask, config, pooling="cls")
    p = bert.embed(params, p_ids, p_mask, config, pooling="cls")
    logits = (
        jnp.einsum(
            "bd,cd->bc",
            q,
            p,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        / temperature
    )
    labels = jnp.arange(q.shape[0])
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(loss)


@partial(jax.jit, static_argnames=("config", "optimizer"), donate_argnums=(0, 1))
def contrastive_train_step(
    params, opt_state, q_ids, q_mask, p_ids, p_mask, config: BertConfig, optimizer
):
    """One InfoNCE step; params/opt_state donated for in-place updates."""
    loss, grads = jax.value_and_grad(contrastive_loss)(
        params, q_ids, q_mask, p_ids, p_mask, config
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


# ---------------------------------------------------------------------------
# Reward-model pairwise training
# ---------------------------------------------------------------------------


def reward_pairwise_loss(
    params, chosen_ids, chosen_mask, rejected_ids, rejected_mask, config
):
    from ..models import deberta

    r_chosen = deberta.reward(params, chosen_ids, chosen_mask, config)
    r_rejected = deberta.reward(params, rejected_ids, rejected_mask, config)
    # Bradley-Terry: -log sigmoid(r_chosen - r_rejected)
    return jnp.mean(jax.nn.softplus(-(r_chosen - r_rejected)))


@partial(jax.jit, static_argnames=("config", "optimizer"), donate_argnums=(0, 1))
def reward_train_step(
    params,
    opt_state,
    chosen_ids,
    chosen_mask,
    rejected_ids,
    rejected_mask,
    config: DebertaConfig,
    optimizer,
):
    loss, grads = jax.value_and_grad(reward_pairwise_loss)(
        params, chosen_ids, chosen_mask, rejected_ids, rejected_mask, config
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


# ---------------------------------------------------------------------------
# Checkpointing (orbax)
# ---------------------------------------------------------------------------


def save_checkpoint(path: str, params) -> None:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, params, force=True)


def load_checkpoint(path: str, like=None):
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(path, like)
        return ckptr.restore(path)


def save_train_state(path: str, params, opt_state, step: int) -> None:
    """Checkpoint the FULL training state — params, optimizer state
    (adamw moments/counts), and step — so an interrupted run resumes
    bit-comparably.  Params-only checkpoints (``save_checkpoint``) are
    the serving artifact; resuming training from one silently resets the
    Adam moments and changes the trajectory."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            path,
            {
                "params": params,
                "opt_state": opt_state,
                "step": jnp.asarray(step, jnp.int32),
            },
            force=True,
        )


def load_train_state(path: str, like_params, like_opt_state):
    """Restore (params, opt_state, step) saved by ``save_train_state``.
    ``like_*`` provide the pytree structure (build them exactly as the
    original run did: init_params + optimizer.init)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(
            path,
            {
                "params": like_params,
                "opt_state": like_opt_state,
                "step": jnp.asarray(0, jnp.int32),
            },
        )
    return state["params"], state["opt_state"], int(state["step"])
