"""Fit per-judge consensus weights from ledger shards (ISSUE 20
tentpole piece c).

The model is the serving tally itself: a candidate's score is the
weighted sum of the panel's soft votes, so the learner fits the weight
vector ``w`` that makes the tally's argmax match the labels —

    logits[r, i] = sum_j w_j * vote[r, j, i]        (w_j = softplus(theta_j))
    loss         = masked softmax cross-entropy(logits, label[r])

batched over every ledger record at once as ONE JAX optimization
(optax adam on ``theta``), optionally dp-sharded over the record axis
on the serving mesh — the learner is an offline-lane tenant, never a
second device owner.

Labels per record, in priority order: an explicit ``labels`` mapping
(record id -> candidate index, the supervised drill), the record's own
``label`` field, else the record's consensus ``winner`` —
self-consistency, the same fallback scoring rule as
``weights/learning.py::judge_alignment_scores``.

The emitted table is versioned by content (``weights/live.py``) and
hot-swaps into the scoring path via PUT /v1/weights.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


class Dataset:
    """Dense tensors over ``R`` records x ``J`` judges x ``N`` candidates."""

    __slots__ = (
        "votes", "vote_mask", "cand_mask", "labels", "sample_weight",
        "judge_ids", "base_weights", "skipped",
    )

    def __init__(
        self, votes, vote_mask, cand_mask, labels, sample_weight,
        judge_ids, base_weights, skipped,
    ) -> None:
        self.votes = votes            # [R, J, N] f32
        self.vote_mask = vote_mask    # [R, J]    f32 — judge voted
        self.cand_mask = cand_mask    # [R, N]    f32 — candidate exists
        self.labels = labels          # [R]       i32
        self.sample_weight = sample_weight  # [R] f32 — 0 = padding
        self.judge_ids = judge_ids    # [J] stable sorted judge ids
        # mean observed serving weight per judge: the "base" baseline
        # the fitted table is evaluated against
        self.base_weights = base_weights  # [J] f32
        self.skipped = skipped

    @property
    def n_records(self) -> int:
        return int(self.sample_weight.sum())

    def subset(self, index) -> "Dataset":
        return Dataset(
            self.votes[index], self.vote_mask[index], self.cand_mask[index],
            self.labels[index], self.sample_weight[index],
            self.judge_ids, self.base_weights, self.skipped,
        )


def _record_label(record: dict, labels: Optional[dict]):
    if labels is not None:
        label = labels.get(record.get("id"))
        if label is not None:
            return int(label)
    label = record.get("label")
    if label is not None:
        return int(label)
    winner = record.get("winner")
    return int(winner) if winner is not None else None


def build_dataset(
    records: Iterable[dict], labels: Optional[dict] = None
) -> Optional[Dataset]:
    """Ledger records -> dense training tensors.  Records with no
    usable label, fewer than two candidates, or no voting judge are
    skipped and counted — all-failed records can never vote."""
    rows = []
    skipped = 0
    judge_ids: set = set()
    max_n = 0
    for record in records:
        label = _record_label(record, labels)
        n = int(record.get("n_choices") or 0)
        judges = [
            j
            for j in (record.get("judges") or [])
            if j.get("model") and j.get("vote")
        ]
        if (
            record.get("all_failed")
            or n < 2
            or label is None
            or not (0 <= label < n)
            or not judges
        ):
            skipped += 1
            continue
        rows.append((label, n, judges))
        max_n = max(max_n, n)
        judge_ids.update(j["model"] for j in judges)
    if not rows:
        return None
    ids = sorted(judge_ids)
    index = {jid: k for k, jid in enumerate(ids)}
    R, J, N = len(rows), len(ids), max_n
    votes = np.zeros((R, J, N), np.float32)
    vote_mask = np.zeros((R, J), np.float32)
    cand_mask = np.zeros((R, N), np.float32)
    out_labels = np.zeros(R, np.int32)
    base_sum = np.zeros(J, np.float64)
    base_count = np.zeros(J, np.float64)
    for r, (label, n, judges) in enumerate(rows):
        out_labels[r] = label
        cand_mask[r, :n] = 1.0
        for judge in judges:
            k = index[judge["model"]]
            vote = judge["vote"][:n]
            votes[r, k, : len(vote)] = np.asarray(vote, np.float32)
            vote_mask[r, k] = 1.0
            weight = judge.get("weight")
            if weight is not None:
                base_sum[k] += float(weight)
                base_count[k] += 1.0
    base = np.where(base_count > 0, base_sum / np.maximum(base_count, 1), 1.0)
    return Dataset(
        votes, vote_mask, cand_mask, out_labels, np.ones(R, np.float32),
        ids, base.astype(np.float32), skipped,
    )


def tally_accuracy(dataset: Dataset, weights: np.ndarray) -> float:
    """Held-out consensus accuracy under a weight vector: the fraction
    of records whose weighted-tally argmax equals the label.  Pure
    numpy — the evaluation must not depend on the fit's device."""
    w = np.asarray(weights, np.float32)
    scores = np.einsum(
        "rjn,j,rj->rn", dataset.votes, w, dataset.vote_mask
    )
    scores = np.where(dataset.cand_mask > 0, scores, -np.inf)
    hit = (scores.argmax(axis=1) == dataset.labels).astype(np.float64)
    denom = float(dataset.sample_weight.sum())
    return float((hit * dataset.sample_weight).sum() / denom) if denom else 0.0


def fit_weights(
    dataset: Dataset,
    steps: int = 300,
    lr: float = 0.1,
    l2: float = 1e-4,
    mesh=None,
) -> np.ndarray:
    """One batched JAX optimization over every record -> per-judge
    weight vector (positive via softplus, mean-normalized to 1.0 so the
    fitted table composes with [min,max] clamps the way static weights
    do).  ``mesh`` dp-shards the record axis on the serving mesh; None
    runs wherever JAX defaults (CPU in tier-1)."""
    import jax
    import jax.numpy as jnp
    import optax

    votes = jnp.asarray(dataset.votes)
    vote_mask = jnp.asarray(dataset.vote_mask)
    cand_mask = jnp.asarray(dataset.cand_mask)
    labels = jnp.asarray(dataset.labels)
    sample_weight = jnp.asarray(dataset.sample_weight)
    if mesh is not None and "dp" in getattr(mesh, "axis_names", ()):
        # offline-lane tenancy: records shard over dp, the tiny theta
        # replicates; a ragged tail pads with zero-sample_weight rows
        # (loss-invisible) so the leading dim divides the mesh
        from jax.sharding import NamedSharding, PartitionSpec

        dp = mesh.shape["dp"]
        R = votes.shape[0]
        pad = (-R) % dp
        if pad:
            votes = jnp.pad(votes, ((0, pad), (0, 0), (0, 0)))
            vote_mask = jnp.pad(vote_mask, ((0, pad), (0, 0)))
            cand_mask = jnp.pad(
                cand_mask, ((0, pad), (0, 0)), constant_values=1.0
            )
            labels = jnp.pad(labels, (0, pad))
            sample_weight = jnp.pad(sample_weight, (0, pad))
        shard = NamedSharding(mesh, PartitionSpec("dp"))
        votes = jax.device_put(votes, shard)
        vote_mask = jax.device_put(vote_mask, shard)
        cand_mask = jax.device_put(cand_mask, shard)
        labels = jax.device_put(labels, shard)
        sample_weight = jax.device_put(sample_weight, shard)

    def loss_fn(theta):
        w = jax.nn.softplus(theta)
        logits = jnp.einsum("rjn,j,rj->rn", votes, w, vote_mask)
        logits = jnp.where(cand_mask > 0, logits, -1e9)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        denom = jnp.maximum(sample_weight.sum(), 1.0)
        return (ce * sample_weight).sum() / denom + l2 * jnp.sum(theta**2)

    optimizer = optax.adam(lr)
    theta = jnp.zeros(len(dataset.judge_ids), jnp.float32)
    opt_state = optimizer.init(theta)

    @jax.jit
    def step(theta, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(theta)
        updates, opt_state = optimizer.update(grads, opt_state)
        return optax.apply_updates(theta, updates), opt_state, loss

    for _ in range(max(1, int(steps))):
        theta, opt_state, _loss = step(theta, opt_state)
    w = np.asarray(jax.nn.softplus(theta), np.float64)
    mean = w.mean() or 1.0
    return (w / mean).astype(np.float32)


def holdout_split(dataset: Dataset, every: int = 4) -> tuple:
    """Deterministic (train, holdout) split: every ``every``-th record
    holds out — reproducible across processes with no RNG to seed."""
    index = np.arange(dataset.votes.shape[0])
    hold = index % max(2, int(every)) == 0
    return dataset.subset(~hold), dataset.subset(hold)


def fit_from_records(
    records: Iterable[dict],
    labels: Optional[dict] = None,
    steps: int = 300,
    lr: float = 0.1,
    holdout_every: int = 4,
    mesh=None,
) -> Optional[dict]:
    """records -> the versioned weights report: the fitted table plus
    held-out consensus accuracy under fitted / uniform / base weights
    — the measurable-improvement evidence the learner drill asserts."""
    dataset = build_dataset(records, labels)
    if dataset is None:
        return None
    train, hold = holdout_split(dataset, holdout_every)
    if train.n_records == 0 or hold.n_records == 0:
        train = hold = dataset
    fitted = fit_weights(train, steps=steps, lr=lr, mesh=mesh)
    uniform = np.ones(len(dataset.judge_ids), np.float32)
    weights = {
        jid: round(float(w), 6)
        for jid, w in zip(dataset.judge_ids, fitted)
    }
    from ..weights.live import weights_version

    return {
        "version": weights_version(weights),
        "weights": weights,
        "judges": list(dataset.judge_ids),
        "records": dataset.n_records,
        "train_records": train.n_records,
        "holdout_records": hold.n_records,
        "skipped": dataset.skipped,
        "accuracy": {
            "fitted": round(tally_accuracy(hold, fitted), 4),
            "uniform": round(tally_accuracy(hold, uniform), 4),
            "base": round(tally_accuracy(hold, dataset.base_weights), 4),
        },
    }


def fit_from_ledger(
    disk_dir: str,
    labels: Optional[dict] = None,
    steps: int = 300,
    lr: float = 0.1,
    holdout_every: int = 4,
    mesh=None,
) -> Optional[dict]:
    """The CLI entry: stream every shard under ``disk_dir`` through
    ``build_dataset`` and fit.  Torn-line counts ride the report."""
    from .feed import LedgerFeed

    feed = LedgerFeed(disk_dir)
    report = fit_from_records(
        feed.records(),
        labels,
        steps=steps,
        lr=lr,
        holdout_every=holdout_every,
        mesh=mesh,
    )
    if report is not None:
        report["shards"] = feed.shards_read
        report["torn"] = feed.torn
    return report
