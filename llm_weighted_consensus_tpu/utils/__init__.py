"""Host-side utilities: canonical JSON, choice indexing, id generation."""

from __future__ import annotations

import itertools
import threading
import uuid

from . import jsonutil  # noqa: F401


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1) — THE runtime R-bucketing rule.

    One definition shared by the grouped dispatch
    (models/embedder.py::consensus_confidence_tokens_many), the batcher's
    chunker (serve/batcher.py::_pow2_chunks) and the WARMUP_R snapping
    (serve/config.py): the warmup's value depends on pre-compiling exactly
    the buckets traffic hits, so the snap must never drift."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return bucket


def env_truthy(value) -> bool:
    """The framework's one definition of an env-flag truthy value."""
    return str(value).lower() in ("1", "true", "yes", "on")


class ChoiceIndexer:
    """Global choice-index allocator keyed ``(judge_index, native_index)``.

    Parity target: reference src/util.rs:5-31 (AtomicU64 + DashMap).  The
    consensus engine re-indexes every judge's native choice indices into one
    global choice space starting after the N candidate slots; allocation order
    follows chunk arrival order, exactly like the reference.  Python's GIL +
    a lock replace the lock-free structure; the allocator is also used from
    a single event loop so contention is nil.
    """

    def __init__(self, initial: int):
        self._counter = itertools.count(initial)
        self._indices: dict[tuple[int, int], int] = {}
        self._lock = threading.Lock()

    def get(self, judge_index: int, native_choice_index: int) -> int:
        key = (judge_index, native_choice_index)
        with self._lock:
            index = self._indices.get(key)
            if index is None:
                index = next(self._counter)
                self._indices[key] = index
            return index


def response_id(prefix: str, created: int) -> str:
    """``{prefix}-{uuid}-{created}`` (score client.rs:22-25 uses ``scrcpl``)."""
    return f"{prefix}-{uuid.uuid4().hex}-{created}"
