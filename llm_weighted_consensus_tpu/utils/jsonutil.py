"""Canonical JSON serialization with exact ``Decimal`` support.

The reference crate serializes every wire type with serde_json and hashes the
resulting canonical string to derive content-addressed model identities
(reference: src/score/llm/mod.rs:513-518).  Python's stdlib ``json`` cannot emit
``decimal.Decimal`` values as bare JSON numbers without precision loss, so this
module implements a small, fully deterministic JSON writer:

* declared field order is preserved (dicts keep insertion order),
* ``Decimal`` values are emitted verbatim (``1.0`` stays ``1.0``, not ``1``),
* floats are emitted with ``repr`` (shortest round-trip form),
* no whitespace (serde_json compact form) unless ``pretty=True`` (serde_json
  ``to_string_pretty`` form: 2-space indent), which the ballot serializer needs
  (reference: src/score/completions/client.rs:1580-1603).

The identity scheme built on top of this writer is *structurally* equivalent to
the reference's (same canonicalized fields, same xxh3-128 + base62 pipeline) but
not byte-compatible with rust_decimal/serde formatting; ids are therefore
versioned as this framework's own id space (see identity/__init__.py).
"""

from __future__ import annotations

import json
import math
from decimal import Decimal

_ESCAPE_MAP = {
    "\\": "\\\\",
    '"': '\\"',
    "\b": "\\b",
    "\f": "\\f",
    "\n": "\\n",
    "\r": "\\r",
    "\t": "\\t",
}


def _escape_string_py(s: str) -> str:
    """Reference implementation of the escaping contract (backslash,
    quote, the five short escapes, \\u%04x for other controls, everything
    else verbatim).  Kept as the spec + fallback; the live path below is
    stdlib's C ``encode_basestring``, which implements the same mapping
    (fuzz-pinned byte-identical in tests/test_jsonutil.py) ~7x faster —
    it was the top host hotspot in a profiled scored request (per-judge
    pretty ballot serialization escapes ~1k strings per request)."""
    out = []
    for ch in s:
        esc = _ESCAPE_MAP.get(ch)
        if esc is not None:
            out.append(esc)
        elif ch < "\x20":
            out.append("\\u%04x" % ord(ch))
        else:
            out.append(ch)
    return '"' + "".join(out) + '"'


try:
    from json.encoder import encode_basestring as _escape_string
except ImportError:  # pragma: no cover - stdlib always has it
    _escape_string = _escape_string_py


def _format_decimal(d: Decimal) -> str:
    # Emit the decimal exactly as constructed, in plain (non-scientific)
    # notation, matching rust_decimal's display form.
    if not d.is_finite():
        raise ValueError("cannot serialize non-finite Decimal to JSON")
    return format(d, "f")


def _format_float(f: float) -> str:
    if math.isnan(f) or math.isinf(f):
        raise ValueError("cannot serialize non-finite float to JSON")
    # float.__repr__ is the shortest round-trip form; whole numbers print
    # as `1.0`, matching serde_json's f64 output.  The UNBOUND call
    # matters: float subclasses (np.float64 under numpy>=2 reprs as
    # 'np.float64(1.5)') must format like the stdlib fast path, which
    # also uses float.__repr__ — both paths stay byte-identical.
    return float.__repr__(f)


def scalar_token(obj) -> "str | None":
    """The exact token the compact writer emits for a JSON scalar, or
    ``None`` when ``obj`` is not a scalar (containers, unknown types).

    This is ``_write_scalar``'s dispatch factored out for the splice
    serializer (types/base.py SpliceEncoder): frame assembly from byte
    templates must format every leaf exactly as ``dumps`` would, or the
    fast lane's byte-identity contract breaks.  Order matters — ``bool``
    before ``int`` (bool is an int subclass), ``Decimal`` before the
    numeric tower."""
    if obj is None:
        return "null"
    if obj is True:
        return "true"
    if obj is False:
        return "false"
    if isinstance(obj, str):
        return _escape_string(obj)
    if isinstance(obj, Decimal):
        return _format_decimal(obj)
    if isinstance(obj, int):
        return int.__repr__(obj)
    if isinstance(obj, float):
        return _format_float(obj)
    return None


def dumps(obj, *, pretty: bool = False) -> str:
    """Serialize ``obj`` (dict/list/str/bool/None/int/float/Decimal) to JSON.

    Decimal-free payloads take the C-accelerated stdlib encoder, whose
    compact output is byte-identical to this module's writer (same float
    repr, same string escaping with ensure_ascii=False, same non-finite
    rejection) and ~10x faster on the string/float-heavy serving
    responses (embeddings, SSE frames).  Anything stdlib cannot encode
    (Decimal) falls back to the exact writer below, which emits dict keys
    (str/int/float/bool/None) byte-identically to stdlib's coercion and
    rejects other key types with the same TypeError stdlib raises."""
    if not pretty:
        try:
            # stdlib raises TypeError on any type it doesn't know
            # (Decimal included) — no default hook needed
            return json.dumps(
                obj,
                separators=(",", ":"),
                ensure_ascii=False,
                allow_nan=False,
            )
        except TypeError:
            pass  # Decimal somewhere: exact writer required
        except ValueError as exc:
            # stdlib's ValueError covers two distinct cases: non-finite
            # floats (fall through so the writer raises this module's
            # contract error) and circular references (re-raise — the
            # recursive writer must never be handed a cycle).
            if "circular" in str(exc).lower():
                raise
    out: list[str] = []
    if pretty:
        _write_pretty(obj, out, 0, set())
    else:
        _write_compact(obj, out, set())
    return "".join(out)


class _EmitBuffer:
    """Bounded segment buffer quacking like the writer's ``out`` list.

    ``_write_compact`` only ever calls ``out.append``, so handing it this
    buffer streams the exact compact byte sequence through ``emit`` in
    joined chunks — no full canonical string is ever materialized.  The
    cache fingerprint path (cache/fingerprint.py) feeds chunks straight
    into the incremental request hasher."""

    __slots__ = ("_emit", "_buf", "_size", "_limit")

    def __init__(self, emit, limit: int):
        self._emit = emit
        self._buf: list[str] = []
        self._size = 0
        self._limit = limit

    def append(self, segment: str) -> None:
        self._buf.append(segment)
        self._size += len(segment)
        if self._size >= self._limit:
            self._emit("".join(self._buf))
            self._buf.clear()
            self._size = 0

    def flush(self) -> None:
        if self._buf:
            self._emit("".join(self._buf))
            self._buf.clear()
            self._size = 0


def dump_into(obj, emit, *, chunk_chars: int = 8192) -> None:
    """Stream ``dumps(obj)`` (compact form) through ``emit(str)`` in
    bounded chunks, byte-identical to the full-string writer — same
    recursive walk, same segments, only the join boundaries differ."""
    buf = _EmitBuffer(emit, chunk_chars)
    _write_compact(obj, buf, set())
    buf.flush()


def _write_scalar(obj, out: list[str]) -> bool:
    if obj is None:
        out.append("null")
    elif obj is True:
        out.append("true")
    elif obj is False:
        out.append("false")
    elif isinstance(obj, str):
        out.append(_escape_string(obj))
    elif isinstance(obj, Decimal):
        out.append(_format_decimal(obj))
    elif isinstance(obj, int):
        # UNBOUND repr (same contract as _format_float/_key_str): an int
        # subclass with a custom __str__ must encode like the stdlib fast
        # path, not emit its display text as raw JSON
        out.append(int.__repr__(obj))
    elif isinstance(obj, float):
        out.append(_format_float(obj))
    else:
        return False
    return True


def _key_str(k) -> str:
    """Object key coerced exactly as the stdlib fast path coerces it
    (bool -> "true"/"false", None -> "null", float -> shortest repr), so a
    Decimal elsewhere in the payload can never flip the key encoding.
    Other key types get the same TypeError stdlib raises."""
    if isinstance(k, str):
        return k
    if k is True:
        return "true"
    if k is False:
        return "false"
    if k is None:
        return "null"
    if isinstance(k, float):
        return _format_float(k)
    if isinstance(k, int):
        # UNBOUND repr, same reason as _format_float: int subclasses with
        # a custom __str__/__repr__ must encode like the stdlib fast path
        return int.__repr__(k)
    raise TypeError(
        f"keys must be str, int, float, bool or None, not {type(k).__name__}"
    )


def _enter(obj, seen: set) -> None:
    if id(obj) in seen:
        raise ValueError("Circular reference detected")
    seen.add(id(obj))


def _write_compact(obj, out: list[str], seen: set) -> None:
    if _write_scalar(obj, out):
        return
    if isinstance(obj, dict):
        _enter(obj, seen)
        out.append("{")
        first = True
        for k, v in obj.items():
            if not first:
                out.append(",")
            first = False
            out.append(_escape_string(_key_str(k)))
            out.append(":")
            _write_compact(v, out, seen)
        out.append("}")
        seen.discard(id(obj))
    elif isinstance(obj, (list, tuple)):
        _enter(obj, seen)
        out.append("[")
        first = True
        for v in obj:
            if not first:
                out.append(",")
            first = False
            _write_compact(v, out, seen)
        out.append("]")
        seen.discard(id(obj))
    else:
        raise TypeError(f"cannot serialize {type(obj)!r} to JSON")


def _write_pretty(obj, out: list[str], indent: int, seen: set) -> None:
    if _write_scalar(obj, out):
        return
    pad = "  " * (indent + 1)
    end_pad = "  " * indent
    if isinstance(obj, dict):
        if not obj:
            out.append("{}")
            return
        _enter(obj, seen)
        out.append("{\n")
        first = True
        for k, v in obj.items():
            if not first:
                out.append(",\n")
            first = False
            out.append(pad)
            out.append(_escape_string(_key_str(k)))
            out.append(": ")
            _write_pretty(v, out, indent + 1, seen)
        out.append("\n")
        out.append(end_pad)
        out.append("}")
        seen.discard(id(obj))
    elif isinstance(obj, (list, tuple)):
        if not obj:
            out.append("[]")
            return
        _enter(obj, seen)
        out.append("[\n")
        first = True
        for v in obj:
            if not first:
                out.append(",\n")
            first = False
            out.append(pad)
            _write_pretty(v, out, indent + 1, seen)
        out.append("\n")
        out.append(end_pad)
        out.append("]")
        seen.discard(id(obj))
    else:
        raise TypeError(f"cannot serialize {type(obj)!r} to JSON")


def loads(s: str):
    """Parse JSON preserving exact decimal literals as ``Decimal``."""
    return json.loads(s, parse_float=Decimal)
