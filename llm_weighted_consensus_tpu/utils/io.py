"""Small filesystem helpers shared by the snapshot writers."""

from __future__ import annotations

import os


def atomic_write(path: str, write_fn) -> None:
    """Write via a temp file + ``os.replace`` so readers never observe a
    truncated file and concurrent writers can't corrupt each other.
    ``write_fn(f)`` receives the open binary file object."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def probe_writable(path: str) -> None:
    """Fail fast (OSError) if ``path`` cannot be written, without writing
    anything expensive: create + remove a tiny sibling temp file."""
    probe = f"{path}.probe.{os.getpid()}"
    with open(probe, "wb") as f:
        f.write(b"")
    os.unlink(probe)


def probe_writable_config(path: str, env_name: str, consequence: str) -> None:
    """``probe_writable`` for an env-configured snapshot path: failures
    name the variable and what would be lost, so a startup refusal points
    the operator at the fix."""
    try:
        probe_writable(path)
    except OSError as exc:
        raise OSError(
            f"{env_name}={path!r} is not writable ({exc}); {consequence}"
        ) from exc
