"""Loader for the framework's native (C++) runtime library.

One shared library (``native/liblwc_native.so``) carries every native
component — the SSE parser and the WordPiece and unigram/SentencePiece
tokenizers — compiled on first use from the sources in ``native/``.  The compile goes to a temp file then
``os.replace`` so concurrent builders can't hand anyone a truncated .so
(and processes that already mapped the old inode keep it).  Loading is
blocking: call from sync startup code, never from the event loop.

``LWC_NATIVE=0`` disables all native paths (``LWC_NATIVE_SSE=0`` keeps
working for the SSE parser specifically, handled in clients/sse.py).
"""

from __future__ import annotations

import ctypes
import glob
import os
import subprocess
from typing import Optional

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
NATIVE_SO = os.path.join(NATIVE_DIR, "liblwc_native.so")

_lib = None
_tried = False


def _sources() -> list:
    return sorted(glob.glob(os.path.join(NATIVE_DIR, "*.cpp")))


def _stale(sources: list) -> bool:
    if not os.path.exists(NATIVE_SO):
        return True
    built = os.path.getmtime(NATIVE_SO)
    return any(os.path.getmtime(s) > built for s in sources)


def load_library() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None — remembered —
    when it can't be built/loaded or ``LWC_NATIVE=0``."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("LWC_NATIVE", "1").lower() in ("0", "false", "no"):
        return None
    try:
        sources = _sources()
        # no sources (a packaged deployment shipping only the .so) is fine:
        # load the prebuilt library as-is
        if sources and _stale(sources):
            tmp = f"{NATIVE_SO}.tmp.{os.getpid()}"
            subprocess.run(
                [
                    "g++", "-O3", "-std=c++17", "-fPIC", "-Wall",
                    "-shared", "-o", tmp, *sources,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, NATIVE_SO)
        if not os.path.exists(NATIVE_SO):
            return None
        _lib = ctypes.CDLL(NATIVE_SO)
    except Exception:
        _lib = None
    return _lib
