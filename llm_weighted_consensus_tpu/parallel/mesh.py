"""Mesh construction over data-parallel and tensor-parallel axes."""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(
    dp: Optional[int] = None, tp: int = 1, devices: Optional[list] = None
) -> Mesh:
    """A (dp, tp) mesh over the available devices.

    ``dp=None`` takes every device not consumed by ``tp``.  On real slices
    the device order from ``jax.devices()`` follows the ICI torus, so
    neighboring tp groups ride the fastest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if dp is None:
        dp = len(devices) // tp
    if dp < 1 or tp < 1:
        raise ValueError(f"mesh axes must be >= 1, got dp={dp} tp={tp}")
    n = dp * tp
    if n > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {n} devices, have {len(devices)}"
        )
    grid = np.array(devices[:n]).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    return make_mesh(dp=n, tp=1)
