"""Mesh construction over data-parallel and tensor-parallel axes."""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    devices: Optional[list] = None,
    names: tuple = ("dp", "tp"),
) -> Mesh:
    """A 2-axis mesh over the available devices (axis names default to
    (dp, tp); sequence-parallel serving reuses this with ("dp", "sp")).

    ``dp=None`` takes every device not consumed by the inner axis.  On
    real slices the device order from ``jax.devices()`` follows the ICI
    torus, so neighboring inner-axis groups ride the fastest links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if tp < 1:  # before the auto-fill division below
        raise ValueError(f"mesh axes must be >= 1, got {names[1]}={tp}")
    if dp is None:
        dp = len(devices) // tp
    if dp < 1:
        # include the other axis: an auto-filled dp=0 means the INNER axis
        # exceeded the device count, which is the user's actual mistake
        raise ValueError(
            f"mesh axes must be >= 1, got {names[0]}={dp} {names[1]}={tp} "
            f"over {len(devices)} devices"
        )
    n = dp * tp
    if n > len(devices):
        raise ValueError(
            f"mesh {dp}x{tp} needs {n} devices, have {len(devices)}"
        )
    grid = np.array(devices[:n]).reshape(dp, tp)
    return Mesh(grid, names)


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    return make_mesh(dp=n, tp=1)
