"""Mesh construction over data-parallel and tensor-parallel axes."""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    devices: Optional[list] = None,
    names: tuple = ("dp", "tp"),
    sp: int = 1,
) -> Mesh:
    """A 2-axis mesh over the available devices (axis names default to
    (dp, tp); sequence-parallel serving reuses this with ("dp", "sp")).

    ``sp > 1`` opts into a third, innermost sequence-parallel axis: the
    grid becomes (dp, tp, sp) with names ``names + ("sp",)``.  ``sp=1``
    is byte-identical to the historical 2-axis mesh — no third axis is
    materialized, so every (dp, tp) consumer downstream is untouched.

    ``dp=None`` takes every device not consumed by the inner axes.  On
    real slices the device order from ``jax.devices()`` follows the ICI
    torus, so neighboring inner-axis groups ride the fastest links —
    with sp innermost, the ring permutation stays on nearest neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    if tp < 1:  # before the auto-fill division below
        raise ValueError(f"mesh axes must be >= 1, got {names[1]}={tp}")
    if sp < 1:
        raise ValueError(f"mesh axes must be >= 1, got sp={sp}")
    inner = tp * sp
    if dp is None:
        dp = len(devices) // inner
    if dp < 1:
        # include the other axes: an auto-filled dp=0 means the INNER
        # axes exceeded the device count, which is the user's actual
        # mistake
        raise ValueError(
            f"mesh axes must be >= 1, got {names[0]}={dp} {names[1]}={tp} "
            + (f"sp={sp} " if sp > 1 else "")
            + f"over {len(devices)} devices"
        )
    n = dp * inner
    if n > len(devices):
        shape = f"{dp}x{tp}" + (f"x{sp}" if sp > 1 else "")
        raise ValueError(
            f"mesh {shape} needs {n} devices, have {len(devices)}"
        )
    if sp > 1:
        grid = np.array(devices[:n]).reshape(dp, tp, sp)
        return Mesh(grid, tuple(names) + ("sp",))
    grid = np.array(devices[:n]).reshape(dp, tp)
    return Mesh(grid, names)


def data_parallel_mesh(n: Optional[int] = None) -> Mesh:
    return make_mesh(dp=n, tp=1)
