"""Two-process DCN smoke: actually form a ``jax.distributed`` group.

`parallel.dist.maybe_initialize_distributed` is the multi-host entry point;
this module proves it forms a real process group without TPU pod hardware:
N CPU processes (one virtual device each) rendezvous at a localhost
coordinator, build ONE GLOBAL mesh over ``jax.devices()``, and run the
``sharded_tally`` consensus reduction with the cross-process psum riding
the distributed backend — the same code path that rides DCN on a pod
(SURVEY §2.8 "DCN for multi-host slices"; DESIGN.md §multi-host).

Two entry points:

* ``python -m llm_weighted_consensus_tpu.parallel.multihost_smoke`` — one
  worker process (env: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID, set
  by the launcher).  Prints ``MULTIHOST_OK {json}`` on success.
* ``run_group(num_processes)`` — spawn the workers, collect and
  cross-check their tallies; used by tests/test_multihost.py and
  ``__graft_entry__.dryrun_multihost``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

# deterministic fixture: M=4 judges, N=3 candidates
VOTES = [
    [1.0, 0.0, 0.0],
    [0.0, 1.0, 0.0],
    [0.25, 0.5, 0.25],
    [0.0, 0.0, 1.0],
]
WEIGHTS = [2.0, 1.0, 1.0, 0.5]


def expected_confidence():
    total = sum(WEIGHTS)
    per = [
        sum(VOTES[m][n] * WEIGHTS[m] for m in range(len(WEIGHTS)))
        for n in range(len(VOTES[0]))
    ]
    return [p / total for p in per]


def worker_main() -> None:
    """One process of the group (see module doc)."""
    from .dist import maybe_initialize_distributed

    assert maybe_initialize_distributed(), "MULTIHOST env not set?"
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from .collectives import sharded_tally

    num = int(os.environ["NUM_PROCESSES"])
    assert jax.process_count() == num, (
        f"process group has {jax.process_count()} processes, want {num}"
    )
    devices = jax.devices()  # GLOBAL list across the group
    assert len(devices) == num, f"{len(devices)} global devices, want {num}"
    mesh = Mesh(np.array(devices), ("dp",))

    votes_np = np.array(VOTES, np.float32)
    weights_np = np.array(WEIGHTS, np.float32)

    def globalize(arr, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    votes = globalize(votes_np, P("dp", None))
    weights = globalize(weights_np, P("dp"))
    conf = sharded_tally(votes, weights, mesh)
    assert conf.is_fully_replicated
    out = np.asarray(conf).tolist()
    print(
        "MULTIHOST_OK "
        + json.dumps(
            {
                "process_id": jax.process_index(),
                "num_processes": num,
                "confidence": out,
            }
        ),
        flush=True,
    )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_group(
    num_processes: int = 2, timeout: float = 300.0, attempts: int = 2
) -> list:
    """Spawn the worker group; return per-process confidence vectors.

    Raises on any worker failure or cross-process disagreement — this is
    the pass/fail gate for the DCN smoke.  The coordinator port is probed
    then released (TOCTOU window before the coordinator re-binds it), so
    one retry with a fresh port absorbs the rare steal.
    """
    last: Exception = RuntimeError("unreachable")
    for _ in range(attempts):
        try:
            return _run_group_once(num_processes, timeout)
        except RuntimeError as exc:
            last = exc
    raise last


def _run_group_once(num_processes: int, timeout: float) -> list:
    coordinator = f"127.0.0.1:{_free_port()}"
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from .dist import force_cpu_env

    procs = []
    for pid in range(num_processes):
        # the smoke is about GROUP FORMATION, so workers run pure-CPU;
        # force_cpu_env also defeats the TPU-tunnel sitecustomize, which
        # would otherwise hijack the jax.distributed bootstrap
        env = force_cpu_env(dict(os.environ), n_devices=1)
        env.update(
            MULTIHOST="1",
            COORDINATOR_ADDRESS=coordinator,
            NUM_PROCESSES=str(num_processes),
            PROCESS_ID=str(pid),
            PYTHONPATH=repo_root
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "llm_weighted_consensus_tpu.parallel.multihost_smoke",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    results = []
    failures = []
    for pid, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            failures.append(f"process {pid} timed out:\n{out}")
            continue
        marker = [
            line for line in out.splitlines() if line.startswith("MULTIHOST_OK ")
        ]
        if proc.returncode != 0 or not marker:
            failures.append(
                f"process {pid} rc={proc.returncode}:\n{out[-2000:]}"
            )
            continue
        results.append(json.loads(marker[0][len("MULTIHOST_OK "):]))
    if failures:
        raise RuntimeError("DCN smoke failed:\n" + "\n---\n".join(failures))
    confs = [r["confidence"] for r in sorted(results, key=lambda r: r["process_id"])]
    first = confs[0]
    for other in confs[1:]:
        if any(abs(a - b) > 1e-6 for a, b in zip(first, other)):
            raise RuntimeError(
                f"processes disagree on the tally: {confs}"
            )
    exp = expected_confidence()
    if any(abs(a - b) > 1e-5 for a, b in zip(first, exp)):
        raise RuntimeError(f"tally {first} != expected {exp}")
    return confs


if __name__ == "__main__":
    worker_main()
