"""Multi-process DCN smoke: actually form a ``jax.distributed`` group.

`parallel.dist.maybe_initialize_distributed` is the multi-host entry point;
this module proves it forms a real process group without TPU pod hardware:
N CPU processes rendezvous at a localhost coordinator, build ONE GLOBAL
mesh over ``jax.devices()``, and run the consensus reduction with the
cross-process psum riding the distributed backend — the same code path
that rides DCN on a pod (SURVEY §2.8 "DCN for multi-host slices";
DESIGN.md §multi-host).

With ``devices_per_proc > 1`` (VERDICT r3 item 5) each process hosts
several virtual devices and the group EXECUTES the DESIGN.md axis
placement instead of just arguing it: a global (dp=processes,
tp=devices-per-process) mesh runs the TP-sharded encoder forward
(Megatron split, parallel/sharding.py) + the dp tally, every process
checks the sharded output against an unsharded local reference, and the
compiled HLO's replica groups are asserted to keep tp collectives INSIDE
a process (ICI) while only dp-sized groups cross the process boundary
(DCN).

Two entry points:

* ``python -m llm_weighted_consensus_tpu.parallel.multihost_smoke`` — one
  worker process (env: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID/
  DEVICES_PER_PROC, set by the launcher).  Prints ``MULTIHOST_OK {json}``
  on success.
* ``run_group(num_processes, devices_per_proc)`` — spawn the workers,
  collect and cross-check their outputs; used by tests/test_multihost.py
  and ``__graft_entry__.dryrun_multihost``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

# deterministic fixture: M=4 judges, N=3 candidates
VOTES = [
    [1.0, 0.0, 0.0],
    [0.0, 1.0, 0.0],
    [0.25, 0.5, 0.25],
    [0.0, 0.0, 1.0],
]
WEIGHTS = [2.0, 1.0, 1.0, 0.5]


def expected_confidence():
    total = sum(WEIGHTS)
    per = [
        sum(VOTES[m][n] * WEIGHTS[m] for m in range(len(WEIGHTS)))
        for n in range(len(VOTES[0]))
    ]
    return [p / total for p in per]


def _parse_replica_groups(hlo_text: str) -> list:
    """Every ``replica_groups=...`` in an HLO dump as a list of id lists.

    Handles both the explicit ``{{0,1},{2,3}}`` form and the iota form
    ``[G,S]<=[N]`` (row-major) / ``[G,S]<=[a,b]T(1,0)`` (transposed).
    """
    import re

    groups = []
    for m in re.finditer(r"replica_groups=\{\{([0-9,{} ]*)\}\}", hlo_text):
        # tolerate whitespace between groups: '{{0,1}, {2,3}}' must split
        # into two groups, not merge into one
        for grp in re.split(r"\}\s*,\s*\{", m.group(1)):
            ids = [
                int(x)
                for x in grp.replace("{", "").replace("}", "").split(",")
                if x.strip()
            ]
            if ids:
                groups.append(ids)
    for m in re.finditer(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](T\(([0-9,]+)\))?",
        hlo_text,
    ):
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        import numpy as np

        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(5):
            ids = ids.transpose([int(x) for x in m.group(5).split(",")])
        for row in ids.reshape(g, s):
            groups.append([int(x) for x in row])
    return groups


def _collective_boundary_report(hlo_text: str, num: int, dpp: int) -> dict:
    """Classify every replica group: within one process's device block
    (tp riding ICI) or crossing processes (dp riding DCN)."""
    blocks = [set(range(p * dpp, (p + 1) * dpp)) for p in range(num)]
    within = crossing = 0
    crossing_sizes = set()
    for grp in _parse_replica_groups(hlo_text):
        ids = set(grp)
        if any(ids <= b for b in blocks):
            within += 1
        else:
            crossing += 1
            crossing_sizes.add(len(grp))
    return {
        "within_process_groups": within,
        "crossing_groups": crossing,
        "crossing_group_sizes": sorted(crossing_sizes),
    }


def worker_main() -> None:
    """One process of the group (see module doc)."""
    from .dist import maybe_initialize_distributed

    assert maybe_initialize_distributed(), "MULTIHOST env not set?"
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from .collectives import sharded_tally

    num = int(os.environ["NUM_PROCESSES"])
    dpp = int(os.environ.get("DEVICES_PER_PROC", "1"))
    assert jax.process_count() == num, (
        f"process group has {jax.process_count()} processes, want {num}"
    )
    devices = jax.devices()  # GLOBAL list across the group
    assert len(devices) == num * dpp, (
        f"{len(devices)} global devices, want {num * dpp}"
    )
    # dp outer (across processes / DCN), tp inner (within a process / ICI):
    # jax.devices() is process-major, so the reshape rows are processes
    if dpp > 1:
        mesh = Mesh(np.array(devices).reshape(num, dpp), ("dp", "tp"))
    else:
        mesh = Mesh(np.array(devices), ("dp",))

    # dp must divide the row count; replicating the WHOLE vote table
    # scales numerator and denominator of the normalized tally equally,
    # so expected_confidence() is unchanged at any group size
    reps = 1
    while (len(VOTES) * reps) % num:
        reps += 1
    votes_np = np.array(VOTES * reps, np.float32)
    weights_np = np.array(WEIGHTS * reps, np.float32)

    def globalize(arr, spec):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )

    votes = globalize(votes_np, P("dp", None))
    weights = globalize(weights_np, P("dp"))
    import time

    t0 = time.perf_counter()
    conf = jax.block_until_ready(sharded_tally(votes, weights, mesh))
    tally_ms = round((time.perf_counter() - t0) * 1e3, 3)
    assert conf.is_fully_replicated
    out = {
        "process_id": jax.process_index(),
        "num_processes": num,
        "global_devices": len(devices),
        "mesh_shape": {"dp": num, "tp": dpp},
        "confidence": np.asarray(conf).tolist(),
        # wall time of the cross-process psum incl. compile on first use —
        # the number that blows up when dp traffic accidentally rides a
        # slow transport, which the bare ok-marker never showed
        "tally_ms": tally_ms,
    }

    if dpp > 1:
        out.update(_encoder_phase(mesh, num, dpp))

    print("MULTIHOST_OK " + json.dumps(out), flush=True)


def _encoder_phase(mesh, num: int, dpp: int) -> dict:
    """TP-sharded encoder forward on the global (dp, tp) mesh: numerics
    checked against an unsharded local reference, and the compiled HLO's
    collectives classified by process boundary (see module doc)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import bert
    from ..models.configs import TEST_TINY
    from .sharding import bert_param_specs, shard_bert_params

    config = TEST_TINY
    params = bert.init_params(jax.random.PRNGKey(0), config)
    rng = np.random.default_rng(7)
    batch = 2 * num  # divides dp
    ids_np = rng.integers(
        3, config.vocab_size, size=(batch, 16)
    ).astype(np.int32)
    mask_np = np.ones((batch, 16), np.int32)

    # local, unsharded reference BEFORE params are device_put on the mesh
    ref = np.asarray(
        bert.embed(params, ids_np, mask_np, config, pooling="cls")
    )

    sharded_params = shard_bert_params(params, mesh, tp=True)
    batch_sharding = NamedSharding(mesh, P("dp", None))

    def globalize(arr):
        return jax.make_array_from_callback(
            arr.shape, batch_sharding, lambda idx: arr[idx]
        )

    fwd = jax.jit(
        lambda p, i, m: bert.embed(p, i, m, config, pooling="cls"),
        in_shardings=(
            jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                bert_param_specs(tp=True),
                is_leaf=lambda x: isinstance(x, P),
            ),
            batch_sharding,
            batch_sharding,
        ),
        out_shardings=NamedSharding(mesh, P()),
    )
    ids = globalize(ids_np)
    mask = globalize(mask_np)
    import time

    t0 = time.perf_counter()
    lowered = fwd.lower(sharded_params, ids, mask)
    compiled = lowered.compile()
    compile_ms = round((time.perf_counter() - t0) * 1e3, 3)
    t0 = time.perf_counter()
    emb = np.asarray(
        jax.block_until_ready(compiled(sharded_params, ids, mask))
    )
    forward_ms = round((time.perf_counter() - t0) * 1e3, 3)
    err = float(np.max(np.abs(emb - ref)))

    report = _collective_boundary_report(
        compiled.as_text(), num, dpp
    )
    report["encoder_max_err_vs_unsharded"] = err
    report["encoder_checksum"] = float(np.sum(emb, dtype=np.float64))
    report["encoder_compile_ms"] = compile_ms
    report["encoder_forward_ms"] = forward_ms
    return report


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_group(
    num_processes: int = 2,
    timeout: float = 300.0,
    attempts: int = 2,
    devices_per_proc: int = 1,
) -> list:
    """Spawn the worker group; return per-process result dicts.

    Raises on any worker failure or cross-process disagreement — this is
    the pass/fail gate for the DCN smoke.  The coordinator port is probed
    then released (TOCTOU window before the coordinator re-binds it), so
    one retry with a fresh port absorbs the rare steal.

    With ``devices_per_proc > 1`` the group also runs the TP-sharded
    encoder phase and this gate additionally asserts: all processes agree
    on the encoder output (and match the unsharded reference), the HLO
    has at least one within-process collective (the Megatron TP
    all-reduces), and every process-crossing replica group has exactly
    ``num_processes`` participants — i.e. ONLY dp traffic crosses the
    DCN boundary.
    """
    last: Exception = RuntimeError("unreachable")
    for _ in range(attempts):
        try:
            return _run_group_once(num_processes, timeout, devices_per_proc)
        except RuntimeError as exc:
            last = exc
    raise last


def _run_group_once(
    num_processes: int, timeout: float, devices_per_proc: int = 1
) -> list:
    coordinator = f"127.0.0.1:{_free_port()}"
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from .dist import force_cpu_env

    procs = []
    for pid in range(num_processes):
        # the smoke is about GROUP FORMATION, so workers run pure-CPU;
        # force_cpu_env also defeats the TPU-tunnel sitecustomize, which
        # would otherwise hijack the jax.distributed bootstrap
        env = force_cpu_env(dict(os.environ), n_devices=devices_per_proc)
        env.update(
            MULTIHOST="1",
            COORDINATOR_ADDRESS=coordinator,
            NUM_PROCESSES=str(num_processes),
            PROCESS_ID=str(pid),
            DEVICES_PER_PROC=str(devices_per_proc),
            PYTHONPATH=repo_root
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        )
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "llm_weighted_consensus_tpu.parallel.multihost_smoke",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    results = []
    failures = []
    for pid, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            failures.append(f"process {pid} timed out:\n{out}")
            continue
        marker = [
            line for line in out.splitlines() if line.startswith("MULTIHOST_OK ")
        ]
        if proc.returncode != 0 or not marker:
            failures.append(
                f"process {pid} rc={proc.returncode}:\n{out[-2000:]}"
            )
            continue
        results.append(json.loads(marker[0][len("MULTIHOST_OK "):]))
    if failures:
        raise RuntimeError("DCN smoke failed:\n" + "\n---\n".join(failures))
    results.sort(key=lambda r: r["process_id"])
    confs = [r["confidence"] for r in results]
    first = confs[0]
    for other in confs[1:]:
        if any(abs(a - b) > 1e-6 for a, b in zip(first, other)):
            raise RuntimeError(
                f"processes disagree on the tally: {confs}"
            )
    exp = expected_confidence()
    if any(abs(a - b) > 1e-5 for a, b in zip(first, exp)):
        raise RuntimeError(f"tally {first} != expected {exp}")
    for r in results:
        if r["num_processes"] != num_processes or r[
            "global_devices"
        ] != num_processes * devices_per_proc:
            raise RuntimeError(f"group shape wrong: {r}")
    if devices_per_proc > 1:
        sums = {round(r["encoder_checksum"], 4) for r in results}
        if len(sums) != 1:
            raise RuntimeError(
                f"processes disagree on the encoder output: {sums}"
            )
        for r in results:
            if r["encoder_max_err_vs_unsharded"] > 2e-4:
                raise RuntimeError(
                    "sharded encoder diverges from the unsharded "
                    f"reference: {r['encoder_max_err_vs_unsharded']}"
                )
            if r["within_process_groups"] < 1:
                raise RuntimeError(
                    "no within-process collective found — the Megatron "
                    f"TP all-reduces are missing: {r}"
                )
            if r["crossing_groups"] < 1:
                # without at least one crossing group the 'dp rides DCN'
                # half of the claim is vacuous (e.g. the batch silently
                # became replicated and nothing spans processes)
                raise RuntimeError(
                    f"no process-crossing collective found: {r}"
                )
            bad = [
                s for s in r["crossing_group_sizes"] if s != num_processes
            ]
            if bad:
                raise RuntimeError(
                    "collective groups larger than dp cross the process "
                    f"boundary (tp traffic on DCN): sizes {bad}"
                )
    return results


if __name__ == "__main__":
    worker_main()
