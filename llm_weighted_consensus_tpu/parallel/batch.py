"""Archive batch re-scoring over the mesh (BASELINE config 4).

The checkpoint/resume analog of the reference is its completions archive
(SURVEY §5); re-scoring 10k archived score requests is a single dp-sharded
batched tally — the whole archive crosses the PJRT boundary once.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import consensus


def rescore_batch(
    votes: np.ndarray,
    weights: np.ndarray,
    vote_mask: Optional[np.ndarray] = None,
    mesh: Optional[Mesh] = None,
):
    """votes[B, M, N], weights[B, M] -> (choice_weight[B, N], conf[B, N]).

    With a mesh, B shards over EVERY mesh axis (pad to a multiple);
    without, runs on the default device.  The per-request tallies are
    independent — embarrassingly parallel over B — so any mesh shape
    (dp×tp serving, dp×sp sequence-parallel serving) flattens into one
    batch axis and the only comms are the initial shard placement.
    """
    b = votes.shape[0]
    if vote_mask is None:
        vote_mask = np.ones(weights.shape, dtype=np.float32)
    if mesh is None:
        return consensus.tally_batch(
            jnp.asarray(votes), jnp.asarray(weights), jnp.asarray(vote_mask)
        )
    axes = tuple(mesh.axis_names)
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))
    pad = (-b) % n_dev
    if pad:
        votes = np.pad(votes, ((0, pad), (0, 0), (0, 0)))
        weights = np.pad(weights, ((0, pad), (0, 0)))
        vote_mask = np.pad(vote_mask, ((0, pad), (0, 0)))
    sharding = NamedSharding(mesh, P(axes))
    vs = jax.device_put(jnp.asarray(votes), sharding)
    ws = jax.device_put(jnp.asarray(weights), sharding)
    ms = jax.device_put(jnp.asarray(vote_mask), sharding)
    cw, conf = consensus.tally_batch(vs, ws, ms)
    return cw[:b], conf[:b]
