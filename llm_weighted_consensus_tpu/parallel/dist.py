"""Multi-host (DCN) initialization for multi-slice / multi-host TPU pods.

The reference's "distributed backend" is HTTPS+SSE to upstream APIs
(SURVEY §2.8); ours is the JAX runtime itself.  Within one host's slice
the mesh collectives ride ICI; across hosts JAX runs one process per host
and XLA routes inter-host collective traffic over DCN automatically once
``jax.distributed.initialize`` has formed the process group.

Design note (what changes at 2+ hosts) — see DESIGN.md §multi-host:
* every host runs this same binary with ``MULTIHOST=1`` and the same
  ``COORDINATOR_ADDRESS``; host 0 doubles as the coordinator;
* ``jax.devices()`` then reports the GLOBAL device list, so
  ``parallel.mesh.make_mesh`` transparently builds a global mesh — mesh
  construction, shardings, and collectives are unchanged by design;
* keep ``dp`` as the outer (cross-host) mesh axis: candidate batches are
  embarrassingly parallel so only the final tally's psum crosses DCN,
  while ``tp``'s per-layer all-reduces stay on intra-host ICI.
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

def force_cpu_env(env: dict, n_devices: int) -> dict:
    """Mutate+return ``env`` so a FRESH process initializes jax on
    ``n_devices`` virtual CPU devices — the one place that knows how to
    defeat the TPU-tunnel sitecustomize, which registers its PJRT plugin
    whenever ``PALLAS_AXON_POOL_IPS`` is set and TRUMPS
    ``JAX_PLATFORMS=cpu`` (with it loaded, ``jax.distributed`` bootstrap
    is hijacked too: the group never forms).  Used by the DCN smoke's
    worker processes and ``__graft_entry__``'s virtual-mesh dryruns."""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = " ".join(
        part
        for part in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in part
    )
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    return env


def multihost_requested(env: Optional[Mapping] = None) -> bool:
    from ..utils import env_truthy

    env = os.environ if env is None else env
    return env_truthy(env.get("MULTIHOST", ""))


def maybe_initialize_distributed(env: Optional[Mapping] = None) -> bool:
    """Form the multi-host process group iff ``MULTIHOST`` is truthy.

    Reads ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID``
    (all optional: on Cloud TPU metadata autodetection fills them in).
    Single-host behavior is unchanged: without the flag this is a no-op
    and returns False.  Call before any other jax API touches a backend.
    """
    env = os.environ if env is None else env
    if not multihost_requested(env):
        return False
    import jax

    kwargs = {}
    if env.get("COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = env["COORDINATOR_ADDRESS"]
    if env.get("NUM_PROCESSES"):
        kwargs["num_processes"] = int(env["NUM_PROCESSES"])
    if env.get("PROCESS_ID"):
        kwargs["process_id"] = int(env["PROCESS_ID"])
    jax.distributed.initialize(**kwargs)
    return True
