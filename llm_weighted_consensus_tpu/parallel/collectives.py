"""The consensus reduction as explicit ICI collectives.

Replaces the reference's host-side tally loop (score client.rs:384-456)
with on-device communication (SURVEY §2.8): candidates are sharded over the
``dp`` axis; each shard computes its local similarity block against an
``all_gather`` of every candidate embedding, and the softmax normalizer is
a ``psum`` — all riding ICI, never the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map


def sharded_cosine_vote(
    embeddings: jax.Array,
    mesh: Mesh,
    temperature: float | jax.Array = 0.05,
    n_valid: int | None = None,
) -> jax.Array:
    """embeddings[N, D] (N divisible by mesh dp) -> confidence[N_valid].

    Matches ops.similarity.cosine_consensus_vote numerically; computed
    distributed: local block matmul against the all-gathered embeddings,
    mean off-diagonal similarity, global max/sum via psum for the softmax.

    ``n_valid`` is the count of real candidate rows when the caller
    already padded (the mesh serving path pads to the AOT row bucket
    before dispatch); rows at and past ``n_valid`` are masked out of the
    mean and the softmax exactly like the internal dp padding, and only
    the first ``n_valid`` confidences are returned.  ``temperature`` may
    be a traced scalar: it rides as a replicated shard_map operand, not
    a closure capture, so this reduction composes under an outer ``jit``
    (the one-dispatch embed+vote executable in models/embedder.py).
    """
    n = embeddings.shape[0] if n_valid is None else n_valid
    dp = mesh.shape["dp"]
    if embeddings.shape[0] % dp != 0:
        # pad candidates to the shard grid; padded rows masked out below
        pad = dp - embeddings.shape[0] % dp
        embeddings = jnp.pad(embeddings, ((0, pad), (0, 0)))
    np_ = embeddings.shape[0]
    temp = jnp.asarray(temperature, jnp.float32)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", None), P()),
        out_specs=P("dp"),
        check_vma=False,
    )
    def vote(x_local, temp):
        shard = jax.lax.axis_index("dp")
        local_n = x_local.shape[0]
        # normalize locally (row-wise, no comms)
        x32 = x_local.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(x32 * x32, axis=-1, keepdims=True))
        x_n = x32 / jnp.maximum(norm, 1e-12)
        # ICI all-gather of all candidates' normalized embeddings
        x_all = jax.lax.all_gather(x_n, "dp", tiled=True)  # [Np, D]
        sims = jnp.einsum(
            "ld,nd->ln",
            x_n,
            x_all,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [local_n, Np]
        # global row/col ids for diagonal + padding masks
        row_ids = shard * local_n + jnp.arange(local_n)
        col_ids = jnp.arange(np_)
        valid_col = (col_ids < n)[None, :] & (
            col_ids[None, :] != row_ids[:, None]
        )
        mean_sim = jnp.sum(jnp.where(valid_col, sims, 0.0), axis=-1) / max(
            n - 1, 1
        )
        logits = mean_sim / temp
        row_valid = row_ids < n
        logits = jnp.where(row_valid, logits, -jnp.inf)
        # globally-stable softmax: psum-reduced max and sum over shards
        local_max = jnp.max(logits)
        global_max = jax.lax.pmax(local_max, "dp")
        e = jnp.where(row_valid, jnp.exp(logits - global_max), 0.0)
        denom = jax.lax.psum(jnp.sum(e), "dp")
        return e / denom

    return vote(embeddings, temp)[:n]


def sharded_tally(
    votes: jax.Array, weights: jax.Array, mesh: Mesh
) -> jax.Array:
    """votes[M, N] sharded over judges (dp), weights[M] -> confidence[N].

    Each shard tallies its local judges; the cross-judge reduction is one
    psum over ICI.  M must divide by dp.
    """
    m, n = votes.shape
    dp = mesh.shape["dp"]
    if m % dp != 0:
        pad = dp - m % dp
        votes = jnp.pad(votes, ((0, pad), (0, 0)))
        weights = jnp.pad(weights, (0, pad))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp")),
        out_specs=P(),
        check_vma=False,
    )
    def tally(v_local, w_local):
        local = jnp.einsum(
            "m,mn->n",
            w_local.astype(jnp.float32),
            v_local.astype(jnp.float32),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        choice_weight = jax.lax.psum(local, "dp")
        total = jnp.sum(choice_weight)
        return jnp.where(total > 0, choice_weight / total, 0.0)

    return tally(votes, weights)
