"""Sharding rules: batch over ``dp``, encoder tensor parallelism over ``tp``.

The recipe (How to Scale Your Model, public jax-ml scaling book): pick a
mesh, annotate input/param shardings with NamedSharding, let XLA insert the
collectives.  For the BERT encoder the TP layout is the Megatron split:

* attention q/k/v kernels column-split over heads  -> P(None, "tp")
* attention output kernel row-split                -> P("tp", None)
* MLP in column-split, MLP out row-split           -> P(None, "tp"), P("tp", None)
* embeddings + layernorms replicated               -> P()

so each layer does two reduce-scatters' worth of comms (XLA chooses
all-reduce/reduce-scatter over ICI).  Batches shard over ``dp``.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", None))


def ring_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input sharding for the long-context ring dispatch: batch rows over
    ``dp`` AND the sequence axis over ``sp`` — the sp-aware twin of
    ``batch_sharding``.  Requires a 3-axis (dp, tp, sp) mesh
    (``make_mesh(..., sp=N)``)."""
    if "sp" not in mesh.shape:
        raise ValueError(
            "ring_batch_sharding needs an 'sp' mesh axis "
            f"(got axes {tuple(mesh.shape)})"
        )
    return NamedSharding(mesh, P("dp", "sp"))


# Leading axis of every stacked layer param is the layer index (scanned) —
# shardings below apply to [layer, in, out] kernels / [layer, dim] biases.
_TP_LAYER_SPECS = {
    "attn_q": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_k": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_v": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_out": {"kernel": P(None, "tp", None), "bias": P(None)},
    "attn_ln": {"scale": P(None), "bias": P(None)},
    "mlp_in": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "mlp_out": {"kernel": P(None, "tp", None), "bias": P(None)},
    "mlp_ln": {"scale": P(None), "bias": P(None)},
}

def _quantized_layer_specs() -> dict:
    """The int8 twin of _TP_LAYER_SPECS, derived mechanically so the two
    tables cannot drift: kernel_q shards like the full-precision kernel;
    the per-output-channel scale follows the kernel's OUT (last) axis, so
    it splits with column-split kernels and replicates with row-split
    ones; bias/layernorm leaves are unchanged.  Row-split dequant
    commutes with the tp all-reduce (scales are identical across shards:
    sx[row] * sw[out] * sum(partials) == sum(sx * sw * partials)), so
    the layout needs no numerics caveats."""
    out = {}
    for name, leaf in _TP_LAYER_SPECS.items():
        if "kernel" not in leaf:
            out[name] = dict(leaf)
            continue
        kspec = leaf["kernel"]
        out[name] = {
            "kernel_q": kspec,
            "scale": P(None, kspec[-1]),  # [layer, out]: follows OUT
            "bias": leaf["bias"],
        }
    return out


_TP_LAYER_SPECS_Q = _quantized_layer_specs()


def bert_param_specs(tp: bool = True, quantized: bool = False) -> dict:
    """PartitionSpec pytree matching models.bert param layout."""
    base = _TP_LAYER_SPECS_Q if quantized else _TP_LAYER_SPECS
    layer = (
        base
        if tp
        else {
            name: {k: P(None) for k in leaf}
            for name, leaf in base.items()
        }
    )
    return {
        "token_embed": P(),
        "position_embed": P(),
        "type_embed": P(),
        "embed_ln": {"scale": P(), "bias": P()},
        "layers": layer,
    }


# --- first-class partition-rule tables -------------------------------------
#
# The dict tables above mirror the param tree shape; the rule tables below
# are the audit-friendly dual: an ordered list of (name, anchored-regex,
# spec) matched against the "/"-joined leaf path.  The contract the mesh
# audit (analysis/mesh_audit.py, JXA006) enforces: every leaf matches
# EXACTLY one rule, and every rule matches at least one leaf.

_COL = ("attn_q", "attn_k", "attn_v", "mlp_in")
_ROW = ("attn_out", "mlp_out")


def _encoder_rules(quantized: bool = False) -> tuple:
    """Shared bert/deberta encoder-layer rules.  ``quantized`` swaps the
    dense kernel leaf for the int8 (kernel_q, scale) pair — scale is
    per-OUT-channel, so it follows the kernel's last axis (split for
    column kernels, replicated for row kernels)."""
    col = "|".join(_COL)
    row = "|".join(_ROW)
    kernel = "kernel_q" if quantized else "kernel"
    rules = [
        (
            "layer_col_kernel",
            rf"layers/({col})/{kernel}",
            P(None, None, "tp"),
        ),
        ("layer_col_bias", rf"layers/({col})/bias", P(None, "tp")),
        (
            "layer_row_kernel",
            rf"layers/({row})/{kernel}",
            P(None, "tp", None),
        ),
        ("layer_row_bias", rf"layers/({row})/bias", P(None)),
        ("layer_ln", r"layers/(attn_ln|mlp_ln)/(scale|bias)", P(None)),
    ]
    if quantized:
        rules[2:2] = [
            ("layer_col_scale", rf"layers/({col})/scale", P(None, "tp")),
        ]
        rules[5:5] = [
            ("layer_row_scale", rf"layers/({row})/scale", P(None, None)),
        ]
    return tuple(rules)


def bert_partition_rules(quantized: bool = False) -> tuple:
    """Ordered (name, regex, PartitionSpec) rules for models.bert trees."""
    return (
        ("embed_tables", r"(token|position|type)_embed", P()),
        ("embed_ln", r"embed_ln/(scale|bias)", P()),
    ) + _encoder_rules(quantized=quantized)


def deberta_partition_rules(quantized: bool = False) -> tuple:
    """Rules for models.deberta trees: bert encoder plus disentangled
    position projections (column-split like q/k) and the reward head
    (Megatron pair: dense column-split, scalar out row-split)."""
    return (
        ("embed_tables", r"(token|rel)_embed", P()),
        ("embed_ln", r"(embed|rel)_ln/(scale|bias)", P()),
        # pos_q/pos_k stay full-precision even in int8 trees (models.quant
        # quantizes the six content/MLP kernels only)
        ("pos_proj_kernel", r"layers/(pos_q|pos_k)/kernel", P(None, None, "tp")),
        ("pos_proj_bias", r"layers/(pos_q|pos_k)/bias", P(None, "tp")),
        ("head_dense_kernel", r"head_dense/kernel", P(None, "tp")),
        ("head_dense_bias", r"head_dense/bias", P("tp")),
        ("head_out_kernel", r"head_out/kernel", P("tp", None)),
        ("head_out_bias", r"head_out/bias", P(None)),
    ) + _encoder_rules(quantized=quantized)


def partition_rules_for(arch: str, quantized: bool = False) -> tuple:
    if arch == "bert":
        return bert_partition_rules(quantized=quantized)
    if arch == "deberta":
        return deberta_partition_rules(quantized=quantized)
    raise ValueError(f"no partition rules for arch {arch!r}")


def tree_path_leaves(tree: dict) -> list:
    """[(\"layers/attn_q/kernel\", leaf), ...] — "/"-joined string paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for key in path:
            parts.append(str(getattr(key, "key", getattr(key, "idx", key))))
        out.append(("/".join(parts), leaf))
    return out


def match_report(rules: tuple, tree: dict) -> tuple:
    """Audit-grade matching: (leaf_matches, rule_counts) where
    leaf_matches maps each leaf path to the LIST of matching rule names
    (so callers can flag both uncovered and ambiguous leaves) and
    rule_counts maps each rule name to its total match count (0 = dead
    rule)."""
    leaf_matches: dict = {}
    rule_counts = {name: 0 for name, _, _ in rules}
    for path, _leaf in tree_path_leaves(tree):
        hits = [
            name
            for name, pattern, _spec in rules
            if re.fullmatch(pattern, path)
        ]
        leaf_matches[path] = hits
        for name in hits:
            rule_counts[name] += 1
    return leaf_matches, rule_counts


def match_partition_rules(rules: tuple, tree: dict) -> dict:
    """Param tree -> PartitionSpec tree via the rule table (the
    match_partition_rules shape used by the big public jax LLM repos).
    First matching rule wins; a leaf no rule matches is an error — the
    rule table, not a silent replicate default, is the source of truth."""
    specs = {name: spec for name, _, spec in rules}
    compiled = [(name, pattern) for name, pattern, _ in rules]

    leaves = tree_path_leaves(tree)
    spec_by_path = {}
    for path, _leaf in leaves:
        for name, pattern in compiled:
            if re.fullmatch(pattern, path):
                spec_by_path[path] = specs[name]
                break
        else:
            raise ValueError(f"no partition rule matches param leaf {path!r}")

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out_leaves = []
    for path, _leaf in flat:
        parts = "/".join(
            str(getattr(key, "key", getattr(key, "idx", key))) for key in path
        )
        out_leaves.append(spec_by_path[parts])
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def strip_tp(spec_tree):
    """Replace the "tp" axis with None everywhere (tp=1 / TP-off layout)."""
    return jax.tree_util.tree_map(
        lambda spec: P(*(None if axis == "tp" else axis for axis in spec)),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_by_rules(
    params: dict, mesh: Mesh, rules: tuple, tp: bool = True
) -> dict:
    """Place a param pytree on the mesh per the rule table."""
    specs = match_partition_rules(rules, params)
    if not (tp and mesh.shape.get("tp", 1) > 1):
        specs = strip_tp(specs)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_bert_params(params: dict, mesh: Mesh, tp: bool = True) -> dict:
    """Place a bert param pytree on the mesh with the TP layout."""
    from ..models.quant import is_quantized

    rules = bert_partition_rules(quantized=is_quantized(params))
    return shard_by_rules(params, mesh, rules, tp=tp)


def shard_embedder(embedder, mesh: Mesh, tp: bool = False) -> None:
    """Wire a models.embedder.TpuEmbedder onto a mesh: params placed
    (replicated or TP), batches split over ``dp`` via its put_batch hook.

    Setting ``embedder.batch_multiple = dp`` makes the embedder pad every
    dispatch to a dp multiple, so the split always divides; the replicated
    fallback below is a safety net for direct put_batch callers only.
    """
    embedder.params = shard_bert_params(embedder.params, mesh, tp=tp)
    b_sharding = batch_sharding(mesh)
    repl = replicated(mesh)
    dp = mesh.shape.get("dp", 1)

    def put_batch(ids, mask):
        s = b_sharding if ids.shape[0] % dp == 0 else repl
        return jax.device_put(ids, s), jax.device_put(mask, s)

    embedder.put_batch = put_batch
    embedder.batch_multiple = dp
    embedder.mesh = mesh


def shard_embedder_mesh(embedder, mesh: Mesh) -> None:
    """First-class mesh serving (``MESH_ENABLED``, serve/config.py).

    Params are placed once at load by the partition-rule tables (batch
    rows over ``dp``, encoder kernels Megatron-split over ``tp``),
    dispatch inputs get real NamedShardings instead of the legacy
    put_batch replicate/split heuristic, and the embedder flips into
    mesh mode so its AOT table lowers per-(mesh-shape, bucket)
    executables with the input shardings baked in (models/embedder.py).
    Unlike ``shard_embedder`` (the hook path above, which disables AOT
    and packing), mesh mode keeps both.

    With an ``sp`` axis on the mesh (``MESH_SHAPE=dp,tp,sp``), the dense
    dispatch path is UNCHANGED — same shardings, same batch_multiple,
    same AOT keys as the 2-axis mesh (short traffic replicates over sp)
    — and the embedder additionally gains the ring dispatch state:
    ``mesh_sp``, a (dp, sp)-sharded input sharding, the ring token cap
    (position window rounded down to an sp multiple), and a ring-mode
    twin of its config.  ``MESH_SHAPE`` without sp therefore stays
    byte-identical to the pre-sp serving path.
    """
    import dataclasses

    from ..models.quant import is_quantized

    dp = mesh.shape["dp"]
    tp = mesh.shape.get("tp", 1)
    sp = mesh.shape.get("sp", 1)
    rules = bert_partition_rules(quantized=is_quantized(embedder.params))
    embedder.params = shard_by_rules(embedder.params, mesh, rules, tp=tp > 1)
    b_sharding = batch_sharding(mesh)
    repl = replicated(mesh)

    def put_batch(ids, mask):
        s = b_sharding if ids.shape[0] % dp == 0 else repl
        return jax.device_put(ids, s), jax.device_put(mask, s)

    embedder.put_batch = put_batch
    embedder.batch_multiple = dp
    embedder.mesh = mesh
    embedder.mesh_shape = (dp, tp)
    embedder.batch_sharding = b_sharding
    embedder.repl_sharding = repl
    embedder.mesh_mode = True
    embedder.mesh_sp = sp
    if sp > 1:
        from ..models.configs import usable_positions

        embedder.ring_sharding = ring_batch_sharding(mesh)
        # ring sequences pad to an sp multiple; cap so padding can never
        # push past the position table (same contract as shard_embedder_sp)
        embedder.ring_max_tokens = (
            usable_positions(embedder.config) // sp
        ) * sp
        embedder._ring_config = dataclasses.replace(
            embedder.config, attention_impl="ring", ring_axis="sp"
        )
    else:
        embedder.ring_sharding = None
        embedder.ring_max_tokens = None
        embedder._ring_config = None


def shard_reranker_mesh(reranker, mesh: Mesh) -> None:
    """Place a models.reranker.TpuReranker's DeBERTa params on the mesh
    by its rule table (``MESH_ENABLED``).  The reward forward then rides
    GSPMD from the param shardings alone — its softmax normalizes over
    exactly one request's candidates, so the batch stays unsharded."""
    from ..models.quant import is_quantized

    tp = mesh.shape.get("tp", 1)
    rules = deberta_partition_rules(quantized=is_quantized(reranker.params))
    reranker.params = shard_by_rules(reranker.params, mesh, rules, tp=tp > 1)
    reranker.mesh = mesh
