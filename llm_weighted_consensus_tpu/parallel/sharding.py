"""Sharding rules: batch over ``dp``, encoder tensor parallelism over ``tp``.

The recipe (How to Scale Your Model, public jax-ml scaling book): pick a
mesh, annotate input/param shardings with NamedSharding, let XLA insert the
collectives.  For the BERT encoder the TP layout is the Megatron split:

* attention q/k/v kernels column-split over heads  -> P(None, "tp")
* attention output kernel row-split                -> P("tp", None)
* MLP in column-split, MLP out row-split           -> P(None, "tp"), P("tp", None)
* embeddings + layernorms replicated               -> P()

so each layer does two reduce-scatters' worth of comms (XLA chooses
all-reduce/reduce-scatter over ICI).  Batches shard over ``dp``.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", None))


# Leading axis of every stacked layer param is the layer index (scanned) —
# shardings below apply to [layer, in, out] kernels / [layer, dim] biases.
_TP_LAYER_SPECS = {
    "attn_q": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_k": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_v": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "attn_out": {"kernel": P(None, "tp", None), "bias": P(None)},
    "attn_ln": {"scale": P(None), "bias": P(None)},
    "mlp_in": {"kernel": P(None, None, "tp"), "bias": P(None, "tp")},
    "mlp_out": {"kernel": P(None, "tp", None), "bias": P(None)},
    "mlp_ln": {"scale": P(None), "bias": P(None)},
}

def _quantized_layer_specs() -> dict:
    """The int8 twin of _TP_LAYER_SPECS, derived mechanically so the two
    tables cannot drift: kernel_q shards like the full-precision kernel;
    the per-output-channel scale follows the kernel's OUT (last) axis, so
    it splits with column-split kernels and replicates with row-split
    ones; bias/layernorm leaves are unchanged.  Row-split dequant
    commutes with the tp all-reduce (scales are identical across shards:
    sx[row] * sw[out] * sum(partials) == sum(sx * sw * partials)), so
    the layout needs no numerics caveats."""
    out = {}
    for name, leaf in _TP_LAYER_SPECS.items():
        if "kernel" not in leaf:
            out[name] = dict(leaf)
            continue
        kspec = leaf["kernel"]
        out[name] = {
            "kernel_q": kspec,
            "scale": P(None, kspec[-1]),  # [layer, out]: follows OUT
            "bias": leaf["bias"],
        }
    return out


_TP_LAYER_SPECS_Q = _quantized_layer_specs()


def bert_param_specs(tp: bool = True, quantized: bool = False) -> dict:
    """PartitionSpec pytree matching models.bert param layout."""
    base = _TP_LAYER_SPECS_Q if quantized else _TP_LAYER_SPECS
    layer = (
        base
        if tp
        else {
            name: {k: P(None) for k in leaf}
            for name, leaf in base.items()
        }
    )
    return {
        "token_embed": P(),
        "position_embed": P(),
        "type_embed": P(),
        "embed_ln": {"scale": P(), "bias": P()},
        "layers": layer,
    }


def shard_bert_params(params: dict, mesh: Mesh, tp: bool = True) -> dict:
    """Place a bert param pytree on the mesh with the TP layout."""
    from ..models.quant import is_quantized

    specs = bert_param_specs(
        tp=tp and mesh.shape.get("tp", 1) > 1, quantized=is_quantized(params)
    )
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_embedder(embedder, mesh: Mesh, tp: bool = False) -> None:
    """Wire a models.embedder.TpuEmbedder onto a mesh: params placed
    (replicated or TP), batches split over ``dp`` via its put_batch hook.

    Setting ``embedder.batch_multiple = dp`` makes the embedder pad every
    dispatch to a dp multiple, so the split always divides; the replicated
    fallback below is a safety net for direct put_batch callers only.
    """
    embedder.params = shard_bert_params(embedder.params, mesh, tp=tp)
    b_sharding = batch_sharding(mesh)
    repl = replicated(mesh)
    dp = mesh.shape.get("dp", 1)

    def put_batch(ids, mask):
        s = b_sharding if ids.shape[0] % dp == 0 else repl
        return jax.device_put(ids, s), jax.device_put(mask, s)

    embedder.put_batch = put_batch
    embedder.batch_multiple = dp
    embedder.mesh = mesh
