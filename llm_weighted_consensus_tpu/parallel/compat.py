"""jax API compatibility shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the top-level
``jax`` namespace (and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma``) across the jax versions this framework
supports.  One resolution site here keeps every call site on the new
spelling while still running on older installed runtimes.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _CHECK_KWARG = "check_vma"
else:  # pragma: no cover - exercised only on older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KWARG = "check_rep"


def shard_map(f=None, **kwargs):
    """``jax.shard_map`` with the current kwarg spelling, translating
    ``check_vma`` for runtimes that still call it ``check_rep``."""
    if "check_vma" in kwargs and _CHECK_KWARG != "check_vma":
        kwargs[_CHECK_KWARG] = kwargs.pop("check_vma")
    if f is None:
        return lambda fn: _shard_map(fn, **kwargs)
    return _shard_map(f, **kwargs)
