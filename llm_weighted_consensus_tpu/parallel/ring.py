"""Ring attention: sequence/context parallelism over an ``sp`` mesh axis.

The reference has no long-context machinery (SURVEY §5: "no ring
attention/CP/Ulysses"); for the TPU framework long-context is first-class.
The design follows blockwise ring attention (Liu et al.): the sequence axis
is sharded over the mesh, every device holds one q block permanently, and
k/v blocks rotate around the ring via ``lax.ppermute`` while an
online-softmax accumulator (running max, running denominator, weighted sum)
folds each visiting block in.  After ``sp`` steps every q block has
attended to the full sequence, no device ever materializes more than a
[b, s/sp, s/sp] score tile, and each permute's communication overlaps the
next block's compute (XLA schedules the ppermute DMA concurrently).

Memory: full attention needs O(s^2) scores; ring needs O(s^2/sp^2) per
device — the enabler for 8k-32k-token encoder contexts on fixed VMEM/HBM.

Exact numerics: online softmax is algebraically identical to one softmax
over the full row (up to f32 reassociation); parity with the einsum path
is asserted in tests/test_ring.py.

Layering: ``ring_attention`` is a pure collective, usable inside any
``shard_map`` with a named axis; ``ring_encode``/``ring_embed`` wrap the
whole BERT forward with sequence sharding (positions offset per shard,
layers scanned as usual).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e9


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,
    scale: float,
    axis_name: str,
) -> jax.Array:
    """Blockwise ring attention (call inside shard_map over ``axis_name``).

    q/k/v: [b, s_local, nh, hd] — this device's sequence shard;
    bias:   [b, s_local] additive key-side padding bias for the LOCAL keys
    (0 real, -1e9 pad), the same convention as ops.attention.

    Returns ctx[b, s_local, nh, hd] equal to full softmax(QK^T*scale+bias)V
    over the GLOBAL sequence.
    """
    sp = jax.lax.psum(1, axis_name)
    b, s_loc, nh, hd = q.shape
    qf = q.astype(jnp.float32)

    # online-softmax state per q position/head
    acc = jnp.zeros((b, s_loc, nh, hd), jnp.float32)
    denom = jnp.zeros((b, s_loc, nh), jnp.float32)
    run_max = jnp.full((b, s_loc, nh), NEG_INF, jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]  # ring: shard i -> i+1

    k_cur, v_cur, bias_cur = k, v, bias
    for step in range(sp):
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)
        # [b, q_loc, k_loc, nh]
        logits = (
            jnp.einsum("bqnd,bknd->bqkn", qf, kf,
                       preferred_element_type=jnp.float32)
            * scale
        )
        logits = logits + bias_cur[:, None, :, None].astype(jnp.float32)
        blk_max = jnp.max(logits, axis=2)  # [b, q_loc, nh]
        new_max = jnp.maximum(run_max, blk_max)
        correction = jnp.exp(run_max - new_max)
        p = jnp.exp(logits - new_max[:, :, None, :])  # [b, q, k, nh]
        acc = acc * correction[:, :, :, None] + jnp.einsum(
            "bqkn,bknd->bqnd", p, vf, preferred_element_type=jnp.float32
        )
        denom = denom * correction + jnp.sum(p, axis=2)
        run_max = new_max
        if step < sp - 1:
            # rotate k/v/bias one step around the ring; the DMA overlaps
            # the next iteration's einsums.  Skipped on the last step —
            # collectives carry channel ids and are not reliably DCE'd,
            # so the wasted rotation would cost real ICI traffic.
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            bias_cur = jax.lax.ppermute(bias_cur, axis_name, perm)

    ctx = acc / denom[:, :, :, None]
    return ctx.astype(q.dtype)


# ---------------------------------------------------------------------------
# Sequence-sharded encoder forward
# ---------------------------------------------------------------------------


def _replicated_like(tree):
    return jax.tree_util.tree_map(lambda _: P(), tree)


def ring_encode(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config,
    mesh: Mesh,
    sp_axis: str = "sp",
    dp_axis=None,
) -> jax.Array:
    """BERT forward with the SEQUENCE axis sharded over ``sp_axis``:
    ids/mask[b, s] -> hidden[b, s, h], s sharded, params replicated.
    ``dp_axis`` additionally shards the batch axis (dp x sp mesh).

    Inside the shard each device embeds its own sequence slice (positions
    offset by shard index) and attention runs as a ring.  The global
    sequence length must divide the sp axis size.
    """
    from ..models import bert

    if config.attention_impl != "ring":
        raise ValueError(
            "ring_encode needs a config with attention_impl='ring' "
            f"(got {config.attention_impl!r})"
        )
    s = input_ids.shape[1]
    sp = mesh.shape[sp_axis]
    if s % sp != 0:
        raise ValueError(f"sequence {s} must divide sp={sp}")
    from ..models.configs import usable_positions

    if s > usable_positions(config):
        # jnp gathers clamp out-of-range indices, which would silently
        # reuse the last position embedding instead of failing
        raise ValueError(
            f"sequence {s} exceeds the position table's usable window "
            f"({usable_positions(config)}); long contexts need a config "
            "with a matching position table"
        )

    seq_spec = P(dp_axis, sp_axis)

    def local_forward(params, ids, mask):
        s_loc = ids.shape[1]
        offset = jax.lax.axis_index(sp_axis) * s_loc
        return bert.encode(
            params, ids, mask, config, position_offset=offset
        )

    from .compat import shard_map

    return shard_map(
        local_forward,
        mesh=mesh,
        in_specs=(_replicated_like(params), seq_spec, seq_spec),
        out_specs=P(dp_axis, sp_axis, None),
        check_vma=False,
    )(params, input_ids, attention_mask)


@partial(
    jax.jit,
    static_argnames=(
        "config", "mesh", "sp_axis", "dp_axis", "pooling", "normalize"
    ),
)
def _ring_embed_jit(
    params, ids, mask, config, mesh, sp_axis, dp_axis, pooling, normalize
):
    from ..models import bert

    hidden = ring_encode(params, ids, mask, config, mesh, sp_axis, dp_axis)
    return bert.pool(hidden, mask, pooling, normalize)


@partial(
    jax.jit,
    static_argnames=(
        "n", "config", "mesh", "sp_axis", "dp_axis", "pooling"
    ),
)
def _ring_embed_and_vote(
    params, ids, mask, temperature, n, config, mesh, sp_axis, dp_axis, pooling
):
    """Ring-dispatch twin of ``_mesh_embed_and_vote`` (models/embedder.py):
    sequence-sharded encoder forward + pooling + the dp-sharded consensus
    reduction under ONE jit, so a long-context scored request pays one
    dispatch.  The pooled embeddings leave the ring shard_map sharded
    (batch over dp, the contracted seq axis reduced over sp by GSPMD);
    the vote's shard_map re-enters over dp with sp/tp implicitly
    replicated.  Temperature is always traced — same no-recompile
    contract as the dense mesh vote."""
    from ..models import bert
    from .collectives import sharded_cosine_vote

    hidden = ring_encode(params, ids, mask, config, mesh, sp_axis, dp_axis)
    emb = bert.pool(hidden, mask, pooling, True)
    with jax.named_scope("consensus_vote"):
        return sharded_cosine_vote(emb, mesh, temperature, n_valid=n)


def ring_embed(
    params: dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config,
    mesh: Mesh,
    sp_axis: str = "sp",
    dp_axis=None,
    pooling: str = "cls",
    normalize: bool = True,
) -> jax.Array:
    """Sequence-parallel twin of ``bert.embed``: pooled embeddings for
    long-context inputs, attention memory O(s^2/sp^2) per device."""
    in_sharding = NamedSharding(mesh, P(dp_axis, sp_axis))
    with mesh:
        return _ring_embed_jit(
            params,
            jax.device_put(input_ids, in_sharding),
            jax.device_put(attention_mask, in_sharding),
            config,
            mesh,
            sp_axis,
            dp_axis,
            pooling,
            normalize,
        )


def shard_embedder_sp(
    embedder, mesh: Mesh, sp_axis: str = "sp", dp_axis=None
) -> None:
    """Wire a ``TpuEmbedder`` for sequence-parallel serving: its embedding
    forward is replaced (``embed_override`` hook) by ``ring_embed`` over
    ``mesh``, sequences padded to an sp multiple — enabling long-context
    inputs whose attention would not fit one device.  Consensus-vote fused
    paths keep their single-device dispatch (self-consistency candidates
    are short by contract); this serves the /embeddings + trained-weights
    lookup paths."""
    import dataclasses

    import numpy as np

    sp = mesh.shape[sp_axis]
    # batches pad to a dp multiple (same contract as shard_embedder)
    embedder.batch_multiple = mesh.shape[dp_axis] if dp_axis else 1
    from ..models.configs import usable_positions

    # the sequence pads to an sp multiple before dispatch; cap the token
    # window so padding can never push past the position table
    embedder.max_tokens = min(
        embedder.max_tokens,
        (usable_positions(embedder.config) // sp) * sp,
    )
    ring_config = dataclasses.replace(
        embedder.config, attention_impl="ring", ring_axis=sp_axis
    )

    def forward(ids, mask):
        pad_s = (-ids.shape[1]) % sp
        if pad_s:  # pads are masked keys — attention ignores them
            ids = np.pad(ids, ((0, 0), (0, pad_s)))
            mask = np.pad(mask, ((0, 0), (0, pad_s)))
        return ring_embed(
            embedder.params,
            ids,
            mask,
            ring_config,
            mesh,
            sp_axis=sp_axis,
            dp_axis=dp_axis,
            pooling=embedder.pooling,
            normalize=True,
        )

    embedder.embed_override = forward
    embedder.sp_mesh = mesh  # introspection (tests, config dumps)
