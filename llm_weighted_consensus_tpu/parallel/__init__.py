"""Device-mesh scale-out: shardings, collectives, batch parallelism.

The reference's only concurrency is single-process async IO (SURVEY §2.8);
its "distributed backend" is HTTPS.  Here distribution is first-class and
TPU-shaped:

* ``mesh``      — mesh construction over dp/tp axes (ICI within a slice,
  DCN across hosts comes free with jax.distributed process groups);
* ``sharding``  — NamedSharding rules: batch over ``dp``, optional tensor
  parallelism of attention heads + MLP over ``tp`` (bge-class models need
  only DP — SURVEY §2.8 notes this explicitly — but TP is implemented and
  dry-run tested so larger encoders drop in);
* ``collectives`` — the consensus reduction as explicit ICI collectives:
  candidates sharded over the mesh, ``all_gather`` for pairwise cosine,
  ``psum`` for the global softmax — replacing the reference's host-side
  tally loop with on-device communication;
* ``batch``     — archive batch re-scoring sharded over ``dp`` (BASELINE
  config 4);
* ``ring``      — sequence/context parallelism: blockwise ring attention
  over an ``sp`` axis (ppermute k/v rotation + online softmax), making
  long-context encoders first-class — per-device attention memory is
  O(s^2/sp^2);
* ``dist``      — multi-host (DCN) process-group initialization.

No pipeline parallelism (a 12-24 layer encoder has no use for stages) and
no expert parallelism (no MoE) — by design, stated here per SURVEY §2.8.
"""

from .dist import maybe_initialize_distributed  # noqa: F401
from .mesh import make_mesh  # noqa: F401
from . import batch, collectives, ring, sharding  # noqa: F401
