"""Training-table population from archived outcomes — the learning half of
the trained-weights loop.

The reference defines the trained-weight *lookup* contract
(model/mod.rs:278-429: embed the prompt, take the ``top`` nearest rows of a
per-judge table) but leaves row production entirely external.  Here the
loop closes in-framework:

    serve -> archive (completion + request + ballots)
          -> ``populate_from_archive``  (this module)
          -> TrainingTableStore rows
          -> TpuTrainingTableFetcher lookups on the next request

A table row is (prompt embedding, outcome score in [0, 1]).  The outcome
score per judge:

* **supervised** — when the caller knows the correct candidate
  (``labels[completion_id] = candidate index``): the judge's vote mass on
  that candidate, ``vote_j[label]``;
* **self-consistency** — otherwise: agreement with the panel's final
  consensus, ``sum_i vote_j[i] * confidence_i`` — judges that vote with the
  (weighted) majority earn weight, dissenters lose it.

All prompts embed as ONE device batch (the same dispatch-count discipline
as the serving path).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def judge_alignment_scores(completion, label: Optional[int] = None) -> dict:
    """{judge model_index: outcome score in [0, 1]} for one archived score
    completion.  Judges without a stored vote (errored) are omitted."""
    candidates = {}
    judges = []
    for choice in completion.choices:
        if choice.model_index is None:
            candidates[choice.index] = choice
        else:
            judges.append(choice)
    out: dict = {}
    for judge in judges:
        vote = getattr(judge.message, "vote", None)
        if vote is None:
            continue
        if label is not None:
            # reject negative sentinels too: vote[-1] would silently train
            # against the LAST candidate
            score = float(vote[label]) if 0 <= label < len(vote) else 0.0
        else:
            score = 0.0
            for i, v in enumerate(vote):
                cand = candidates.get(i)
                if cand is not None and cand.confidence is not None:
                    score += float(v) * float(cand.confidence)
        out[judge.model_index] = min(max(score, 0.0), 1.0)
    return out


def populate_from_archive(
    store,
    embedder,
    model,
    table_store,
    *,
    ids: Optional[list] = None,
    labels: Optional[dict] = None,
    max_tokens: Optional[int] = None,
) -> int:
    """Learn table rows for ``model``'s judges from archived completions.

    ``store``: the completions archive (must hold requests too — gateway
    ARCHIVE_WRITE stores them); ``model``: the validated panel whose judges
    (matched by judge id) get rows keyed by their ``training_table_id``;
    ``labels``: optional {completion_id: correct candidate index} for
    supervised scores.

    Judges match their history two ways: exact judge id, or — when the
    archived request carried an inline panel — the archived judge's
    ``training_table_id`` (the WEIGHT-INVARIANT judge identity,
    llm/mod.rs:524-536), so re-weighted panels keep learning from the same
    judges' history.  Idempotence is scoped per (table, completion): a
    table never ingests the same completion twice, while a panel with NEW
    table ids still learns from already-processed history.  Returns the
    number of rows added.
    """
    from ..identity.model import ModelBase

    # dedup: repeated ids in one call would pass the is_ingested scan
    # twice (marks only land after embedding) and double-insert rows
    ids = list(dict.fromkeys(ids if ids is not None else store.score_ids()))
    by_judge_id = {llm.id: llm for llm in model.llms}
    if max_tokens is None:
        # match the LOOKUP's truncation (panel embeddings config): stored
        # and query embeddings of the same prompt must be the same vector
        max_tokens = getattr(
            getattr(model.weight, "embeddings", None), "max_tokens", None
        )

    texts = []
    per_completion = []  # (completion id, text position, {table_id: score})
    for cid in ids:
        completion = store.score_completion(cid)
        request = store.score_request(cid)
        if completion is None or request is None:
            continue
        # weight-invariant fallback identities from the archived panel
        archived_by_id: dict = {}
        archived_model = getattr(request, "model", None)
        if isinstance(archived_model, ModelBase):
            try:
                validated = archived_model.into_model_validate()
                archived_by_id = {llm.id: llm for llm in validated.llms}
            except Exception:
                pass
        scores = judge_alignment_scores(
            completion, (labels or {}).get(cid)
        )
        rows: dict = {}
        for choice in completion.choices:
            if choice.model_index is None or choice.model_index not in scores:
                continue
            llm = by_judge_id.get(choice.model) or archived_by_id.get(
                choice.model
            )
            if llm is None or not llm.training_table_id:
                continue
            if table_store.is_ingested(f"{llm.training_table_id}/{cid}"):
                continue
            rows[llm.training_table_id] = scores[choice.model_index]
        if not rows:
            continue
        per_completion.append((cid, len(texts), rows))
        texts.append(request.template_content())

    if not texts:
        return 0
    embeddings = embedder.embed_texts(texts, max_tokens=max_tokens)

    # group rows per table so each table concatenates ONCE (appending row
    # by row would copy the whole table per completion — quadratic)
    by_table: dict = {}
    for cid, pos, rows in per_completion:
        for table_id, score in rows.items():
            embs, scores, cids = by_table.setdefault(table_id, ([], [], []))
            embs.append(embeddings[pos])
            scores.append(score)
            cids.append(cid)
    added = 0
    for table_id, (embs, scores, cids) in by_table.items():
        table_store.add_rows(
            table_id, np.stack(embs), np.asarray(scores, dtype=np.float32)
        )
        # mark ONLY what actually landed: marking up front would poison
        # idempotence if an add_rows raises (e.g. embedding-dim mismatch
        # after an embedder swap) and strand that history as unlearnable
        for cid in cids:
            table_store.mark_ingested(f"{table_id}/{cid}")
        added += len(scores)
    return added
