"""The TPU trained-weights path: embedding lookup -> per-judge weights.

This is the seam the reference leaves external (SURVEY §2.1 "this is where
the TPU embedding/cosine/softmax path plugs in"; model/mod.rs:278-429
defines the config: ``embeddings{model, max_tokens, provider}`` + ``top``).
Implementation:

1. flatten the conversation with ``template_content`` (the reference's
   designated embedding input, chat request.rs:78-91);
2. embed it on device (models.embedder);
3. per judge: cosine top-k lookup into that judge's training table
   (historical prompt embeddings + outcome scores), attention-weighted mean
   of the top rows' scores, linear interpolation into the judge's
   [min_weight, max_weight] band (ops.similarity.training_table_weights);
4. judges without table data fall back to ``base_weight``;
5. the embeddings_response evidence is echoed as ``weight_data`` and its
   usage seeds cost accounting (score client.rs:330-337).
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional, Tuple

import numpy as np

from ..types.score_response import TrainingTableData
from . import TrainingTableWeightFetcher


class TrainingTableStore:
    """In-memory training tables keyed by judge ``training_table_id``.

    A row is (prompt embedding, outcome score in [0, 1]); rows are appended
    as scored conversations are archived.
    """

    def __init__(self) -> None:
        self._tables: dict = {}

    def add_rows(
        self, table_id: str, embeddings: np.ndarray, scores: np.ndarray
    ) -> None:
        embeddings = np.asarray(embeddings, dtype=np.float32)
        scores = np.asarray(scores, dtype=np.float32)
        if table_id in self._tables:
            old_e, old_s = self._tables[table_id]
            embeddings = np.concatenate([old_e, embeddings])
            scores = np.concatenate([old_s, scores])
        self._tables[table_id] = (embeddings, scores)

    def get(self, table_id: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        return self._tables.get(table_id)

    def __len__(self) -> int:
        return len(self._tables)


class TpuTrainingTableFetcher(TrainingTableWeightFetcher):
    def __init__(self, embedder, store: Optional[TrainingTableStore] = None):
        self.embedder = embedder
        self.store = store or TrainingTableStore()

    async def fetch(self, ctx, request, model):
        import asyncio

        # device work off the event loop thread
        return await asyncio.get_running_loop().run_in_executor(
            None, self._fetch_sync, request, model
        )

    def _fetch_sync(self, request, model):
        import jax.numpy as jnp

        from ..ops.similarity import training_table_weights

        cfg = model.weight  # PanelWeightTrainingTable
        max_tokens = getattr(cfg.embeddings, "max_tokens", None)
        text = request.template_content()
        response = self.embedder.embeddings_response(
            [text], max_tokens=max_tokens
        )
        query = jnp.asarray(
            [response.data[0].embedding], dtype=jnp.float32
        )  # [1, D]
        top = int(cfg.top)

        weights = []
        for llm in model.llms:
            w = llm.base.weight  # WeightTrainingTable
            table = (
                self.store.get(llm.training_table_id)
                if llm.training_table_id
                else None
            )
            if table is None:
                weights.append(w.base_weight)
                continue
            emb, scores = table
            # the device kernel owns the top-k/softmax/lerp recipe
            out = training_table_weights(
                jnp.asarray(emb),
                jnp.asarray(scores)[None, :],
                query,
                jnp.asarray([float(w.min_weight)]),
                jnp.asarray([float(w.max_weight)]),
                min(top, emb.shape[0]),
            )
            weights.append(Decimal(repr(float(out[0, 0]))))
        return weights, TrainingTableData(embeddings_response=response)
