"""The TPU trained-weights path: embedding lookup -> per-judge weights.

This is the seam the reference leaves external (SURVEY §2.1 "this is where
the TPU embedding/cosine/softmax path plugs in"; model/mod.rs:278-429
defines the config: ``embeddings{model, max_tokens, provider}`` + ``top``).
Implementation:

1. flatten the conversation with ``template_content`` (the reference's
   designated embedding input, chat request.rs:78-91);
2. embed it on device (models.embedder);
3. per judge: cosine top-k lookup into that judge's training table
   (historical prompt embeddings + outcome scores), attention-weighted mean
   of the top rows' scores, linear interpolation into the judge's
   [min_weight, max_weight] band (ops.similarity.training_table_weights);
4. judges without table data fall back to ``base_weight``;
5. the embeddings_response evidence is echoed as ``weight_data`` and its
   usage seeds cost accounting (score client.rs:330-337).
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional, Tuple

import numpy as np

from ..types.score_response import TrainingTableData
from . import TrainingTableWeightFetcher


class TrainingTableStore:
    """In-memory training tables keyed by judge ``training_table_id``.

    A row is (prompt embedding, outcome score in [0, 1]); rows are appended
    as scored conversations are archived.
    """

    def __init__(self) -> None:
        self._tables: dict = {}
        # completion ids already learned from (weights/learning.py) — the
        # idempotence guard for repeated sync passes over one archive
        self._ingested: set = set()

    def is_ingested(self, completion_id: str) -> bool:
        return completion_id in self._ingested

    def mark_ingested(self, completion_id: str) -> None:
        self._ingested.add(completion_id)

    def add_rows(
        self, table_id: str, embeddings: np.ndarray, scores: np.ndarray
    ) -> None:
        embeddings = np.asarray(embeddings, dtype=np.float32)
        scores = np.asarray(scores, dtype=np.float32)
        if table_id in self._tables:
            old_e, old_s = self._tables[table_id]
            embeddings = np.concatenate([old_e, embeddings])
            scores = np.concatenate([old_s, scores])
        self._tables[table_id] = (embeddings, scores)

    def get(self, table_id: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        return self._tables.get(table_id)

    def __len__(self) -> int:
        return len(self._tables)

    # -- disk snapshot (pairs with archive snapshots for full resume) -------

    def save(self, path: str) -> None:
        """One .npz holding every table + the ingested-id set (atomic)."""
        from ..utils.io import atomic_write

        arrays = {}
        for table_id, (emb, scores) in self._tables.items():
            arrays[f"e:{table_id}"] = emb
            arrays[f"s:{table_id}"] = scores
        arrays["ingested"] = np.asarray(sorted(self._ingested), dtype="U")
        atomic_write(path, lambda f: np.savez(f, **arrays))

    @classmethod
    def load(cls, path: str) -> "TrainingTableStore":
        store = cls()
        with np.load(path) as data:
            for key in data.files:
                if key.startswith("e:"):
                    table_id = key[2:]
                    store._tables[table_id] = (
                        data[key], data[f"s:{table_id}"]
                    )
            if "ingested" in data.files:
                store._ingested = set(data["ingested"].tolist())
        return store


class TpuTrainingTableFetcher(TrainingTableWeightFetcher):
    def __init__(
        self,
        embedder,
        store: Optional[TrainingTableStore] = None,
        batcher=None,
    ):
        self.embedder = embedder
        # NOT `store or ...`: an EMPTY shared store is falsy (__len__ == 0)
        # and would be silently replaced, detaching the fetcher from the
        # store that learning later populates
        self.store = store if store is not None else TrainingTableStore()
        # when set (serve/batcher.py), the prompt embedding — the heavy
        # dispatch — rides the serving micro-batcher, coalescing with other
        # concurrent requests' device work
        self.batcher = batcher

    async def fetch(self, ctx, request, model):
        import asyncio

        loop = asyncio.get_running_loop()
        if self.batcher is None:
            # device work off the event loop thread
            return await loop.run_in_executor(
                None, self._fetch_sync, request, model
            )
        cfg = model.weight  # PanelWeightTrainingTable
        max_tokens = getattr(cfg.embeddings, "max_tokens", None)
        emb, tokens = await self.batcher.embed(
            [request.template_content()], max_tokens=max_tokens
        )
        response = self.embedder.wire_response(emb, tokens)
        # the per-judge table lookup is a small dispatch; plain executor hop
        return await loop.run_in_executor(None, self._lookup, response, model)

    def _fetch_sync(self, request, model):
        cfg = model.weight  # PanelWeightTrainingTable
        max_tokens = getattr(cfg.embeddings, "max_tokens", None)
        text = request.template_content()
        response = self.embedder.embeddings_response(
            [text], max_tokens=max_tokens
        )
        return self._lookup(response, model)

    def _lookup(self, response, model):
        import jax.numpy as jnp

        from ..ops.similarity import training_table_weights_batched

        cfg = model.weight  # PanelWeightTrainingTable
        query = np.asarray(response.data[0].embedding, dtype=np.float32)
        top = int(cfg.top)

        # collect judges with table data; all lookups run as ONE padded
        # batched dispatch + ONE host fetch (a per-judge loop costs a link
        # round-trip per judge — up to 128 per request)
        with_table = []  # (position, table_emb, table_scores, min_w, max_w)
        weights: list = []
        for llm in model.llms:
            w = llm.base.weight  # WeightTrainingTable
            table = (
                self.store.get(llm.training_table_id)
                if llm.training_table_id
                else None
            )
            if table is None:
                weights.append(w.base_weight)
                continue
            weights.append(None)  # filled from the batched lookup below
            with_table.append(
                (len(weights) - 1, *table, float(w.min_weight), float(w.max_weight))
            )
        if with_table:
            t_max = max(emb.shape[0] for _, emb, _, _, _ in with_table)
            j = len(with_table)
            d = with_table[0][1].shape[1]
            tables = np.zeros((j, t_max, d), dtype=np.float32)
            row_mask = np.zeros((j, t_max), dtype=np.float32)
            scores = np.zeros((j, t_max), dtype=np.float32)
            lo = np.zeros((j,), dtype=np.float32)
            hi = np.zeros((j,), dtype=np.float32)
            for idx, (_, emb, sc, mn, mx) in enumerate(with_table):
                rows = emb.shape[0]
                tables[idx, :rows] = emb
                row_mask[idx, :rows] = 1.0
                scores[idx, :rows] = sc
                lo[idx], hi[idx] = mn, mx
            out = np.asarray(
                training_table_weights_batched(
                    jnp.asarray(tables),
                    jnp.asarray(row_mask),
                    jnp.asarray(scores),
                    jnp.asarray(query),
                    jnp.asarray(lo),
                    jnp.asarray(hi),
                    min(top, t_max),
                )
            )
            for (pos, *_), value in zip(with_table, out):
                weights[pos] = Decimal(repr(float(value)))
        return weights, TrainingTableData(embeddings_response=response)
