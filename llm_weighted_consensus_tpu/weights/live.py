"""Versioned live judge-weight tables behind atomic hot-swap (ISSUE 20).

The learner (``train/fit.py``) emits a per-judge weight table; this
module serves it to the scoring path without a restart:

* the **active** table overrides each judge's fetched weight (static or
  training-table) by judge id inside ``ScoreClient``'s tally, and the
  table's version is stamped on every ``consensus:tally`` span and
  ledger record — a hot swap mid-stream is race-safe because the tally
  captures ``(weights, version)`` in one read before scoring;
* the **shadow** table never changes served results: at tally time the
  same ballots are re-tallied under it and the would-have-flipped /
  confidence-delta counters feed the PR 12 quality scorecards, so an
  operator can stage a candidate table against live traffic before
  promoting it.

Swap atomicity is the event-loop kind used throughout the serving
stack (OutcomeLedger, TraceSink): ``put`` binds one ``_Table`` object
in a single assignment, readers grab the whole object once, and no
threading primitive (or concurrency_model.py registry row) is needed.

Versions are content-addressed — ``wv-`` + the first 12 hex of the
SHA-256 over the canonically serialized weights — so re-PUTting the
same table is idempotent and two replicas fitting the same ledger
agree on the version string without coordination.
"""

from __future__ import annotations

import hashlib
import os
from decimal import Decimal, InvalidOperation
from typing import Optional

from ..utils import jsonutil
from ..utils.io import atomic_write

# versions stamped when no live table overrode the fetched weights —
# the span/ledger annotation is always present, never null-ish
BASE_VERSION = "base"


def weights_version(weights: dict) -> str:
    """Deterministic content-addressed version for a weight table."""
    canon = jsonutil.dumps(
        {str(k): str(Decimal(str(v))) for k, v in sorted(weights.items())}
    )
    return "wv-" + hashlib.sha256(canon.encode("utf-8")).hexdigest()[:12]


class _Table:
    """One immutable-by-convention (version, weights) pair."""

    __slots__ = ("version", "weights")

    def __init__(self, version: str, weights: dict) -> None:
        self.version = version
        self.weights = weights  # judge id -> Decimal

    def as_wire(self) -> dict:
        return {
            "version": self.version,
            "weights": {k: str(v) for k, v in self.weights.items()},
        }


def _parse_weights(raw: dict) -> dict:
    out = {}
    for judge_id, value in raw.items():
        try:
            weight = Decimal(str(value))
        except (InvalidOperation, ValueError, TypeError):
            raise ValueError(
                f"weight for judge {judge_id!r} is not numeric: {value!r}"
            )
        if not weight.is_finite() or weight < 0:
            raise ValueError(
                f"weight for judge {judge_id!r} must be finite and >= 0,"
                f" got {value!r}"
            )
        out[str(judge_id)] = weight
    return out


class LiveWeightStore:
    """Single-threaded by contract (event loop only), like the ledger."""

    def __init__(self, path: Optional[str] = None) -> None:
        # WEIGHTS_PATH: tables survive a restart; None keeps them
        # process-local (PUT-only)
        self.path = path
        self._active: Optional[_Table] = None
        self._shadow: Optional[_Table] = None
        self.swaps = 0
        self.applied = 0
        # shadow-mode counters (quality scorecards): requests compared,
        # verdicts the shadow table would have flipped, and the summed
        # |top-confidence delta| between the two tallies
        self.shadow_compared = 0
        self.shadow_would_flip = 0
        self.shadow_confidence_delta_sum = 0.0
        if path and os.path.exists(path):
            self._load(path)

    # -- swap side ------------------------------------------------------------

    def put(
        self,
        weights: dict,
        version: Optional[str] = None,
        mode: str = "active",
    ) -> str:
        """Install a table (validated, one-assignment swap) and persist
        when a path is configured.  Returns the installed version."""
        parsed = _parse_weights(weights)
        if not parsed:
            raise ValueError("weights table must name at least one judge")
        table = _Table(version or weights_version(parsed), parsed)
        if mode == "shadow":
            self._shadow = table
        elif mode == "active":
            self._active = table
        else:
            raise ValueError(f"mode must be active|shadow, got {mode!r}")
        self.swaps += 1
        if self.path:
            self._save()
        return table.version

    def clear(self, mode: str = "shadow") -> None:
        if mode == "shadow":
            self._shadow = None
        else:
            self._active = None
        if self.path:
            self._save()

    # -- scoring side ---------------------------------------------------------

    @property
    def version(self) -> str:
        table = self._active
        return table.version if table is not None else BASE_VERSION

    def apply(self, model, weights: list) -> tuple:
        """Override the fetched per-judge weights with the active table
        — ``(weights, version)``, captured in ONE table read so a
        concurrent hot swap can never mix two versions inside a tally.
        Judges absent from the table keep their fetched weight; with no
        active table the fetched list passes through under ``base``."""
        table = self._active
        if table is None:
            return weights, BASE_VERSION
        out = list(weights)
        for llm in model.llms:
            override = table.weights.get(llm.id)
            if override is not None:
                out[llm.index] = override
        self.applied += 1
        return out, table.version

    def observe_shadow(self, ballots, n_choices: int) -> None:
        """Re-tally the request's ballots under the shadow table and
        record whether the verdict would flip (plus the top-confidence
        delta).  Pure host float math on data the tally already built —
        never on the serving critical path's Decimal contract."""
        table = self._shadow
        if table is None or not ballots or n_choices <= 0:
            return
        active = [0.0] * n_choices
        shadow = [0.0] * n_choices
        voted = False
        for ballot in ballots:
            vote = ballot.vote
            if not vote:
                continue
            voted = True
            w_active = float(ballot.weight)
            w_shadow = float(table.weights.get(ballot.model, ballot.weight))
            for i in range(min(n_choices, len(vote))):
                active[i] += w_active * float(vote[i])
                shadow[i] += w_shadow * float(vote[i])
        if not voted:
            return
        self.shadow_compared += 1
        win_active = max(range(n_choices), key=active.__getitem__)
        win_shadow = max(range(n_choices), key=shadow.__getitem__)
        if win_active != win_shadow:
            self.shadow_would_flip += 1
        sum_active = sum(active) or 1.0
        sum_shadow = sum(shadow) or 1.0
        self.shadow_confidence_delta_sum += abs(
            shadow[win_shadow] / sum_shadow - active[win_active] / sum_active
        )

    # -- persistence ----------------------------------------------------------

    def _save(self) -> None:
        doc = {
            "schema": "lwc.weights.v1",
            "active": self._active.as_wire() if self._active else None,
            "shadow": self._shadow.as_wire() if self._shadow else None,
        }
        payload = jsonutil.dumps(doc).encode("utf-8")
        atomic_write(self.path, lambda f: f.write(payload))

    def _load(self, path: str) -> None:
        with open(path, encoding="utf-8") as f:
            doc = jsonutil.loads(f.read())
        for mode in ("active", "shadow"):
            entry = doc.get(mode)
            if not isinstance(entry, dict):
                continue
            weights = _parse_weights(entry.get("weights") or {})
            if not weights:
                continue
            table = _Table(
                entry.get("version") or weights_version(weights), weights
            )
            if mode == "active":
                self._active = table
            else:
                self._shadow = table

    # -- observability (metrics section "weights") ----------------------------

    def snapshot(self) -> dict:
        active, shadow = self._active, self._shadow
        return {
            "version": active.version if active else BASE_VERSION,
            "judges": len(active.weights) if active else 0,
            "shadow_version": shadow.version if shadow else None,
            "swaps": self.swaps,
            "applied": self.applied,
            "shadow_compared": self.shadow_compared,
            "shadow_would_flip": self.shadow_would_flip,
            "shadow_confidence_delta_sum": round(
                self.shadow_confidence_delta_sum, 6
            ),
            "path": self.path,
        }

    def wire(self) -> dict:
        """The GET /v1/weights body."""
        active, shadow = self._active, self._shadow
        return {
            "version": active.version if active else BASE_VERSION,
            "weights": (
                {k: str(v) for k, v in active.weights.items()}
                if active
                else {}
            ),
            "shadow": shadow.as_wire() if shadow else None,
            "shadow_compared": self.shadow_compared,
            "shadow_would_flip": self.shadow_would_flip,
        }
