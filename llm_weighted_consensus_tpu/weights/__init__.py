"""Per-judge weight resolution: static config or trained training-table.

Parity target: reference src/score/completions/weight.rs — the ``Fetchers``
dispatch (40-64), ``StaticFetcher`` (76-97), and the training-table seam
(5-18, 99-117) whose ``TrainingTableData.embeddings_response`` evidence is
echoed in responses as ``weight_data`` and whose usage seeds cost accounting
(score client.rs:330-337).

The TPU trained-weight path (embed the prompt on device, cosine top-k lookup
per judge, interpolate within [min,max]) lives in ``weights.training_table``
and plugs in here — this is where host orchestration meets device math
(SURVEY §2.1 row "Weight seam").
"""

from __future__ import annotations

from decimal import Decimal
from typing import Tuple

from ..errors import ResponseError
from ..types.score_response import StaticData, TrainingTableData


class StaticWeightFetcher:
    """Read per-judge static weights straight from panel config."""

    async def fetch(self, ctx, request, model) -> Tuple[list, StaticData]:
        return model.static_weights(), StaticData()


class TrainingTableWeightFetcher:
    """Seam: resolve per-judge weights from trained tables.

    Implementations return (weights, TrainingTableData) where the data
    carries the embeddings_response evidence.  The device-backed
    implementation is ``weights.training_table.TpuTrainingTableFetcher``.
    """

    async def fetch(self, ctx, request, model) -> Tuple[list, TrainingTableData]:
        raise NotImplementedError


class UnimplementedTrainingTableFetcher(TrainingTableWeightFetcher):
    async def fetch(self, ctx, request, model):
        raise ResponseError(
            code=501, message="training-table weight fetcher not configured"
        )


class WeightFetchers:
    """Dispatch on the panel's weight mode (weight.rs:40-64)."""

    def __init__(
        self,
        static_fetcher: StaticWeightFetcher = None,
        training_table_fetcher: TrainingTableWeightFetcher = None,
    ) -> None:
        self.static = static_fetcher or StaticWeightFetcher()
        self.training_table = (
            training_table_fetcher or UnimplementedTrainingTableFetcher()
        )

    async def fetch(self, ctx, request, model):
        """Returns (weights: list[Decimal], data: StaticData|TrainingTableData)."""
        if model.weight.type == "static":
            return await self.static.fetch(ctx, request, model)
        return await self.training_table.fetch(ctx, request, model)
