"""Admission control: shed excess work at the door, not in the queue.

The serving edge funnels every request's device work through one PJRT
queue, so overload does not degrade gracefully on its own — it turns
into unbounded queue growth and blown deadlines for *everyone*.  The
standard SRE answer (Netflix concurrency-limits, the Google SRE book's
load-shedding chapter) is to bound concurrency at admission and reject
the excess early with a retryable 503, keeping the latency of the work
actually admitted close to its unloaded latency.

``AdmissionController`` is the pure core (clock-injectable, no aiohttp
at module scope): a hard ``max_inflight`` cap, plus an optional
AIMD/gradient limit that tracks observed latency against a slowly
drifting baseline — when latency inflates past
``latency_factor x baseline`` the limit decays multiplicatively, and
while the pipe is full-but-healthy it recovers additively.  A
``max_inflight`` of 0 disables shedding entirely; the controller then
only *tracks* in-flight work (the gauge the drain path needs), which
preserves pre-admission behavior byte for byte.

``admission_middleware`` is the aiohttp wiring: health/metrics probes
are exempt, everything else either acquires a slot or gets
``503 + Retry-After + {"kind": "overloaded", "shed_reason": ...}``.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Optional

# probes must keep answering while the service sheds or drains — a load
# balancer that cannot read /readyz cannot take us out of rotation
# /v1/profile rides the probe exemption too: an on-demand profiler
# capture is exactly the tool for diagnosing an overload, so the gate
# must not shed it (serve/gateway.py guards it behind PROFILE_DIR)
# /v1/weights likewise: promoting a fitted table is a tiny admin swap an
# operator may need mid-overload, and shedding it can't relieve load
EXEMPT_PATHS = frozenset(
    {"/healthz", "/livez", "/readyz", "/metrics", "/v1/profile", "/v1/weights"}
)

# endpoints whose handler requires a live device forward: when the
# watchdog marks the device unhealthy and no CPU fallback is configured,
# only THESE shed — host-only endpoints (chat/score fan-out, archive)
# keep serving
DEVICE_PATHS = frozenset({"/embeddings", "/consensus"})


@dataclass
class AdmissionConfig:
    """Knobs, env-mapped in serve/config.py (``ADMISSION_*``)."""

    max_inflight: int = 0  # hard concurrency cap; 0 = no shedding
    max_queue_depth: int = 0  # DeviceBatcher queue bound; 0 = unbounded
    adaptive: bool = False  # AIMD limit under the hard cap
    min_limit: int = 2  # adaptive floor
    latency_factor: float = 2.0  # congestion when ms > factor * baseline
    retry_after_ms: float = 1000.0  # Retry-After hint on sheds


class AdmissionController:
    """In-flight accounting + the shed decision.  Single-threaded by
    contract (mutated only from the event loop), like every counter
    object in this package."""

    def __init__(
        self,
        config: AdmissionConfig,
        *,
        clock: Callable[[], float] = time.monotonic,
        device_gate: Optional[Callable[[], Optional[str]]] = None,
        mem_gate: Optional[Callable[[], Optional[str]]] = None,
    ) -> None:
        self.config = config
        self.clock = clock
        # extra shed policy for device-dependent work (wired to the
        # watchdog): returns a shed reason, or None to admit
        self.device_gate = device_gate
        # memory-pressure shed policy (wired to MemGuard.gate): unlike
        # device_gate it applies to ALL non-exempt work — host-only
        # endpoints allocate too, and under hard memory pressure every
        # new request is one the OOM killer might answer instead of us
        self.mem_gate = mem_gate
        self.inflight = 0
        self.draining = False
        # the adaptive limit lives under the hard cap; without the
        # adaptive mode it just mirrors max_inflight
        self.limit = float(config.max_inflight)
        # surviving-chip fraction under a degraded mesh
        # (resilience/meshfault.py rescale hook): scales the hard cap —
        # and the AIMD limit, so it converges from the right side —
        # while the mesh serves at reduced shape; 1.0 = full capacity
        self._mesh_scale = 1.0
        self._baseline_ms: Optional[float] = None
        self._last_decrease = -math.inf
        self.admitted = 0
        self.shed: dict = {}

    # -- the decision ---------------------------------------------------------

    def try_acquire(self, device_work: bool = False) -> Optional[str]:
        """Admit (returns None, slot held until ``release``) or the shed
        reason."""
        if self.draining:
            return self._shed("draining")
        if self.mem_gate is not None:
            reason = self.mem_gate()
            if reason is not None:
                return self._shed(reason)
        if self.device_gate is not None and device_work:
            reason = self.device_gate()
            if reason is not None:
                return self._shed(reason)
        cap = self.config.max_inflight
        if cap > 0:
            if self._mesh_scale != 1.0:
                cap = max(1, int(cap * self._mesh_scale))
            effective = (
                max(self.config.min_limit, int(self.limit))
                if self.config.adaptive
                else cap
            )
            if self.inflight >= min(cap, effective):
                return self._shed("inflight_limit")
        self.inflight += 1
        self.admitted += 1
        return None

    def release(self, latency_ms: float, *, error: bool = False) -> None:
        self.inflight = max(0, self.inflight - 1)
        if self.config.adaptive and self.config.max_inflight > 0:
            self._adapt(latency_ms, error)

    def rescale(self, scale: float) -> None:
        """Scale admission to the surviving chip fraction (a
        MeshFaultManager rescale hook): a downsized mesh admits
        proportionally less work instead of queueing the overflow into
        blown deadlines.  The AIMD limit is rescaled by the same ratio
        so it starts the new regime near the right value rather than
        decaying toward it one congestion sample at a time; scale=1.0
        (recovery upsize) restores the full cap."""
        scale = max(0.0, float(scale))
        prev = self._mesh_scale
        self._mesh_scale = scale
        if self.config.adaptive and self.config.max_inflight > 0 and prev > 0:
            self.limit = min(
                float(self.config.max_inflight),
                max(float(self.config.min_limit), self.limit * scale / prev),
            )

    def _shed(self, reason: str) -> str:
        self.shed[reason] = self.shed.get(reason, 0) + 1
        return reason

    # -- the AIMD/gradient limit ----------------------------------------------

    def _adapt(self, ms: float, error: bool) -> None:
        """Latency-gradient AIMD (concurrency-limits' Gradient idea in
        its simplest honest form): the baseline is a decayed minimum of
        observed latency — it snaps down instantly and drifts up 0.1%
        per sample so a regime change (bigger model, slower link) is
        eventually accepted as the new normal.  Congestion = a sample
        beyond ``latency_factor x baseline``: multiplicative decrease,
        at most once per baseline-interval so one slow burst doesn't
        collapse the limit to the floor.  Full-but-healthy = additive
        increase (+1/limit per sample, the classic probe)."""
        if self._baseline_ms is None:
            self._baseline_ms = ms
        else:
            self._baseline_ms = min(ms, self._baseline_ms * 1.001)
        congested = error or ms > self._baseline_ms * max(
            1.0, self.config.latency_factor
        )
        now = self.clock()
        if congested:
            # decrease cooldown: the in-flight samples that started
            # before the last decrease still carry the old congestion
            hold_sec = max(0.05, self._baseline_ms / 1e3)
            if now - self._last_decrease >= hold_sec:
                self.limit = max(self.config.min_limit, self.limit * 0.9)
                self._last_decrease = now
        elif self.inflight >= int(self.limit) - 1:
            self.limit = min(
                float(self.config.max_inflight),
                self.limit + 1.0 / max(self.limit, 1.0),
            )

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        out = {
            "inflight": self.inflight,
            "admitted": self.admitted,
            "shed": dict(self.shed),
            "draining": self.draining,
            "max_inflight": self.config.max_inflight,
            "max_queue_depth": self.config.max_queue_depth,
        }
        if self._mesh_scale != 1.0:
            out["mesh_scale"] = round(self._mesh_scale, 4)
        if self.config.adaptive:
            out["limit"] = round(self.limit, 2)
            if self._baseline_ms is not None:
                out["baseline_ms"] = round(self._baseline_ms, 2)
        return out


def shed_response(reason: str, retry_after_ms: float):
    """The uniform 503 shed response: ``Retry-After`` header + the
    ``{code, message}`` envelope with a machine-readable ``shed_reason``
    (same body shape OverloadedError renders on the batcher path)."""
    from aiohttp import web

    from ..utils import jsonutil

    from ..errors import with_trace_id

    return web.Response(
        status=503,
        headers={
            "Retry-After": str(max(1, math.ceil(retry_after_ms / 1000.0)))
        },
        text=jsonutil.dumps(
            with_trace_id(
                {
                    "code": 503,
                    "message": {"kind": "overloaded", "shed_reason": reason},
                }
            )
        ),
        content_type="application/json",
    )


def admission_middleware(admission: AdmissionController):
    """Outer-edge gate: every non-probe request either holds an
    admission slot for its whole lifetime (streams included — the
    handler returns only after the last SSE frame) or is shed before
    any work happens."""
    from aiohttp import web

    from ..obs import annotate as trace_annotate
    from ..obs import observe_phase

    @web.middleware
    async def _mw(request, handler):
        # /v1/traces rides the probe exemption: operators debugging an
        # overload need to READ traces exactly while the gate sheds.
        # /fleet/v1 does too: peer calls are cheap bounded cache/lease
        # bookkeeping that must keep answering while the gate sheds —
        # an overloaded replica that stops granting leases would turn
        # fleet-wide single-flight into a fleet-wide stampede
        if request.path in EXEMPT_PATHS or request.path.startswith(
            ("/v1/traces", "/fleet/v1")
        ):
            return await handler(request)
        t_wait = time.perf_counter()
        reason = admission.try_acquire(
            device_work=request.path in DEVICE_PATHS
        )
        if reason is not None:
            # lands on the gateway root span (the trace middleware wraps
            # this one); the 503 status forces trace retention there
            trace_annotate(shed_reason=reason)
            return shed_response(reason, admission.config.retry_after_ms)
        # door -> slot held; the gate is currently admit-or-shed (no
        # queueing), so this phase reads ~0 until a waiting acquire
        # exists — recorded anyway so the breakdown stays complete
        wait_ms = (time.perf_counter() - t_wait) * 1e3
        observe_phase("admission_wait", wait_ms)
        trace_annotate(
            admission_inflight=admission.inflight,
            admission_wait_ms=round(wait_ms, 3),
        )
        t0 = admission.clock()
        error = True
        try:
            resp = await handler(request)
            error = resp.status >= 500
            return resp
        finally:
            admission.release(
                (admission.clock() - t0) * 1e3, error=error
            )

    return _mw
