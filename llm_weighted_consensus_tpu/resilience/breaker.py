"""Per-upstream circuit breakers (Nygard, *Release It!*).

One breaker per (api_base, model) pair — the unit the chat client's
attempt matrix iterates — with the classic three states:

* CLOSED     — requests flow; outcomes land in a sliding window of the
  last ``window`` attempts.  When the window holds at least
  ``min_samples`` outcomes and the failure rate reaches ``threshold``,
  the breaker opens.
* OPEN       — requests are refused without touching the upstream until
  ``cooldown_ms`` has elapsed, at which point the next ``allow()``
  transitions to half-open.
* HALF_OPEN  — a bounded number of probe requests (``half_open_probes``)
  is let through; one success closes the breaker (window reset), one
  failure re-opens it for a fresh cooldown.  A probe whose attempt is
  cancelled or ends without a verdict must hand its slot back via
  ``release_probe()`` so the breaker can probe again.

The clock is injectable so the state machine is testable without
sleeping; nothing here is async — callers sequence ``allow`` /
``record_*`` from the event loop.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class BreakerConfig:
    # failure-rate in (0, 1] that opens the breaker; <= 0 disables
    threshold: float = 0.5
    # sliding window size (attempt outcomes remembered)
    window: int = 20
    # outcomes required before the rate is meaningful (Nygard's
    # "volume threshold": 1 failure out of 1 must not open anything)
    min_samples: int = 5
    # how long an open breaker refuses before probing
    cooldown_ms: float = 5000.0
    # concurrent probes admitted while half-open
    half_open_probes: int = 1


class CircuitBreaker:
    def __init__(
        self,
        config: BreakerConfig,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config
        self.clock = clock
        self.state = CLOSED
        self._outcomes: deque = deque(maxlen=max(1, config.window))
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.opened_total = 0

    # -- gating ---------------------------------------------------------------

    def allow(self) -> bool:
        """May an attempt proceed?  Transitions OPEN -> HALF_OPEN once the
        cooldown has elapsed; the admitting call claims a probe slot."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            elapsed_ms = (self.clock() - self._opened_at) * 1000.0
            if elapsed_ms < self.config.cooldown_ms:
                return False
            self.state = HALF_OPEN
            self._probes_in_flight = 0
        # HALF_OPEN: bounded probes
        if self._probes_in_flight >= self.config.half_open_probes:
            return False
        self._probes_in_flight += 1
        return True

    # -- outcome recording ----------------------------------------------------

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            # one healthy probe closes the breaker with a fresh window
            self.state = CLOSED
            self._outcomes.clear()
            self._probes_in_flight = 0
            return
        self._outcomes.append(True)

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self._trip()
            return
        self._outcomes.append(False)
        if self.state == CLOSED and self._failure_rate_trips():
            self._trip()

    def force_close(self) -> None:
        """Close the breaker on out-of-band evidence of recovery (the
        fleet's liveness probe answered while the breaker was open).
        Fresh window — the failures that opened it belong to the
        incident that just ended, not to the recovered peer.  Named
        ``force_close`` (not ``reset``) so the concurrency auditor's
        name-based call resolution can't conflate it with other
        ``reset`` methods (QualityAggregator.reset is lock-guarded)."""
        self.state = CLOSED
        self._outcomes.clear()
        self._probes_in_flight = 0

    def release_probe(self) -> None:
        """Return a probe slot claimed by ``allow()`` without recording an
        outcome — the attempt was cancelled (hedge loser, quorum early-exit,
        client disconnect) or ended neutrally (our deadline expired before
        the upstream answered).  Without this, a cancelled half-open probe
        would strand its slot and the breaker would reject forever."""
        if self.state == HALF_OPEN and self._probes_in_flight > 0:
            self._probes_in_flight -= 1

    def _failure_rate_trips(self) -> bool:
        cfg = self.config
        if cfg.threshold <= 0:
            return False
        n = len(self._outcomes)
        if n < max(1, cfg.min_samples):
            return False
        failures = sum(1 for ok in self._outcomes if not ok)
        return failures / n >= cfg.threshold

    def _trip(self) -> None:
        self.state = OPEN
        self._opened_at = self.clock()
        self._outcomes.clear()
        self._probes_in_flight = 0
        self.opened_total += 1

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "window": list(self._outcomes).count(False),
            "samples": len(self._outcomes),
            "opened_total": self.opened_total,
        }

    def describe(self) -> str:
        """One-token state summary for trace attributes — cheap enough to
        stamp on every attempt span (``snapshot()`` walks the window)."""
        if self.state == HALF_OPEN:
            return f"half_open:{self._probes_in_flight}"
        return self.state


class BreakerRegistry:
    """Breakers keyed by ``api_base|model`` — the attempt-matrix unit.

    Unknown keys lazily create a CLOSED breaker, so the registry needs no
    upfront knowledge of the endpoint list (ctx handlers may rewrite it
    per request)."""

    def __init__(
        self,
        config: BreakerConfig,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config
        self.clock = clock
        self._breakers: Dict[str, CircuitBreaker] = {}

    @staticmethod
    def key(api_base: str, model: str) -> str:
        return f"{api_base}|{model}"

    def get(self, api_base: str, model: str) -> CircuitBreaker:
        key = self.key(api_base, model)
        breaker = self._breakers.get(key)
        if breaker is None:
            breaker = CircuitBreaker(self.config, clock=self.clock)
            self._breakers[key] = breaker
        return breaker

    def peek(self, api_base: str, model: str) -> Optional[str]:
        """The breaker's current state, or None when no attempt ever
        touched this key.  Read-only: an observer (e.g. the fleet's
        lease-liveness check asking "is the holder's breaker open?")
        must not lazily materialize breakers it never drives."""
        breaker = self._breakers.get(self.key(api_base, model))
        return breaker.state if breaker is not None else None

    def snapshot(self) -> dict:
        return {
            key: breaker.snapshot()
            for key, breaker in sorted(self._breakers.items())
        }
