"""Host memory governor: bound RSS before the OOM killer does.

A serving host under hostile input or a slow leak does not fail
gracefully on its own — RSS climbs until the kernel kills the process
mid-stream, which reads as a crash to every in-flight client.  The
governor samples process RSS on the watchdog cadence and holds two
watermarks:

* **soft** (``MEM_SOFT_BYTES``) — shrink the knobs that trade memory
  for hit rate: cache byte budgets, the trace ring, and the AIMD
  admission limit all scale down by ``shrink`` (originals restored on
  recovery).  The service keeps serving everything.
* **hard** (``MEM_HARD_BYTES``) — shed all new non-exempt work with a
  retryable ``503 {"kind": "overloaded", "shed_reason": "memory"}``
  (the admission ``mem_gate``) and flip a ``degraded_mem`` flag on
  ``/readyz`` (still 200 — in-flight work is finishing and probes must
  keep answering).

Recovery is hysteretic: a level is left only when RSS falls below
``recover_fraction`` of its watermark, so a process hovering at the
boundary doesn't flap between shedding and admitting every sample.

Pure-core hygiene (the DeviceWatchdog pattern): ``rss_fn`` injectable,
``check()`` callable directly so tests drive trip/recovery with a fake
RSS sequence and no thread; the monitor thread is a thin ``check()``
loop.  One lock guards the level state — ``check()`` runs on the
monitor thread while snapshot/gate reads come from the event loop.

RSS source: ``/proc/self/status`` ``VmRSS`` (current resident set),
falling back to ``resource.getrusage`` ``ru_maxrss`` (peak, not
current — recovery never fires on a peak counter, so the fallback is
trip-only).  No new dependencies.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

LEVEL_OK = 0
LEVEL_SOFT = 1
LEVEL_HARD = 2

_LEVEL_NAMES = {LEVEL_OK: "ok", LEVEL_SOFT: "soft", LEVEL_HARD: "hard"}


def read_rss_bytes() -> Optional[int]:
    """Current resident set size, or None when unmeasurable."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) * 1024  # kB -> bytes
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # Linux reports ru_maxrss in kilobytes; this is the PEAK rss,
        # good enough to trip on, useless for recovery (documented above)
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return None


def read_mem_total_bytes() -> Optional[int]:
    """Host MemTotal for auto watermarks, or None when unavailable."""
    try:
        with open("/proc/meminfo", "rb") as f:
            for line in f:
                if line.startswith(b"MemTotal:"):
                    return int(line.split()[1]) * 1024  # kB -> bytes
    except (OSError, ValueError, IndexError):
        pass
    return None


def resolve_watermarks(
    soft_bytes: int, hard_bytes: int
) -> Optional[tuple]:
    """Resolve the ``MEM_SOFT_BYTES``/``MEM_HARD_BYTES`` knobs: 0 means
    auto (80% / 90% of host MemTotal).  Returns ``(soft, hard)``, or
    None when an auto watermark is needed but MemTotal is unreadable —
    the governor is then disabled rather than guessing."""
    soft = int(soft_bytes)
    hard = int(hard_bytes)
    if soft > 0 and hard > 0:
        return soft, max(soft, hard)
    total = read_mem_total_bytes()
    if total is None:
        return None
    if soft <= 0:
        soft = int(total * 0.8)
    if hard <= 0:
        hard = int(total * 0.9)
    return soft, max(soft, hard)


class MemGuard:
    """Two-watermark RSS governor with hysteretic recovery."""

    def __init__(
        self,
        soft_bytes: int,
        hard_bytes: int,
        *,
        interval_ms: float = 1000.0,
        recover_fraction: float = 0.9,
        shrink: float = 0.5,
        rss_fn: Callable[[], Optional[int]] = read_rss_bytes,
        on_soft: Optional[Callable[[int], None]] = None,
        on_soft_clear: Optional[Callable[[], None]] = None,
        on_hard: Optional[Callable[[int], None]] = None,
        on_hard_clear: Optional[Callable[[], None]] = None,
    ) -> None:
        self.soft_bytes = int(soft_bytes)
        self.hard_bytes = max(self.soft_bytes, int(hard_bytes))
        self.interval_ms = max(10.0, float(interval_ms))
        self.recover_fraction = min(1.0, max(0.0, float(recover_fraction)))
        self.shrink = min(1.0, max(0.01, float(shrink)))
        self.rss_fn = rss_fn
        self.on_soft = on_soft
        self.on_soft_clear = on_soft_clear
        self.on_hard = on_hard
        self.on_hard_clear = on_hard_clear
        self._lock = threading.Lock()
        self._level = LEVEL_OK
        self.last_rss: Optional[int] = None
        self.peak_rss = 0
        self.soft_trips = 0
        self.hard_trips = 0
        self.recoveries = 0
        # governed objects: (object, original budget) pairs captured by
        # govern(); soft pressure scales them, recovery restores them
        self._caches: list = []
        self._sinks: list = []
        self._admission = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- governed budgets ------------------------------------------------------

    def govern(self, *, caches=(), sinks=(), admission=None) -> None:
        """Register the memory-for-performance knobs soft pressure may
        shrink: cache stores (``max_bytes``), trace sinks
        (``capacity``), and the admission controller (AIMD ``limit``
        decays once per soft entry; AIMD's additive increase recovers it
        naturally).  Original budgets are captured here and restored on
        soft exit."""
        self._caches = [
            (c, c.max_bytes) for c in caches if c is not None
        ]
        self._sinks = [
            (s, s.capacity) for s in sinks if s is not None
        ]
        self._admission = admission

    def _apply_soft(self) -> None:
        for cache, orig in self._caches:
            # put-side eviction enforces the shrunk budget (store.py
            # tolerates max_bytes shrinking under live entries)
            cache.max_bytes = max(1, int(orig * self.shrink))
        for sink, orig in self._sinks:
            sink.capacity = max(1, int(orig * self.shrink))
        adm = self._admission
        if (
            adm is not None
            and adm.config.adaptive
            and adm.config.max_inflight > 0
        ):
            adm.limit = max(
                float(adm.config.min_limit), adm.limit * self.shrink
            )

    def _restore_soft(self) -> None:
        for cache, orig in self._caches:
            cache.max_bytes = orig
        for sink, orig in self._sinks:
            sink.capacity = orig
        # the AIMD limit is NOT snapped back: additive increase probes it
        # back up against observed latency, which is the honest signal

    # -- the admission gate ----------------------------------------------------

    @property
    def shedding(self) -> bool:
        with self._lock:
            return self._level >= LEVEL_HARD

    def gate(self) -> Optional[str]:
        """Admission ``mem_gate`` hook: the shed reason under hard
        pressure, None otherwise."""
        return "memory" if self.shedding else None

    @property
    def degraded(self) -> bool:
        """The /readyz ``degraded_mem`` flag: any pressure level."""
        with self._lock:
            return self._level > LEVEL_OK

    # -- the check (monitor thread, or tests directly) ------------------------

    def check(self) -> int:
        """One governor pass; returns the current pressure level."""
        rss = self.rss_fn()
        if rss is None:
            with self._lock:
                return self._level
        fire: list = []
        with self._lock:
            self.last_rss = rss
            if rss > self.peak_rss:
                self.peak_rss = rss
            old = self._level
            new = self._transition(old, rss)
            if new != old:
                self._level = new
                if old == LEVEL_OK and new >= LEVEL_SOFT:
                    self.soft_trips += 1
                    fire.append(("soft", rss))
                if old < LEVEL_HARD and new == LEVEL_HARD:
                    self.hard_trips += 1
                    fire.append(("hard", rss))
                if old == LEVEL_HARD and new < LEVEL_HARD:
                    fire.append(("hard_clear", rss))
                if old >= LEVEL_SOFT and new == LEVEL_OK:
                    self.recoveries += 1
                    fire.append(("soft_clear", rss))
        # budget changes + user hooks run outside the lock (watchdog
        # discipline: never call out while holding it)
        for kind, observed in fire:
            if kind == "soft":
                self._apply_soft()
                if self.on_soft is not None:
                    self.on_soft(observed)
            elif kind == "soft_clear":
                self._restore_soft()
                if self.on_soft_clear is not None:
                    self.on_soft_clear()
            elif kind == "hard":
                if self.on_hard is not None:
                    self.on_hard(observed)
            elif kind == "hard_clear":
                if self.on_hard_clear is not None:
                    self.on_hard_clear()
        with self._lock:
            return self._level

    # caller-holds-lock: MemGuard._lock (only check() calls this)
    def _transition(self, level: int, rss: int) -> int:
        rf = self.recover_fraction
        if level == LEVEL_HARD:
            if rss >= self.hard_bytes * rf:
                return LEVEL_HARD
            return LEVEL_SOFT if rss >= self.soft_bytes * rf else LEVEL_OK
        if level == LEVEL_SOFT:
            if rss > self.hard_bytes:
                return LEVEL_HARD
            return LEVEL_SOFT if rss >= self.soft_bytes * rf else LEVEL_OK
        if rss > self.hard_bytes:
            return LEVEL_HARD
        return LEVEL_SOFT if rss > self.soft_bytes else LEVEL_OK

    def level(self) -> int:
        with self._lock:
            return self._level

    # -- monitor thread -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="lwc-memguard", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1e3):
            self.check()

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "level": _LEVEL_NAMES[self._level],
                "soft_bytes": self.soft_bytes,
                "hard_bytes": self.hard_bytes,
                "soft_trips": self.soft_trips,
                "hard_trips": self.hard_trips,
                "recoveries": self.recoveries,
                "shedding": self._level >= LEVEL_HARD,
            }
            if self.last_rss is not None:
                out["rss_bytes"] = self.last_rss
                out["peak_rss_bytes"] = self.peak_rss
        return out
