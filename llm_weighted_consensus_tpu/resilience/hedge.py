"""Hedged requests (Dean & Barroso, *The Tail at Scale*).

The chat client fires its primary attempt; if no first chunk has
arrived after the hedge delay, a single backup attempt is launched
against the *next* endpoint in the attempt matrix and the two race.
The first stream to commit (deliver a good first chunk) wins; the
loser is cancelled and closed.  The delay is either a static
millisecond value or an observed-latency quantile: a ``LatencyTracker``
records every committed attempt's time-to-first-chunk, and once it
holds enough samples the hedge fires at e.g. the p95 — "hedge only
the requests that are already slower than 95 % of their peers", the
paper's 'hedged request' recipe, which bounds extra load at ~(1-q).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


class LatencyTracker:
    """Bounded reservoir of recent latencies with an exact quantile.

    A plain ring of the last ``capacity`` observations: the serving
    path records one sample per committed attempt, so even a busy
    gateway writes a few hundred floats per second — no need for
    P² or t-digest approximations at this volume.
    """

    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(1, int(capacity))
        self._samples: List[float] = []
        self._next = 0
        self.total = 0

    def record(self, latency_ms: float) -> None:
        if len(self._samples) < self.capacity:
            self._samples.append(float(latency_ms))
        else:
            self._samples[self._next] = float(latency_ms)
            self._next = (self._next + 1) % self.capacity
        self.total += 1

    def __len__(self) -> int:
        return len(self._samples)

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile of the reservoir; None when empty."""
        if not self._samples:
            return None
        ordered = sorted(self._samples)
        q = min(1.0, max(0.0, q))
        rank = min(len(ordered) - 1, max(0, int(q * len(ordered) + 0.5) - 1))
        return ordered[rank]


@dataclass
class HedgePolicy:
    """When (and whether) to launch the backup attempt.

    ``delay_ms`` is the static floor; when ``quantile`` is set and the
    tracker has at least ``min_samples`` observations, the observed
    quantile replaces it.  ``delay_ms_effective()`` is what the chat
    client actually waits before hedging.
    """

    delay_ms: float = 0.0
    quantile: float = 0.0  # 0 = static delay only
    min_samples: int = 20
    tracker: LatencyTracker = field(default_factory=LatencyTracker)

    @property
    def enabled(self) -> bool:
        return self.delay_ms > 0 or self.quantile > 0

    def delay_ms_effective(self) -> Optional[float]:
        """Delay before the backup launches, or ``None`` when hedging is
        off for this attempt: a quantile-only config whose reservoir is
        still cold must not fall back to 0 ms — that would hedge *every*
        request (2x upstream load) until the tracker warms up."""
        if self.quantile > 0:
            if len(self.tracker) >= max(1, self.min_samples):
                observed = self.tracker.quantile(self.quantile)
                if observed is not None:
                    return observed
            if self.delay_ms <= 0:
                return None
        return self.delay_ms

    def observe(self, first_chunk_latency_ms: float) -> None:
        self.tracker.record(first_chunk_latency_ms)

    def explain(self) -> dict:
        """Why the hedge fired when it did — stamped on trace spans so a
        surprising backup launch is attributable to the reservoir state
        at decision time."""
        delay = self.delay_ms_effective()
        return {
            "delay_ms_effective": round(delay, 3) if delay is not None else None,
            "quantile": self.quantile,
            "static_delay_ms": self.delay_ms,
            "observations": self.tracker.total,
        }
