"""Deterministic fault injection at the Transport seam.

``FaultInjectionTransport`` wraps any real ``Transport`` and, per
request, consults a seeded ``FaultPlan`` for one of ten fault kinds —
the failure modes an OpenAI-compatible SSE upstream actually exhibits:

* ``connect``      — connection refused (``TransportError`` before any
  response exists);
* ``5xx``          — synthetic 503 with a JSON error body (the bad-status
  path, no stream);
* ``stall_first``  — delay before the first byte chunk (trips the
  first-chunk timeout tier / the hedge delay);
* ``stall_mid``    — delay mid-stream after bytes have flowed (trips the
  other-chunk tier — the committed-stream failure mode);
* ``malformed``    — an invalid SSE data frame injected mid-stream
  (exercises per-frame decode-error tolerance);
* ``truncate``     — stream ends early without ``[DONE]``.

Hostile-ingest kinds (ISSUE 19 — the byte-budget plane's adversaries,
all sized by the ``flood_bytes`` plan knob, default 8 MiB):

* ``giant_line``         — one complete, terminated ``data:`` line of
  ``flood_bytes`` payload injected after the first real chunk (trips
  the SSE event byte budget);
* ``newline_less_flood`` — ``flood_bytes`` of newline-less bytes
  streamed in chunks, then the stream cuts (trips the SSE residue
  buffer cap or the per-judge stream budget, whichever is tighter);
* ``oversized_unary``    — a synthetic bad-status response whose body
  is ``flood_bytes`` long (trips the unary body cap on the bad-status
  read path);
* ``binary_garbage``     — seeded random binary chunks injected
  mid-stream (exercises decode tolerance: garbage lines are not
  ``data:`` fields, mid-UTF-8 cuts must not crash the decoder).

Determinism: one ``random.Random(seed)`` drawn once per request in
request order, so a single-threaded test driving requests in a fixed
order sees the exact same fault sequence every run.  For tests that
want full control, ``FaultPlan.scripted([...])`` replays an explicit
fault list instead of sampling.

Selectable in production-shaped runs via the ``FAULT_PLAN`` env spec,
e.g. ``seed=42,connect=0.1,5xx=0.1,stall_first=0.1,stall_ms=200``.
"""

from __future__ import annotations

import asyncio
import random
from typing import AsyncIterator, Dict, List, Optional

# fault kinds, in the fixed order the sampler walks (order is part of
# the determinism contract — do not reorder)
CONNECT = "connect"
BAD_STATUS = "5xx"
STALL_FIRST = "stall_first"
STALL_MID = "stall_mid"
MALFORMED = "malformed"
TRUNCATE = "truncate"
GIANT_LINE = "giant_line"
NEWLINE_LESS_FLOOD = "newline_less_flood"
OVERSIZED_UNARY = "oversized_unary"
BINARY_GARBAGE = "binary_garbage"

KINDS = (
    CONNECT,
    BAD_STATUS,
    STALL_FIRST,
    STALL_MID,
    MALFORMED,
    TRUNCATE,
    GIANT_LINE,
    NEWLINE_LESS_FLOOD,
    OVERSIZED_UNARY,
    BINARY_GARBAGE,
)

_MALFORMED_FRAME = b"data: {this is not json\n\n"

# chunk size hostile floods stream in — large enough that an 8 MiB flood
# is ~128 chunks, small enough to exercise incremental byte accounting
_FLOOD_CHUNK = 64 * 1024


def iter_plan_spec(spec: str, label: str):
    """Yield ``(key, value)`` pairs from a comma-separated ``key=value``
    plan spec — the shared grammar of every ``*_PLAN`` env knob
    (``FAULT_PLAN``, ``JUDGE_BIAS_PLAN``, ``FLEET_FAULT_PLAN``).
    ``label`` names the knob in error messages so a bad spec points at
    the env var the operator actually set."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"{label}: expected key=value, got {part!r}")
        key, _, value = part.partition("=")
        yield key.strip(), value.strip()


class FaultPlan:
    """Per-request fault schedule: seeded sampling or an explicit script."""

    def __init__(
        self,
        seed: int = 0,
        probabilities: Optional[Dict[str, float]] = None,
        stall_ms: float = 100.0,
        flood_bytes: int = 8 << 20,
        script: Optional[List[Optional[str]]] = None,
    ) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.probabilities = {
            kind: float((probabilities or {}).get(kind, 0.0)) for kind in KINDS
        }
        self.stall_ms = float(stall_ms)
        self.flood_bytes = max(1, int(flood_bytes))
        self._script = list(script) if script is not None else None
        self._script_pos = 0
        self.requests = 0
        self.injected: Dict[str, int] = {kind: 0 for kind in KINDS}

    @classmethod
    def scripted(
        cls, faults: List[Optional[str]], *, stall_ms: float = 100.0
    ) -> "FaultPlan":
        """Replay ``faults`` verbatim (None = healthy request); healthy
        after exhaustion."""
        return cls(script=faults, stall_ms=stall_ms)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse a ``FAULT_PLAN`` env spec.

        Comma-separated ``key=value``: ``seed``, ``stall_ms``,
        ``flood_bytes`` (hostile-ingest payload size), one key per fault
        kind with its probability, or ``script=a|b|ok|c`` (``ok``/empty
        = healthy slot).
        """
        seed = 0
        stall_ms = 100.0
        flood_bytes = 8 << 20
        probs: Dict[str, float] = {}
        script: Optional[List[Optional[str]]] = None
        for key, value in iter_plan_spec(spec, "FAULT_PLAN"):
            if key == "seed":
                seed = int(value)
            elif key == "stall_ms":
                stall_ms = float(value)
            elif key == "flood_bytes":
                flood_bytes = int(value)
            elif key == "script":
                script = [
                    None if slot in ("", "ok") else slot
                    for slot in value.split("|")
                ]
                for slot in script:
                    if slot is not None and slot not in KINDS:
                        raise ValueError(f"FAULT_PLAN: unknown fault {slot!r}")
            elif key in KINDS:
                probs[key] = float(value)
            else:
                raise ValueError(f"FAULT_PLAN: unknown key {key!r}")
        return cls(
            seed=seed,
            probabilities=probs,
            stall_ms=stall_ms,
            flood_bytes=flood_bytes,
            script=script,
        )

    def next_fault(self) -> Optional[str]:
        """The fault for the next request (None = healthy)."""
        self.requests += 1
        if self._script is not None:
            if self._script_pos >= len(self._script):
                return None
            fault = self._script[self._script_pos]
            self._script_pos += 1
            if fault is not None:
                self.injected[fault] += 1
            return fault
        draw = self.rng.random()
        edge = 0.0
        for kind in KINDS:
            edge += self.probabilities[kind]
            if draw < edge:
                self.injected[kind] += 1
                return kind
        return None

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "injected": {k: v for k, v in self.injected.items() if v},
        }


# judge-bias kinds, in the fixed order the sampler walks (order is part
# of the determinism contract — do not reorder).  All three perturb a
# soft vote vector Decimal-in, Decimal-out (LWC005):
#
# * ``flip``    — rotate the vote vector one candidate left: the judge
#   systematically prefers "the next" candidate, so it disagrees with
#   consensus on any peaked ballot;
# * ``uniform`` — replace the vote with 1/n everywhere: a maximally
#   hedged, uninformative judge (calibration drifts, agreement decays
#   toward chance);
# * ``invert``  — reverse the vote vector: strong votes become strong
#   votes for the opposite end of the candidate list.
BIAS_FLIP = "flip"
BIAS_UNIFORM = "uniform"
BIAS_INVERT = "invert"

BIAS_KINDS = (BIAS_FLIP, BIAS_UNIFORM, BIAS_INVERT)


class JudgeBiasPlan:
    """Deterministic per-judge vote perturbation (``JUDGE_BIAS_PLAN``).

    The consensus-quality analogue of ``FaultPlan``: where FaultPlan
    breaks the transport, this miscalibrates a *judge* — so the drift
    detector in ``obs/quality.py`` can be drilled with a reproducibly
    biased panel member instead of waiting for a real model to rot.

    Determinism does not depend on judge-stream interleaving: each
    judge keeps its own ballot ordinal, and the per-ballot decision is
    drawn from ``random.Random((seed << 16) ^ (judge << 8) ^ ordinal)``
    — the same judge sees the same perturbation sequence no matter how
    the async fan-out schedules it.  ``after`` healthy ballots are
    passed through first so the drift baseline can form before the
    bias begins.
    """

    def __init__(
        self,
        judge: int = 0,
        seed: int = 0,
        after: int = 0,
        probabilities: Optional[Dict[str, float]] = None,
        script: Optional[List[Optional[str]]] = None,
    ) -> None:
        self.judge = int(judge)
        self.seed = int(seed)
        self.after = max(0, int(after))
        self.probabilities = {
            kind: float((probabilities or {}).get(kind, 0.0))
            for kind in BIAS_KINDS
        }
        self._script = list(script) if script is not None else None
        self._ordinals: Dict[int, int] = {}
        self.injected: Dict[str, int] = {kind: 0 for kind in BIAS_KINDS}

    @classmethod
    def parse(cls, spec: str) -> "JudgeBiasPlan":
        """Parse a ``JUDGE_BIAS_PLAN`` env spec.

        Comma-separated ``key=value``: ``judge`` (target judge index),
        ``seed``, ``after`` (healthy ballots before bias begins), one
        key per bias kind with its probability, or ``script=flip|ok``
        (``ok``/empty = honest ballot), e.g.
        ``judge=2,after=16,flip=1.0,seed=7``.
        """
        judge = 0
        seed = 0
        after = 0
        probs: Dict[str, float] = {}
        script: Optional[List[Optional[str]]] = None
        for key, value in iter_plan_spec(spec, "JUDGE_BIAS_PLAN"):
            if key == "judge":
                judge = int(value)
            elif key == "seed":
                seed = int(value)
            elif key == "after":
                after = int(value)
            elif key == "script":
                script = [
                    None if slot in ("", "ok") else slot
                    for slot in value.split("|")
                ]
                for slot in script:
                    if slot is not None and slot not in BIAS_KINDS:
                        raise ValueError(
                            f"JUDGE_BIAS_PLAN: unknown bias {slot!r}"
                        )
            elif key in BIAS_KINDS:
                probs[key] = float(value)
            else:
                raise ValueError(f"JUDGE_BIAS_PLAN: unknown key {key!r}")
        return cls(
            judge=judge,
            seed=seed,
            after=after,
            probabilities=probs,
            script=script,
        )

    def _next_bias(self, judge_index: int) -> Optional[str]:
        ordinal = self._ordinals.get(judge_index, 0)
        self._ordinals[judge_index] = ordinal + 1
        if judge_index != self.judge or ordinal < self.after:
            return None
        slot = ordinal - self.after
        if self._script is not None:
            if slot >= len(self._script):
                return None
            bias = self._script[slot]
            if bias is not None:
                self.injected[bias] += 1
            return bias
        draw = random.Random(
            (self.seed << 16) ^ (judge_index << 8) ^ slot
        ).random()
        edge = 0.0
        for kind in BIAS_KINDS:
            edge += self.probabilities[kind]
            if draw < edge:
                self.injected[kind] += 1
                return kind
        return None

    def perturb(self, judge_index: int, vote: list) -> list:
        """The (possibly perturbed) vote for ``judge_index``'s next
        ballot.  Decimal-in, Decimal-out: every kind permutes or
        replaces entries without any float arithmetic."""
        bias = self._next_bias(judge_index)
        if bias is None or len(vote) < 2:
            return vote
        if bias == BIAS_FLIP:
            return list(vote[1:]) + [vote[0]]
        if bias == BIAS_INVERT:
            return list(reversed(vote))
        # BIAS_UNIFORM
        from decimal import Decimal

        n = Decimal(len(vote))
        return [Decimal(1) / n for _ in vote]

    def snapshot(self) -> dict:
        return {
            "judge": self.judge,
            "after": self.after,
            "ballots": dict(self._ordinals),
            "injected": {k: v for k, v in self.injected.items() if v},
        }


class _SyntheticBadStatus:
    """A response-shaped 503 that never touched the network."""

    status = 503

    async def read_body(self) -> bytes:
        return b'{"error":{"code":503,"message":"fault-injected upstream error"}}'

    async def byte_stream(self) -> AsyncIterator[bytes]:
        return
        yield b""  # pragma: no cover — makes this an async generator

    async def close(self) -> None:
        pass


class _SyntheticOversizedBody:
    """A bad-status response whose body is a ``flood_bytes`` blob — the
    hostile upstream that answers a failed request with a memory bomb
    instead of an error envelope (trips the unary body cap)."""

    status = 503

    def __init__(self, n_bytes: int) -> None:
        self._n_bytes = n_bytes

    async def read_body(self) -> bytes:
        return b"x" * self._n_bytes

    async def byte_stream(self) -> AsyncIterator[bytes]:
        return
        yield b""  # pragma: no cover — makes this an async generator

    async def close(self) -> None:
        pass


class _FaultedResponse:
    """Delegates to the real response, perturbing the byte stream."""

    def __init__(
        self,
        inner,
        fault: Optional[str],
        stall_s: float,
        flood_bytes: int = 8 << 20,
        garbage_rng: Optional[random.Random] = None,
    ) -> None:
        self._inner = inner
        self._fault = fault
        self._stall_s = stall_s
        self._flood_bytes = flood_bytes
        self._garbage_rng = garbage_rng
        self.status = inner.status

    async def read_body(self) -> bytes:
        return await self._inner.read_body()

    async def byte_stream(self) -> AsyncIterator[bytes]:
        seen = 0
        async for data in self._inner.byte_stream():
            if seen == 0 and self._fault == STALL_FIRST:
                await asyncio.sleep(self._stall_s)
            if seen == 1 and self._fault == STALL_MID:
                await asyncio.sleep(self._stall_s)
            yield data
            seen += 1
            if seen == 1:
                if self._fault == MALFORMED:
                    yield _MALFORMED_FRAME
                elif self._fault == GIANT_LINE:
                    # one complete, terminated data line: the parser sees
                    # the newline and trips the *event* budget (not the
                    # residue cap) on a deterministic byte boundary
                    yield b"data: " + b"A" * self._flood_bytes + b"\n\n"
                elif self._fault == NEWLINE_LESS_FLOOD:
                    # newline-less chunks accumulate as parser residue
                    # (or burn the per-judge stream budget), then the
                    # stream cuts without [DONE]
                    remaining = self._flood_bytes
                    while remaining > 0:
                        n = min(remaining, _FLOOD_CHUNK)
                        yield b"B" * n
                        remaining -= n
                    return
                elif self._fault == BINARY_GARBAGE:
                    # seeded random chunks: newlines land anywhere, lines
                    # are not data: fields, UTF-8 cuts mid-sequence — the
                    # decode-tolerance gauntlet
                    for _ in range(4):
                        yield self._garbage_rng.randbytes(4096)
            if self._fault == TRUNCATE and seen >= 1:
                return

    async def close(self) -> None:
        await self._inner.close()


class FaultInjectionTransport:
    """A ``Transport`` decorator: same interface, scheduled misbehavior."""

    def __init__(self, inner, plan: FaultPlan) -> None:
        self.inner = inner
        self.plan = plan

    async def post_sse(self, url: str, headers: dict, body: bytes):
        from ..errors import TransportError

        fault = self.plan.next_fault()
        if fault == CONNECT:
            raise TransportError("fault-injected connection refused")
        if fault == BAD_STATUS:
            return _SyntheticBadStatus()
        if fault == OVERSIZED_UNARY:
            return _SyntheticOversizedBody(self.plan.flood_bytes)
        resp = await self.inner.post_sse(url, headers, body)
        if fault is None:
            return resp
        garbage_rng = None
        if fault == BINARY_GARBAGE:
            # fresh per-request rng keyed on (seed, request ordinal): the
            # garbage is deterministic without disturbing the plan rng's
            # next_fault draw sequence
            garbage_rng = random.Random(
                (self.plan.seed << 16) ^ self.plan.requests
            )
        return _FaultedResponse(
            resp,
            fault,
            self.plan.stall_ms / 1000.0,
            flood_bytes=self.plan.flood_bytes,
            garbage_rng=garbage_rng,
        )

    async def close(self) -> None:
        await self.inner.close()
