"""Mesh fault domains: keep serving through device loss (``MESH_FAULT_*``).

PR 9 put the serving path on a dp×tp mesh; this module is its failure
story.  A bad chip on an 8-chip dispatch used to surface as an
``XlaRuntimeError`` (or a wedge the watchdog catches) with exactly one
recovery lever — collapse to the single-device CPU twin.  Instead:

* ``classify_dispatch_error`` sorts dispatch failures at the
  embedder/batcher seam into *transient* (retry on the same shape) vs
  *persistent* (a device is gone) vs not-a-device-fault (ordinary
  application errors keep their existing fail-the-group path);
* ``MeshFaultManager`` owns a pre-declared **downsize ladder** — dp
  halving toward 1, tp preserved — with every rung's mesh built over a
  device-list *prefix* (parallel/mesh.py reshapes ``devices[:n]``, so
  each rung is a subset of the last and dropping the tail sheds the
  faulted fault-domain).  Every rung is ``aot_warmup``-ed at startup
  under its own ``("mesh", dp, tp)`` key namespace, so a downsize is a
  param re-shard plus an executable-table swap, not a compile storm;
* the batcher re-queues the failed group's in-flight items onto the new
  shape, bounded by their propagated deadlines (past-budget items shed
  504 exactly like the PR 4 drain path), and the admission controller's
  AIMD limit plus the batcher capacity rescale to the surviving chips;
* a recovery prober periodically re-validates the full mesh and upsizes
  back; readiness stays up throughout, flagged ``degraded_mesh``.

``DeviceFaultPlan`` is the deterministic injection seam (the
``DEVICE_FAULT_PLAN`` env spec), mirroring ``faults.FaultPlan``'s
seeded-plan contract at the embedder dispatch boundary instead of the
Transport seam: raise-transient, raise-persistent, or hang (a bounded
sleep the watchdog can observe, then a raise — so tier-1 never blocks).

Pure-core hygiene: jax is imported lazily inside the methods that
re-shard, never at module scope.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Dict, List, Optional

# device-fault kinds, in the fixed order the sampler walks (order is
# part of the determinism contract — do not reorder)
TRANSIENT = "transient"
PERSISTENT = "persistent"
HANG = "hang"

DEVICE_FAULT_KINDS = (TRANSIENT, PERSISTENT, HANG)

# XlaRuntimeError status substrings that mean "retry the dispatch":
# allocator pressure and preempted/aborted collectives clear on their
# own; anything stateful (device halted, data loss) does not.
_TRANSIENT_STATUSES = ("RESOURCE_EXHAUSTED", "ABORTED", "UNAVAILABLE")
_PERSISTENT_STATUSES = (
    "DATA_LOSS",
    "INTERNAL",
    "FAILED_PRECONDITION",
    "device halted",
    "Device lost",
)


class InjectedTransientError(RuntimeError):
    """A ``DeviceFaultPlan`` transient dispatch failure."""


class InjectedPersistentError(RuntimeError):
    """A ``DeviceFaultPlan`` persistent device loss."""


class InjectedHangError(RuntimeError):
    """Raised after a ``DeviceFaultPlan`` hang's bounded sleep (the real
    failure mode never returns; the sleep gives the watchdog its overdue
    observation, then this raise unwedges the test)."""


def classify_dispatch_error(exc: BaseException) -> Optional[str]:
    """Sort a dispatch exception: ``"transient"`` / ``"persistent"`` /
    ``None`` (not a device fault — ordinary application error).

    Matches ``XlaRuntimeError`` by type NAME, not import: the class
    lives in jaxlib and this module keeps the pure-core no-jax-at-scope
    rule.  Unknown XLA statuses classify transient — one free retry
    costs a dispatch, while a wrong "persistent" costs half the mesh
    (the escalation counter in ``MeshFaultManager.classify`` converts a
    transient streak into persistent anyway).
    """
    if isinstance(exc, InjectedTransientError):
        return TRANSIENT
    if isinstance(exc, InjectedPersistentError):
        return PERSISTENT
    if isinstance(exc, InjectedHangError):
        return TRANSIENT  # escalated via the watchdog-overdue note
    if type(exc).__name__ != "XlaRuntimeError":
        return None
    msg = str(exc)
    if any(status in msg for status in _PERSISTENT_STATUSES):
        return PERSISTENT
    if any(status in msg for status in _TRANSIENT_STATUSES):
        return TRANSIENT
    return TRANSIENT


class DeviceFaultPlan:
    """Per-dispatch device-fault schedule: seeded sampling or a script.

    The ``faults.FaultPlan`` contract verbatim — one
    ``random.Random(seed)`` drawn once per dispatch in dispatch order,
    or ``scripted([...])`` replay — but at the embedder dispatch
    boundary with the device failure modes: ``transient``,
    ``persistent``, ``hang``.
    """

    def __init__(
        self,
        seed: int = 0,
        probabilities: Optional[Dict[str, float]] = None,
        hang_ms: float = 50.0,
        script: Optional[List[Optional[str]]] = None,
    ) -> None:
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.probabilities = {
            kind: float((probabilities or {}).get(kind, 0.0))
            for kind in DEVICE_FAULT_KINDS
        }
        self.hang_ms = float(hang_ms)
        self._script = list(script) if script is not None else None
        self._script_pos = 0
        self.requests = 0
        self.injected: Dict[str, int] = {
            kind: 0 for kind in DEVICE_FAULT_KINDS
        }

    @classmethod
    def scripted(
        cls, faults: List[Optional[str]], *, hang_ms: float = 50.0
    ) -> "DeviceFaultPlan":
        """Replay ``faults`` verbatim (None = healthy dispatch); healthy
        after exhaustion."""
        return cls(script=faults, hang_ms=hang_ms)

    @classmethod
    def parse(cls, spec: str) -> "DeviceFaultPlan":
        """Parse a ``DEVICE_FAULT_PLAN`` env spec.

        Comma-separated ``key=value``: ``seed``, ``hang_ms``, one key
        per fault kind with its probability, or ``script=a|b|ok|c``
        (``ok``/empty = healthy slot).
        """
        seed = 0
        hang_ms = 50.0
        probs: Dict[str, float] = {}
        script: Optional[List[Optional[str]]] = None
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"DEVICE_FAULT_PLAN: expected key=value, got {part!r}"
                )
            key, _, value = part.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "seed":
                seed = int(value)
            elif key == "hang_ms":
                hang_ms = float(value)
            elif key == "script":
                script = [
                    None if slot in ("", "ok") else slot
                    for slot in value.split("|")
                ]
                for slot in script:
                    if slot is not None and slot not in DEVICE_FAULT_KINDS:
                        raise ValueError(
                            f"DEVICE_FAULT_PLAN: unknown fault {slot!r}"
                        )
            elif key in DEVICE_FAULT_KINDS:
                probs[key] = float(value)
            else:
                raise ValueError(f"DEVICE_FAULT_PLAN: unknown key {key!r}")
        return cls(
            seed=seed, probabilities=probs, hang_ms=hang_ms, script=script
        )

    def next_fault(self) -> Optional[str]:
        """The fault for the next dispatch (None = healthy)."""
        self.requests += 1
        if self._script is not None:
            if self._script_pos >= len(self._script):
                return None
            fault = self._script[self._script_pos]
            self._script_pos += 1
            if fault is not None:
                self.injected[fault] += 1
            return fault
        draw = self.rng.random()
        edge = 0.0
        for kind in DEVICE_FAULT_KINDS:
            edge += self.probabilities[kind]
            if draw < edge:
                self.injected[kind] += 1
                return kind
        return None

    def snapshot(self) -> dict:
        return {
            "requests": self.requests,
            "injected": {k: v for k, v in self.injected.items() if v},
        }


class _ShapeGate:
    """Reader-writer gate serializing mesh shape transitions with device
    dispatches.

    The batcher's dispatch executor runs ``pipeline_depth`` worker
    threads (2 by default), so "run the re-shard on the executor" does
    NOT serialize it with dispatches — a second worker can be mid-PJRT
    on the old params while ``shard_embedder_mesh`` mutates them.  Every
    dispatch therefore holds the *shared* side for the duration of its
    device call, and ``downsize``/``try_recover`` hold the *exclusive*
    side across the re-shard: a shape change waits out in-flight
    dispatches, and dispatches never observe a torn embedder.  Writer
    preference (a waiting writer blocks new readers) bounds the wait to
    the dispatches already in flight.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextlib.contextmanager
    def shared(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if not self._readers:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def exclusive(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class _Rung:
    """One ladder step: the shape plus the Mesh built at warmup.

    The Mesh object itself is load-bearing: mesh-mode AOT executables
    bake NamedShardings referencing the exact device set they lowered
    against, so downsizing MUST re-shard onto this stored mesh — an
    equal-shape mesh over different devices would fail the executables'
    aval check.
    """

    __slots__ = ("dp", "tp", "mesh", "devices")

    def __init__(self, dp: int, tp: int, mesh, devices: list) -> None:
        self.dp = dp
        self.tp = tp
        self.mesh = mesh
        self.devices = devices  # row-major prefix of the full device list


class MeshFaultManager:
    """The mesh fault-domain brain: classify → downsize → re-dispatch →
    probe → upsize.

    Thread-safety: ``classify``/``note_*``/``snapshot`` and the state
    properties run under a lock (dispatch executor threads + event loop
    all call in).  ``downsize`` and ``try_recover`` mutate the embedder,
    which the dispatch threads read mid-PJRT-call; they take the
    exclusive side of the shape gate (``_ShapeGate``) across the
    re-shard while every dispatch holds the shared side
    (``dispatch_guard``, wired in the batcher's ``_dispatch``), so a
    shape change drains in-flight dispatches first no matter which
    thread runs it.
    """

    def __init__(
        self,
        embedder,
        *,
        shape: tuple,
        transient_retries: int = 2,
        probe_millis: float = 0.0,
        fault_plan: Optional[DeviceFaultPlan] = None,
        clock=time.monotonic,
    ) -> None:
        self.embedder = embedder
        self.full_shape = (int(shape[0]), int(shape[1]))
        # sequence-parallel width (MESH_SHAPE=dp,tp,sp): preserved down
        # every rung like tp — the ladder halves dp only, so a degraded
        # mesh keeps serving long-context ring traffic
        self.sp = int(getattr(embedder, "mesh_sp", 1) or 1)
        self.transient_retries = int(transient_retries)
        self.probe_millis = float(probe_millis)
        self.fault_plan = fault_plan
        self.clock = clock
        # callables(scale: float) run after every shape change — the
        # admission AIMD limit and the batcher capacity rescale hooks
        self.rescale_hooks: list = []
        # optional zero-arg probe run on the full shape by try_recover
        # AFTER the upsize re-shard; a device-classified raise rolls the
        # upsize back
        self.probe_fn = None
        # re-entrant: snapshot() reads the locked state properties while
        # already holding the lock
        self._lock = threading.RLock()
        self._shape_gate = _ShapeGate()
        self._rungs: List[_Rung] = []
        self._rung_index = 0
        self._epoch = 0
        self._downsizes = 0
        self._upsizes = 0
        self._re_dispatches = 0
        self._probe_failures = 0
        self._consecutive_probe_failures = 0
        self._warned_blind_upsize = False
        self._transient_streak = 0
        self._watchdog_overdue = False
        self._faulted_devices: list = []

    # -- ladder construction / warmup ----------------------------------------

    def build_ladder(self) -> List[tuple]:
        """Declare the downsize ladder (without warming it): dp halving
        from the full shape toward 1, tp preserved, each rung's mesh a
        row-major device-list prefix of the previous rung's."""
        from ..parallel.mesh import make_mesh

        if self._rungs:
            return [(r.dp, r.tp) for r in self._rungs]
        full_mesh = self.embedder.mesh
        if full_mesh is None:
            raise RuntimeError(
                "MeshFaultManager needs a mesh-sharded embedder "
                "(parallel.shard_embedder_mesh) before build_ladder"
            )
        devices = list(full_mesh.devices.reshape(-1))
        dp, tp = self.full_shape
        sp = self.sp
        self._rungs = [_Rung(dp, tp, full_mesh, devices)]
        step = dp // 2
        while step >= 1:
            sub = devices[: step * tp * sp]
            mesh = make_mesh(dp=step, tp=tp, sp=sp, devices=sub)
            self._rungs.append(_Rung(step, tp, mesh, sub))
            step //= 2
        return [(r.dp, r.tp) for r in self._rungs]

    def warm_ladder(
        self,
        specs: list,
        r_buckets: list = (),
        packed_buckets: list = (),
        ring_buckets: list = (),
    ) -> list:
        """AOT-warm every fallback rung so a downsize never compiles.

        Walks the ladder bottom-up (smallest rung first, full shape
        last): each step re-shards the embedder's params onto the rung
        mesh and runs the same ``aot_warmup`` bucket set the primary
        shape got, landing executables under that rung's
        ``("mesh", dp, tp)`` key namespace.  The final step is the full
        shape, so the embedder exits warmed AND sharded exactly as it
        entered.  Returns [(label, seconds)] for startup logging.
        """
        from ..parallel.sharding import shard_embedder_mesh

        self.build_ladder()
        timings = []
        with self._shape_gate.exclusive():
            for rung in reversed(self._rungs):
                shard_embedder_mesh(self.embedder, rung.mesh)
                timings.extend(
                    self.embedder.aot_warmup(
                        specs, r_buckets, packed_buckets, ring_buckets
                    )
                )
        return timings

    # -- dispatch/transition serialization ------------------------------------

    def dispatch_guard(self):
        """Shared-side context for one device dispatch: the batcher's
        ``_dispatch`` holds this across the embedder call, so
        ``downsize``/``try_recover`` (exclusive side) wait out in-flight
        dispatches before re-sharding instead of tearing the params a
        concurrent executor thread is reading."""
        return self._shape_gate.shared()

    # -- classification -------------------------------------------------------

    def classify(self, exc: BaseException) -> Optional[str]:
        """Policy layered over ``classify_dispatch_error``: a watchdog
        overdue note or a transient streak past ``transient_retries``
        escalates transient to persistent."""
        kind = classify_dispatch_error(exc)
        if kind is None:
            return None
        with self._lock:
            if kind == PERSISTENT:
                self._transient_streak = 0
                self._watchdog_overdue = False
                return PERSISTENT
            if self._watchdog_overdue:
                self._watchdog_overdue = False
                self._transient_streak = 0
                return PERSISTENT
            self._transient_streak += 1
            if self._transient_streak > self.transient_retries:
                self._transient_streak = 0
                return PERSISTENT
            return TRANSIENT

    def note_watchdog_trip(self) -> None:
        """Mark the next classified dispatch failure persistent: a
        watchdog-overdue dispatch is a wedge, not a blip (wired from the
        watchdog's on_trip in serve/__main__.py)."""
        with self._lock:
            self._watchdog_overdue = True

    def note_dispatch_ok(self) -> None:
        """A clean dispatch resets the transient-escalation streak."""
        with self._lock:
            self._transient_streak = 0
            self._watchdog_overdue = False

    def note_redispatch(self, count: int = 1) -> None:
        with self._lock:
            self._re_dispatches += int(count)

    # -- injection seam --------------------------------------------------------

    def maybe_inject(self) -> None:
        """The ``DEVICE_FAULT_PLAN`` seam, called at the top of every
        device dispatch (serve/batcher.py's ``_dispatch``, on the
        dispatch thread).  ``hang`` sleeps ``hang_ms`` — long enough for
        the watchdog monitor to observe the overdue bracket — then
        raises, so tier-1 can never block on a real wedge."""
        plan = self.fault_plan
        if plan is None:
            return
        with self._lock:
            fault = plan.next_fault()
        if fault == TRANSIENT:
            raise InjectedTransientError(
                "DEVICE_FAULT_PLAN: injected transient dispatch failure"
            )
        if fault == PERSISTENT:
            raise InjectedPersistentError(
                "DEVICE_FAULT_PLAN: injected persistent device loss"
            )
        if fault == HANG:
            time.sleep(plan.hang_ms / 1000.0)
            raise InjectedHangError(
                "DEVICE_FAULT_PLAN: injected dispatch hang"
            )

    # -- shape transitions -----------------------------------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._rung_index > 0

    @property
    def exhausted(self) -> bool:
        """Past the last rung: every fallback shape is spent and the
        CPU twin (DEVICE_WATCHDOG_CPU_FALLBACK) is the only lever left."""
        with self._lock:
            return self._rung_index >= len(self._rungs) - 1

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def current_shape(self) -> tuple:
        with self._lock:
            if not self._rungs:
                return self.full_shape
            rung = self._rungs[self._rung_index]
            return (rung.dp, rung.tp)

    def _rescale(self) -> None:
        scale = self.current_shape[0] / self.full_shape[0]
        for hook in self.rescale_hooks:
            hook(scale)

    def downsize(self, observed_epoch: Optional[int] = None) -> bool:
        """Step down one ladder rung: re-shard params onto the stored
        surviving submesh (the executable-table swap is implicit —
        dispatch keys follow ``embedder.mesh_shape``), record the
        dropped tail devices as the faulted domain, bump the epoch, and
        rescale admission/batcher capacity.  Returns False when the
        ladder is exhausted (caller falls back to the CPU twin).

        ``observed_epoch`` is the mesh epoch the failed dispatch was
        stamped with at launch.  Pipelined dispatches can fault on the
        SAME dead device concurrently; only the first fault per epoch
        may step the ladder — a stale epoch means the shape already
        changed since this dispatch launched, so the fault is old news
        and the caller should just re-queue onto the current shape
        (returns True without stepping).

        Runs under the exclusive side of the shape gate: the re-shard
        waits out in-flight dispatches (which hold the shared side), so
        it is safe from any thread — including the multi-worker dispatch
        executor.
        """
        from ..parallel.sharding import shard_embedder_mesh

        self.build_ladder()
        with self._shape_gate.exclusive():
            with self._lock:
                if (
                    observed_epoch is not None
                    and observed_epoch != self._epoch
                ):
                    return True
                if self._rung_index >= len(self._rungs) - 1:
                    return False
                old = self._rungs[self._rung_index]
                self._rung_index += 1
                rung = self._rungs[self._rung_index]
                dropped = [
                    d for d in old.devices if d not in rung.devices
                ]
                self._faulted_devices.extend(
                    getattr(d, "id", d) for d in dropped
                )
                self._downsizes += 1
                self._epoch += 1
                self._transient_streak = 0
                self._watchdog_overdue = False
            shard_embedder_mesh(self.embedder, rung.mesh)
        self._rescale()
        return True

    def try_recover(self) -> bool:
        """The recovery probe: while degraded, re-validate the full mesh
        and upsize back.  A ``DeviceFaultPlan`` draw models the probe
        dispatch (a still-faulty plan keeps the mesh down); with a real
        ``probe_fn`` attached (serve/__main__.py wires a warmed-bucket
        full-mesh dispatch), the upsize re-shard happens first and a
        device-classified raise rolls it back.  With NEITHER, the upsize
        is unvalidated — a still-dead device faults the next dispatch
        and the mesh flaps down again — so that mode logs a one-time
        warning.  Holds the exclusive side of the shape gate across the
        re-shard + probe + possible rollback, like ``downsize``.
        """
        from ..parallel.sharding import shard_embedder_mesh

        if not self.degraded:
            return False
        if self.fault_plan is not None:
            with self._lock:
                fault = self.fault_plan.next_fault()
            if fault is not None:
                with self._lock:
                    self._probe_failures += 1
                    self._consecutive_probe_failures += 1
                return False
        elif self.probe_fn is None:
            # test-and-set under the lock: two prober threads racing the
            # unlocked flag would both pass the check and double-warn
            with self._lock:
                warn = not self._warned_blind_upsize
                self._warned_blind_upsize = True
            if warn:
                import logging

                logging.getLogger("lwc.resilience").warning(
                    "mesh fault recovery has no probe_fn and no "
                    "DEVICE_FAULT_PLAN: upsizing to the full mesh "
                    "without validating it — a still-dead device will "
                    "fault the next dispatch and downsize again (attach "
                    "probe_fn, as serve/__main__.py does, to validate "
                    "before upsizing)"
                )
        with self._shape_gate.exclusive():
            with self._lock:
                prev_index = self._rung_index
            full = self._rungs[0]
            shard_embedder_mesh(self.embedder, full.mesh)
            if self.probe_fn is not None:
                try:
                    self.probe_fn()
                except Exception as exc:
                    # roll back FIRST either way: the manager still
                    # reports the surviving rung, so the embedder must
                    # not be left sharded at the full shape
                    shard_embedder_mesh(
                        self.embedder, self._rungs[prev_index].mesh
                    )
                    if classify_dispatch_error(exc) is None:
                        raise  # probe bug, not a device fault
                    with self._lock:
                        self._probe_failures += 1
                        self._consecutive_probe_failures += 1
                    self._rescale()
                    return False
            with self._lock:
                self._rung_index = 0
                self._upsizes += 1
                self._epoch += 1
                self._faulted_devices.clear()
                self._consecutive_probe_failures = 0
                self._transient_streak = 0
                self._watchdog_overdue = False
        self._rescale()
        return True

    def probe_backoff_scale(self, cap: float = 32.0) -> float:
        """Multiplier for the prober's sleep between attempts: doubles
        per consecutive probe failure (capped) so a long-dead device is
        probed ever more lazily — each failed probe re-shards and
        rolls back, work worth not repeating every interval — and resets
        to 1 on a successful upsize."""
        with self._lock:
            return float(
                min(2.0 ** self._consecutive_probe_failures, cap)
            )

    # -- observability ---------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``meshfault`` /metrics section."""
        with self._lock:
            snap = {
                "current_shape": list(self.current_shape),
                "full_shape": list(self.full_shape),
                "sp": self.sp,
                "degraded": self.degraded,
                "epoch": self._epoch,
                "downsizes": self._downsizes,
                "upsizes": self._upsizes,
                "re_dispatches": self._re_dispatches,
                "probe_failures": self._probe_failures,
                "probe_backoff": self.probe_backoff_scale(),
                "faulted_devices": list(self._faulted_devices),
                "ladder": [[r.dp, r.tp] for r in self._rungs],
            }
            if self.fault_plan is not None:
                snap["fault_plan"] = self.fault_plan.snapshot()
        return snap
