"""Weight-quorum graceful degradation.

The consensus argmax is over ``choice_weight[i] = Σ vote_j[i] · w_j``.
A judge that has not settled yet can move any single candidate by at
most its full weight (votes are distributions, each component ≤ 1), so
once

    settled_weight ≥ fraction · total_weight          (quorum reached)
    leader_weight  >  runner_up + remaining_weight    (argmax locked)

no combination of straggler votes can flip the winner and the fan-out
may cancel them.  The flip test is strict (``>``): on a potential tie
we keep waiting — conservative, because a tie would renormalize to a
different confidence vector even if the argmax index survived.

Arithmetic is ``Decimal`` end to end, matching the host-side tally in
``clients/score.py`` exactly — the early-exit decision must agree with
the number the full panel would have produced.
"""

from __future__ import annotations

from decimal import Decimal
from typing import Dict, Iterable, Optional, Set


class QuorumTracker:
    def __init__(
        self,
        weights_by_judge: Dict[int, Decimal],
        n_choices: int,
        fraction: float,
    ) -> None:
        self.weights = {int(k): Decimal(v) for k, v in weights_by_judge.items()}
        self.n_choices = int(n_choices)
        self.fraction = Decimal(str(fraction))
        self.total_weight = sum(self.weights.values(), Decimal(0))
        self.choice_weight = [Decimal(0)] * self.n_choices
        self._settled: Set[int] = set()
        self.settled_weight = Decimal(0)
        self.voted: Set[int] = set()
        self.errored: Set[int] = set()

    # -- outcome recording ----------------------------------------------------

    def record_vote(self, judge_index: int, vote: Iterable) -> None:
        """A judge's final frame arrived with a vote distribution."""
        if judge_index in self._settled:
            return
        self._mark_settled(judge_index)
        self.voted.add(judge_index)
        w = self.weights.get(judge_index, Decimal(0))
        for i, v in enumerate(vote):
            if i < self.n_choices:
                self.choice_weight[i] += Decimal(v) * w

    def record_error(self, judge_index: int) -> None:
        """A judge failed terminally: its outcome is known (contributes
        nothing) and its weight leaves the remaining pool."""
        if judge_index in self._settled:
            return
        self._mark_settled(judge_index)
        self.errored.add(judge_index)

    def _mark_settled(self, judge_index: int) -> None:
        self._settled.add(judge_index)
        self.settled_weight += self.weights.get(judge_index, Decimal(0))

    # -- the decision ---------------------------------------------------------

    def settled(self, judge_index: int) -> bool:
        return judge_index in self._settled

    @property
    def remaining_weight(self) -> Decimal:
        return self.total_weight - self.settled_weight

    def pending(self) -> Set[int]:
        return set(self.weights) - self._settled

    def leader(self) -> Optional[int]:
        if not any(w > 0 for w in self.choice_weight):
            return None
        return max(range(self.n_choices), key=lambda i: self.choice_weight[i])

    def decided(self) -> bool:
        """True when the stragglers mathematically cannot flip the argmax."""
        if self.fraction <= 0 or self.total_weight <= 0:
            return False
        if not self.pending():
            return False  # nothing left to cancel; let the merge finish
        if self.settled_weight < self.fraction * self.total_weight:
            return False
        lead = self.leader()
        if lead is None:
            return False
        runner_up = max(
            (w for i, w in enumerate(self.choice_weight) if i != lead),
            default=Decimal(0),
        )
        return self.choice_weight[lead] > runner_up + self.remaining_weight

    # -- observability --------------------------------------------------------

    def explain(self) -> dict:
        """The quorum decision as a trace attribute: why the fan-out was
        (or was not) cut.  Decimals stringified so the record survives
        JSON serialization without precision loss."""
        return {
            "settled_weight": str(self.settled_weight),
            "total_weight": str(self.total_weight),
            "remaining_weight": str(self.remaining_weight),
            "leader": self.leader(),
            "decided": self.decided(),
            "voted": sorted(self.voted),
            "errored": sorted(self.errored),
            "pending": sorted(self.pending()),
        }
