"""Shared retry budget: a token bucket scoped to one score request.

Without it, a browning-out upstream turns an N-judge fan-out into an
N-way retry storm: every judge independently walks the full backoff
schedule against the same sick endpoint.  The score client attaches one
``RetryBudget`` to the fan-out (via contextvar, so it flows into the
pump tasks the stream merge spawns) and the chat client's backoff loop
draws a token before every retry sleep; when the bucket is dry the
attempt fails over to the per-judge error path immediately instead of
hammering on.

An optional refill rate supports long-lived scopes (e.g. a process-wide
budget), but the per-request default is a fixed allotment — the scope
dies with the request.
"""

from __future__ import annotations

import contextvars
import time
from typing import Callable, Optional

_BUDGET: contextvars.ContextVar = contextvars.ContextVar(
    "lwc_retry_budget", default=None
)


class RetryBudget:
    def __init__(
        self,
        tokens: int,
        *,
        refill_per_sec: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.capacity = max(0, int(tokens))
        self._tokens = float(self.capacity)
        self.refill_per_sec = float(refill_per_sec)
        self.clock = clock
        self._last = clock()
        self.denied = 0
        self.spent = 0

    def _refill(self) -> None:
        if self.refill_per_sec <= 0:
            return
        now = self.clock()
        self._tokens = min(
            float(self.capacity),
            self._tokens + (now - self._last) * self.refill_per_sec,
        )
        self._last = now

    def try_acquire(self) -> bool:
        """Spend one retry token; False when the budget is exhausted."""
        self._refill()
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            self.spent += 1
            return True
        self.denied += 1
        return False

    @property
    def remaining(self) -> int:
        self._refill()
        return int(self._tokens)

    # -- contextvar scope -----------------------------------------------------

    def activate(self) -> contextvars.Token:
        """Install as the ambient budget; tasks created while active
        inherit it (contextvars copy per task)."""
        return _BUDGET.set(self)

    @staticmethod
    def deactivate(token: contextvars.Token) -> None:
        _BUDGET.reset(token)


def current_retry_budget() -> Optional[RetryBudget]:
    return _BUDGET.get()
