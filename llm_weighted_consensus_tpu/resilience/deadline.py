"""Deadline propagation: one per-request time budget, ambient everywhere.

The gateway stamps a ``Deadline`` when the request arrives (from the
client's ``x-deadline-ms`` header or the configured default) and
activates it on a contextvar.  Every layer below — the score fan-out's
merge loop, the chat client's retry/backoff/hedge decisions, the
per-chunk stream timeouts — reads ``current_deadline()`` and clamps its
own waits to ``remaining()``, so a request that has 800 ms left never
starts a 10 s first-chunk wait or a 1 s backoff sleep.

Propagation rides asyncio's context inheritance: tasks created while
the deadline is active (the stream-merge pump tasks, hedge attempts)
carry a copy automatically.  No deadline active = every clamp is a
no-op — the pre-resilience fixed-timeout behavior, byte for byte.
"""

from __future__ import annotations

import contextvars
import time
from typing import Callable, Optional

_DEADLINE: contextvars.ContextVar = contextvars.ContextVar(
    "lwc_deadline", default=None
)


class Deadline:
    def __init__(
        self,
        seconds: float,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.clock = clock
        self._expires_at = clock() + max(0.0, float(seconds))

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._expires_at - self.clock())

    def expired(self) -> bool:
        return self.clock() >= self._expires_at

    def clamp(self, timeout: Optional[float]) -> float:
        """The smaller of ``timeout`` and the remaining budget (a
        ``timeout`` of None means 'only the deadline bounds this')."""
        rem = self.remaining()
        return rem if timeout is None else min(timeout, rem)

    # -- contextvar scope -----------------------------------------------------

    def activate(self) -> contextvars.Token:
        return _DEADLINE.set(self)

    @staticmethod
    def deactivate(token: contextvars.Token) -> None:
        _DEADLINE.reset(token)


def current_deadline() -> Optional[Deadline]:
    return _DEADLINE.get()
