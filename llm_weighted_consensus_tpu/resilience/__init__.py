"""Tail-tolerant fan-out: the serving path's resilience subsystem.

The whole value of weighted consensus is that the panel tolerates
individual judge failure — but tolerance has to be *engineered* at the
transport edge, not assumed.  This package supplies the mechanisms, each
one its own module with a pure, clock-injectable core:

* ``breaker``   — per-upstream circuit breakers (closed/open/half-open
  over a sliding failure-rate window, keyed by ``api_base + model``),
  Nygard's pattern from *Release It!*: a browning-out upstream is skipped
  outright instead of timing out every judge that touches it;
* ``budget``    — a shared retry budget (token bucket) so the N judges
  of one score request cannot collectively retry-storm an upstream that
  is already failing;
* ``hedge``     — hedged requests in the spirit of Dean & Barroso's
  *The Tail at Scale*: after a delay (static, or an observed-latency
  quantile), a backup attempt races the primary and the loser is
  cancelled;
* ``deadline``  — a per-request deadline set at the gateway flows
  through the score fan-out into every chat attempt via a contextvar,
  so backoff/retry/hedge decisions respect the remaining budget instead
  of a fixed elapsed cap;
* ``quorum``    — weight-quorum graceful degradation: once enough panel
  weight has voted that the stragglers cannot flip the argmax, they are
  cancelled and the final frame ships with ``degraded: true``;
* ``faults``    — a deterministic, seeded fault-injection ``Transport``
  (connect refusal, 5xx, stalls, malformed SSE, truncation, plus the
  hostile-ingest kinds: giant lines, newline-less floods, oversized
  unary bodies, binary garbage) so every degradation path above is
  exercised in tests instead of discovered in production;
* ``memguard``  — a host memory governor sampling RSS against soft/hard
  watermarks: soft pressure shrinks cache/trace budgets and the AIMD
  limit, hard pressure sheds new work (``shed_reason: "memory"``) and
  flags ``degraded_mem`` on /readyz, recovering hysteretically;
* ``admission`` — overload protection at the gateway door: a hard
  in-flight cap plus an AIMD/gradient adaptive limit, shedding excess
  work with ``503 + Retry-After + shed_reason`` instead of queueing it
  to death (Netflix concurrency-limits / SRE load-shedding pattern);
* ``watchdog``  — a device dispatch watchdog (begin/end brackets around
  every batched TPU dispatch + a monitor thread): a hung PJRT dispatch
  marks the device unhealthy — readiness flips, admission sheds
  device-dependent work, and a configured CPU fallback takes over;
* ``meshfault`` — mesh fault domains for the dp×tp serving mesh:
  dispatch-failure classification (transient/persistent/watchdog-
  overdue), an AOT-prewarmed downsize ladder that re-shards onto the
  surviving submesh instead of collapsing to the CPU twin, deadline-
  bounded in-flight re-dispatch, a recovery prober that upsizes back,
  and the seeded ``DEVICE_FAULT_PLAN`` injection seam at the embedder
  dispatch boundary.

Everything is opt-in: a ``ResiliencePolicy`` of ``None`` (the default
everywhere) preserves pre-resilience behavior byte-for-byte.  Pure-core
hygiene: nothing here imports jax or aiohttp at module scope.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .admission import AdmissionConfig, AdmissionController  # noqa: F401
from .breaker import BreakerConfig, BreakerRegistry, CircuitBreaker  # noqa: F401
from .budget import RetryBudget, current_retry_budget  # noqa: F401
from .deadline import Deadline, current_deadline  # noqa: F401
from .faults import (  # noqa: F401
    FaultInjectionTransport,
    FaultPlan,
    JudgeBiasPlan,
)
from .hedge import HedgePolicy, LatencyTracker  # noqa: F401
from .memguard import MemGuard  # noqa: F401
from .meshfault import (  # noqa: F401
    DeviceFaultPlan,
    InjectedHangError,
    InjectedPersistentError,
    InjectedTransientError,
    MeshFaultManager,
    classify_dispatch_error,
)
from .quorum import QuorumTracker  # noqa: F401
from .watchdog import DeviceWatchdog  # noqa: F401


@dataclass
class ResiliencePolicy:
    """One bundle wired through the client and serving layers.

    Every member defaults to "off"; ``enabled`` properties gate each
    feature independently so e.g. breakers can run without hedging.
    Counters are plain ints mutated from the (single-threaded) event
    loop; ``snapshot()`` is the ``/metrics`` provider payload.
    """

    breakers: Optional[BreakerRegistry] = None
    hedge: Optional[HedgePolicy] = None
    # retries each score request's fan-out may spend collectively;
    # 0 = unlimited (no budget attached)
    retry_budget_tokens: int = 0
    # fraction of total panel weight that must settle before the
    # quorum early-exit is considered; 0 = disabled
    quorum_fraction: float = 0.0
    # default per-request deadline the gateway applies when the client
    # sends no x-deadline-ms header; 0 = none
    deadline_ms: float = 0.0
    counters: dict = field(default_factory=dict)

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def snapshot(self) -> dict:
        out = {"counters": dict(self.counters)}
        if self.breakers is not None:
            out["breakers"] = self.breakers.snapshot()
        if self.hedge is not None and self.hedge.tracker is not None:
            delay = self.hedge.delay_ms_effective()
            # None = quantile config still cold, hedging suppressed
            out["hedge_delay_ms"] = None if delay is None else round(delay, 2)
        return out


__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "BreakerConfig",
    "BreakerRegistry",
    "CircuitBreaker",
    "Deadline",
    "DeviceFaultPlan",
    "DeviceWatchdog",
    "FaultInjectionTransport",
    "FaultPlan",
    "HedgePolicy",
    "InjectedHangError",
    "InjectedPersistentError",
    "InjectedTransientError",
    "JudgeBiasPlan",
    "LatencyTracker",
    "MemGuard",
    "MeshFaultManager",
    "QuorumTracker",
    "ResiliencePolicy",
    "RetryBudget",
    "classify_dispatch_error",
    "current_deadline",
    "current_retry_budget",
]
