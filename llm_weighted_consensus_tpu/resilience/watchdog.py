"""Device dispatch watchdog: detect a wedged TPU before the operator does.

A hung PJRT dispatch (wedged tunnel, driver fault, a device-side
deadlock) does not raise — it just never returns, silently eating one
executor thread while every queued request behind it times out.  The
watchdog brackets each device dispatch (``begin``/``end`` hooks called
by ``serve/batcher.DeviceBatcher``) and a monitor thread checks the
open brackets against ``timeout_ms``: any dispatch overdue marks the
device unhealthy, which flips ``/readyz`` (the load balancer routes
away), makes admission shed device-dependent endpoints, and — where a
CPU fallback is configured — reroutes subsequent embed/consensus work
off the wedged device.  If the overdue dispatch eventually completes,
the device is marked healthy again and traffic returns.

Pure-core hygiene: clock-injectable, ``check()`` callable directly so
tests drive trip/recovery deterministically without the thread; the
thread itself is a thin ``check()`` loop.  Thread-safety matters here
(begin/end run on device-executor threads, check on the monitor
thread): one lock guards the bracket table and health flag.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class DeviceWatchdog:
    def __init__(
        self,
        timeout_ms: float,
        *,
        interval_ms: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        on_trip: Optional[Callable[[str, float], None]] = None,
        on_recover: Optional[Callable[[], None]] = None,
    ) -> None:
        self.timeout_ms = float(timeout_ms)
        # 0 = auto: four checks per timeout window bounds detection
        # latency at ~1.25x the configured timeout
        self.interval_ms = float(interval_ms) or max(
            10.0, self.timeout_ms / 4.0
        )
        self.clock = clock
        self.on_trip = on_trip
        self.on_recover = on_recover
        self._lock = threading.Lock()
        self._active: dict = {}  # token -> (start, label)
        self._seq = 0
        self._healthy = True
        self.trips = 0
        self.recoveries = 0
        self.dispatches = 0
        self._last_overdue_ms = 0.0
        self._last_label: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- dispatch brackets (called from device-executor threads) --------------

    def begin(self, label: str = "dispatch") -> int:
        with self._lock:
            self._seq += 1
            token = self._seq
            self._active[token] = (self.clock(), label)
            self.dispatches += 1
        return token

    def end(self, token: int) -> None:
        fire_recover = False
        with self._lock:
            self._active.pop(token, None)
            if not self._healthy and not self._overdue_locked():
                # the wedged dispatch came back and nothing else is
                # overdue: the device answers again
                self._healthy = True
                self.recoveries += 1
                fire_recover = True
        if fire_recover and self.on_recover is not None:
            self.on_recover()

    # -- the check (monitor thread, or tests directly) ------------------------

    # caller-holds-lock: DeviceWatchdog._lock (only end/check call this, inside their with-lock blocks)
    def _overdue_locked(self):
        now = self.clock()
        worst = None
        for start, label in self._active.values():
            elapsed_ms = (now - start) * 1e3
            if elapsed_ms > self.timeout_ms and (
                worst is None or elapsed_ms > worst[0]
            ):
                worst = (elapsed_ms, label)
        return worst

    def check(self) -> bool:
        """One watchdog pass; returns the current health."""
        fire_trip = None
        with self._lock:
            worst = self._overdue_locked()
            if worst is not None and self._healthy:
                self._healthy = False
                self.trips += 1
                self._last_overdue_ms, self._last_label = worst
                fire_trip = worst
        if fire_trip is not None and self.on_trip is not None:
            self.on_trip(fire_trip[1], fire_trip[0])
        return self.healthy()

    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    # -- monitor thread -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="lwc-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1e3):
            self.check()

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "healthy": self._healthy,
                "active_dispatches": len(self._active),
                "dispatches": self.dispatches,
                "trips": self.trips,
                "recoveries": self.recoveries,
                "timeout_ms": self.timeout_ms,
            }
            if not self._healthy:
                out["overdue_ms"] = round(self._last_overdue_ms, 1)
                out["overdue_kind"] = self._last_label
        return out
