"""End-to-end request tracing (ISSUE 5 / Dapper-style).

``span`` — contextvar-carried span tree; ``sink`` — bounded ring +
JSONL retention with head sampling and forced capture for degraded/
shed/error requests; ``propagate`` — W3C ``traceparent`` inject at
upstream calls / extract at the gateway door.

The whole package is dependency-free below ``utils`` so any layer
(cache, clients, batcher, resilience) can instrument without cycles.
With no sink configured nothing ever activates a root span, and every
ambient helper here is a single contextvar read returning None.
"""

from .histogram import Histogram  # noqa: F401
from .ledger import (  # noqa: F401
    LEDGER_SCHEMA,
    OutcomeLedger,
    load_ledger_records,
)
from .phases import (  # noqa: F401
    PHASES,
    observe_device,
    observe_phase,
    phase_breakdown,
    phases_snapshot,
    reset_phases,
)
from .propagate import (  # noqa: F401
    TRACEPARENT_HEADER,
    extract,
    format_traceparent,
    inject,
    parse_traceparent,
)
from .quality import (  # noqa: F401
    JudgeBallot,
    Outcome,
    QualityAggregator,
    configure_quality,
    observe_outcome,
    quality_aggregator,
    quality_snapshot,
    quality_summary,
    reset_quality,
)
from .sink import TraceSink  # noqa: F401
from .span import (  # noqa: F401
    Span,
    Trace,
    annotate,
    child_span,
    current_span,
    current_trace_id,
    force_keep,
    span,
    start_trace,
)
