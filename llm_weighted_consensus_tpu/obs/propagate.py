"""W3C Trace Context (``traceparent``) inject/extract.

Format (https://www.w3.org/TR/trace-context/):
``00-{32 hex trace_id}-{16 hex parent_span_id}-{2 hex flags}``; flag
bit 0 is "sampled".  We extract at the gateway door (an external
caller's trace adopts ours as a subtree) and inject on every upstream
judge HTTP call (the attempt span's id becomes the upstream's parent,
so hedged attempts are distinguishable in a cross-service view).
Malformed headers are ignored — tracing must never fail a request.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .span import current_span

TRACEPARENT_HEADER = "traceparent"

_HEX = set("0123456789abcdef")


def format_traceparent(trace_id: str, span_id: str, sampled: bool) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def _is_hex(s: str, width: int, allow_zero: bool = False) -> bool:
    if len(s) != width or any(c not in _HEX for c in s):
        return False
    # all-zero trace/span ids are invalid per spec; the version byte 00
    # is the (only) current version and perfectly legal
    return allow_zero or set(s) != {"0"}


def parse_traceparent(
    header: Optional[str],
) -> Optional[Tuple[str, str, bool]]:
    """``(trace_id, parent_span_id, sampled)`` or None when absent or
    malformed (per spec, a bad header is treated as no header)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if version == "ff" or not _is_hex(version, 2, allow_zero=True):
        return None
    if not _is_hex(trace_id, 32) or not _is_hex(span_id, 16):
        return None
    if len(flags) != 2 or not all(c in _HEX for c in flags):
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)


def inject(headers: dict) -> None:
    """Stamp the ambient span's context onto outgoing request headers;
    no-op when tracing is off."""
    span = current_span()
    if span is None:
        return
    headers[TRACEPARENT_HEADER] = format_traceparent(
        span.trace.trace_id, span.span_id, span.trace.sampled
    )


def extract(headers) -> Optional[Tuple[str, str, bool]]:
    """Parse an incoming request's ``traceparent`` (any mapping with
    ``.get``)."""
    return parse_traceparent(headers.get(TRACEPARENT_HEADER))
