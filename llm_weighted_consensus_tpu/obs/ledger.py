"""Persistent consensus-outcome ledger (ISSUE 12 tentpole piece 3).

One self-describing record per scored request — panel id, per-judge
votes and weights, the confidence vector, the degraded/quorum verdict
and the trace id — retained in a bounded in-memory ring and optionally
appended to a per-process JSONL file, following the ``obs/sink.py``
ring + disk pattern (``LEDGER_RING`` / ``LEDGER_DIR``).

Unlike the trace sink there is no sampling: the ledger is the training
substrate ROADMAP items 4–5 (archive re-scoring, on-TPU weight
learning) consume, and a sampled training set would silently bias the
learned weights.  Every offered record is kept; the ring bounds memory
and the JSONL tier is append-only and crash-tolerant like
``cache/store.py``.

The record schema is pinned by the ledger → training round-trip test:
per-judge rows carry the soft vote vector (floats) and the
Decimal-exact alignment score already computed by the tally, so
``weights/training_table.py`` ingests them without transformation.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict
from typing import Optional

from ..utils import jsonutil

# schema tag stamped on every record; bump on incompatible change
LEDGER_SCHEMA = "lwc.outcome.v1"


class OutcomeLedger:
    """Single-threaded by contract (mutated only from the event loop),
    like TraceSink and every counter object in the serving stack."""

    def __init__(
        self,
        capacity: int = 256,
        disk_dir: Optional[str] = None,
        rotate_bytes: int = 0,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: OrderedDict = OrderedDict()
        self.kept = 0
        self._disk_path: Optional[str] = None
        self._disk_errors = 0
        # LEDGER_ROTATE_BYTES: once the active shard reaches this size
        # it is sealed under a timestamped name and a fresh active file
        # starts; 0 keeps the single ever-growing file
        self.rotate_bytes = max(0, int(rotate_bytes))
        self.rotations = 0
        self._rotate_seq = 0
        self._active_bytes = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
            self._disk_path = os.path.join(
                disk_dir, f"ledger-{os.getpid()}.jsonl"
            )
            try:
                self._active_bytes = os.path.getsize(self._disk_path)
            except OSError:
                self._active_bytes = 0

    def _rotate(self) -> None:
        """Seal the active shard under a timestamped, size-ordered name
        that still matches the ``ledger-*.jsonl`` read glob, so
        ``load_ledger_records`` picks up every generation unchanged.
        The sequence number keeps names unique (and sorted) even when
        two rotations land inside the same second."""
        self._rotate_seq += 1
        sealed = self._disk_path[: -len(".jsonl")] + (
            f"-{int(time.time())}-{self._rotate_seq:06d}.jsonl"
        )
        try:
            os.replace(self._disk_path, sealed)
        except OSError:
            self._disk_errors += 1
            return
        self.rotations += 1
        self._active_bytes = 0

    def offer(self, record: dict) -> None:
        """Request end: keep (ring + disk) in O(1); never raises into
        the request path."""
        record.setdefault("schema", LEDGER_SCHEMA)
        key = record.get("id") or f"outcome-{self.kept}"
        self.kept += 1
        self._ring[key] = record
        self._ring.move_to_end(key)
        while len(self._ring) > self.capacity:
            self._ring.popitem(last=False)
        if self._disk_path is not None:
            line = jsonutil.dumps(record) + "\n"
            try:
                with open(self._disk_path, "a", encoding="utf-8") as f:
                    f.write(line)
            except OSError:
                # the ledger must never fail the request path; the
                # error count surfaces on /metrics instead
                self._disk_errors += 1
                return
            self._active_bytes += len(line.encode("utf-8"))
            if self.rotate_bytes and self._active_bytes >= self.rotate_bytes:
                self._rotate()

    # -- read side ------------------------------------------------------------

    def index(self, limit: int = 50) -> list:
        """Recent-first records, newest first."""
        out = []
        for record in reversed(self._ring.values()):
            out.append(record)
            if len(out) >= limit:
                break
        return out

    def get(self, record_id: str) -> Optional[dict]:
        return self._ring.get(record_id)

    # -- observability of the observer (metrics provider "ledger") ------------

    def snapshot(self) -> dict:
        return {
            "schema": LEDGER_SCHEMA,
            "capacity": self.capacity,
            "size": len(self._ring),
            "kept": self.kept,
            "disk_errors": self._disk_errors,
            "disk_path": self._disk_path,
            "rotate_bytes": self.rotate_bytes,
            "rotations": self.rotations,
        }


def ledger_shard_paths(disk_dir: str) -> list:
    """Sorted ledger shard paths under ``disk_dir`` — the sealed
    (timestamped) generations plus the active file, all matching the
    one ``ledger-*.jsonl`` glob the writer guarantees."""
    if not os.path.isdir(disk_dir):
        return []
    return sorted(
        os.path.join(disk_dir, f)
        for f in os.listdir(disk_dir)
        if f.startswith("ledger-") and f.endswith(".jsonl")
    )


def read_shard_records(path: str) -> tuple:
    """Read one shard → ``(records, torn)`` with the same torn-tail
    skip-and-count contract as ``load_ledger_records``: a replica
    killed mid-append leaves a torn final line, which is skipped and
    counted, never fatal; unreadable files count as one torn entry."""
    records = []
    torn = 0
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        return records, 1
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = jsonutil.loads(line)
        except ValueError:
            torn += 1
            continue
        if isinstance(record, dict):
            records.append(record)
        else:
            torn += 1
    return records, torn


def load_ledger_records(disk_dir: str) -> tuple:
    """Read back every ledger JSONL file under ``disk_dir`` —
    ``(records, torn)``.  The read side of the crash-tolerance
    contract: a replica killed mid-append leaves a torn final line,
    which is skipped and counted, never fatal — the training pipeline
    (weights/training_table.py) would rather lose one record than the
    archive.  Unreadable files are skipped the same way."""
    records = []
    torn = 0
    for path in ledger_shard_paths(disk_dir):
        shard_records, shard_torn = read_shard_records(path)
        records.extend(shard_records)
        torn += shard_torn
    return records, torn
