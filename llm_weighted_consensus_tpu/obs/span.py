"""Contextvar-carried span tree: the request-scoped side of tracing.

Dapper-style (Sigelman et al., 2010): every request gets at most one
``Trace`` (created at the gateway door), spans hang off it as a flat
list with parent pointers, and the *ambient* current span rides a
contextvar exactly like ``resilience/deadline.py`` — aiohttp runs each
handler in its own task and asyncio tasks copy their parent's context
at creation, so judge pump tasks and hedge-attempt tasks inherit the
right parent span with zero plumbing.

Cost model (the "cheap no-op" contract): when tracing is disabled —
no sink configured, so no root span was ever activated —
``current_span()`` is ``None`` and every helper below short-circuits
on that one contextvar read; no IDs, no dicts, no timestamps.  When a
sink IS configured, spans are built even for requests head-sampling
declined, because degraded/shed/error outcomes force retention at the
sink (sink.py) and that verdict only exists at request end.  The
keep/drop decision is the sink's; span construction stays allocation-
light (``__slots__``, one attributes dict).

Generator caveat (why instrumentation sites look the way they do):
an async generator's body runs in whichever task drives ``__anext__``.
Activating a span inside a generator is only safe when that generator
is driven by a single dedicated task for its whole life (the judge
pump tasks in ``clients/score.merge_streams``) — otherwise pass spans
explicitly (the batcher carries one per queued item).
"""

from __future__ import annotations

import contextvars
import os
import random
import time
from typing import Optional

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "lwc_span", default=None
)

# ids are correlation keys, not secrets: a urandom-seeded PRNG avoids a
# getrandom(2) syscall per span (ids are the hottest allocation on the
# traced path — every request builds spans once a sink exists)
_RNG = random.Random(os.urandom(16))


def _gen_id(nbytes: int) -> str:
    value = _RNG.getrandbits(8 * nbytes)
    while value == 0:  # all-zero ids are invalid in W3C
        value = _RNG.getrandbits(8 * nbytes)
    return format(value, f"0{2 * nbytes}x")


# Every span name the tree can contain; a trailing ``*`` covers a
# dynamic suffix (f-string call sites).  The registry the LWC010 lint
# checks both ways: a span started with an unlisted name fails lint
# (trace queries and the explain renderer match on these), and a listed
# name no call site uses is a stale entry to delete.
KNOWN_SPANS = (
    "gateway:*",
    "batcher:*",
    "device:dispatch",
    "singleflight:wait",
    "cache:lookup",
    "consensus:tally",
    "judge:stream",
    "judge:attempt",
)


class Trace:
    """One request's span collection + the retention verdict inputs."""

    __slots__ = (
        "trace_id",
        "sampled",
        "forced",
        "force_reason",
        "spans",
        "started_epoch",
        "t0",
    )

    def __init__(self, trace_id: Optional[str], sampled: bool) -> None:
        self.trace_id = trace_id or _gen_id(16)
        self.sampled = bool(sampled)
        # degraded / shed / error outcomes set this: the sink keeps the
        # trace regardless of the head-sampling decision
        self.forced = False
        self.force_reason: Optional[str] = None
        self.spans: list = []
        self.started_epoch = time.time()
        self.t0 = time.perf_counter()

    def force(self, reason: str) -> None:
        if not self.forced:
            self.forced = True
            self.force_reason = reason

    def to_json_obj(self) -> dict:
        root = self.spans[0] if self.spans else None
        return {
            "trace_id": self.trace_id,
            "name": root.name if root is not None else None,
            "started_epoch": round(self.started_epoch, 6),
            "duration_ms": root.duration_ms() if root is not None else None,
            "status": root.status if root is not None else None,
            "sampled": self.sampled,
            "forced": self.forced,
            "force_reason": self.force_reason,
            "spans": [s.to_json_obj() for s in self.spans],
        }


class Span:
    __slots__ = (
        "trace",
        "span_id",
        "parent_id",
        "name",
        "attributes",
        "status",
        "_start",
        "_end",
    )

    def __init__(
        self, trace: Trace, name: str, parent_id: Optional[str], **attrs
    ) -> None:
        self.trace = trace
        self.span_id = _gen_id(8)
        self.parent_id = parent_id
        self.name = name
        self.attributes = attrs
        self.status = "ok"
        self._start = time.perf_counter()
        self._end: Optional[float] = None
        trace.spans.append(self)

    # -- lifecycle ----------------------------------------------------------

    def child(self, name: str, **attrs) -> "Span":
        return Span(self.trace, name, self.span_id, **attrs)

    def annotate(self, **attrs) -> None:
        self.attributes.update(attrs)

    def set_error(self, detail) -> None:
        """Mark this span errored AND force trace retention — an error
        anywhere in the tree makes the whole trace worth keeping."""
        self.status = "error"
        self.attributes["error"] = str(detail)
        self.trace.force(f"error:{self.name}")

    def finish(self, status: Optional[str] = None) -> None:
        if self._end is None:
            self._end = time.perf_counter()
        if status is not None:
            self.status = status

    # -- ambient activation (deadline.py token pattern) ---------------------

    def activate(self) -> contextvars.Token:
        return _CURRENT.set(self)

    @staticmethod
    def deactivate(token: contextvars.Token) -> None:
        _CURRENT.reset(token)

    # -- rendering ----------------------------------------------------------

    def start_ms(self) -> float:
        return round((self._start - self.trace.t0) * 1e3, 3)

    def duration_ms(self) -> Optional[float]:
        if self._end is None:
            return None
        return round((self._end - self._start) * 1e3, 3)

    def to_json_obj(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ms": self.start_ms(),
            "duration_ms": self.duration_ms(),
            "status": self.status,
            "attributes": self.attributes,
        }


# ---------------------------------------------------------------------------
# Module-level ambient API (every call is safe with tracing off)
# ---------------------------------------------------------------------------


def start_trace(
    name: str,
    *,
    sampled: bool,
    trace_id: Optional[str] = None,
    parent_span_id: Optional[str] = None,
    **attrs,
) -> Span:
    """New trace + its root span (gateway door only).  ``trace_id`` /
    ``parent_span_id`` come from an extracted upstream ``traceparent``
    so external callers can stitch our tree under theirs."""
    trace = Trace(trace_id, sampled)
    return Span(trace, name, parent_span_id, **attrs)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    span = _CURRENT.get()
    return span.trace.trace_id if span is not None else None


def child_span(name: str, **attrs) -> Optional[Span]:
    """Child of the ambient span, or None when tracing is off — callers
    keep the reference and finish it themselves."""
    parent = _CURRENT.get()
    if parent is None:
        return None
    return parent.child(name, **attrs)


def annotate(**attrs) -> None:
    """Attach attributes to the ambient span; no-op when tracing is off."""
    span = _CURRENT.get()
    if span is not None:
        span.attributes.update(attrs)


def force_keep(reason: str) -> None:
    """Mark the ambient trace must-keep (degraded/shed/error outcomes)."""
    span = _CURRENT.get()
    if span is not None:
        span.trace.force(reason)


class _SpanScope:
    """``with span("name") as s:`` — a real child span when tracing is
    on, an inert scope when off.  Sync context manager on purpose: it
    works identically inside coroutines, and never crosses a yield."""

    __slots__ = ("_name", "_attrs", "_span", "_token")

    def __init__(self, name: str, attrs: dict) -> None:
        self._name = name
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        parent = _CURRENT.get()
        if parent is None:
            return None
        self._span = parent.child(self._name, **self._attrs)
        self._token = self._span.activate()
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            if exc is not None:
                if isinstance(exc, Exception):
                    self._span.set_error(exc)
                else:
                    # cancellation / GeneratorExit: the caller went away —
                    # mark the span, but don't force whole-trace retention
                    # (a disconnect is not a service error)
                    self._span.annotate(cancelled=True)
                    self._span.status = "error"
            Span.deactivate(self._token)
            self._span.finish()
        return False


def span(name: str, **attrs) -> _SpanScope:
    return _SpanScope(name, attrs)
