"""Completed-trace retention: bounded ring + optional JSONL disk tier.

Head-based sampling happens at the gateway door (``sample()`` is the
coin flip the trace middleware calls before building the root span);
the retention decision happens HERE at request end — a trace survives
if head sampling said yes OR something forced it (degraded consensus,
load shed, any error).  That split is what makes "always capture the
bad ones" compatible with a 1% sample rate: spans are always built
once a sink exists, and ``offer()`` discards the healthy unsampled
majority in O(1).

The in-memory ring is an OrderedDict bounded at ``capacity`` (oldest
evicted first), served by ``GET /v1/traces`` (recent index, newest
first) and ``GET /v1/traces/{trace_id}`` (full span tree).  The disk
tier mirrors the cache's JSONL idiom (cache/store.py): one self-
describing JSON line per kept trace, append-only, per-process file —
crash-tolerant and greppable.
"""

from __future__ import annotations

import os
import random
from collections import OrderedDict
from typing import Optional

from ..utils import jsonutil


class TraceSink:
    """Single-threaded by contract (mutated only from the event loop),
    like every counter object in the serving stack."""

    def __init__(
        self,
        capacity: int = 256,
        sample_rate: float = 0.0,
        disk_dir: Optional[str] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.capacity = max(1, int(capacity))
        self.sample_rate = float(sample_rate)
        self._rng = rng or random.Random()
        self._ring: OrderedDict = OrderedDict()
        self.kept = 0
        self.forced = 0
        self.dropped = 0
        self._disk_path: Optional[str] = None
        self._disk_errors = 0
        if disk_dir:
            os.makedirs(disk_dir, exist_ok=True)
            self._disk_path = os.path.join(
                disk_dir, f"traces-{os.getpid()}.jsonl"
            )

    # -- head sampling -------------------------------------------------------

    def sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        return self._rng.random() < self.sample_rate

    # -- retention -----------------------------------------------------------

    def offer(self, trace) -> None:
        """Request end: keep (ring + disk) or drop in O(1)."""
        if not (trace.sampled or trace.forced):
            self.dropped += 1
            return
        self.kept += 1
        if trace.forced:
            self.forced += 1
        record = trace.to_json_obj()
        self._ring[trace.trace_id] = record
        self._ring.move_to_end(trace.trace_id)
        while len(self._ring) > self.capacity:
            self._ring.popitem(last=False)
        if self._disk_path is not None:
            try:
                with open(self._disk_path, "a", encoding="utf-8") as f:
                    f.write(jsonutil.dumps(record) + "\n")
            except OSError:
                # tracing must never fail the request path; the error
                # count surfaces on /metrics instead
                self._disk_errors += 1

    # -- read side (GET /v1/traces[/{trace_id}]) -----------------------------

    def index(self, limit: int = 50) -> list:
        """Recent-first summaries (no span bodies — those are per-trace)."""
        out = []
        for record in reversed(self._ring.values()):
            out.append(
                {
                    "trace_id": record["trace_id"],
                    "name": record["name"],
                    "started_epoch": record["started_epoch"],
                    "duration_ms": record["duration_ms"],
                    "status": record["status"],
                    "sampled": record["sampled"],
                    "forced": record["forced"],
                    "force_reason": record["force_reason"],
                    "spans": len(record["spans"]),
                }
            )
            if len(out) >= limit:
                break
        return out

    def get(self, trace_id: str) -> Optional[dict]:
        return self._ring.get(trace_id)

    # -- observability of the observer (metrics provider "traces") -----------

    def snapshot(self) -> dict:
        return {
            "sample_rate": self.sample_rate,
            "capacity": self.capacity,
            "size": len(self._ring),
            "kept": self.kept,
            "forced": self.forced,
            "dropped": self.dropped,
            "disk_errors": self._disk_errors,
            "disk_path": self._disk_path,
        }
