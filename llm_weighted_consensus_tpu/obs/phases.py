"""Phase-attributed request timing (ISSUE 11 tentpole piece 1).

BENCH_r03's post-mortem had to hand-derive that device time was 35 ms
of a 145 ms p50; this module makes that split a first-class, always-on
aggregate.  Every scored request is bracketed through a fixed phase
vocabulary:

* ``admission_wait``  — gateway door to admission slot held
* ``batcher_queue``   — item enqueued to its group taking the device
* ``pack_plan``       — host-side ragged packing plan (packed path)
* ``device_dispatch`` — the device executable itself, measured
  enqueue-to-ready at the embedder seam (models/dispatch_seam.py: the
  batcher's waiter thread blocks; direct callers pay an inline
  bracket), per (mesh-shape, bucket)
* ``host_tally``      — consensus tally / packed reassembly on host
* ``upstream_judge``  — judge LLM streaming fan-out

Two consumers, two mechanisms:

1. **Aggregates** — instrumentation sites call ``observe_phase`` /
   ``observe_device`` directly into one process-global aggregator of
   mergeable log-bucket histograms (obs/histogram.py).  Global on
   purpose: the sites span the event loop, executor threads and the
   score client, and the phases section must work for harnesses that
   drive the batcher without a gateway (bench_scaling.py).  The
   aggregator takes a lock per observe — the executor threads are real
   writers — and the whole observe stays inside the ≤2% hot-path
   budget (bench_host.py --metrics-overhead).
2. **Per-request breakdown** — ``phase_breakdown(trace)`` re-derives
   the same vocabulary from a finished PR 5 span tree (interval union,
   so R concurrent judge streams attribute wall time once, not R
   times).  The gateway's trace middleware annotates every root span
   with it; tests assert the phase sum lands within 10% of the
   request's end-to-end latency.

Stdlib-only, dependency-free below ``utils`` like the rest of ``obs/``.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from .histogram import Histogram

# the phase vocabulary, in request order; the /metrics ``phases``
# section and the BENCH phase summaries render exactly these keys
PHASES = (
    "admission_wait",
    "batcher_queue",
    "pack_plan",
    "device_dispatch",
    "host_tally",
    "upstream_judge",
)


class PhaseAggregator:
    """Process-global phase + per-bucket device-time histograms.

    Lock-guarded because device timing lands from executor threads
    while HTTP phases land from the event loop; each observe is one
    O(1) histogram increment under an uncontended lock."""

    # retained (enqueue, ready) device intervals for the overlap gauge;
    # a rolling window so the gauge tracks the CURRENT pipelining
    # behavior, not the process lifetime average
    INTERVAL_WINDOW = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._phases: Dict[str, Histogram] = {}
        self._device: Dict[str, Histogram] = {}
        self._intervals: deque = deque(maxlen=self.INTERVAL_WINDOW)

    def observe_phase(self, phase: str, ms: float) -> None:
        with self._lock:
            hist = self._phases.get(phase)
            if hist is None:
                hist = self._phases[phase] = Histogram()
            hist.observe(ms)

    def observe_device(self, bucket: str, ms: float) -> None:
        """One device executable run at ``bucket`` (a canonical label
        like ``vote1(n=8,s=16)@dp4xtp2``): feeds both the per-bucket
        table the roofline gauge reads and the ``device_dispatch``
        phase aggregate."""
        with self._lock:
            hist = self._device.get(bucket)
            if hist is None:
                hist = self._device[bucket] = Histogram()
            hist.observe(ms)
            phist = self._phases.get("device_dispatch")
            if phist is None:
                phist = self._phases["device_dispatch"] = Histogram()
            phist.observe(ms)

    def observe_device_interval(self, start: float, end: float) -> None:
        """One device dispatch's (enqueue, ready) interval in
        ``time.perf_counter`` seconds — the raw material for the
        ``overlap`` gauge (pipelined dispatches' intervals genuinely
        overlap; a serialized pipeline's tile end to start)."""
        with self._lock:
            self._intervals.append((float(start), float(end)))

    def device_intervals(self) -> List[Tuple[float, float]]:
        """The retained interval window (tests and the gauge)."""
        with self._lock:
            return list(self._intervals)

    # -- read side ------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /metrics ``phases`` section: per-phase histogram summary
        plus the device share of all attributed time (the figure
        BENCH_r03 had to hand-derive) and the ``overlap`` gauge
        (ISSUE 13): device-busy union-interval over wall time across the
        retained dispatch window.  ~1.0 means pipelined dispatches keep
        the device continuously busy; a fully serialized pipeline with
        host work between dispatches reads well below 1.  None until
        two dispatches have landed (no overlap to speak of)."""
        with self._lock:
            rows = {
                phase: hist.to_json_obj()
                for phase, hist in self._phases.items()
            }
            total = sum(h.sum for h in self._phases.values())
            device = self._phases.get("device_dispatch")
            device_sum = device.sum if device is not None else 0.0
            intervals = list(self._intervals)
        out: dict = {phase: rows[phase] for phase in PHASES if phase in rows}
        out["device_time_share"] = (
            round(device_sum / total, 4) if total > 0 else None
        )
        overlap = None
        if len(intervals) >= 2:
            wall = max(e for _, e in intervals) - min(s for s, _ in intervals)
            if wall > 0:
                overlap = round(min(_union_ms(intervals) / wall, 1.0), 4)
        out["overlap"] = overlap
        return out

    def device_snapshot(self) -> Dict[str, dict]:
        """Per-(mesh-shape, bucket) device-time summaries."""
        with self._lock:
            return {
                bucket: hist.to_json_obj()
                for bucket, hist in sorted(self._device.items())
            }

    def raw_histograms(self) -> Tuple[Dict[str, Histogram], Dict[str, Histogram]]:
        """Cloned (phases, device) histogram maps for the Prometheus
        renderer — clones, so rendering never races an executor-thread
        observe."""
        with self._lock:
            return (
                {k: _clone(h) for k, h in self._phases.items()},
                {k: _clone(h) for k, h in self._device.items()},
            )

    def device_quantile(self, bucket: str, q: float) -> Optional[float]:
        with self._lock:
            hist = self._device.get(bucket)
            return hist.quantile(q) if hist is not None else None

    def reset(self) -> None:
        with self._lock:
            self._phases.clear()
            self._device.clear()
            self._intervals.clear()


def _clone(hist: Histogram) -> Histogram:
    return Histogram().merge(hist)


_AGG = PhaseAggregator()


def aggregator() -> PhaseAggregator:
    return _AGG


def observe_phase(phase: str, ms: float) -> None:
    _AGG.observe_phase(phase, ms)


def observe_device(bucket: str, ms: float) -> None:
    _AGG.observe_device(bucket, ms)


def observe_device_interval(start: float, end: float) -> None:
    _AGG.observe_device_interval(start, end)


def phases_snapshot() -> dict:
    return _AGG.snapshot()


def reset_phases() -> None:
    _AGG.reset()


# ---------------------------------------------------------------------------
# Per-request breakdown from a finished span tree
# ---------------------------------------------------------------------------


def _union_ms(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of (start, end) intervals — concurrent
    judge streams (or pipelined dispatches) attribute wall time once."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            total += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    return total + (cur_end - cur_start)


def phase_breakdown(trace) -> dict:
    """Attribute one finished trace's wall time to the phase vocabulary.

    Span-derived: ``batcher:*`` minus its ``device:dispatch`` children
    is queue time; the dispatch bracket minus the batcher span's
    annotated host sub-costs (``pack_plan_ms`` / ``host_tally_ms``,
    stamped per item by the packed dispatch) is device time;
    ``consensus:tally`` and ``judge:stream`` map directly;
    ``admission_wait_ms`` rides a root annotation (the admission
    middleware runs before any child span exists).  Returns
    ``{phase: ms}`` plus ``e2e_ms`` and the unattributed ``other_ms``
    remainder — the acceptance bar is that the named phases sum to
    within 10% of ``e2e_ms`` on a served request."""
    batcher: List[Tuple[float, float]] = []
    device: List[Tuple[float, float]] = []
    tally: List[Tuple[float, float]] = []
    judge: List[Tuple[float, float]] = []
    pack_plan_ms = 0.0
    tally_attr_ms = 0.0
    root = trace.spans[0] if trace.spans else None
    for span in trace.spans:
        dur = span.duration_ms()
        if dur is None:
            continue
        start = span.start_ms()
        interval = (start, start + dur)
        name = span.name
        if name.startswith("batcher:"):
            batcher.append(interval)
            pack_plan_ms += float(span.attributes.get("pack_plan_ms", 0.0))
            tally_attr_ms += float(span.attributes.get("host_tally_ms", 0.0))
        elif name == "device:dispatch":
            device.append(interval)
        elif name == "consensus:tally":
            tally.append(interval)
        elif name == "judge:stream":
            judge.append(interval)
    device_ms = _union_ms(device)
    batcher_ms = max(0.0, _union_ms(batcher) - device_ms)
    out = {
        "admission_wait": float(
            root.attributes.get("admission_wait_ms", 0.0)
        )
        if root is not None
        else 0.0,
        "batcher_queue": batcher_ms,
        "pack_plan": pack_plan_ms,
        "device_dispatch": max(0.0, device_ms - pack_plan_ms - tally_attr_ms),
        "host_tally": _union_ms(tally) + tally_attr_ms,
        "upstream_judge": _union_ms(judge),
    }
    out = {k: round(v, 3) for k, v in out.items()}
    e2e = root.duration_ms() if root is not None else None
    if e2e is not None:
        attributed = sum(out.values())
        out["e2e_ms"] = e2e
        out["other_ms"] = round(max(0.0, e2e - attributed), 3)
    return out
