"""Consensus-quality telemetry (ISSUE 12 tentpole piece 1).

PR 11 made the *machine* observable; this module makes the *consensus*
observable — the paper's actual subject.  Every scored request lands one
``observe_outcome`` at the tally seam in ``clients/score.py``, feeding:

* **Per-judge scorecards** — agreement-with-final-consensus rate,
  soft-vote calibration bins (the ``top_logprobs`` vote mass a judge
  put on each candidate vs whether that candidate won), vote entropy,
  abstain / error / hedge / cancelled rates, and the judge's
  weight-contribution share of total consensus weight.  Weight math is
  Decimal-exact (LWC005): the running sums stay ``Decimal`` and only
  the snapshot edge converts to float, mirroring ``explain_judges``.
* **Pairwise inter-judge agreement** — Cohen's kappa over shared
  ballots (both judges voted on the same request), with per-judge
  marginals so chance agreement is corrected per pair.
* **Drift detection** — a sliding window of recent ballots per judge;
  a judge is flagged when its windowed agreement rate or windowed
  vote-mass-on-winner drops more than ``drift_threshold`` below its
  baseline (everything before the window).  Deterministic: no decay,
  no randomness — a seeded ``JUDGE_BIAS_PLAN`` drill flags within a
  bounded request count.
* **Consensus-health SLIs** — the confidence-margin (top1 − top2)
  histogram on the shared log-bucket layout (obs/histogram.py), and
  degraded / quorum-degraded / all-failed outcome counters.

Aggregation is process-global, lock-guarded and O(judges × choices)
per request — the same work the tally itself already does — and the
observe path is held to the existing ≤2% hot-path budget
(``bench_host.py --quality-overhead``).  Stdlib-only, dependency-free
below ``utils`` like the rest of ``obs/``.
"""

from __future__ import annotations

import math
import threading
import time
from collections import Counter, deque
from decimal import Decimal
from typing import Dict, List, Optional, Tuple

from .histogram import Histogram

# fixed calibration layout: 10 equal-width bins over vote mass [0, 1]
N_CALIBRATION_BINS = 10

# outcome vocabulary rendered by the ``quality`` /metrics section and
# the ``lwc_consensus_outcomes`` Prometheus counter
OUTCOMES = ("scored", "degraded", "quorum_degraded", "all_failed")


class JudgeBallot:
    """One judge's contribution to one scored request, captured at the
    tally seam BEFORE the per-chunk deltas are cleared.

    ``vote`` is the soft-vote vector as *floats*: the seam converts the
    Decimal vote exactly once (shared with the ledger record) because
    every per-ballot statistic here is float math — only ``weight``
    stays Decimal, it feeds the exact weight-contribution share."""

    __slots__ = ("model", "model_index", "weight", "vote", "error_code")

    def __init__(
        self,
        model: str,
        model_index: int,
        weight: Decimal,
        vote: Optional[List[float]],
        error_code: Optional[int] = None,
    ) -> None:
        self.model = model
        self.model_index = model_index
        self.weight = weight
        self.vote = vote
        self.error_code = error_code


class Outcome:
    """One scored request's consensus verdict, as seen by the tally."""

    __slots__ = (
        "winner",
        "margin",
        "weight_sum",
        "n_choices",
        "degraded",
        "quorum_degraded",
        "all_failed",
        "trace_id",
        "judges",
    )

    def __init__(
        self,
        winner: Optional[int],
        margin: Optional[float],
        weight_sum: Decimal,
        n_choices: int,
        degraded: bool,
        quorum_degraded: bool,
        all_failed: bool,
        trace_id: Optional[str],
        judges: List[JudgeBallot],
    ) -> None:
        self.winner = winner
        self.margin = margin
        self.weight_sum = weight_sum
        self.n_choices = n_choices
        self.degraded = degraded
        self.quorum_degraded = quorum_degraded
        self.all_failed = all_failed
        self.trace_id = trace_id
        self.judges = judges


class _JudgeCard:
    """Running per-judge aggregates; all counters O(1) per ballot."""

    __slots__ = (
        "model",
        "seen",
        "voted",
        "agreements",
        "abstains",
        "errors",
        "cancelled",
        "hedges",
        "entropy_sum",
        "weight_contrib",
        "panel_weight",
        "bins",
        "window",
        "agree_total",
        "mass_total",
    )

    def __init__(self, model: str, window: int) -> None:
        self.model = model
        self.seen = 0  # ballots the judge appeared in at all
        self.voted = 0  # ballots with a usable vote vector
        self.agreements = 0
        self.abstains = 0
        self.errors = 0
        self.cancelled = 0
        self.hedges = 0
        self.entropy_sum = 0.0
        # Decimal-exact running sums (LWC005): converted to float only
        # at the snapshot edge, like explain_judges
        self.weight_contrib = Decimal(0)
        self.panel_weight = Decimal(0)
        # top-1 calibration: per-bin [count, top-pick mass sum, wins]
        self.bins = [[0, 0.0, 0] for _ in range(N_CALIBRATION_BINS)]
        # drift: recent (agree_bit, mass_on_winner) pairs
        self.window: deque = deque(maxlen=max(1, int(window)))
        self.agree_total = 0
        self.mass_total = 0.0

    # -- drift ---------------------------------------------------------------

    def drift(self, threshold: float) -> dict:
        """Windowed-vs-baseline comparison; flagged only once both the
        window AND the baseline hold a full window of ballots, so a
        cold judge is never flagged on noise."""
        filled = len(self.window)
        cap = self.window.maxlen or 1
        recent_agree = sum(b for b, _ in self.window)
        recent_mass = sum(m for _, m in self.window)
        base_n = self.voted - filled
        out: dict = {
            "flagged": False,
            "window_fill": filled,
            "window": cap,
        }
        if filled:
            out["recent_agreement"] = round(recent_agree / filled, 4)
            out["recent_mass_on_winner"] = round(recent_mass / filled, 4)
        if base_n > 0:
            base_agree = (self.agree_total - recent_agree) / base_n
            base_mass = (self.mass_total - recent_mass) / base_n
            out["baseline_agreement"] = round(base_agree, 4)
            out["baseline_mass_on_winner"] = round(base_mass, 4)
            if filled >= cap and base_n >= cap:
                agree_drop = base_agree - recent_agree / filled
                mass_drop = base_mass - recent_mass / filled
                out["flagged"] = (
                    agree_drop > threshold or mass_drop > threshold
                )
                out["agreement_drop"] = round(agree_drop, 4)
                out["mass_drop"] = round(mass_drop, 4)
        return out

    # -- snapshot ------------------------------------------------------------

    def calibration(self) -> dict:
        """Top-1 reliability diagram + ECE: each voted ballot lands in
        the bin of the mass the judge put on its own pick; ``win_rate``
        is how often that pick was the consensus winner."""
        total = sum(b[0] for b in self.bins)
        rows = []
        ece = 0.0
        for i, (count, p_sum, wins) in enumerate(self.bins):
            if not count:
                continue
            p_avg = p_sum / count
            win_rate = wins / count
            ece += (count / total) * abs(p_avg - win_rate)
            rows.append(
                {
                    "le": round((i + 1) / N_CALIBRATION_BINS, 1),
                    "count": count,
                    "p_avg": round(p_avg, 4),
                    "win_rate": round(win_rate, 4),
                }
            )
        return {
            "samples": total,
            "ece": round(ece, 4) if total else None,
            "bins": rows,
        }

    def scorecard(self, threshold: float) -> dict:
        seen = self.seen
        voted = self.voted
        out = {
            "model": self.model,
            "ballots": seen,
            "voted": voted,
            "agreement_rate": (
                round(self.agreements / voted, 4) if voted else None
            ),
            "entropy_mean": (
                round(self.entropy_sum / voted, 4) if voted else None
            ),
            "hedge_rate": round(self.hedges / voted, 4) if voted else None,
            "abstain_rate": round(self.abstains / seen, 4) if seen else None,
            "error_rate": round(self.errors / seen, 4) if seen else None,
            "cancelled_rate": (
                round(self.cancelled / seen, 4) if seen else None
            ),
            "weight_share": (
                float(self.weight_contrib / self.panel_weight)
                if self.panel_weight > 0
                else None
            ),
            "calibration": self.calibration(),
            "drift": self.drift(threshold),
        }
        return out


class _PairStats:
    """Shared-ballot tallies for one (judge, judge) pair's kappa."""

    __slots__ = ("count", "agree", "marg_a", "marg_b")

    def __init__(self) -> None:
        self.count = 0
        self.agree = 0
        self.marg_a: Counter = Counter()
        self.marg_b: Counter = Counter()

    def kappa(self) -> Optional[float]:
        """Cohen's kappa: observed agreement corrected for the chance
        agreement implied by each judge's own pick marginals."""
        if not self.count:
            return None
        po = self.agree / self.count
        pe = sum(
            (self.marg_a[k] / self.count) * (self.marg_b[k] / self.count)
            for k in self.marg_a
            if k in self.marg_b
        )
        if pe >= 1.0:
            # degenerate marginals (both judges always pick the same
            # single candidate): agreement is total, chance is total
            return 1.0 if po >= 1.0 else 0.0
        return (po - pe) / (1.0 - pe)


class QualityAggregator:
    """Process-global consensus-quality aggregates.

    Lock-guarded like ``PhaseAggregator``: the tally seam runs on the
    event loop today, but benches drive ``ScoreClient`` from plain
    threads and the read side (metrics renderer, /v1/judges) must never
    race an observe."""

    def __init__(
        self, window: int = 64, drift_threshold: float = 0.25
    ) -> None:
        self._lock = threading.Lock()
        self.window = max(1, int(window))
        self.drift_threshold = float(drift_threshold)
        self._judges: Dict[str, _JudgeCard] = {}
        self._pairs: Dict[Tuple[str, str], _PairStats] = {}
        self._margin = Histogram()
        self._outcomes: Counter = Counter()
        self._requests = 0
        self._exemplar: Optional[Tuple[str, float, float]] = None

    def configure(
        self,
        window: Optional[int] = None,
        drift_threshold: Optional[float] = None,
    ) -> None:
        """Apply config knobs to the singleton; existing drift windows
        are re-bounded in place."""
        with self._lock:
            if window is not None:
                self.window = max(1, int(window))
                for card in self._judges.values():
                    card.window = deque(card.window, maxlen=self.window)
            if drift_threshold is not None:
                self.drift_threshold = float(drift_threshold)

    # -- write side (the tally seam) -----------------------------------------

    def observe_outcome(self, outcome: Outcome) -> None:
        with self._lock:
            self._requests += 1
            if outcome.all_failed:
                self._outcomes["all_failed"] += 1
            else:
                self._outcomes["scored"] += 1
            if outcome.degraded:
                self._outcomes["degraded"] += 1
            if outcome.quorum_degraded:
                self._outcomes["quorum_degraded"] += 1
            if outcome.margin is not None:
                self._margin.observe(outcome.margin)
                if outcome.trace_id:
                    self._exemplar = (
                        outcome.trace_id,
                        outcome.margin,
                        time.time(),
                    )
            winner = outcome.winner
            n = outcome.n_choices
            picks: List[Tuple[str, int]] = []
            for ballot in outcome.judges:
                card = self._judges.get(ballot.model)
                if card is None:
                    card = self._judges[ballot.model] = _JudgeCard(
                        ballot.model, self.window
                    )
                card.seen += 1
                vote = ballot.vote
                if vote is None:
                    if ballot.error_code == 499:
                        card.cancelled += 1
                    elif ballot.error_code is not None:
                        card.errors += 1
                    else:
                        card.abstains += 1
                    continue
                card.voted += 1
                card.weight_contrib += ballot.weight
                card.panel_weight += outcome.weight_sum
                # the vote is already floats (converted once at the
                # seam); per-ballot statistics are two O(n) passes
                # (argmax + entropy) and O(1) updates — this runs once
                # per judge per scored request under the
                # --quality-overhead 2% budget
                pick = 0
                best = vote[0]
                entropy = 0.0
                for i in range(1, len(vote)):
                    p = vote[i]
                    if p > best:
                        pick = i
                        best = p
                if n > 1:
                    for p in vote:
                        if p > 0.0:
                            entropy -= p * math.log(p)
                    entropy /= math.log(n)
                card.entropy_sum += entropy
                picks.append((ballot.model, pick))
                if best < 0.5:
                    card.hedges += 1
                agree = 1 if winner is not None and pick == winner else 0
                card.agreements += agree
                mass = vote[winner] if winner is not None else 0.0
                card.window.append((agree, mass))
                card.agree_total += agree
                card.mass_total += mass
                if winner is not None:
                    # top-1 calibration (standard ECE): bin the mass
                    # the judge put on its own pick vs "was the pick
                    # the consensus winner" — the "says 0.9, right 60%
                    # of the time" signal, O(1) per ballot
                    p = 0.0 if best < 0.0 else (1.0 if best > 1.0 else best)
                    idx = int(p * N_CALIBRATION_BINS)
                    if idx >= N_CALIBRATION_BINS:
                        idx = N_CALIBRATION_BINS - 1
                    b = card.bins[idx]
                    b[0] += 1
                    b[1] += p
                    b[2] += agree
            # pairwise agreement over shared ballots
            picks.sort()
            for i in range(len(picks)):
                model_a, pick_a = picks[i]
                for j in range(i + 1, len(picks)):
                    model_b, pick_b = picks[j]
                    pair = self._pairs.get((model_a, model_b))
                    if pair is None:
                        pair = self._pairs[(model_a, model_b)] = (
                            _PairStats()
                        )
                    pair.count += 1
                    pair.marg_a[pick_a] += 1
                    pair.marg_b[pick_b] += 1
                    if pick_a == pick_b:
                        pair.agree += 1

    # -- read side ------------------------------------------------------------

    def scorecard(self, model: str) -> Optional[dict]:
        with self._lock:
            card = self._judges.get(model)
            if card is None:
                return None
            return card.scorecard(self.drift_threshold)

    def scorecards(self) -> List[dict]:
        with self._lock:
            threshold = self.drift_threshold
            return [
                card.scorecard(threshold)
                for _, card in sorted(self._judges.items())
            ]

    def snapshot(self) -> dict:
        """The /metrics ``quality`` section."""
        with self._lock:
            requests = self._requests
            outcomes = {k: self._outcomes.get(k, 0) for k in OUTCOMES}
            margin = self._margin.to_json_obj()
            threshold = self.drift_threshold
            judges = {
                model: card.scorecard(threshold)
                for model, card in sorted(self._judges.items())
            }
            kappa = {
                f"{a}|{b}": {
                    "ballots": pair.count,
                    "kappa": (
                        round(pair.kappa(), 4)
                        if pair.kappa() is not None
                        else None
                    ),
                }
                for (a, b), pair in sorted(self._pairs.items())
            }
        out = {
            "requests": requests,
            "outcomes": outcomes,
            "degraded_rate": (
                round(outcomes["degraded"] / requests, 4) if requests else None
            ),
            "quorum_degraded_rate": (
                round(outcomes["quorum_degraded"] / requests, 4)
                if requests
                else None
            ),
            "all_failed_rate": (
                round(outcomes["all_failed"] / requests, 4)
                if requests
                else None
            ),
            "confidence_margin": margin,
            "window": self.window,
            "drift_threshold": threshold,
            "judges": judges,
            "pairwise_kappa": kappa,
            "flagged": [
                m for m, c in judges.items() if c["drift"]["flagged"]
            ],
        }
        return out

    def summary(self) -> dict:
        """Compact consensus-quality summary for BENCH records: the
        three numbers a bench reader wants next to a latency figure."""
        with self._lock:
            requests = self._requests
            degraded = self._outcomes.get("degraded", 0)
            median = self._margin.quantile(0.5)
            threshold = self.drift_threshold
            rates = [
                card.agreements / card.voted
                for card in self._judges.values()
                if card.voted
            ]
            flagged = [
                card.model
                for card in self._judges.values()
                if card.drift(threshold)["flagged"]
            ]
        return {
            "requests": requests,
            "degraded_rate": (
                round(degraded / requests, 4) if requests else None
            ),
            "median_confidence_margin": (
                round(median, 4) if median is not None else None
            ),
            "judge_agreement_spread": (
                round(max(rates) - min(rates), 4) if rates else None
            ),
            "flagged_judges": sorted(flagged),
        }

    def prom_snapshot(self) -> dict:
        """Cloned margin histogram + flat per-judge gauges for the
        Prometheus renderer — clones and plain floats, so rendering
        never races an observe."""
        with self._lock:
            threshold = self.drift_threshold
            return {
                "margin": Histogram().merge(self._margin),
                "exemplar": self._exemplar,
                "outcomes": {k: self._outcomes.get(k, 0) for k in OUTCOMES},
                "agreement": {
                    model: card.agreements / card.voted
                    for model, card in sorted(self._judges.items())
                    if card.voted
                },
                "drift_flagged": {
                    model: 1.0 if card.drift(threshold)["flagged"] else 0.0
                    for model, card in sorted(self._judges.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._judges.clear()
            self._pairs.clear()
            self._margin = Histogram()
            self._outcomes.clear()
            self._requests = 0
            self._exemplar = None


_AGG = QualityAggregator()


def quality_aggregator() -> QualityAggregator:
    return _AGG


def observe_outcome(outcome: Outcome) -> None:
    _AGG.observe_outcome(outcome)


def quality_snapshot() -> dict:
    return _AGG.snapshot()


def quality_summary() -> dict:
    return _AGG.summary()


def configure_quality(
    window: Optional[int] = None,
    drift_threshold: Optional[float] = None,
) -> None:
    _AGG.configure(window=window, drift_threshold=drift_threshold)


def reset_quality() -> None:
    _AGG.reset()
