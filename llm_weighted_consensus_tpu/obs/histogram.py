"""Mergeable log-bucketed latency histograms (ISSUE 11).

The old ``serve/metrics.py`` percentile source was a 1024-sample recent
window: honest for a single process eyeballing /metrics, useless for
anything that must *aggregate* — dashboards summing replicas, benches
summing worker processes, phase attributions summing requests.  This is
the standard fix (Prometheus classic histograms / DDSketch's log
buckets): a FIXED exponential bucket layout every instance shares, so

* ``observe`` is O(1) — one ``log2``, one index increment, no sorting,
  no allocation (the hot-path budget ``bench_host.py
  --metrics-overhead`` enforces);
* two histograms **merge** by adding counts elementwise — cross-replica
  and cross-phase aggregation is exact, not approximate;
* quantiles carry a *bounded relative error*: with growth
  ``2**(1/4)`` per bucket and the geometric-mean midpoint estimate the
  worst case is ``2**(1/8) - 1`` (~9.05%) — tested against exact
  percentiles in ``tests/test_perfobs.py``.

Values are unit-agnostic but every call site in this repo passes
milliseconds; the layout spans 1 microsecond to ~17 minutes with an
overflow bucket above, which covers everything from a histogram
``observe`` itself to a wedged device dispatch.

Stdlib-only, like the rest of ``obs/`` (dependency-free below
``utils``).
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional, Tuple

# Fixed layout shared by every instance: bucket ``i`` holds values in
# (BASE * GROWTH**(i-1), BASE * GROWTH**i]; bucket 0 holds (0, BASE].
# 4 buckets per octave = relative quantile error <= 2**(1/8) - 1.
BASE_MS = 1e-3
BUCKETS_PER_OCTAVE = 4
GROWTH = 2.0 ** (1.0 / BUCKETS_PER_OCTAVE)
# 30 octaves above BASE_MS: 1e-3 ms .. ~2**30 * 1e-3 ms (~17.9 min)
N_BUCKETS = 30 * BUCKETS_PER_OCTAVE + 1
_TOP_MS = BASE_MS * GROWTH ** (N_BUCKETS - 1)

# upper bound of bucket i, precomputed once (quantile + exposition)
_BOUNDS = tuple(BASE_MS * GROWTH**i for i in range(N_BUCKETS))


def bucket_index(value: float) -> int:
    """The fixed-layout bucket for ``value``: O(1), no search."""
    if value <= BASE_MS:
        return 0
    if value > _TOP_MS:
        return N_BUCKETS  # overflow (le = +Inf)
    idx = math.ceil(math.log2(value / BASE_MS) * BUCKETS_PER_OCTAVE)
    # float round-trip guard: log2 can land a boundary value one bucket
    # low/high; the invariant is bounds[idx-1] < value <= bounds[idx]
    if idx > 0 and value <= _BOUNDS[idx - 1]:
        idx -= 1
    elif value > _BOUNDS[min(idx, N_BUCKETS - 1)]:
        idx += 1
    return min(idx, N_BUCKETS)


class Histogram:
    """Counts + sum over the fixed log-bucket layout.

    Single-writer by contract in the event loop (like every counter in
    ``serve/``); the phase aggregator that IS shared across executor
    threads wraps its histograms in one lock (obs/phases.py)."""

    __slots__ = ("counts", "count", "sum")

    def __init__(self) -> None:
        # N_BUCKETS finite buckets + 1 overflow
        self.counts: List[int] = [0] * (N_BUCKETS + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bucket_index(value)] += 1
        self.count += 1
        self.sum += value

    def merge(self, other: "Histogram") -> "Histogram":
        """Elementwise-add ``other`` into self (exact: shared layout)."""
        counts = self.counts
        for i, c in enumerate(other.counts):
            if c:
                counts[i] += c
        self.count += other.count
        self.sum += other.sum
        return self

    def quantile(self, q: float) -> Optional[float]:
        """Bounded-error quantile: the geometric midpoint of the bucket
        containing rank ``ceil(q * count)``; None when empty."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(min(max(q, 0.0), 1.0) * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i >= N_BUCKETS:
                    return _TOP_MS  # overflow: the honest lower bound
                upper = _BOUNDS[i]
                lower = _BOUNDS[i - 1] if i > 0 else upper / GROWTH
                return math.sqrt(lower * upper)
        return _TOP_MS  # unreachable with a consistent count

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    # -- exposition -----------------------------------------------------------

    def cumulative(self) -> Iterator[Tuple[str, int]]:
        """(le, cumulative count) pairs for Prometheus ``_bucket``
        rendering: only occupied buckets plus the mandatory ``+Inf``
        terminator, so the exposition stays proportional to the spread
        actually observed, not the 121-bucket layout."""
        seen = 0
        for i, c in enumerate(self.counts):
            if c:
                seen += c
                le = "+Inf" if i >= N_BUCKETS else _format_le(_BOUNDS[i])
                if i < N_BUCKETS:
                    yield le, seen
        yield "+Inf", self.count

    def to_json_obj(self) -> dict:
        """Compact JSON summary (the /metrics ``phases`` section rows)."""
        out = {
            "count": self.count,
            "sum_ms": round(self.sum, 3),
        }
        if self.count:
            out["p50_ms"] = round(self.quantile(0.5), 3)
            out["p99_ms"] = round(self.quantile(0.99), 3)
        return out


def _format_le(bound: float) -> str:
    """A stable short decimal for a bucket bound label."""
    return format(bound, ".6g")


def le_for(value: float) -> str:
    """The ``le`` label of the bucket ``value`` lands in — lets the
    Prometheus renderer attach an exemplar to exactly the ``_bucket``
    line whose range contains the exemplar's own latency."""
    idx = bucket_index(value)
    return "+Inf" if idx >= N_BUCKETS else _format_le(_BOUNDS[idx])
