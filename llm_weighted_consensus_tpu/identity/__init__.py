"""Content-addressed identity: canonical JSON -> xxh3-128 -> base62(22).

Parity target: reference src/score/llm/mod.rs:513-548 and
src/score/model/mod.rs:97-189.  The pipeline is identical (xxh3-128 with seed
0 over a canonical JSON string, base62-encoded and zero-padded to 22 chars),
but the canonical JSON is produced by this framework's own writer
(utils/jsonutil), so ids form this framework's own id space ("v1") rather
than being byte-compatible with the Rust crate's serde output.  Within the
framework ids are fully deterministic and stable — guarded by golden tests
(tests/test_identity.py).
"""

from __future__ import annotations

import xxhash

from ..utils import jsonutil

BASE62_ALPHABET = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"

ID_LEN = 22


def base62_encode(n: int) -> str:
    if n == 0:
        return "0"
    out = []
    while n > 0:
        n, r = divmod(n, 62)
        out.append(BASE62_ALPHABET[r])
    return "".join(reversed(out))


def hash_json_obj(obj) -> int:
    """xxh3-128(canonical JSON) as an unsigned 128-bit integer."""
    return xxhash.xxh3_128_intdigest(jsonutil.dumps(obj).encode("utf-8"))


def id_string(n: int) -> str:
    """base62, zero-padded to 22 chars (llm/mod.rs:520-522)."""
    return base62_encode(n).rjust(ID_LEN, "0")


class IncrementalHasher:
    """Streaming xxh3-128 used for panel ids (model/mod.rs:97-115)."""

    def __init__(self):
        self._h = xxhash.xxh3_128(seed=0)

    def write(self, data: str) -> None:
        self._h.update(data.encode("utf-8"))

    def finish_id(self) -> str:
        return id_string(self._h.intdigest())


from .llm import (  # noqa: E402
    Llm,
    LlmBase,
    LlmWithoutIndices,
    OUTPUT_MODE_DEFAULT,
    Weight,
    WeightStatic,
    WeightTrainingTable,
    default_weight,
)
from .model import (  # noqa: E402
    Model,
    ModelBase,
    PanelWeight,
    PanelWeightStatic,
    PanelWeightTrainingTable,
    WeightTrainingTableEmbeddings,
)

__all__ = [
    "BASE62_ALPHABET",
    "ID_LEN",
    "IncrementalHasher",
    "Llm",
    "LlmBase",
    "LlmWithoutIndices",
    "Model",
    "ModelBase",
    "OUTPUT_MODE_DEFAULT",
    "PanelWeight",
    "PanelWeightStatic",
    "PanelWeightTrainingTable",
    "Weight",
    "WeightStatic",
    "WeightTrainingTable",
    "WeightTrainingTableEmbeddings",
    "base62_encode",
    "default_weight",
    "hash_json_obj",
    "id_string",
]
