"""Panel model: 1-128 judges + panel-level weight mode and identities.

Parity target: reference src/score/model/mod.rs (429 LoC):

* ``ModelBase{llms, weight}`` -> ``into_model_validate`` (model/mod.rs:37-199):
  prepare + validate every judge, judges sorted by id for deterministic order
  (model/mod.rs:90), per-judge ``index`` / ``training_table_index`` (position
  among unique training-table ids) / ``multichat_index`` (position among
  sorted multichat ids, duplicates getting consecutive slots,
  model/mod.rs:153-178) assigned;
* panel-level ``id`` / ``training_table_id`` / ``multichat_id`` are streaming
  hashes over (weight-config JSON + sorted member ids) (model/mod.rs:97-189).
  This framework hashes each multichat id once (the reference's second hashing
  pass over the same ids, model/mod.rs:166-178, is redundant with the first
  and ids here are an independent id space anyway — see identity/__init__).
* trained-weight config ``WeightTrainingTable{embeddings:{model, max_tokens,
  provider}, top}`` (model/mod.rs:278-429).
"""

from __future__ import annotations

from typing import Optional

from ..types.base import Enum, List, Struct, TaggedUnion, _encode, field
from ..types.chat_request import ProviderPreferences
from .llm import (
    I32_MAX,
    Llm,
    LlmBase,
    _prepare_provider,
    _validate_provider,
    weight_type,
)

MAX_LLMS = 128


class PanelWeightStatic(Struct):
    type: str = field(Enum("static"), default="static")

    def prepare(self) -> None:
        pass

    def validate(self) -> None:
        pass


class WeightTrainingTableEmbeddings(Struct):
    model: str = field(str)
    max_tokens: int = field(int, default=0, skip_if_none=False)
    provider: Optional[ProviderPreferences] = field(ProviderPreferences, default=None)

    def prepare(self) -> None:
        self.provider = _prepare_provider(self.provider)

    def validate(self) -> None:
        if not self.model:
            raise ValueError("`embeddings.model` cannot be empty")
        if self.max_tokens < 0 or self.max_tokens > I32_MAX:
            raise ValueError(
                f"`embeddings.max_tokens` must be between 0 and {I32_MAX}: "
                f"got {self.max_tokens}"
            )
        _validate_provider(self.provider)


class PanelWeightTrainingTable(Struct):
    type: str = field(Enum("training_table"), default="training_table")
    embeddings: WeightTrainingTableEmbeddings = field(
        WeightTrainingTableEmbeddings, default=None
    )
    top: int = field(int, default=1, skip_if_none=False)

    def prepare(self) -> None:
        if self.embeddings is not None:
            self.embeddings.prepare()

    def validate(self) -> None:
        if self.embeddings is None:
            raise ValueError("`embeddings` is required for training table weights")
        if self.top < 1:
            raise ValueError(
                f"training table weight `top` must be at least 1: `top`={self.top}"
            )
        if self.top > I32_MAX:
            raise ValueError(
                f"training table weight `top` must be at most {I32_MAX}: `top`={self.top}"
            )
        self.embeddings.validate()


PANEL_WEIGHT = TaggedUnion(
    "type", {"static": PanelWeightStatic, "training_table": PanelWeightTrainingTable}
)

PanelWeight = (PanelWeightStatic, PanelWeightTrainingTable)


def default_panel_weight() -> PanelWeightStatic:
    return PanelWeightStatic(type="static")


class ModelBase(Struct):
    llms: list = field(List(LlmBase), default_factory=list, skip_if_none=False)
    weight: object = field(
        PANEL_WEIGHT, default_factory=default_panel_weight, skip_if_none=False
    )

    def prepare(self) -> None:
        self.weight.prepare()
        for llm in self.llms:
            llm.prepare()

    def validate_llms_len(self) -> None:
        if len(self.llms) < 1:
            raise ValueError("query model must have at least 1 llm")
        if len(self.llms) > MAX_LLMS:
            raise ValueError(
                f"query model must have at most {MAX_LLMS} llms: "
                f"llms_len={len(self.llms)}"
            )

    def into_model_validate(self) -> "Model":
        from . import IncrementalHasher
        from ..utils import jsonutil

        self.prepare()
        self.validate_llms_len()
        self.weight.validate()
        panel_weight_type = self.weight.type

        is_training_table = panel_weight_type == "training_table"

        llms: list[Llm] = []
        training_table_ids: list[str] = [] if is_training_table else None
        multichat_ids: list[str] = []

        for base in self.llms:
            base.validate(panel_weight_type)
            llm_id = base.id_string()
            training_table_id = base.training_table_id_string()
            multichat_id = base.multichat_id_string()
            if is_training_table and training_table_id is not None:
                if training_table_id not in training_table_ids:
                    training_table_ids.append(training_table_id)
            multichat_ids.append(multichat_id)
            llms.append(
                Llm(
                    id=llm_id,
                    index=0,
                    multichat_id=multichat_id,
                    multichat_index=0,
                    training_table_id=training_table_id,
                    training_table_index=None,
                    base=base,
                )
            )

        # deterministic ordering (model/mod.rs:88-94)
        llms.sort(key=lambda l: l.id)
        if training_table_ids is not None:
            training_table_ids.sort()
        multichat_ids.sort()

        # panel id: hash(weight JSON + member ids in sorted order)
        hasher = IncrementalHasher()
        hasher.write(jsonutil.dumps(_encode(PANEL_WEIGHT, self.weight)))

        tt_hasher = None
        if is_training_table:
            tt_hasher = IncrementalHasher()
            tt_hasher.write(self.weight.embeddings.to_json())

        mc_hasher = IncrementalHasher()
        multichat_seen: dict[str, int] = {}

        for i, llm in enumerate(llms):
            hasher.write(llm.id)
            llm.index = i
            if tt_hasher is not None:
                tt_hasher.write(llm.training_table_id)
                llm.training_table_index = training_table_ids.index(
                    llm.training_table_id
                )
            # duplicates of the same generator get consecutive slots
            # (model/mod.rs:153-163)
            multichat_seen[llm.multichat_id] = multichat_seen.get(llm.multichat_id, 0) + 1
            llm.multichat_index = (
                multichat_ids.index(llm.multichat_id)
                + multichat_seen[llm.multichat_id]
                - 1
            )

        for multichat_id in multichat_ids:
            mc_hasher.write(multichat_id)

        return Model(
            id=hasher.finish_id(),
            multichat_id=mc_hasher.finish_id(),
            training_table_id=tt_hasher.finish_id() if tt_hasher else None,
            llms=llms,
            weight=self.weight,
        )


class Model(Struct):
    id: str = field(str)
    multichat_id: str = field(str, default="", skip_if_none=False)
    training_table_id: Optional[str] = field(str, default=None)
    llms: list = field(List(Llm), default_factory=list, skip_if_none=False)
    weight: object = field(
        PANEL_WEIGHT, default_factory=default_panel_weight, skip_if_none=False
    )

    def static_weights(self) -> list:
        """Per-judge static weights (weight.rs:76-97)."""
        return [llm.base.weight.weight for llm in self.llms]
