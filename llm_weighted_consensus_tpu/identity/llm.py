"""Judge (LLM) configuration: canonicalization, validation, 3-way identity.

Parity target: reference src/score/llm/mod.rs (745 LoC):

* ``prepare`` (llm/mod.rs:76-258) canonicalizes the config *before hashing* so
  semantically-equal configs hash equal (drop fields equal to defaults, sort
  provider string lists, collapse singleton ``stop`` arrays, ...);
* ``validate`` (llm/mod.rs:260-511) enforces ranges;
* three content-addressed identities (llm/mod.rs:513-548):
  - ``id``                — full config,
  - ``training_table_id`` — weight reset to default (judges sharing a training
                            table row regardless of weight bounds),
  - ``multichat_id``      — weight/output_mode/synthetic_reasoning/top_logprobs
                            reset (judges that are the same *generator*).

The default weight (static 1.0) participates in hashing and is frozen, same
as the reference's ``NEVER change this implementation`` guard
(llm/mod.rs:597-605).
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

from ..types.base import (
    Map,
    Struct,
    TaggedUnion,
    field,
)
from ..types.chat_request import (
    MESSAGE,
    ProviderPreferences,
    REASONING_EFFORT,
    Reasoning,
    STOP,
    VERBOSITY,
)
from ..types.base import Enum, List

OUTPUT_MODE = Enum("instruction", "json_schema", "tool_call")
OUTPUT_MODE_DEFAULT = "instruction"

MAX_TOP_LOGPROBS = 20
I32_MAX = 2**31 - 1


class WeightStatic(Struct):
    type: str = field(Enum("static"), default="static")
    weight: Decimal = field(Decimal, default_factory=lambda: Decimal("1.0"))

    def validate(self) -> None:
        if not self.weight.is_finite() or self.weight <= 0:
            raise ValueError(
                f"`weight` must be a normal positive number: `weight`={self.weight}"
            )


class WeightTrainingTable(Struct):
    type: str = field(Enum("training_table"), default="training_table")
    base_weight: Decimal = field(Decimal, default_factory=lambda: Decimal("1.0"))
    min_weight: Decimal = field(Decimal, default_factory=lambda: Decimal("1.0"))
    max_weight: Decimal = field(Decimal, default_factory=lambda: Decimal("1.0"))

    def validate(self) -> None:
        if (
            not self.base_weight.is_finite()
            or not self.min_weight.is_finite()
            or not self.max_weight.is_finite()
            or self.base_weight < self.min_weight
            or self.base_weight > self.max_weight
            or self.min_weight > self.max_weight
            or self.base_weight <= 0
            or self.min_weight <= 0
            or self.max_weight <= 0
        ):
            raise ValueError(
                "LLM must have normal positive base, min, and max weights for "
                "training table weights mode: "
                f"`base_weight={self.base_weight}`, `min_weight={self.min_weight}`, "
                f"`max_weight={self.max_weight}`"
            )


# Weight is untagged in serde but fully determined by its `type` value, so a
# tagged union reproduces the same JSON (llm/mod.rs:590-605).
WEIGHT = TaggedUnion(
    "type", {"static": WeightStatic, "training_table": WeightTrainingTable}
)

Weight = (WeightStatic, WeightTrainingTable)


def default_weight() -> WeightStatic:
    # NEVER change: participates in id hashing (llm/mod.rs:597-605).
    return WeightStatic(type="static", weight=Decimal("1.0"))


def weight_type(weight) -> str:
    return weight.type


def _validate_range_f(value, name, lo, hi):
    if value is None:
        return
    import math

    if not math.isfinite(value):
        raise ValueError(f"`{name}` must be a finite number: `{name}`={value}")
    if value < lo or value > hi:
        raise ValueError(
            f"`{name}` must be between {lo} and {hi}: `{name}`={value}"
        )


def _validate_range_u(value, name, lo, hi):
    if value is None:
        return
    if value < lo or value > hi:
        raise ValueError(
            f"`{name}` must be between {lo} and {hi}: `{name}`={value}"
        )


def _validate_strings(values, name):
    if values is None:
        return
    seen = set()
    for s in values:
        if s == "":
            raise ValueError(f"`{name}` cannot contain empty strings")
        if s in seen:
            raise ValueError(f"`{name}` cannot contain duplicate strings: `{s}`")
        seen.add(s)


def _prepare_provider(provider: Optional[ProviderPreferences]):
    """Canonicalize provider preferences (llm/mod.rs:158-207)."""
    if provider is None:
        return None
    if provider.is_empty():
        return None
    if provider.order is not None and not provider.order:
        provider.order = None
    if provider.allow_fallbacks is True:
        provider.allow_fallbacks = None
    if provider.require_parameters is False:
        provider.require_parameters = None
    if provider.data_collection == "allow":
        provider.data_collection = None
    for attr in ("only", "ignore", "quantizations"):
        values = getattr(provider, attr)
        if values is not None:
            values.sort()
            if not values:
                setattr(provider, attr, None)
    if provider.is_empty():
        return None
    return provider


def _validate_provider(provider: Optional[ProviderPreferences]):
    if provider is None:
        return
    _validate_strings(provider.order, "provider.order")
    _validate_strings(provider.only, "provider.only")
    _validate_strings(provider.ignore, "provider.ignore")
    _validate_strings(provider.quantizations, "provider.quantizations")
    if provider.sort is not None and provider.sort == "":
        raise ValueError("`provider.sort` cannot be empty")


class LlmBase(Struct):
    """Per-judge config (llm/mod.rs:7-73); field order is hash-significant."""

    model: str = field(str)
    weight: object = field(WEIGHT, default_factory=default_weight, skip_if_none=False)
    output_mode: str = field(OUTPUT_MODE, default=OUTPUT_MODE_DEFAULT, skip_if_none=False)
    synthetic_reasoning: Optional[bool] = field(bool, default=None)
    top_logprobs: Optional[int] = field(int, default=None)
    prefix_messages: Optional[list] = field(List(MESSAGE), default=None)
    suffix_messages: Optional[list] = field(List(MESSAGE), default=None)
    # openai fields
    frequency_penalty: Optional[float] = field(float, default=None)
    logit_bias: Optional[dict] = field(Map(int), default=None)
    max_completion_tokens: Optional[int] = field(int, default=None)
    presence_penalty: Optional[float] = field(float, default=None)
    stop: object = field(STOP, default=None)
    temperature: Optional[float] = field(float, default=None)
    top_p: Optional[float] = field(float, default=None)
    # openrouter fields
    max_tokens: Optional[int] = field(int, default=None)
    min_p: Optional[float] = field(float, default=None)
    provider: Optional[ProviderPreferences] = field(ProviderPreferences, default=None)
    reasoning: Optional[Reasoning] = field(Reasoning, default=None)
    repetition_penalty: Optional[float] = field(float, default=None)
    top_a: Optional[float] = field(float, default=None)
    top_k: Optional[int] = field(int, default=None)
    verbosity: Optional[str] = field(VERBOSITY, default=None)
    models: Optional[list] = field(List(str), default=None)

    # -- canonicalization (llm/mod.rs:76-258) -------------------------------

    def prepare(self) -> None:
        def drop_default(attr, default):
            if getattr(self, attr) == default:
                setattr(self, attr, None)

        if self.synthetic_reasoning is False:
            self.synthetic_reasoning = None
        if self.top_logprobs == 0:
            self.top_logprobs = None
        if self.prefix_messages is not None and not self.prefix_messages:
            self.prefix_messages = None
        if self.suffix_messages is not None and not self.suffix_messages:
            self.suffix_messages = None
        drop_default("frequency_penalty", 0.0)
        if self.logit_bias is not None and not self.logit_bias:
            self.logit_bias = None
        drop_default("max_completion_tokens", 0)
        drop_default("presence_penalty", 0.0)
        # stop: [] -> None, [x] -> x, else sorted
        if isinstance(self.stop, list):
            if not self.stop:
                self.stop = None
            elif len(self.stop) == 1:
                self.stop = self.stop[0]
            else:
                self.stop.sort()
        drop_default("temperature", 1.0)
        drop_default("top_p", 1.0)
        drop_default("max_tokens", 0)
        drop_default("min_p", 0.0)
        self.provider = _prepare_provider(self.provider)
        self._prepare_reasoning()
        drop_default("repetition_penalty", 1.0)
        drop_default("top_a", 0.0)
        drop_default("top_k", 0)
        if self.verbosity == "medium":
            self.verbosity = None
        if self.models is not None and not self.models:
            self.models = None

    def _prepare_reasoning(self) -> None:
        r = self.reasoning
        if r is None:
            return
        if r.max_tokens == 0:
            r.max_tokens = None
        if r.enabled is True and (r.effort is not None or r.max_tokens is not None):
            r.enabled = None
        elif r.enabled is False and r.effort is None and r.max_tokens is None:
            r.enabled = None
        if r.max_tokens is None and r.enabled is None and r.effort is None:
            self.reasoning = None

    # -- validation (llm/mod.rs:260-511) ------------------------------------

    def validate(self, expect_weight_type: str) -> None:
        if not self.model:
            raise ValueError("`model` cannot be empty")
        if weight_type(self.weight) != expect_weight_type:
            raise ValueError(
                f"expected weight of type `{expect_weight_type}`, "
                f"found `{weight_type(self.weight)}`"
            )
        self.weight.validate()
        if self.synthetic_reasoning and self.output_mode == "instruction":
            raise ValueError(
                "`synthetic_reasoning` cannot be true when `output_mode` is `instruction`"
            )
        _validate_range_u(self.top_logprobs, "top_logprobs", 0, MAX_TOP_LOGPROBS)
        _validate_range_f(self.frequency_penalty, "frequency_penalty", -2.0, 2.0)
        self._validate_logit_bias()
        _validate_range_u(
            self.max_completion_tokens, "max_completion_tokens", 0, I32_MAX
        )
        _validate_range_f(self.presence_penalty, "presence_penalty", -2.0, 2.0)
        self._validate_stop()
        _validate_range_f(self.temperature, "temperature", 0.0, 2.0)
        _validate_range_f(self.top_p, "top_p", 0.0, 1.0)
        _validate_range_u(self.max_tokens, "max_tokens", 0, I32_MAX)
        _validate_range_f(self.min_p, "min_p", 0.0, 1.0)
        _validate_provider(self.provider)
        self._validate_reasoning()
        _validate_range_f(self.repetition_penalty, "repetition_penalty", 0.0, 2.0)
        _validate_range_f(self.top_a, "top_a", 0.0, 1.0)
        _validate_range_u(self.top_k, "top_k", 0, I32_MAX)
        self._validate_models()

    def _validate_logit_bias(self) -> None:
        if self.logit_bias is None:
            return
        for token, bias in self.logit_bias.items():
            if token == "":
                raise ValueError("`logit_bias` keys cannot be empty")
            if not token.isdigit():
                raise ValueError(
                    f"`logit_bias` keys must be numeric: `logit_bias`={token}"
                )
            if token[0] == "0" and len(token) > 1:
                raise ValueError(
                    f"`logit_bias` keys cannot have leading zeroes: `logit_bias`={token}"
                )
            if bias > 100 or bias < -100:
                raise ValueError(
                    "`logit_bias` values must be between -100 and 100: "
                    f"`logit_bias[{token}]`={bias}"
                )

    def _validate_stop(self) -> None:
        if self.stop is None:
            return
        if isinstance(self.stop, str):
            if self.stop == "":
                raise ValueError("`stop` cannot be an empty string")
        else:
            _validate_strings(self.stop, "stop")

    def _validate_reasoning(self) -> None:
        r = self.reasoning
        if r is None:
            return
        _validate_range_u(r.max_tokens, "reasoning.max_tokens", 0, I32_MAX)
        if r.effort is not None and r.max_tokens is not None:
            raise ValueError(
                "`reasoning.max_tokens` and `reasoning.effort` cannot be set at the same time"
            )
        if r.enabled is False and r.max_tokens is not None:
            raise ValueError(
                "`reasoning.enabled` cannot be false when `reasoning.max_tokens` is set"
            )
        if r.enabled is False and r.effort is not None:
            raise ValueError(
                "`reasoning.enabled` cannot be false when `reasoning.effort` is set"
            )

    def _validate_models(self) -> None:
        if self.models is None:
            return
        seen = set()
        for model in self.models:
            if model == "":
                raise ValueError("models cannot contain empty strings")
            if model == self.model or model in seen:
                raise ValueError(
                    f"models cannot contain duplicate strings: `models`={model}"
                )
            seen.add(model)

    # -- identity (llm/mod.rs:513-548) --------------------------------------

    def id_number(self) -> int:
        from . import hash_json_obj

        return hash_json_obj(self.to_json_obj())

    def id_string(self) -> str:
        from . import id_string

        return id_string(self.id_number())

    def training_table_id_string(self) -> Optional[str]:
        if weight_type(self.weight) != "training_table":
            return None
        clone = self.clone()
        clone.weight = default_weight()
        return clone.id_string()

    def multichat_id_string(self) -> str:
        clone = self.clone()
        clone.weight = default_weight()
        clone.output_mode = OUTPUT_MODE_DEFAULT
        clone.synthetic_reasoning = None
        clone.top_logprobs = None
        return clone.id_string()

    # -- conversion ---------------------------------------------------------

    def into_llm_without_indices(self) -> "LlmWithoutIndices":
        self.prepare()
        self.validate(weight_type(self.weight))
        return LlmWithoutIndices(
            id=self.id_string(),
            multichat_id=self.multichat_id_string(),
            training_table_id=self.training_table_id_string(),
            base=self,
        )


class _FlattenedLlm(Struct):
    """Shared flatten(base) serialization for Llm/LlmWithoutIndices."""

    def to_json_obj(self):
        out = super().to_json_obj()
        out.update(out.pop("base", {}) or {})
        return out

    @classmethod
    def from_json_obj(cls, obj, *, path: str = ""):
        import dataclasses

        own = {f.metadata.get("json_name") or f.name for f in dataclasses.fields(cls)}
        own.discard("base")
        top = {k: v for k, v in obj.items() if k in own}
        rest = {k: v for k, v in obj.items() if k not in own}
        top["base"] = rest
        return super().from_json_obj(top, path=path)


class LlmWithoutIndices(_FlattenedLlm):
    id: str = field(str)
    multichat_id: str = field(str)
    training_table_id: Optional[str] = field(str, default=None)
    base: LlmBase = field(LlmBase, default=None)


class Llm(_FlattenedLlm):
    """Validated judge with panel indices (llm/mod.rs:720-745)."""

    id: str = field(str)
    index: int = field(int, default=0, skip_if_none=False)
    multichat_id: str = field(str, default="", skip_if_none=False)
    multichat_index: int = field(int, default=0, skip_if_none=False)
    training_table_id: Optional[str] = field(str, default=None)
    training_table_index: Optional[int] = field(int, default=None)
    base: LlmBase = field(LlmBase, default=None)
