"""Batched logprob -> probability soft votes on device.

Device twin of the numeric tail of ``ballot.vote.extract_vote``
(reference get_vote, client.rs:1764-1792): map each ``top_logprobs``
alternative of the final key token to its candidate leaf, ``exp`` the
logprobs, and normalize to a distribution over candidates.

The host path handles one judge at a time with exact Decimal math; this
path handles a whole batch of judges (archive re-scoring, multichat) as one
fused exp/scatter/normalize.  Scatter is expressed as a one-hot matmul so
it lands on the MXU instead of serializing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_choices",))
def softmax_votes(
    logprobs: jax.Array,
    candidate_ids: jax.Array,
    valid: jax.Array,
    n_choices: int,
) -> jax.Array:
    """logprobs[M, K], candidate_ids[M, K] (int, -1 for invalid),
    valid[M, K] -> votes[M, n_choices], rows normalized (zero if no valid
    alternative).

    K is the ``top_logprobs`` fan (<= 20).  Invalid slots (letter not a
    sibling leaf, missing logprob) carry ``valid=0``.
    """
    logprobs = logprobs.astype(jnp.float32)
    valid = valid.astype(jnp.float32)
    p = jnp.exp(logprobs) * valid  # [M, K]
    # scatter-add via one-hot contraction (MXU-friendly, no dynamic shapes)
    onehot = jax.nn.one_hot(candidate_ids, n_choices, dtype=jnp.float32)
    votes = jnp.einsum("mk,mkn->mn", p, onehot, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)
    total = jnp.sum(votes, axis=-1, keepdims=True)
    return jnp.where(total > 0, votes / total, 0.0)

# (a one_hot_votes twin was removed: the revote path encodes one-hot
# fallbacks as a single logprob-0 alternative through softmax_votes —
# exp(0)=1 normalizes to the one-hot row, no second kernel needed)
