"""Embedding cosine math: pairwise similarity, consensus votes, top-k.

Pure TPU territory (SURVEY §3.5 item 4): the reference keeps its trained
weight path behind the ``weight::Fetcher`` seam and does no tensor math;
here the embedding consensus becomes real device kernels:

* ``cosine_consensus_vote`` — self-consistency scoring (BASELINE config 1):
  each candidate's confidence is the softmax of its mean cosine similarity
  to all other candidates (centroid agreement);
* ``top_k_similar`` — training-table lookup: nearest archived prompts per
  judge (BASELINE config 3 / trained weights);
* all matmuls bf16-in/f32-accumulate for the MXU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    x = x.astype(jnp.float32)
    norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    return x / jnp.maximum(norm, eps)


@jax.jit
def cosine_similarity(a: jax.Array, b: jax.Array) -> jax.Array:
    """a[B, D], b[C, D] -> [B, C] cosine similarity (one MXU contraction)."""
    return jnp.einsum(
        "bd,cd->bc",
        l2_normalize(a),
        l2_normalize(b),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


@jax.jit
def pairwise_cosine(x: jax.Array) -> jax.Array:
    """x[N, D] -> [N, N] full pairwise cosine similarity."""
    n = l2_normalize(x)
    return jnp.einsum("nd,md->nm", n, n, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST)


def dyn_cosine_vote(embeddings: jax.Array, temperature) -> jax.Array:
    """``cosine_consensus_vote`` numerics with a TRACED temperature and
    optional leading batch dims: embeddings[..., N, D] ->
    confidence[..., N].

    The ONE implementation of the vote math — the jitted static-
    temperature wrapper below and the serving batched paths
    (models/embedder.py) all reduce to this.  Temperature must be traced
    on user-facing paths: a jit-static temperature would compile a fresh
    program per distinct user value (a recompile-DoS through
    POST /consensus).
    """
    nrm = l2_normalize(embeddings)
    sims = jnp.einsum(
        "...nd,...md->...nm",
        nrm,
        nrm,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    n = sims.shape[-1]
    off_diag = sims - jnp.eye(n, dtype=sims.dtype) * sims
    mean_sim = jnp.sum(off_diag, axis=-1) / jnp.maximum(n - 1, 1)
    return jax.nn.softmax(mean_sim / temperature, axis=-1)


@partial(jax.jit, static_argnames=("temperature",))
def cosine_consensus_vote(
    embeddings: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """embeddings[N, D] -> confidence[N]: softmax over mean off-diagonal
    cosine similarity (the embedding self-consistency vote).

    Candidates that agree with the cluster get high confidence; outliers
    get low.  ``temperature`` sharpens the softmax (0.05 suits bge-class
    cosine ranges).
    """
    return dyn_cosine_vote(embeddings, temperature)


@jax.jit
def masked_cosine_vote(
    embeddings: jax.Array, valid: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """``cosine_consensus_vote`` over a FIXED-capacity buffer with a
    dynamic valid mask: embeddings[CAP, D], valid[CAP] (1.0 = live row)
    -> confidence[CAP], softmax over valid rows only (invalid rows 0).

    The streaming-consensus state keeps one device-resident buffer and
    flips mask bits as candidates finish, so the jit specializes per
    capacity bucket instead of per candidate count — and the embed +
    revote fuse into ONE dispatch per update (one link round-trip).
    """
    valid = valid.astype(jnp.float32)
    sims = pairwise_cosine(embeddings).astype(jnp.float32)
    pair = valid[:, None] * valid[None, :]
    cap = sims.shape[0]
    off_diag = pair * (1.0 - jnp.eye(cap, dtype=sims.dtype))
    n_valid = jnp.sum(valid)
    mean_sim = jnp.sum(sims * off_diag, axis=-1) / jnp.maximum(
        n_valid - 1.0, 1.0
    )
    logits = jnp.where(valid > 0, mean_sim / temperature, -1e9)
    conf = jax.nn.softmax(logits)
    return jnp.where(valid > 0, conf, 0.0)


@partial(jax.jit, static_argnames=("k",))
def top_k_similar(table: jax.Array, queries: jax.Array, k: int):
    """table[T, D], queries[B, D] -> (scores[B, k], indices[B, k]).

    The training-table nearest-row lookup: embed the prompt, find its k
    closest archived prompts per judge table.
    """
    sims = cosine_similarity(queries, table)
    return jax.lax.top_k(sims, k)


@partial(jax.jit, static_argnames=("k",))
def training_table_weights_batched(
    tables: jax.Array,
    row_mask: jax.Array,
    table_scores: jax.Array,
    query: jax.Array,
    min_weight: jax.Array,
    max_weight: jax.Array,
    k: int,
) -> jax.Array:
    """Per-judge trained weights with per-judge tables, ONE dispatch.

    tables[J, T, D] judge-specific prompt embeddings padded to a common row
    count; row_mask[J, T] 1 for real rows; table_scores[J, T] historical
    accuracy; query[D]; min/max_weight[J].  Returns weights[J].  Padded
    rows are masked out of the top-k and of the attention softmax, so a
    judge with fewer than ``k`` real rows attends only to its real rows
    (matching the per-judge ``k=min(top, rows)`` of the loop form).
    """
    j, t, d = tables.shape
    nq = l2_normalize(query)[None, :]  # [1, D]
    nt = l2_normalize(tables.reshape(j * t, d)).reshape(j, t, d)
    sims = jnp.einsum(
        "jtd,od->jt", nt, nq, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    valid = row_mask > 0
    sims = jnp.where(valid, sims, -jnp.inf)
    k_eff = min(k, t)
    top_scores, top_idx = jax.lax.top_k(sims, k_eff)  # [J, k]
    top_valid = jnp.isfinite(top_scores)
    # masked softmax over each judge's valid top rows
    logits = jnp.where(top_valid, top_scores / 0.05, -jnp.inf)
    mx = jnp.max(
        jnp.where(top_valid, logits, -1e30), axis=-1, keepdims=True
    )
    e = jnp.where(top_valid, jnp.exp(logits - mx), 0.0)
    attn = e / jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    gathered = jnp.take_along_axis(
        table_scores.astype(jnp.float32), top_idx, axis=-1
    )  # [J, k]
    quality = jnp.sum(attn * gathered, axis=-1)  # [J]
    lo = min_weight.astype(jnp.float32)
    hi = max_weight.astype(jnp.float32)
    return lo + (hi - lo) * quality


@partial(jax.jit, static_argnames=("k",))
def training_table_weights(
    table: jax.Array,
    table_scores: jax.Array,
    queries: jax.Array,
    min_weight: jax.Array,
    max_weight: jax.Array,
    k: int,
) -> jax.Array:
    """Trained per-judge weights from a training table.

    table[T, D] archived prompt embeddings; table_scores[J, T] per-judge
    historical accuracy in [0, 1]; queries[B, D] prompt embeddings;
    min/max_weight[J] per-judge bounds.  Returns weights[B, J]:
    similarity-weighted mean of the top-k rows' scores, linearly
    interpolated into [min_weight, max_weight].
    """
    scores, idx = top_k_similar(table, queries, k)  # [B,k] both
    # softmax over similarity -> attention over the k nearest rows
    attn = jax.nn.softmax(scores / 0.05, axis=-1)  # [B, k]
    per_judge = table_scores.astype(jnp.float32)[:, idx]  # [J, B, k]
    quality = jnp.einsum(
        "bk,jbk->bj", attn, per_judge, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )  # [B, J] in [0, 1]
    lo = min_weight.astype(jnp.float32)[None, :]
    hi = max_weight.astype(jnp.float32)[None, :]
    return lo + (hi - lo) * quality
