"""Device core: JAX/Pallas consensus kernels.

The numeric heart of the framework, TPU-first (SURVEY §2.8, §3.5):

* ``consensus`` — weighted tally + confidence: ``votes[M,N] x weights[M]``
  einsum + normalize, replacing the reference's host-side Decimal loop
  (score client.rs:384-456) for batched/device paths;
* ``votes``     — batched logprob->probability soft votes (the numeric tail
  of get_vote, client.rs:1764-1792);
* ``similarity``— embedding cosine math: pairwise cosine, top-k lookup,
  softmax cosine consensus vote (the self-consistency scorer);
* ``kernels``   — fused Pallas TPU kernels for the hot compositions.

Everything is jittable, static-shaped, bf16-friendly with f32 accumulation.
The streaming serve path keeps exact Decimal math on host (parity with the
reference); these kernels power archive batch re-scoring, trained weights,
and the multichat incremental consensus where throughput dominates.
"""

from . import consensus, kernels, similarity, votes  # noqa: F401
