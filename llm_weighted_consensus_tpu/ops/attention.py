"""Fused multi-head attention Pallas kernel for the encoder hot path.

The einsum attention in ``models/bert.py`` materializes the [b, nh, s, s]
logits and probs tensors in HBM between XLA ops.  For encoder sequence
lengths (<=1024) a block of flat (batch, head) tiles — q/k/v [k, s, hd]
plus the [k, s, s] score tile — fits in VMEM, so the whole
QK^T -> bias -> softmax -> PV chain runs as ONE kernel with f32
accumulation on the MXU and no HBM round-trips for the intermediates
(SURVEY §3.5; VERDICT r1 item 2, r3 item 2).

Layout: grid (b*nh // heads_per_step,); each step processes
``heads_per_step`` flat (batch, head) tiles.  The additive padding bias
[b, s] (0 for real tokens, -1e9 for padding) is pre-expanded to one row
per flat tile so a step may straddle batch elements — any power-of-two
divisor of b*nh inside the VMEM budget works (``best_heads_per_step``).

Measured on the real v5e chip (bge-large shape nh=16, hd=64, bf16,
bench_attn.py + bench_fwd.py, r4):

  isolated op (b=64)          s=128   s=256   s=512
    XLA einsum                0.077   0.922   3.233  ms
    1-head/step kernel (r3)   0.564   0.966   1.716  ms
    re-tiled kernel (best k)  0.076   0.582   1.553  ms

  in-context full forward     s=128/b64  s=256/b32  s=384/b16  s=512/b16
    einsum (bf16 logits)      31.97      36.46      30.50      47.65 ms
    re-tiled kernel           35.54      39.56      30.87      42.79 ms

Isolated, the re-tiled kernel matches einsum at s=128 and wins 1.6-2.1x
at s>=256.  In context it pays the [b, s, nh, hd] -> [b*nh, s, hd]
transpose materializations (~0.14 ms/layer at s=128) that XLA fuses into
the einsum, so the in-context crossover is s>=512.  A native-layout
variant (BlockSpec carving [1, s, kh, hd] tiles straight out of the
encoder layout, no transposes) was tried and hits a Mosaic INTERNAL
error on batched dot_general with a middle batch axis; revisit when the
toolchain moves.

On non-TPU backends the kernel runs in interpret mode (same code path,
same numerics) so the CPU test mesh exercises it; parity with the einsum
reference is asserted in tests/test_models.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x names it TPUCompilerParams; newer jax renamed it
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

# One (s, s) f32 score tile + 3 (s, hd) operand tiles must fit VMEM.
MAX_FUSED_SEQ = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _attn_kernel_tiled(
    q_ref, k_ref, v_ref, bias_ref, out_ref, *, scale: float
):
    # q/k/v blocks: [k, s, hd] (k flat (batch, head) tiles); bias block:
    # [k, 1, s] (pre-expanded per head, so a step may straddle batch
    # elements).  Matmul inputs stay in the storage dtype (bf16 feeds the
    # MXU natively with f32 accumulation); softmax is f32 — same numerics
    # as the einsum path.
    q = q_ref[:]  # [k, s, hd]
    k = k_ref[:]
    v = v_ref[:]
    logits = (
        jax.lax.dot_general(
            q,
            k,
            # batch over heads, contract over hd
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [k, s, s] f32
    logits = logits + bias_ref[:, 0, :][:, None, :]  # key-side padding bias
    mx = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - mx)
    probs = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(v.dtype)
    ctx = jax.lax.dot_general(
        probs,
        v,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [k, s, hd] f32
    out_ref[:] = ctx.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "heads_per_step"))
def fused_attention_tiled(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,
    scale: float,
    heads_per_step: int = 8,
) -> jax.Array:
    """q/k/v[b, s, nh, hd], bias[b, s] additive key padding -> ctx[b, s, nh, hd].

    Softmax(QK^T * scale + bias) V fused over ``heads_per_step`` flat
    (batch, head) tiles per grid step, amortizing per-step grid/DMA
    overhead (the r3 kernel's 1-head steps were overhead-bound at s=128,
    0.56 vs 0.08 ms isolated).  ``heads_per_step`` may be any divisor of
    b*nh within the VMEM budget; ``best_heads_per_step`` picks one.
    """
    b, s, nh, hd = q.shape
    kk = heads_per_step
    if (b * nh) % kk:
        raise ValueError(f"heads_per_step={kk} must divide b*nh={b * nh}")
    grid = (b * nh // kk,)

    def to_heads(t):
        return t.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)

    flat_bias = jnp.broadcast_to(bias[:, None, :], (b, nh, s)).reshape(
        b * nh, 1, s
    )
    qkv_spec = pl.BlockSpec(
        (kk, s, hd), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    bias_spec = pl.BlockSpec(
        (kk, 1, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(_attn_kernel_tiled, scale=scale),
        grid=grid,
        in_specs=[qkv_spec, qkv_spec, qkv_spec, bias_spec],
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((b * nh, s, hd), q.dtype),
        # independent grid steps: lets Mosaic double-buffer the block DMAs
        compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(to_heads(q), to_heads(k), to_heads(v), flat_bias)
    return out.reshape(b, nh, s, hd).transpose(0, 2, 1, 3)


def _attn_kernel_tiled_seg(
    q_ref, k_ref, v_ref, seg_ref, out_ref, *, scale: float
):
    # Segment-masked variant for the packed (continuous-batching) layout:
    # instead of a per-key additive padding bias, the block carries the
    # int32 segment-ids row [k, 1, s] and the mask is computed IN VMEM —
    # query i attends key j iff seg[i] == seg[j] and seg[j] > 0 (0 marks
    # pad slots).  Building the [s, s] mask here costs one compare per
    # logit and keeps the HBM traffic identical to the padded kernel
    # (a pre-materialized [b*nh, s, s] bias would triple it at s=512).
    q = q_ref[:]  # [k, s, hd]
    k = k_ref[:]
    v = v_ref[:]
    logits = (
        jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [k, s, s] f32
    seg = seg_ref[:, 0, :]  # [k, s] int32
    same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, None, :] > 0)
    logits = jnp.where(same, logits, -1e9)
    # pad-slot query rows are fully masked: every logit is the same -1e9,
    # so the softmax is uniform (never 0/0) and the garbage rows are
    # dropped by segment pooling downstream
    mx = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - mx)
    probs = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(v.dtype)
    ctx = jax.lax.dot_general(
        probs,
        v,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )  # [k, s, hd] f32
    out_ref[:] = ctx.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "heads_per_step"))
def fused_attention_tiled_seg(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array,
    scale: float,
    heads_per_step: int = 8,
) -> jax.Array:
    """q/k/v[b, s, nh, hd], segment_ids[b, s] int32 (0 = pad slot) ->
    ctx[b, s, nh, hd] with attention confined to same-segment tokens.

    The packed-serving twin of ``fused_attention_tiled``: same grid/tile
    layout and numerics, but the key-side padding bias is replaced by an
    in-kernel segment equality mask so one dense row can carry many
    independent sequences (serve/packing.py builds the layout).  VMEM
    cost matches the padded kernel (the int32 seg row replaces the f32
    bias row), so ``best_heads_per_step`` applies unchanged.
    """
    b, s, nh, hd = q.shape
    kk = heads_per_step
    if (b * nh) % kk:
        raise ValueError(f"heads_per_step={kk} must divide b*nh={b * nh}")
    grid = (b * nh // kk,)

    def to_heads(t):
        return t.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)

    flat_seg = jnp.broadcast_to(
        segment_ids.astype(jnp.int32)[:, None, :], (b, nh, s)
    ).reshape(b * nh, 1, s)
    qkv_spec = pl.BlockSpec(
        (kk, s, hd), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    seg_spec = pl.BlockSpec(
        (kk, 1, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(_attn_kernel_tiled_seg, scale=scale),
        grid=grid,
        in_specs=[qkv_spec, qkv_spec, qkv_spec, seg_spec],
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((b * nh, s, hd), q.dtype),
        compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(to_heads(q), to_heads(k), to_heads(v), flat_seg)
    return out.reshape(b, nh, s, hd).transpose(0, 2, 1, 3)


def best_heads_per_step(
    b: int,
    s: int,
    nh: int,
    hd: int,
    itemsize: int = 2,
    score_itemsize: int = 4,
    bias_itemsize: int = 4,
) -> int:
    """Largest power-of-two divisor of b*nh whose block set fits VMEM,
    or 0 if not even a 1-tile step fits (callers fall back to einsum).

    Per step the kernel holds 4 [k, s, hd] operand/output blocks in the
    storage dtype (``itemsize`` bytes/element, x2 for double-buffering),
    the [k, s, s] score/prob tiles (``score_itemsize``, f32 today), and
    the bias row (``bias_itemsize``; the packed variant's int32 segment
    row has the same width).  The per-dtype byte widths are parameters
    — not baked-in 4s — so a narrower score accumulator or bias layout
    reuses this one fit model, mirroring ``w8a8_shape_fits``'s
    ``w_bytes``.  11 MB of the ~16 MB VMEM admits the measured-best
    tiles (bf16: kk=32 @ s=128: 8.4 MB; kk=4 @ s=512: 10.5 MB) and
    rejects the ones Mosaic refuses or that regress from double-buffer
    pressure (kk=64 @ s=128: 16.8 MB).
    """
    budget = 11 * 1024 * 1024
    best = 0
    kk = 1
    while kk <= b * nh:
        if (b * nh) % kk == 0:
            need = kk * (
                8 * s * hd * itemsize
                + 2 * s * s * score_itemsize
                + s * bias_itemsize
            )
            if need <= budget:
                best = kk
        kk *= 2
    return best


def attention_fits(s: int, hd: int) -> bool:
    """Coarse shape gate for the fused kernel; the binding per-dtype fit
    decision is ``best_heads_per_step(...) > 0``."""
    return s <= MAX_FUSED_SEQ and hd <= 256
