"""Fused multi-head attention Pallas kernel for the encoder hot path.

The einsum attention in ``models/bert.py`` materializes the [b, nh, s, s]
logits and probs tensors in HBM between XLA ops.  For encoder sequence
lengths (<=512) one (batch, head) tile — q/k/v [s, hd] plus the [s, s]
score matrix — fits comfortably in VMEM, so the whole
QK^T -> bias -> softmax -> PV chain runs as ONE kernel with f32
accumulation on the MXU and no HBM round-trips for the intermediates
(SURVEY §3.5; VERDICT r1 item 2).

Layout: grid (b, nh); block = one head of one sequence.  The additive
padding bias [b, s] (0 for real tokens, -1e9 for padding) is shared across
heads and rows, matching ``bert.encode``'s mask construction.

On non-TPU backends the kernel runs in interpret mode (same code path,
same numerics) so the CPU test mesh exercises it; parity with the einsum
reference is asserted in tests/test_models.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# One (s, s) f32 score tile + 3 (s, hd) operand tiles must fit VMEM.
MAX_FUSED_SEQ = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _attn_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, *, scale: float):
    # q/k/v blocks: [1, s, hd] (one (batch, head) tile); bias block: [1, 1, s]
    q = q_ref[0].astype(jnp.float32)  # [s, hd]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    logits = (
        jax.lax.dot_general(
            q,
            k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [s, s]
    logits = logits + bias_ref[0, 0, :][None, :]  # key-side padding bias
    mx = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - mx)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    ctx = jnp.dot(probs, v, preferred_element_type=jnp.float32)  # [s, hd]
    out_ref[0] = ctx.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale",))
def fused_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: jax.Array,
    scale: float,
) -> jax.Array:
    """q/k/v[b, s, nh, hd], bias[b, s] additive key padding -> ctx[b, s, nh, hd].

    Softmax(QK^T * scale + bias) V fused per (batch, head) tile in VMEM.
    Operands are laid out [b*nh, s, hd] so each grid step's block keeps the
    (s, hd) tile dimensions equal to the array's (Mosaic block constraint);
    XLA fuses the surrounding transposes into the projection matmuls.
    """
    b, s, nh, hd = q.shape
    grid = (b * nh,)

    def to_heads(t):
        return t.transpose(0, 2, 1, 3).reshape(b * nh, s, hd)

    qkv_spec = pl.BlockSpec(
        (1, s, hd), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    bias_spec = pl.BlockSpec(
        (1, 1, s), lambda i: (i // nh, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale),
        grid=grid,
        in_specs=[qkv_spec, qkv_spec, qkv_spec, bias_spec],
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((b * nh, s, hd), q.dtype),
        interpret=_interpret(),
    )(to_heads(q), to_heads(k), to_heads(v), bias[:, None, :])
    return out.reshape(b, nh, s, hd).transpose(0, 2, 1, 3)


def attention_fits(s: int, hd: int) -> bool:
    """Whether one (batch, head) tile fits the kernel's VMEM budget."""
    return s <= MAX_FUSED_SEQ and hd <= 256
