"""Weighted consensus tally on device.

Device twin of the exact-Decimal host tally (reference score
client.rs:384-456, SURVEY §3.5 hot loop #3):

    choice_weight[n] = sum_m votes[m, n] * weights[m]
    confidence[n]    = choice_weight[n] / sum(choice_weight)   (0 if sum==0)
    judge_confidence[m] = sum_n votes[m, n] * confidence[n]

Shapes are static; failed judges are represented by a zero ``vote_mask``
row (SURVEY §5: a failed shard masks a mesh slot — vote row zeroed, weight
renormalized — instead of aborting the batch).  All functions accept a
leading batch dimension via vmap and are safe under pjit/shard_map: the
reductions are plain sums XLA turns into psums over sharded axes.

Tolerance contract vs the Decimal host path: f32 accumulation, votes sum to
1 +- 1e-6 (tests/test_ops.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def tally(votes: jax.Array, weights: jax.Array, vote_mask=None):
    """votes[M, N] (rows sum to 1 or are zero), weights[M] ->
    (choice_weight[N], confidence[N]).

    ``vote_mask[M]`` zeroes failed judges (1.0 = counted).
    """
    votes = votes.astype(jnp.float32)
    weights = weights.astype(jnp.float32)
    if vote_mask is not None:
        weights = weights * vote_mask.astype(jnp.float32)
    # MXU-friendly: a single [1,M]x[M,N] contraction
    choice_weight = jnp.einsum(
        "m,mn->n", weights, votes, preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )
    total = jnp.sum(choice_weight)
    confidence = jnp.where(total > 0, choice_weight / total, 0.0)
    return choice_weight, confidence


@jax.jit
def judge_confidence(votes: jax.Array, confidence: jax.Array) -> jax.Array:
    """Per-judge confidence: how much the consensus agrees with each judge
    (client.rs:438-449): votes[M, N] x confidence[N] -> [M]."""
    return jnp.einsum(
        "mn,n->m",
        votes.astype(jnp.float32),
        confidence.astype(jnp.float32),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )


# Batched forms for archive re-scoring (BASELINE config 4): one pjit/vmap
# over a [B, M, N] vote tensor re-scores B archived requests at once.
_tally_batch = jax.jit(jax.vmap(tally, in_axes=(0, 0, 0)))
judge_confidence_batch = jax.jit(jax.vmap(judge_confidence))


def tally_batch(votes: jax.Array, weights: jax.Array, vote_mask=None):
    """Batched tally; ``vote_mask`` optional like :func:`tally`."""
    if vote_mask is None:
        vote_mask = jnp.ones(weights.shape, dtype=jnp.float32)
    return _tally_batch(votes, weights, vote_mask)


@jax.jit
def incremental_tally(
    running_weight: jax.Array,
    new_vote: jax.Array,
    new_weight: jax.Array,
):
    """Streaming update: fold one completed judge vote into the running
    tally (BASELINE config 5 — incremental on-device consensus).

    Recomputes confidence after each completed vote without re-reducing the
    full vote matrix: O(N) per update.
    """
    running_weight = running_weight + new_vote.astype(jnp.float32) * new_weight
    total = jnp.sum(running_weight)
    confidence = jnp.where(total > 0, running_weight / total, 0.0)
    return running_weight, confidence


def all_failed(vote_mask: jax.Array) -> jax.Array:
    """AllVotesFailed predicate on device: no judge produced a vote."""
    return jnp.sum(vote_mask.astype(jnp.float32)) == 0
