"""Fused Pallas TPU kernels for the consensus hot path.

Two fusions that matter for serving latency (keeping intermediates in VMEM
instead of round-tripping HBM between XLA ops):

* ``fused_consensus``    — weights x votes matmul + normalize in one pass;
* ``fused_cosine_vote``  — l2-normalize + pairwise cosine + mean-off-diag +
  masked softmax in one pass (the whole self-consistency scorer).

On non-TPU backends the kernels run in interpret mode (same code path, same
results) so the CPU test mesh exercises them; beyond the single-block VMEM
budget the jnp compositions in ``consensus``/``similarity`` are the
fallback.  Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM is ~16 MB/core; cap single-block shapes well under it
MAX_FUSED_CHOICES = 1024
MAX_FUSED_DIM = 2048


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# Fused tally + normalize
# ---------------------------------------------------------------------------


def _consensus_kernel(weights_ref, votes_ref, out_ref):
    # [1, M] x [M, N] on the MXU, then VPU normalize — one VMEM residency
    cw = jnp.dot(
        weights_ref[:], votes_ref[:], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST
    )  # [1, N]
    total = jnp.sum(cw)
    out_ref[:] = jnp.where(total > 0, cw / total, 0.0)


@jax.jit
def fused_consensus(votes: jax.Array, weights: jax.Array) -> jax.Array:
    """votes[M, N], weights[M] -> confidence[N] in a single fused kernel.

    Padding rows/cols are zero so they contribute nothing to the tally.
    Beyond the single-block VMEM budget the jnp composition takes over.
    """
    m, n = votes.shape
    # same single-block VMEM budget as fused_cosine_vote (~8 MB f32)
    if m > MAX_FUSED_CHOICES or n > MAX_FUSED_DIM:
        from .consensus import tally

        _, confidence = tally(votes, weights)
        return confidence
    votes_p = _pad_to(_pad_to(votes.astype(jnp.float32), 0, 8), 1, 128)
    weights_p = _pad_to(weights.astype(jnp.float32)[None, :], 1, 8)
    mp, np_ = votes_p.shape
    out = pl.pallas_call(
        _consensus_kernel,
        out_shape=jax.ShapeDtypeStruct((1, np_), jnp.float32),
        interpret=_interpret(),
    )(weights_p, votes_p)
    return out[0, :n]


# ---------------------------------------------------------------------------
# Fused cosine self-consistency vote
# ---------------------------------------------------------------------------


def _cosine_vote_kernel(x_ref, out_ref, *, n_valid: int, temperature: float):
    x = x_ref[:].astype(jnp.float32)  # [Np, Dp], padding rows are zero
    norm = jax.lax.rsqrt(
        jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12
    )
    nx = x * norm
    sims = jnp.dot(nx, nx.T, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # [Np, Np]
    np_ = sims.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 1)
    valid = (col < n_valid) & (row != col)
    mean_sim = jnp.sum(jnp.where(valid, sims, 0.0), axis=-1) / max(
        n_valid - 1, 1
    )  # [Np]
    logits = mean_sim / temperature
    row_valid = jax.lax.iota(jnp.int32, np_) < n_valid
    logits = jnp.where(row_valid, logits, -jnp.inf)
    # masked softmax over the valid candidates
    mx = jnp.max(logits)
    e = jnp.where(row_valid, jnp.exp(logits - mx), 0.0)
    out_ref[:] = (e / jnp.sum(e))[None, :]


@functools.partial(jax.jit, static_argnames=("temperature",))
def fused_cosine_vote(
    embeddings: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """embeddings[N, D] -> confidence[N]: the whole self-consistency scorer
    (normalize + cosine + mean + softmax) fused into one kernel."""
    n, d = embeddings.shape
    if n > MAX_FUSED_CHOICES or d > MAX_FUSED_DIM:
        from .similarity import cosine_consensus_vote

        return cosine_consensus_vote(embeddings, temperature=temperature)
    x = _pad_to(_pad_to(embeddings.astype(jnp.float32), 0, 8), 1, 128)
    np_ = x.shape[0]
    out = pl.pallas_call(
        functools.partial(
            _cosine_vote_kernel, n_valid=n, temperature=temperature
        ),
        out_shape=jax.ShapeDtypeStruct((1, np_), jnp.float32),
        interpret=_interpret(),
    )(x)
    return out[0, :n]
