"""Fused Pallas TPU kernels for the consensus hot path.

The fusion that matters for serving latency (keeping intermediates in
VMEM instead of round-tripping HBM between XLA ops):

* ``fused_cosine_vote``  — l2-normalize + pairwise cosine + mean-off-diag +
  masked softmax in one pass (the whole self-consistency scorer); the
  serving hot path's scorer (models/embedder.py, clients/multichat.py).
* ``w8a8_matmul``        — fused W8A8 quantized dense: per-row dynamic
  activation int8 quant + int8 x int8 -> int32 MXU matmul + dequant/bias
  (+ optional GELU) epilogue in one kernel, so neither the quantized
  activations nor the int32 accumulator nor a dequantized bf16 weight
  copy ever materializes in HBM (models/quant.py's fast path).

(A fused tally kernel existed but was removed: the live tally is host
Decimal by product contract and batched re-scoring uses
``consensus.tally_batch`` — a device twin with no caller is dead weight.)

On non-TPU backends the kernels run in interpret mode (same code path, same
results) so the CPU test mesh exercises them; beyond the single-block VMEM
budget the jnp compositions in ``consensus``/``similarity`` are the
fallback.  Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM is ~16 MB/core; cap single-block shapes well under it
MAX_FUSED_CHOICES = 1024
MAX_FUSED_DIM = 2048


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# Fused cosine self-consistency vote
# ---------------------------------------------------------------------------


def _cosine_vote_kernel(x_ref, out_ref, *, n_valid: int, temperature: float):
    x = x_ref[:].astype(jnp.float32)  # [Np, Dp], padding rows are zero
    norm = jax.lax.rsqrt(
        jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12
    )
    nx = x * norm
    sims = jnp.dot(nx, nx.T, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # [Np, Np]
    np_ = sims.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 1)
    valid = (col < n_valid) & (row != col)
    mean_sim = jnp.sum(jnp.where(valid, sims, 0.0), axis=-1) / max(
        n_valid - 1, 1
    )  # [Np]
    logits = mean_sim / temperature
    row_valid = jax.lax.iota(jnp.int32, np_) < n_valid
    logits = jnp.where(row_valid, logits, -jnp.inf)
    # masked softmax over the valid candidates
    mx = jnp.max(logits)
    e = jnp.where(row_valid, jnp.exp(logits - mx), 0.0)
    out_ref[:] = (e / jnp.sum(e))[None, :]


@functools.partial(jax.jit, static_argnames=("temperature",))
def fused_cosine_vote(
    embeddings: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """embeddings[N, D] -> confidence[N]: the whole self-consistency scorer
    (normalize + cosine + mean + softmax) fused into one kernel."""
    n, d = embeddings.shape
    if n > MAX_FUSED_CHOICES or d > MAX_FUSED_DIM:
        from .similarity import cosine_consensus_vote

        return cosine_consensus_vote(embeddings, temperature=temperature)
    x = _pad_to(_pad_to(embeddings.astype(jnp.float32), 0, 8), 1, 128)
    np_ = x.shape[0]
    out = pl.pallas_call(
        functools.partial(
            _cosine_vote_kernel, n_valid=n, temperature=temperature
        ),
        out_shape=jax.ShapeDtypeStruct((1, np_), jnp.float32),
        interpret=_interpret(),
    )(x)
    return out[0, :n]


# ---------------------------------------------------------------------------
# Fused W8A8 quantized matmul (the int8 serving path's dense op)
# ---------------------------------------------------------------------------

# Grid over M row tiles with the whole [K, N] int8 weight block resident
# in VMEM: the weight BlockSpec's index map is constant, so the Mosaic
# pipeline DMAs it once and revisits it across grid steps — activations
# stream through while the weights stay put (the opposite split would
# re-fetch the big operand per tile).
W8A8_TILE_M = 128
# weight block + double-buffered x/out tiles must fit comfortably under
# the ~16 MB/core VMEM; beyond this the caller's dot_general fallback
# (models/quant.py) takes over
_W8A8_VMEM_BUDGET = 12 * 1024 * 1024


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def w8a8_shape_fits(m: int, k: int, n: int, x_bytes: int) -> bool:
    """Whether the single-weight-block tiling fits the VMEM budget.

    Every preset's encoder matmul fits (bge-large mlp_in, the largest:
    1 MB x-tile + 4 MB int8 weights + 4 MB f32 out-tile, double-buffered
    tiles well under 12 MB); the gate exists for hypothetical huge
    projections, which fall back to the XLA int8 dot_general."""
    kp = _round_up(k, 128)
    np_ = _round_up(n, 128)
    tm = min(W8A8_TILE_M, _round_up(m, 8))
    weight = kp * np_  # int8: 1 byte
    tiles = 2 * tm * kp * x_bytes + 2 * tm * np_ * x_bytes  # double-buffered
    scale_bias = 2 * np_ * 4
    return weight + tiles + scale_bias <= _W8A8_VMEM_BUDGET


def _w8a8_kernel(x_ref, wq_ref, sw_ref, b_ref, o_ref, *, gelu, approx_gelu):
    # lazy: ops must not import models at module load (models/__init__
    # imports embedder, which imports this module)
    from ..models.layers import gelu_f32

    x = x_ref[:].astype(jnp.float32)  # [TM, Kp]; pad rows/cols are zero
    # per-row dynamic activation quant, fused: the int8 activations never
    # leave VMEM (the whole point — a separate XLA quant pass would write
    # xq + scale to HBM and read them back)
    sx = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0  # [TM, 1]
    sx = jnp.maximum(sx, 1e-12)
    xq = jnp.clip(jnp.round(x / sx), -127.0, 127.0).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq,
        wq_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [TM, Np] int32 on the MXU, exact
    # epilogue: rank-1 dequant (sx x sw) + bias (+ GELU), all in f32 in
    # registers, single cast on the way out
    out = acc.astype(jnp.float32) * sx * sw_ref[:] + b_ref[:]
    if gelu:
        out = gelu_f32(out, approx=approx_gelu)
    o_ref[:] = out.astype(o_ref.dtype)


def w8a8_matmul(
    x: jax.Array,
    wq: jax.Array,
    sw: jax.Array,
    bias: jax.Array,
    *,
    gelu: bool = False,
    interpret=None,
) -> jax.Array:
    """Fused W8A8 dense: ``x[..., K] @ wq[K, N] -> [..., N]`` in x.dtype.

    ``wq`` is the per-output-channel int8 kernel and ``sw`` its f32 scale
    (models/quant.py:quantize_weight); activations are quantized per row
    INSIDE the kernel.  ``gelu=True`` folds the exact-profile GELU into
    the epilogue (erf for f32 activations, the A&S 7.1.26 form for bf16 —
    the same dtype split as layers.gelu_erf, so the fused MLP matches the
    unfused composition's numerics).  Non-TPU backends run in interpret
    mode; ``interpret`` overrides for tests."""
    k = x.shape[-1]
    n = wq.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    tm = min(W8A8_TILE_M, _round_up(m, 8))
    xp = _pad_to(_pad_to(x2, 0, tm), 1, 128)
    wqp = _pad_to(_pad_to(wq, 0, 128), 1, 128)
    swp = _pad_to(sw.astype(jnp.float32).reshape(1, n), 1, 128)
    bp = _pad_to(bias.astype(jnp.float32).reshape(1, n), 1, 128)
    mp, kp = xp.shape
    np_ = wqp.shape[1]
    out = pl.pallas_call(
        functools.partial(
            _w8a8_kernel,
            gelu=gelu,
            approx_gelu=x.dtype == jnp.bfloat16,
        ),
        grid=(mp // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0)),
            pl.BlockSpec((kp, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tm, np_), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(xp, wqp, swp, bp)
    return out[:m, :n].reshape(*x.shape[:-1], n)
