"""Fused Pallas TPU kernels for the consensus hot path.

The fusion that matters for serving latency (keeping intermediates in
VMEM instead of round-tripping HBM between XLA ops):

* ``fused_cosine_vote``  — l2-normalize + pairwise cosine + mean-off-diag +
  masked softmax in one pass (the whole self-consistency scorer); the
  serving hot path's scorer (models/embedder.py, clients/multichat.py).
* ``w8a8_matmul``        — fused W8A8 quantized dense: per-row dynamic
  activation int8 quant + int8 x int8 -> int32 MXU matmul + dequant/bias
  (+ optional GELU) epilogue in one kernel, so neither the quantized
  activations nor the int32 accumulator nor a dequantized bf16 weight
  copy ever materializes in HBM (models/quant.py's fast path).

(A fused tally kernel existed but was removed: the live tally is host
Decimal by product contract and batched re-scoring uses
``consensus.tally_batch`` — a device twin with no caller is dead weight.)

On non-TPU backends the kernels run in interpret mode (same code path, same
results) so the CPU test mesh exercises them; beyond the single-block VMEM
budget the jnp compositions in ``consensus``/``similarity`` are the
fallback.  Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM is ~16 MB/core; cap single-block shapes well under it
MAX_FUSED_CHOICES = 1024
MAX_FUSED_DIM = 2048


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# Fused cosine self-consistency vote
# ---------------------------------------------------------------------------


def _cosine_vote_kernel(x_ref, out_ref, *, n_valid: int, temperature: float):
    x = x_ref[:].astype(jnp.float32)  # [Np, Dp], padding rows are zero
    norm = jax.lax.rsqrt(
        jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12
    )
    nx = x * norm
    sims = jnp.dot(nx, nx.T, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # [Np, Np]
    np_ = sims.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 1)
    valid = (col < n_valid) & (row != col)
    mean_sim = jnp.sum(jnp.where(valid, sims, 0.0), axis=-1) / max(
        n_valid - 1, 1
    )  # [Np]
    logits = mean_sim / temperature
    row_valid = jax.lax.iota(jnp.int32, np_) < n_valid
    logits = jnp.where(row_valid, logits, -jnp.inf)
    # masked softmax over the valid candidates
    mx = jnp.max(logits)
    e = jnp.where(row_valid, jnp.exp(logits - mx), 0.0)
    out_ref[:] = (e / jnp.sum(e))[None, :]


@functools.partial(jax.jit, static_argnames=("temperature",))
def fused_cosine_vote(
    embeddings: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """embeddings[N, D] -> confidence[N]: the whole self-consistency scorer
    (normalize + cosine + mean + softmax) fused into one kernel."""
    n, d = embeddings.shape
    if n > MAX_FUSED_CHOICES or d > MAX_FUSED_DIM:
        from .similarity import cosine_consensus_vote

        return cosine_consensus_vote(embeddings, temperature=temperature)
    x = _pad_to(_pad_to(embeddings.astype(jnp.float32), 0, 8), 1, 128)
    np_ = x.shape[0]
    out = pl.pallas_call(
        functools.partial(
            _cosine_vote_kernel, n_valid=n, temperature=temperature
        ),
        out_shape=jax.ShapeDtypeStruct((1, np_), jnp.float32),
        interpret=_interpret(),
    )(x)
    return out[0, :n]


# ---------------------------------------------------------------------------
# Fused W8A8 quantized matmul (the int8 serving path's dense op)
# ---------------------------------------------------------------------------

# Grid over M row tiles with the whole [K, N] int8 weight block resident
# in VMEM: the weight BlockSpec's index map is constant, so the Mosaic
# pipeline DMAs it once and revisits it across grid steps — activations
# stream through while the weights stay put (the opposite split would
# re-fetch the big operand per tile).
W8A8_TILE_M = 128
# weight block + double-buffered x/out tiles must fit comfortably under
# the ~16 MB/core VMEM; beyond this the caller's dot_general fallback
# (models/quant.py) takes over
_W8A8_VMEM_BUDGET = 12 * 1024 * 1024


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def w8a8_shape_fits(
    m: int, k: int, n: int, x_bytes: int, w_bytes: float = 1.0
) -> bool:
    """Whether the single-weight-block tiling fits the VMEM budget.

    Every preset's encoder matmul fits (bge-large mlp_in, the largest:
    1 MB x-tile + 4 MB int8 weights + 4 MB f32 out-tile, double-buffered
    tiles well under 12 MB); the gate exists for hypothetical huge
    projections, which fall back to the XLA int8 dot_general.

    ``w_bytes`` is the resident weight block's bytes per element — 1 for
    the int8 kernel, 0.5 for the packed-int4 kernel (two weights per
    uint8 byte) — so both quantized paths share this one gate instead of
    diverging copies."""
    kp = _round_up(k, 128)
    np_ = _round_up(n, 128)
    tm = min(W8A8_TILE_M, _round_up(m, 8))
    weight = int(kp * np_ * w_bytes)  # int8: 1 byte; packed int4: 1/2
    tiles = 2 * tm * kp * x_bytes + 2 * tm * np_ * x_bytes  # double-buffered
    scale_bias = 2 * np_ * 4
    return weight + tiles + scale_bias <= _W8A8_VMEM_BUDGET


def _w8a8_kernel(x_ref, wq_ref, sw_ref, b_ref, o_ref, *, gelu, approx_gelu):
    # lazy: ops must not import models at module load (models/__init__
    # imports embedder, which imports this module)
    from ..models.layers import gelu_f32

    x = x_ref[:].astype(jnp.float32)  # [TM, Kp]; pad rows/cols are zero
    # per-row dynamic activation quant, fused: the int8 activations never
    # leave VMEM (the whole point — a separate XLA quant pass would write
    # xq + scale to HBM and read them back)
    sx = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0  # [TM, 1]
    sx = jnp.maximum(sx, 1e-12)
    xq = jnp.clip(jnp.round(x / sx), -127.0, 127.0).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq,
        wq_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [TM, Np] int32 on the MXU, exact
    # epilogue: rank-1 dequant (sx x sw) + bias (+ GELU), all in f32 in
    # registers, single cast on the way out
    out = acc.astype(jnp.float32) * sx * sw_ref[:] + b_ref[:]
    if gelu:
        out = gelu_f32(out, approx=approx_gelu)
    o_ref[:] = out.astype(o_ref.dtype)


def w8a8_matmul(
    x: jax.Array,
    wq: jax.Array,
    sw: jax.Array,
    bias: jax.Array,
    *,
    gelu: bool = False,
    interpret=None,
) -> jax.Array:
    """Fused W8A8 dense: ``x[..., K] @ wq[K, N] -> [..., N]`` in x.dtype.

    ``wq`` is the per-output-channel int8 kernel and ``sw`` its f32 scale
    (models/quant.py:quantize_weight); activations are quantized per row
    INSIDE the kernel.  ``gelu=True`` folds the exact-profile GELU into
    the epilogue (erf for f32 activations, the A&S 7.1.26 form for bf16 —
    the same dtype split as layers.gelu_erf, so the fused MLP matches the
    unfused composition's numerics).  Non-TPU backends run in interpret
    mode; ``interpret`` overrides for tests."""
    k = x.shape[-1]
    n = wq.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    tm = min(W8A8_TILE_M, _round_up(m, 8))
    xp = _pad_to(_pad_to(x2, 0, tm), 1, 128)
    wqp = _pad_to(_pad_to(wq, 0, 128), 1, 128)
    swp = _pad_to(sw.astype(jnp.float32).reshape(1, n), 1, 128)
    bp = _pad_to(bias.astype(jnp.float32).reshape(1, n), 1, 128)
    mp, kp = xp.shape
    np_ = wqp.shape[1]
    out = pl.pallas_call(
        functools.partial(
            _w8a8_kernel,
            gelu=gelu,
            approx_gelu=x.dtype == jnp.bfloat16,
        ),
        grid=(mp // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0)),
            pl.BlockSpec((kp, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tm, np_), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(xp, wqp, swp, bp)
    return out[:m, :n].reshape(*x.shape[:-1], n)


# ---------------------------------------------------------------------------
# Fused W4A8 quantized matmul (packed int4 weights, the long-context path)
# ---------------------------------------------------------------------------

# Packed layout contract (shared with models/quant.py): an int4 kernel
# [K, N] is stored as ONE uint8 array [Kp/2, N] where Kp = K rounded up
# to a multiple of 2*128 (so each nibble half stays lane-aligned).  The
# LOW nibble of row kk holds weight row kk; the HIGH nibble holds weight
# row kk + Kp/2 (split-K halves, not interleaved pairs — the kernel then
# needs no gather/concat, just two lane-aligned dot_generals).  Nibbles
# are stored biased by +8: quantized values live in [-7, 7], stored as
# [1, 15], and the zero-pad nibble is 8 (unbiases to exactly 0, so K
# padding contributes nothing to the accumulator).
W4A8_PACK_K = 256


def pack_int4_weights(wq: jax.Array) -> jax.Array:
    """Pack a per-channel int4-quantized kernel ``wq[..., K, N]`` (int8
    values in [-7, 7]) into the biased-nibble uint8 layout above along
    the K axis (leading dims — e.g. a stacked per-layer kernel — ride
    through untouched)."""
    k = wq.shape[-2]
    kp = _round_up(k, W4A8_PACK_K)
    half = kp // 2
    biased = (wq.astype(jnp.int32) + 8).astype(jnp.uint8)
    pad = [(0, 0)] * wq.ndim
    pad[-2] = (0, kp - k)
    biased = jnp.pad(biased, pad, constant_values=8)
    lo = biased[..., :half, :]
    hi = biased[..., half:, :]
    return (lo | (hi << 4)).astype(jnp.uint8)


def _w4a8_kernel(x_ref, wq_ref, sw_ref, b_ref, o_ref, *, gelu, approx_gelu):
    # lazy: ops must not import models at module load (models/__init__
    # imports embedder, which imports this module)
    from ..models.layers import gelu_f32

    x = x_ref[:].astype(jnp.float32)  # [TM, Kp]; pad rows/cols are zero
    # the SAME per-row dynamic activation quant as the W8A8 kernel — the
    # two paths differ only in how the weight block decodes
    sx = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0  # [TM, 1]
    sx = jnp.maximum(sx, 1e-12)
    xq = jnp.clip(jnp.round(x / sx), -127.0, 127.0).astype(jnp.int8)
    # unpack via int32 (Mosaic's comfortable width for bit ops), then
    # narrow to int8 for the MXU
    packed = wq_ref[:].astype(jnp.int32)  # [Kp/2, Np], biased nibbles
    lo = ((packed & 0xF) - 8).astype(jnp.int8)  # weight rows [0, Kp/2)
    hi = ((packed >> 4) - 8).astype(jnp.int8)  # weight rows [Kp/2, Kp)
    half = packed.shape[0]
    acc = jax.lax.dot_general(
        xq[:, :half],
        lo,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) + jax.lax.dot_general(
        xq[:, half:],
        hi,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [TM, Np] int32 on the MXU, exact
    # identical epilogue to _w8a8_kernel: rank-1 dequant + bias (+ GELU)
    out = acc.astype(jnp.float32) * sx * sw_ref[:] + b_ref[:]
    if gelu:
        out = gelu_f32(out, approx=approx_gelu)
    o_ref[:] = out.astype(o_ref.dtype)


def w4a8_matmul(
    x: jax.Array,
    wq4: jax.Array,
    sw: jax.Array,
    bias: jax.Array,
    *,
    gelu: bool = False,
    interpret=None,
) -> jax.Array:
    """Fused W4A8 dense: ``x[..., K] @ unpack(wq4)[K, N] -> [..., N]``.

    ``wq4`` is the packed biased-nibble uint8 kernel from
    ``pack_int4_weights`` and ``sw`` its per-output-channel f32 scale
    (max|W|/7); activations are quantized to int8 per row INSIDE the
    kernel.  Reuses the W8A8 grid and epilogue; the weight block is half
    the VMEM of int8, which is what buys long-context activations room
    next to the weights.  Non-TPU backends run in interpret mode."""
    k = x.shape[-1]
    n = wq4.shape[-1]
    kp = 2 * wq4.shape[0]  # pack-time K padding (multiple of 256)
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    tm = min(W8A8_TILE_M, _round_up(m, 8))
    xp = jnp.pad(_pad_to(x2, 0, tm), ((0, 0), (0, kp - k)))
    wq4p = _pad_to(wq4, 1, 128)
    swp = _pad_to(sw.astype(jnp.float32).reshape(1, n), 1, 128)
    bp = _pad_to(bias.astype(jnp.float32).reshape(1, n), 1, 128)
    mp = xp.shape[0]
    np_ = wq4p.shape[1]
    out = pl.pallas_call(
        functools.partial(
            _w4a8_kernel,
            gelu=gelu,
            approx_gelu=x.dtype == jnp.bfloat16,
        ),
        grid=(mp // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0)),
            pl.BlockSpec((kp // 2, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
            pl.BlockSpec((1, np_), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tm, np_), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(xp, wq4p, swp, bp)
    return out[:m, :n].reshape(*x.shape[:-1], n)
