"""Fused Pallas TPU kernels for the consensus hot path.

The fusion that matters for serving latency (keeping intermediates in
VMEM instead of round-tripping HBM between XLA ops):

* ``fused_cosine_vote``  — l2-normalize + pairwise cosine + mean-off-diag +
  masked softmax in one pass (the whole self-consistency scorer); the
  serving hot path's scorer (models/embedder.py, clients/multichat.py).

(A fused tally kernel existed but was removed: the live tally is host
Decimal by product contract and batched re-scoring uses
``consensus.tally_batch`` — a device twin with no caller is dead weight.)

On non-TPU backends the kernels run in interpret mode (same code path, same
results) so the CPU test mesh exercises them; beyond the single-block VMEM
budget the jnp compositions in ``consensus``/``similarity`` are the
fallback.  Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM is ~16 MB/core; cap single-block shapes well under it
MAX_FUSED_CHOICES = 1024
MAX_FUSED_DIM = 2048


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_to(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    target = -(-size // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


# ---------------------------------------------------------------------------
# Fused cosine self-consistency vote
# ---------------------------------------------------------------------------


def _cosine_vote_kernel(x_ref, out_ref, *, n_valid: int, temperature: float):
    x = x_ref[:].astype(jnp.float32)  # [Np, Dp], padding rows are zero
    norm = jax.lax.rsqrt(
        jnp.sum(x * x, axis=-1, keepdims=True) + 1e-12
    )
    nx = x * norm
    sims = jnp.dot(nx, nx.T, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)  # [Np, Np]
    np_ = sims.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (np_, np_), 1)
    valid = (col < n_valid) & (row != col)
    mean_sim = jnp.sum(jnp.where(valid, sims, 0.0), axis=-1) / max(
        n_valid - 1, 1
    )  # [Np]
    logits = mean_sim / temperature
    row_valid = jax.lax.iota(jnp.int32, np_) < n_valid
    logits = jnp.where(row_valid, logits, -jnp.inf)
    # masked softmax over the valid candidates
    mx = jnp.max(logits)
    e = jnp.where(row_valid, jnp.exp(logits - mx), 0.0)
    out_ref[:] = (e / jnp.sum(e))[None, :]


@functools.partial(jax.jit, static_argnames=("temperature",))
def fused_cosine_vote(
    embeddings: jax.Array, temperature: float = 0.05
) -> jax.Array:
    """embeddings[N, D] -> confidence[N]: the whole self-consistency scorer
    (normalize + cosine + mean + softmax) fused into one kernel."""
    n, d = embeddings.shape
    if n > MAX_FUSED_CHOICES or d > MAX_FUSED_DIM:
        from .similarity import cosine_consensus_vote

        return cosine_consensus_vote(embeddings, temperature=temperature)
    x = _pad_to(_pad_to(embeddings.astype(jnp.float32), 0, 8), 1, 128)
    np_ = x.shape[0]
    out = pl.pallas_call(
        functools.partial(
            _cosine_vote_kernel, n_valid=n, temperature=temperature
        ),
        out_shape=jax.ShapeDtypeStruct((1, np_), jnp.float32),
        interpret=_interpret(),
    )(x)
    return out[0, :n]
