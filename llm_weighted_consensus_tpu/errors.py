"""Uniform error taxonomy — ``{code, message}`` JSON errors.

Parity target: reference src/error.rs (StatusError trait + ResponseError),
src/chat/completions/error.rs (chat client errors incl. OpenRouter provider
error passthrough) and src/score/completions/error.rs (consensus errors).
Every error renders as ``{"code": <http status>, "message": <json>}`` and the
message payloads carry the same ``kind`` discriminators as the reference so
clients can switch on them.
"""

from __future__ import annotations

from http import HTTPStatus
from typing import Optional

from .types.base import ResponseError  # noqa: F401  (canonical home: type core)

# Every masking site — anywhere internal detail is folded into a uniform
# client envelope with the real exception diverted to the server log —
# logs under THIS name.  One constant so an operator tailing one logger
# sees all masked detail; it matches the serving stack's configured
# logger ("lwc.serve", serve/__main__.py) because masked errors only
# arise on client-visible surfaces, which the gateway owns.
MASKING_LOGGER = "lwc.serve"


def with_trace_id(envelope: dict) -> dict:
    """Stamp the ambient request's trace id onto an error envelope (the
    ``{code, message}`` dict or a mid-stream SSE error frame) so a
    client holding a failure can hand the operator the exact trace.
    No-op (and allocation-free) when tracing is off or inactive."""
    from .obs import current_trace_id

    trace_id = current_trace_id()
    if trace_id is not None:
        envelope["trace_id"] = trace_id
    return envelope


def _status_phrase(code: int) -> str:
    try:
        return f"{code} {HTTPStatus(code).phrase}"
    except ValueError:
        return "unknown"


class StatusError(Exception):
    """Base for rich errors that know their HTTP status + JSON message."""

    def status(self) -> int:
        return 500

    def message(self):
        return None

    def to_response_error(self) -> ResponseError:
        msg = self.message()
        if msg is None:
            msg = _status_phrase(self.status())
        return ResponseError(code=self.status(), message=msg)


def to_response_error(err) -> ResponseError:
    if isinstance(err, ResponseError):
        return err
    if isinstance(err, StatusError):
        return err.to_response_error()
    # Unexpected (non-taxonomy) exception reaching a client-visible
    # surface — mid-stream SSE error frames, per-judge error choices:
    # uniform envelope only, detail to the server log (the reference's
    # envelope, src/error.rs:8-13, never echoes internals; neither do we).
    import logging

    logging.getLogger(MASKING_LOGGER).error(
        "unexpected error folded into response envelope",
        exc_info=err if isinstance(err, BaseException) else None,
    )
    return ResponseError(code=500, message="internal error")


class OverloadedError(StatusError):
    """Load shed (503): the gateway's admission gate or the device
    batcher's bounded queue refused the work.  ``shed_reason`` is the
    machine-readable discriminator clients back off on (the gateway
    middleware renders the same body shape plus a ``Retry-After``
    header — resilience/admission.py)."""

    def __init__(
        self,
        shed_reason: str,
        retry_after_ms: Optional[float] = None,
    ):
        super().__init__(f"overloaded: {shed_reason}")
        self.shed_reason = shed_reason
        self.retry_after_ms = retry_after_ms

    def status(self) -> int:
        return 503

    def message(self):
        return {"kind": "overloaded", "shed_reason": self.shed_reason}


# ---------------------------------------------------------------------------
# Chat client errors (reference src/chat/completions/error.rs)
# ---------------------------------------------------------------------------


class ChatError(StatusError):
    kind = "chat"

    def __init__(self, inner_kind: str, error, code: int = 500):
        super().__init__(f"{self.kind}/{inner_kind}: {error}")
        self.inner_kind = inner_kind
        self.error = error
        self.code = code

    def status(self) -> int:
        return self.code

    def message(self):
        return {"kind": "chat", "error": {"kind": self.inner_kind, "error": self.error}}


class TransportError(ChatError):
    def __init__(self, error: str, code: int = 500):
        super().__init__("transport", error, code)


class EmptyStreamError(ChatError):
    def __init__(self):
        super().__init__("empty_stream", "received an empty stream", 500)


class DeserializationError(ChatError):
    def __init__(self, error: str):
        super().__init__("deserialization", error, 500)


class BadStatusError(ChatError):
    def __init__(self, code: int, body):
        super().__init__("bad_status", body, code)


class StreamTimeoutError(ChatError):
    """Per-chunk stream timeout; records which tier fired and how long it
    actually waited (``tier`` is ``"first_chunk"`` or ``"other_chunk"``).
    The argless form keeps the reference's constant message."""

    def __init__(
        self,
        tier: Optional[str] = None,
        elapsed_ms: Optional[float] = None,
    ):
        if tier is None:
            message = "error fetching stream: timeout"
        elif elapsed_ms is None:
            message = f"error fetching stream: {tier} timeout"
        else:
            message = (
                f"error fetching stream: {tier} timeout after {elapsed_ms:.0f}ms"
            )
        super().__init__("stream_timeout", message, 500)
        self.tier = tier
        self.elapsed_ms = elapsed_ms


class IngestCapError(ChatError):
    """A byte-budget cap tripped while ingesting upstream bytes.

    ``what`` names the tripped budget: ``sse_buffer`` (newline-less
    residue in the SSE parser), ``sse_event`` (one event's accumulated
    ``data:`` payload), ``judge_stream`` (a judge leg's cumulative
    stream budget, JUDGE_STREAM_MAX_BYTES) or ``unary_body`` (a
    non-streaming body read).  502: the upstream is misbehaving, not
    us — and like any upstream failure the trip counts against that
    upstream's breaker (clients/chat.py ``_breaker_failure``)."""

    def __init__(self, what: str, limit_bytes: int, observed_bytes: int):
        super().__init__(
            "ingest_cap",
            (
                f"{what} exceeded {limit_bytes} bytes "
                f"(observed {observed_bytes})"
            ),
            502,
        )
        self.what = what
        self.limit_bytes = limit_bytes
        self.observed_bytes = observed_bytes


class BreakerOpenError(ChatError):
    """Attempt refused locally: the upstream's circuit breaker is open."""

    def __init__(self, api_base: str, model: str):
        super().__init__(
            "breaker_open",
            f"circuit breaker open for {api_base}|{model}",
            503,
        )
        self.api_base = api_base
        self.model = model


class DeadlineExceededError(ChatError):
    """The request's propagated deadline ran out mid-attempt."""

    def __init__(self, detail: Optional[str] = None):
        message = "request deadline exceeded"
        if detail:
            message = f"{message}: {detail}"
        super().__init__("deadline_exceeded", message, 504)


class CtxHandlerError(ChatError):
    def __init__(self, inner: ResponseError):
        super().__init__("ctx", inner.message, inner.code)

    def message(self):
        return self.error


class ArchiveFetchError(ChatError):
    def __init__(self, inner: ResponseError):
        super().__init__("completions_archive", inner.message, inner.code)

    def message(self):
        return self.error


class InvalidCompletionChoiceIndex(ChatError):
    def __init__(self, completion_id: str, choice_index: int):
        super().__init__(
            "invalid_completion_choice_index",
            f"invalid choice_index for completion {completion_id}: {choice_index}",
            400,
        )
        self.completion_id = completion_id
        self.choice_index = choice_index


class ProviderError(ChatError):
    """OpenRouter mid-stream provider error shape (error.rs:99-141)."""

    def __init__(self, code: Optional[int], message, metadata=None, user_id=None):
        self.provider_code = code
        self.provider_message = message
        self.metadata = metadata
        self.user_id = user_id
        super().__init__("provider", message, code if code is not None else 500)

    def message(self):
        return {
            "kind": "provider",
            "message": self.provider_message,
            "metadata": self.metadata,
        }


# ---------------------------------------------------------------------------
# Score / consensus errors (reference src/score/completions/error.rs)
# ---------------------------------------------------------------------------


class ScoreError(StatusError):
    def __init__(self, inner_kind: str, error, code: int = 500):
        super().__init__(f"score/{inner_kind}: {error}")
        self.inner_kind = inner_kind
        self.error = error
        self.code = code

    def status(self) -> int:
        return self.code

    def message(self):
        return {
            "kind": "score",
            "error": {"kind": self.inner_kind, "error": self.error},
        }


class FetchModelError(ScoreError):
    def __init__(self, inner: ResponseError):
        super().__init__("fetch_model", inner.message, inner.code)

    def message(self):
        return {"kind": "score", "error": self.error}


class FetchModelWeightsError(ScoreError):
    def __init__(self, inner: ResponseError):
        super().__init__("fetch_model_weights", inner.message, inner.code)

    def message(self):
        return {"kind": "score", "error": self.error}


class InvalidModelError(ScoreError):
    def __init__(self, error: str):
        super().__init__("invalid_model", error, 400)


class ExpectedTwoOrMoreChoices(ScoreError):
    def __init__(self, got: int):
        super().__init__(
            "expected_two_or_more_choices",
            f"expected 2 or more provided choices but got {got}",
            400,
        )


class InvalidContentError(ScoreError):
    """No parseable ballot key in a judge's output.

    ``detail`` refines the diagnostic for logs; the wire message stays the
    reference's fixed string (score/completions/error.rs:12-13).
    """

    def __init__(self, detail: Optional[str] = None):
        super().__init__("invalid_content", "expected a valid response key", 500)
        self.detail = detail


class AllVotesFailed(ScoreError):
    def __init__(self, code: Optional[int]):
        super().__init__(
            "all_votes_failed",
            "all votes failed, see choices for further details",
            code if code is not None else 500,
        )


class ScoreArchiveError(ScoreError):
    def __init__(self, inner: ResponseError):
        super().__init__("completions_archive", inner.message, inner.code)

    def message(self):
        return {"kind": "score", "error": self.error}


class ScoreInvalidCompletionChoiceIndex(ScoreError):
    def __init__(self, completion_id: str, choice_index: int):
        super().__init__(
            "invalid_completion_choice_index",
            f"invalid choice_index for completion {completion_id}: {choice_index}",
            400,
        )


class ScoreChatError(ScoreError):
    """Chat error surfaced through the score endpoint (transparent wrap)."""

    def __init__(self, chat_error: ChatError):
        self.chat_error = chat_error
        super().__init__("chat", str(chat_error), chat_error.status())

    def message(self):
        return {"kind": "score", "error": self.chat_error.message()}
