"""Prefix-tree ballot construction.

Reference: src/score/completions/client.rs:1342-1631 (SelectPfx, SelectPfxTree,
pfx_indices, json_serialize_select_choices, regex_patterns).
"""

from __future__ import annotations

import random
from typing import Optional, Union

from ..utils import jsonutil

# 20-symbol ballot alphabet (client.rs:1342-1364).  20 = the max
# ``top_logprobs`` fan-out, so a single completion token can carry a full
# probability distribution over one tree level.
ALPHABET = "ABCDEFGHIJKLMNOPQRST"
MAX_BRANCH = len(ALPHABET)

# node representation: a Leaf is an int candidate index; a Branch is an
# insertion-ordered dict letter -> node.
Node = Union[int, dict]


def branch_limit(top_logprobs: Optional[int]) -> int:
    """Branching limit for a judge (client.rs:503-508).

    ``top_logprobs`` >= 2 caps the branch factor so every sibling key letter
    can appear among the logprob alternatives of one token; otherwise the
    full alphabet is available.
    """
    if top_logprobs is None or top_logprobs in (0, 1):
        return MAX_BRANCH
    return min(top_logprobs, MAX_BRANCH)


class PrefixTree:
    """Randomized candidate->key assignment tree.

    All leaves sit at the same depth (the splitter forces uniform sub-branch
    nesting, client.rs:1494-1497), so key length == depth for every
    candidate.
    """

    def __init__(self, root: Node, depth: int):
        self.root = root
        self.depth = depth

    # -- construction (client.rs:1455-1516) ---------------------------------
    #
    # Deviation from the reference: its splitter propagates the
    # force-sub-branch flag only one level, which produces *mixed* leaf
    # depths for some (N, limit) combinations (e.g. N=9, limit=2) and then
    # panics (`unreachable!()`) during vote extraction on the shallower
    # keys.  We instead compute the uniform target depth up front — the
    # smallest d with limit**d >= N — and split every node to exactly that
    # depth, so key length is constant by construction.

    @classmethod
    def build(
        cls, rng: random.Random, source_len: int, max_branch_len: int
    ) -> "PrefixTree":
        if source_len < 1:
            raise ValueError("ballot needs at least one candidate")
        if max_branch_len < 2:
            raise ValueError("branch limit must be >= 2")
        source = list(range(source_len))
        rng.shuffle(source)
        depth = 1
        while max_branch_len**depth < source_len:
            depth += 1
        root = cls._build_node(rng, source, max_branch_len, depth)
        return cls(root, depth)

    @classmethod
    def _build_node(
        cls,
        rng: random.Random,
        source: list,
        max_branch_len: int,
        depth_remaining: int,
    ) -> Node:
        letters = list(ALPHABET)
        rng.shuffle(letters)
        if depth_remaining == 1:
            return {letters[i]: idx for i, idx in enumerate(source)}
        # minimal branching that keeps every child within the capacity of
        # the remaining levels, sizes as even as possible
        capacity = max_branch_len ** (depth_remaining - 1)
        n = min(-(-len(source) // capacity), max_branch_len)
        base_per, extra = divmod(len(source), n)
        branch: dict = {}
        offset = 0
        for i in range(n):
            size = base_per + (1 if i < extra else 0)
            branch[letters[i]] = cls._build_node(
                rng, source[offset : offset + size], max_branch_len, depth_remaining - 1
            )
            offset += size
        return branch

    # -- key enumeration (client.rs:1518-1549) ------------------------------

    def key_indices(self, rng: random.Random) -> list:
        """All (key, candidate_index) pairs, in shuffled presentation order.

        Keys are backtick-quoted per level: depth 1 -> "`C`", depth 2 ->
        "`C``B`".
        """
        pairs: list = []
        self._collect(self.root, "", pairs)
        rng.shuffle(pairs)
        return pairs

    @staticmethod
    def _collect(node: Node, prefix: str, out: list) -> None:
        if isinstance(node, int):
            out.append((prefix, node))
            return
        for letter, child in node.items():
            PrefixTree._collect(child, f"{prefix}`{letter}`", out)

    # -- lookup --------------------------------------------------------------

    def child(self, node: Node, letter: str) -> Optional[Node]:
        if isinstance(node, dict):
            return node.get(letter)
        return None

    def walk(self, key: str) -> dict:
        """Descend to the lowest branch selected by ``key``'s letters.

        Mirrors get_vote's descent (client.rs:1700-1716): consume alphabet
        letters from the key until one level above the leaves; returns that
        branch (letter -> leaf index).
        """
        node = self.root
        remaining = self.depth - 1
        if remaining > 0:
            for c in key:
                if c in ALPHABET:
                    nxt = self.child(node, c)
                    if nxt is None:
                        break
                    node = nxt
                    remaining -= 1
                    if remaining == 0:
                        break
        if not isinstance(node, dict):
            raise ValueError("prefix tree walk ended on a leaf")
        return node

    # -- reconstruction from flattened records -------------------------------

    @staticmethod
    def leaf_branch_of(key_indices: list, key: str) -> dict:
        """Reconstruct the leaf branch containing ``key`` from a flattened
        ``(key, candidate_index)`` record (the archivable form of a ballot):
        all keys sharing ``key``'s letter prefix, mapped final-letter ->
        candidate.  This is what archive re-extraction stores instead of
        the full tree (archive/rescore.py).

        Comparison is over ALPHABET letter sequences, not raw strings, so a
        tick-stripped match from ``find_key`` ("C``B" for stored "`C``B`")
        still resolves — mirroring ``walk``, which also consumes only
        alphabet letters.
        """
        letters = [c for c in key if c in ALPHABET]
        branch: dict = {}
        for k, idx in key_indices:
            kl = [c for c in k if c in ALPHABET]
            if len(kl) == len(letters) and kl[:-1] == letters[:-1]:
                branch[kl[-1]] = idx
        return branch

    # -- regex patterns (client.rs:1605-1630) -------------------------------

    @staticmethod
    def regex_patterns(keys: list) -> tuple:
        """(with_ticks, without_ticks) alternation patterns over the keys.

        Keys contain only letters and backticks — no regex metacharacters —
        so they embed literally.  The stripped variant drops the outermost
        backticks (tolerates models that eat the quoting).

        Construction matters for the host hot path (SURVEY §3.5: vote
        extraction runs per judge per request): the reference's shape —
        one CAPTURE GROUP per key, ``(`D`)|(`J`)|...`` — defeats CPython
        sre's literal-prefix and charset optimizations, costing a full
        alternation trial at every content position (measured 4.7 ms per
        2 KB judge output at n=64, 140 ms at n=400).  Factoring the
        shared leading backtick out and dropping the unused per-key
        groups (only ``group(0)`` is ever read) keeps the match set and
        leftmost-first priority byte-identical while letting sre skip by
        literal prefix: 3-23 µs on the same inputs (~1500x).  Parity with
        the reference's group(0) semantics is property-tested against the
        naive construction in tests/test_ballot.py."""
        inner = "|".join(k[1:-1] for k in keys)
        with_ticks = f"`(?:{inner})`"
        without_ticks = f"(?:{inner})"
        return with_ticks, without_ticks


def serialize_ballot(choices_texts: list, key_indices: list) -> str:
    """Pretty JSON map of key -> candidate text, in shuffled ballot order.

    Reference serializes the request ``Choice`` values, which are plain text
    at this point in the engine (client.rs:1580-1603).
    """
    ordered = {key: choices_texts[idx] for key, idx in key_indices}
    return jsonutil.dumps(ordered, pretty=True)
