"""Vote extraction: judge output text -> probability vector over candidates.

Reference: get_vote, src/score/completions/client.rs:1661-1800.

The reference does this math in exact decimal (rust_decimal +
MathematicalOps::exp); we use Python ``decimal.Decimal`` whose ``exp`` is
correctly rounded — at least as precise.  The batched device analog (f32 on
TPU, used for archive re-scoring) lives in ``ops.votes``; tolerance contract
in tests/test_ballot.py.
"""

from __future__ import annotations

import re
from decimal import Decimal
from typing import Optional

from ..errors import InvalidContentError
from .tree import ALPHABET, PrefixTree


def _last_match(pattern: "str | re.Pattern", content: str) -> Optional[str]:
    last = None
    # pre-compiled patterns scan through their own method: re.finditer
    # on a Pattern object pays a failed module-cache probe (KeyError in
    # re._compile) on every call
    if isinstance(pattern, re.Pattern):
        it = pattern.finditer(content)
    else:
        it = re.finditer(pattern, content)
    for m in it:
        last = m
    return last.group(0) if last else None


def _byte_len(c: str) -> int:
    return len(c.encode("utf-8"))


def _char_at_byte(token: str, byte_index: int) -> Optional[str]:
    """Char starting exactly at UTF-8 ``byte_index``, if any.

    Token alignment works in byte offsets so that alternative tokens from
    ``top_logprobs`` index consistently with the sampled token
    (client.rs:1767-1778 uses char_indices over bytes).
    """
    i = 0
    for ch in token:
        if i == byte_index:
            return ch
        i += _byte_len(ch)
        if i > byte_index:
            return None
    return None


def find_key(
    content: Optional[str],
    with_ticks_pattern: "str | re.Pattern",
    without_ticks_pattern: "str | re.Pattern",
) -> Optional[str]:
    """Last ballot-key occurrence in ``content``: models often restate keys
    while reasoning, the final statement is the decision
    (client.rs:1675-1688).  Backticked match preferred, tick-stripped
    fallback.  Patterns may arrive pre-compiled (the HOST_FASTPATH judge
    stream compiles its two ballot patterns once per panel member);
    matches are identical either way."""
    if not content:
        return None
    key = _last_match(with_ticks_pattern, content)
    if key is None:
        key = _last_match(without_ticks_pattern, content)
    return key


def final_letter(key: str) -> str:
    """The key's final alphabet letter — selects within the lowest branch."""
    return next(c for c in reversed(key) if c in ALPHABET)


def extract_vote(
    tree: PrefixTree,
    with_ticks_pattern: "str | re.Pattern",
    without_ticks_pattern: "str | re.Pattern",
    n_choices: int,
    content: Optional[str],
    logprob_tokens: Optional[list] = None,
) -> list:
    """Extract a vote vector (list of Decimal summing to 1) from judge output.

    ``logprob_tokens`` is the accumulated ``logprobs.content`` token list:
    each item must expose ``.token`` (str), and ``.top_logprobs`` — a list of
    items with ``.token`` and ``.logprob``.  When present and alignable, the
    vote is the normalized ``exp(logprob)`` distribution over sibling leaf
    letters; otherwise one-hot on the selected candidate.

    Raises :class:`InvalidContentError` when no ballot key is found.
    """
    if not content:
        raise InvalidContentError("judge output is empty")

    key = find_key(content, with_ticks_pattern, without_ticks_pattern)
    if key is None:
        raise InvalidContentError("no ballot key found in judge output")

    final_char = final_letter(key)

    branch = tree.walk(key)

    vote = [Decimal(0)] * n_choices

    soft = _soft_vote(branch, key, final_char, vote, logprob_tokens)
    if soft is not None:
        return soft

    # one-hot fallback (client.rs:1796-1798)
    leaf = branch.get(final_char)
    if not isinstance(leaf, int):
        raise InvalidContentError(f"ballot key {key!r} selects no candidate")
    vote[leaf] = Decimal(1)
    return vote


def align_key_token(
    key: str, final_char: str, logprob_tokens: Optional[list]
):
    """Reverse-align ``key`` against the token stream to find the token that
    carries the final key letter (client.rs:1721-1762).  Multi-char tokens,
    split keys, and unicode are all handled by byte-offset matching.

    Returns ``(entry, byte_index)`` — the logprob entry and the UTF-8 byte
    offset of the final letter inside its token — or None when the key is
    not alignable (missing/partial logprobs)."""
    if not logprob_tokens:
        return None

    key_rev = key[::-1]
    remaining = key_rev
    key_token = None
    key_byte_index = 0

    done = False
    for entry in reversed(logprob_tokens):
        token = getattr(entry, "token", None)
        if token is None:
            continue
        i = _byte_len(token)
        for c in reversed(token):
            i -= _byte_len(c)
            if remaining.startswith(c):
                remaining = remaining[1:]
                if key_token is None and c == final_char:
                    key_token = entry
                    key_byte_index = i
                if not remaining:
                    done = True
                    break
            elif len(remaining) != len(key_rev):
                # partial match broke: reset and keep scanning backwards
                remaining = key_rev
                key_token = None
                key_byte_index = 0
            # else: unrelated char before any match begins — keep going
        if done:
            break

    if remaining or key_token is None:
        return None
    return key_token, key_byte_index


def soft_vote_alternatives(
    branch: dict, key_token, key_byte_index: int
) -> list:
    """The ``top_logprobs`` alternatives of the aligned key token that map
    to sibling leaves: list of (candidate_index, raw logprob).  This is
    the input both vote paths share — the host path exp/normalizes it in
    Decimal below, the device path (ops.votes.softmax_votes) in f32 as one
    batched kernel (archive re-extraction, SURVEY §3.5 hot loop #2)."""
    out = []
    for alt in getattr(key_token, "top_logprobs", None) or []:
        token = getattr(alt, "token", None)
        logprob = getattr(alt, "logprob", None)
        if token is None or logprob is None:
            continue
        c = _char_at_byte(token, key_byte_index)
        if c is None or c not in ALPHABET:
            continue
        leaf = branch.get(c)
        if not isinstance(leaf, int):
            continue
        out.append((leaf, logprob))
    return out


def _soft_vote(
    branch: dict,
    key: str,
    final_char: str,
    vote: list,
    logprob_tokens: Optional[list],
) -> Optional[list]:
    """Logprob soft-vote path (client.rs:1721-1792); None -> fall back to one-hot."""
    aligned = align_key_token(key, final_char, logprob_tokens)
    if aligned is None:
        return None
    key_token, key_byte_index = aligned

    total = Decimal(0)
    for leaf, logprob in soft_vote_alternatives(
        branch, key_token, key_byte_index
    ):
        p = Decimal(str(logprob)).exp()
        vote[leaf] += p
        total += p

    if total == 0:
        # the sampled letter was not among the alternatives; degrade to
        # one-hot rather than divide by zero (reference marks this
        # unreachable, client.rs:1784-1786)
        return None
    for i in range(len(vote)):
        vote[i] /= total
    return vote
