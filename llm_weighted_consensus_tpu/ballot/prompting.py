"""Ballot prompt text + forced-output schema.

Reference: src/score/completions/client.rs:533-572 (prompt injection) and
1291-1340 (ResponseKey::response_format).
"""

from __future__ import annotations

from typing import Optional


def ballot_instruction(
    choices_string: str, keys: list, output_mode: str
) -> str:
    """System-message text presenting the ballot (client.rs:533-543).

    ``instruction`` mode must spell out the output contract; the forced
    modes (json_schema / tool_call) constrain the output mechanically.
    """
    if output_mode == "instruction":
        keys_list = "\n- ".join(keys)
        return (
            "Select the response:\n\n"
            f"{choices_string}\n\n"
            "Output exactly one response key including backticks, "
            "nothing else:\n"
            f"- {keys_list}"
        )
    return f"Select the response:\n\n{choices_string}"


def response_key_schema(keys: list, synthetic_reasoning: bool) -> dict:
    """Strict JSON schema forcing ``response_key`` (client.rs:1297-1340).

    With ``synthetic_reasoning`` a required ``_think`` field precedes the
    key, giving non-reasoning models a scratchpad inside the forced output.
    Used as ``response_format.json_schema.schema`` in json_schema mode and as
    forced-function parameters in tool_call mode.
    """
    properties: dict = {}
    required = []
    if synthetic_reasoning:
        properties["_think"] = {
            "type": "string",
            "description": "The assistant's internal reasoning.",
        }
        required.append("_think")
    properties["response_key"] = {"type": "string", "enum": list(keys)}
    required.append("response_key")
    return {
        "type": "object",
        "properties": properties,
        "required": required,
        "additionalProperties": False,
    }


def response_format_for(keys: list, synthetic_reasoning: bool) -> dict:
    """Full ``response_format`` body for json_schema output mode."""
    return {
        "type": "json_schema",
        "json_schema": {
            "name": "response_key",
            "strict": True,
            "schema": response_key_schema(keys, synthetic_reasoning),
        },
    }
