"""Randomized prefix-tree ballots + vote extraction (pure core).

The consensus engine asks each judge LLM to *select* the best candidate from a
randomized ballot.  Every candidate is assigned a key of backtick-quoted
letters from a 20-symbol alphabet (A-T); both the letter assignment and the
candidate presentation order are shuffled per judge (anti-position-bias).
When the candidate count exceeds the branching limit the tree nests, giving
multi-letter keys like ```C``B```.

Vote extraction finds the *last* ballot-key occurrence in the judge's output,
walks the prefix tree to the selected leaf, and — when token logprobs are
available — converts the ``top_logprobs`` alternatives of the final key letter
into a normalized probability distribution over candidates (a *soft vote*);
otherwise the vote is one-hot.

Parity target: reference src/score/completions/client.rs:1342-1800
(SelectPfx/SelectPfxTree/get_vote) and 497-659 (ballot prompt + output
forcing).  This module is pure Python: no IO, no JAX.  The numeric tail
(exp/normalize over logprobs) also exists as a batched device kernel in
``ops.votes`` for archive re-scoring.
"""

from .tree import (
    ALPHABET,
    MAX_BRANCH,
    PrefixTree,
    branch_limit,
    serialize_ballot,
)
from .vote import InvalidContentError, extract_vote
from .prompting import (
    ballot_instruction,
    response_format_for,
    response_key_schema,
)

__all__ = [
    "ALPHABET",
    "MAX_BRANCH",
    "PrefixTree",
    "branch_limit",
    "serialize_ballot",
    "InvalidContentError",
    "extract_vote",
    "ballot_instruction",
    "response_format_for",
    "response_key_schema",
]
