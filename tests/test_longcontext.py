"""Long-context serving (ISSUE PR 14): sequence parallelism wired into
the first-class mesh serving path end to end.

What this pins, on the tier-1 8-virtual-device CPU mesh:

* ring-vs-dense parity through the SERVING entry points
  (``embed_tokens_ring`` / ``consensus_confidence_tokens_ring`` on a
  ``shard_embedder_mesh``-sharded embedder) at sp=2/4/8 for the dense,
  int8-pallas and int4-pallas weight paths — the ring rotation changes
  the layout, never the math, regardless of quantization;
* the meshfault downsize drill on an sp-bearing shape — dp halves, sp
  survives every rung, the warmed rung serves ring traffic with zero
  new jit specializations and answers identical to the full shape;
* ``MESH_SHAPE`` without an sp axis is byte-identical to the pre-sp
  serving path (the opt-in contract): same parse, same mesh axes, same
  AOT key namespace, zero ring state on the embedder;
* the e2e acceptance: a scored/embedded request at a sequence length
  the dense window cannot serve full-length rides the ring route
  through the DeviceBatcher and matches a full-window dense reference.

Jit caches are process-global and SHARED across embedder instances, so
every zero-growth assertion is a delta whose reference dispatches all
run BEFORE the first snapshot (the test_aot.py discipline).
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llm_weighted_consensus_tpu.models import configs
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.parallel.mesh import make_mesh
from llm_weighted_consensus_tpu.parallel.sharding import shard_embedder_mesh
from llm_weighted_consensus_tpu.resilience.meshfault import MeshFaultManager
from llm_weighted_consensus_tpu.serve.batcher import DeviceBatcher
from llm_weighted_consensus_tpu.serve.config import _parse_mesh_shape
from llm_weighted_consensus_tpu.serve.metrics import Metrics

TINY = configs.TEST_TINY


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_embedder(**kw):
    kw.setdefault("config", TINY)
    kw.setdefault("max_tokens", 64)
    return TpuEmbedder("test-tiny", seed=3, **kw)


def ring_embedder(sp, dp=None, tp=1, **kw):
    emb = make_embedder(**kw)
    dp = dp if dp is not None else 8 // (tp * sp)
    shard_embedder_mesh(emb, make_mesh(dp=dp, tp=tp, sp=sp))
    return emb


def token_batch(n, s, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(3, TINY.vocab_size, (n, s)).astype(np.int32)
    mask = np.ones((n, s), np.int32)
    mask[-1, s - s // 4 :] = 0  # one ragged row: pads cross sp shards
    return ids, mask


# -- ring-vs-dense parity across sp and quantization --------------------------


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("quantize", ["none", "int8-pallas", "int4-pallas"])
def test_ring_serving_parity_across_sp_and_quant(sp, quantize):
    """The serving-path parity matrix: the sp-sharded ring dispatch
    (embed + fused vote) answers exactly like the single-device dense
    forward under the SAME weight quantization.  int8/int4 run the
    interpret-mode Pallas kernels inside the shard_map — the W8A8/W4A8
    epilogue must be invariant to where the sequence axis lives."""
    ref = make_embedder(quantize=quantize)
    emb = ring_embedder(sp, quantize=quantize)
    assert emb.ring_available()
    assert emb.mesh_sp == sp
    # test-tiny usable window is 64; every sp here divides it exactly
    assert emb.ring_max_tokens == 64

    ids, mask = token_batch(2, 16, seed=sp)
    np.testing.assert_allclose(
        emb.embed_tokens_ring(ids, mask),
        ref.embed_tokens(ids, mask),
        atol=2e-4,
    )
    np.testing.assert_allclose(
        emb.consensus_confidence_tokens_ring(ids, mask, temperature=1.0),
        np.asarray(ref.consensus_confidence_tokens(ids, mask, 1.0)),
        atol=2e-4,
    )


def test_ring_dispatch_at_full_window_beyond_dense_cap():
    """A sequence at the full position window dispatches through the
    ring even when the embedder's dense cap is shorter — the shape the
    dense bucket table would never serve."""
    ref = make_embedder(max_tokens=64)
    emb = ring_embedder(sp=4, max_tokens=32)
    assert emb.max_tokens == 32 and emb.ring_max_tokens == 64
    ids, mask = token_batch(2, 64, seed=11)
    np.testing.assert_allclose(
        emb.embed_tokens_ring(ids, mask),
        ref.embed_tokens(ids, mask),
        atol=2e-4,
    )


# -- meshfault: downsize drill on an sp-bearing shape -------------------------


def test_meshfault_downsize_preserves_sp_and_serves_warmed():
    """dp 4 -> 2 -> 1 with sp=2 riding along: every rung's mesh keeps
    the sp axis, the warmed rung serves the ring bucket with zero new
    specializations, and the degraded answers match the full shape."""
    ref = make_embedder()
    emb = ring_embedder(sp=2, dp=4)
    mgr = MeshFaultManager(emb, shape=(4, 1))
    assert mgr.build_ladder() == [(4, 1), (2, 1), (1, 1)]

    n, s = 2, 32
    ids, mask = token_batch(n, s, seed=21)
    want_conf = np.asarray(ref.consensus_confidence_tokens(ids, mask, 1.0))
    want_emb = ref.embed_tokens(ids, mask)

    mgr.warm_ladder([], ring_buckets=[(n, s)])
    full_conf = np.asarray(
        emb.consensus_confidence_tokens_ring(ids, mask, 1.0)
    )
    before = emb.jit_stats()["specializations"]

    assert mgr.downsize() is True
    snap = mgr.snapshot()
    assert snap["current_shape"] == [2, 1]
    assert snap["sp"] == 2
    assert dict(emb.mesh.shape) == {"dp": 2, "tp": 1, "sp": 2}
    assert emb.ring_available()

    got_conf = np.asarray(
        emb.consensus_confidence_tokens_ring(ids, mask, 1.0)
    )
    got_emb = emb.embed_tokens_ring(ids, mask)
    np.testing.assert_allclose(got_conf, full_conf, atol=1e-5)
    np.testing.assert_allclose(got_conf, want_conf, atol=2e-4)
    np.testing.assert_allclose(got_emb, want_emb, atol=2e-4)
    assert emb.jit_stats()["specializations"] == before

    # the last rung (dp=1) still carries sp and still serves
    assert mgr.downsize() is True
    assert dict(emb.mesh.shape) == {"dp": 1, "tp": 1, "sp": 2}
    np.testing.assert_allclose(
        emb.embed_tokens_ring(ids, mask), want_emb, atol=2e-4
    )
    assert emb.jit_stats()["specializations"] == before


# -- MESH_SHAPE without sp: byte-identical pre-sp path ------------------------


def test_mesh_shape_without_sp_is_the_exact_pre_sp_path():
    """The opt-in contract: no sp in MESH_SHAPE (or sp=1) must leave
    the serving path untouched — same parsed shape, same 2-axis mesh,
    same AOT key namespace, no ring state anywhere."""
    assert _parse_mesh_shape("4x2") == (4, 2)
    # sp=1 normalizes away at parse time AND at mesh-construction time
    assert _parse_mesh_shape("4x2x1") == (4, 2)
    assert make_mesh(dp=4, tp=2, sp=1).axis_names == ("dp", "tp")

    plain = make_embedder()
    shard_embedder_mesh(plain, make_mesh(dp=4, tp=2))
    assert plain.mesh_sp == 1
    assert not plain.ring_available()
    assert plain.ring_sharding is None
    assert plain._ring_config is None

    normalized = make_embedder()
    shard_embedder_mesh(normalized, make_mesh(dp=4, tp=2, sp=1))
    plain.aot_warmup([(4, 16)])
    normalized.aot_warmup([(4, 16)])
    assert set(plain._aot) == set(normalized._aot)
    assert all(key[0] == "mesh" and key[1:3] == (4, 2) for key in plain._aot)
    # a ring bucket request on a no-sp mesh is ignored, not mis-keyed
    keys = set(plain._aot)
    plain.aot_warmup([], ring_buckets=[(2, 32)])
    assert set(plain._aot) == keys
    with pytest.raises(RuntimeError, match="sp axis"):
        plain.embed_tokens_ring(*token_batch(2, 32))


def test_long_context_warmup_requires_sp_mesh_shape():
    from llm_weighted_consensus_tpu.serve.config import Config

    with pytest.raises(ValueError, match="sp"):
        Config.from_env(
            {
                "MESH_ENABLED": "1",
                "MESH_SHAPE": "4x2",
                "LONG_CONTEXT_WARMUP": "2x64",
            }
        )
    config = Config.from_env(
        {
            "MESH_ENABLED": "1",
            "MESH_SHAPE": "2x2x2",
            "LONG_CONTEXT_WARMUP": "2x64,1x32",
        }
    )
    assert config.mesh_shape == (2, 2, 2)
    assert config.long_context_warmup == [(2, 64), (1, 32)]


# -- e2e: the batcher serves what the dense window cannot ---------------------


def test_batcher_routes_over_length_to_ring_full_length():
    """The PR's acceptance shape: a scored request LONGER than the
    dense token window succeeds through the batcher via the ring route
    and matches a full-window dense reference — where the dense path
    would have truncated half the evidence away."""
    # dense window 32, ring window 64 (the full test-tiny position table)
    emb = ring_embedder(sp=2, dp=2, max_tokens=32)
    full_ref = make_embedder(max_tokens=64)
    short = ["compact candidate answer", "another short one"]
    long_texts = [
        "evidence " * 40 + "verdict alpha",
        "evidence " * 40 + "verdict beta",
    ]
    # the long texts genuinely exceed the dense window...
    ids, mask = full_ref.tokenize(long_texts)
    assert int(mask.sum(axis=1).max()) > emb.max_tokens
    # ...but fit the ring window full-length
    assert int(mask.sum(axis=1).max()) <= emb.ring_max_tokens

    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=5.0)

    async def run():
        return await asyncio.gather(
            batcher.consensus(long_texts, 1.0),
            batcher.embed(long_texts),
            batcher.consensus(short, 1.0),
        )

    (conf, conf_tokens), (vecs, emb_tokens), (short_conf, _) = go(run())

    np.testing.assert_allclose(
        conf,
        np.asarray(full_ref.consensus_confidence(long_texts, temperature=1.0)),
        atol=2e-4,
    )
    np.testing.assert_allclose(
        vecs, full_ref.embed_texts(long_texts), atol=2e-4
    )
    # usage accounting saw the FULL length, not the truncated window
    assert conf_tokens > 2 * emb.max_tokens
    assert emb_tokens == conf_tokens
    # short traffic stayed on the dense dispatch — the ring is opt-in
    # per request, not a mode switch
    series = metrics.snapshot()["series"]
    assert series["device:batch:ring_vote"]["count"] == 1
    assert series["device:batch:ring_embed"]["count"] == 1
    assert series["device:batch:consensus"]["count"] == 1
    ref_short = make_embedder(max_tokens=32)
    np.testing.assert_allclose(
        short_conf,
        np.asarray(ref_short.consensus_confidence(short, temperature=1.0)),
        atol=2e-4,
    )


def test_batcher_explicit_truncation_stays_dense():
    """An explicit max_tokens at or under the dense window is an
    intentional truncation request: no ring dispatch, byte-identical
    answers to the dense path."""
    emb = ring_embedder(sp=2, dp=2, max_tokens=32)
    ref = make_embedder(max_tokens=32)
    long_texts = ["evidence " * 40]
    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=5.0)
    vecs, _ = go(batcher.embed(long_texts, max_tokens=32))
    np.testing.assert_allclose(
        vecs, ref.embed_texts(long_texts, max_tokens=32), atol=1e-5
    )
    assert "device:batch:ring_embed" not in metrics.snapshot()["series"]
