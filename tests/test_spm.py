"""SentencePiece unigram tokenizer (models/spm.py).

Parity target: HF ``tokenizers``' ``Unigram`` model — the exact engine
``XLMRobertaTokenizerFast`` runs — configured with the same metaspace
pre-tokenization this module implements.  Shared vocabularies are built in
the tests, segmented by both implementations, and compared piece-for-piece.
The ModelProto parser is pinned by serializing protos with a local
wire-format writer and round-tripping.
"""

import struct

import numpy as np
import pytest

from llm_weighted_consensus_tpu.models.spm import (
    BYTE,
    CONTROL,
    NORMAL,
    UNKNOWN,
    USER_DEFINED,
    SPACE,
    UnigramTokenizer,
    normalize,
    parse_model_proto,
    scheme_for_model,
)


# -- proto writer (test-local; mirrors sentencepiece_model.proto wire fmt) --


def _varint(value: int) -> bytes:
    out = b""
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out += bytes([byte | 0x80])
        else:
            return out + bytes([byte])


def _piece_msg(piece: str, score: float, ptype: int) -> bytes:
    raw = piece.encode("utf-8")
    body = b"\x0a" + _varint(len(raw)) + raw  # field 1, wire 2
    body += b"\x15" + struct.pack("<f", score)  # field 2, wire 5
    if ptype != NORMAL:
        body += b"\x18" + _varint(ptype)  # field 3, wire 0
    return body


def serialize_proto(pieces, trailer: bytes = b"") -> bytes:
    out = b""
    for piece, score, ptype in pieces:
        msg = _piece_msg(piece, score, ptype)
        out += b"\x0a" + _varint(len(msg)) + msg  # ModelProto field 1
    return out + trailer


# an XLM-R-shaped vocab: unk/bos/eos first, then scored pieces
# (scores pass through f32 in the proto, so pin them to f32 values)
_RAW_PIECES = [
    ("<unk>", 0.0, UNKNOWN),
    ("<s>", 0.0, CONTROL),
    ("</s>", 0.0, CONTROL),
    (SPACE, -2.0, NORMAL),
    ("a", -3.0, NORMAL),
    ("b", -3.5, NORMAL),
    ("c", -4.0, NORMAL),
    ("ab", -4.5, NORMAL),
    ("bc", -5.0, NORMAL),
    ("abc", -5.5, NORMAL),
    (SPACE + "ab", -3.2, NORMAL),
    (SPACE + "hello", -6.0, NORMAL),
    ("hello", -7.0, NORMAL),
    ("world", -7.5, NORMAL),
    ("wor", -5.0, NORMAL),
    ("ld", -4.0, NORMAL),
    (SPACE + "the", -2.5, NORMAL),
    ("ing", -3.8, NORMAL),
    ("token", -6.5, NORMAL),
    (SPACE + "token", -6.2, NORMAL),
    ("iz", -4.2, NORMAL),
    ("er", -3.3, NORMAL),
    ("s", -2.9, NORMAL),
]
XLMR_PIECES = [
    (p, float(np.float32(s)), t) for p, s, t in _RAW_PIECES
]


def test_proto_roundtrip():
    data = serialize_proto(XLMR_PIECES)
    assert parse_model_proto(data) == XLMR_PIECES


def test_proto_skips_unknown_fields():
    # trainer_spec (field 2) and normalizer_spec (field 3) are skipped;
    # unknown scalar fields inside a piece are skipped too
    trainer = b"\x12" + _varint(3) + b"abc"
    normalizer = b"\x1a" + _varint(2) + b"xy"
    data = serialize_proto(XLMR_PIECES[:5], trailer=trainer + normalizer)
    # reorder so the trailer is interleaved: parser must not care
    data = trainer + data
    assert parse_model_proto(data) == XLMR_PIECES[:5]


def test_proto_rejects_garbage():
    with pytest.raises(ValueError):
        parse_model_proto(b"\x12\x00")  # valid wire, zero pieces


def test_proto_fuzz_never_crashes():
    """Random bytes must parse or raise a clean error (ValueError /
    UnicodeDecodeError) — never hang or escape with anything else."""
    rng = np.random.default_rng(13)
    base = serialize_proto(XLMR_PIECES)
    for i in range(300):
        if i % 3 == 0:
            data = bytes(rng.integers(0, 256, size=int(rng.integers(0, 200)), dtype=np.uint8))
        else:  # bit-flipped / spliced valid protos hit deeper branches
            buf = bytearray(base)
            for _ in range(int(rng.integers(1, 8))):
                buf[int(rng.integers(0, len(buf)))] = int(rng.integers(0, 256))
            data = bytes(buf)
        try:
            parse_model_proto(data)
        except (ValueError, UnicodeDecodeError):
            pass


def test_proto_rejects_truncation():
    data = serialize_proto(XLMR_PIECES)
    # a partial download must fail loudly, not yield a shorter vocab
    for cut in (len(data) - 1, len(data) // 2, 3):
        with pytest.raises(ValueError):
            parse_model_proto(data[:cut])


def _hf_unigram(pieces):
    """tokenizers' Unigram configured the way transformers' SpmConverter
    builds XLMRobertaTokenizerFast: whitespace collapse (the converter's
    ``Replace(" {2,}", " ")``, mirroring spm remove_extra_whitespaces)
    then Metaspace pre-tokenization, then the Unigram model."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers import Regex, Tokenizer, models, normalizers, pre_tokenizers

    vocab = [(p, s) for p, s, _ in pieces]
    unk_id = next(i for i, (_, _, t) in enumerate(pieces) if t == UNKNOWN)
    tok = Tokenizer(models.Unigram(vocab, unk_id=unk_id, byte_fallback=False))
    tok.normalizer = normalizers.Sequence(
        [
            normalizers.Replace(Regex(" {2,}"), " "),
            normalizers.Strip(),
        ]
    )
    tok.pre_tokenizer = pre_tokenizers.Metaspace(
        replacement=SPACE, prepend_scheme="always"
    )
    return tok


PARITY_TEXTS = [
    "hello world",
    "ab abc bca cab",
    "the tokenizers tokenize tokens",
    "a",
    "abcabcabc",
    "hello helloworld worlds",
    "the the the ab ab",
    "zzz unknown zz chars",  # unknown chars force unk fusion
    "mixed abz zab zzab",
    "s s s ing ing",
]


@pytest.mark.parametrize("text", PARITY_TEXTS)
def test_viterbi_parity_with_hf_tokenizers(text):
    ours = UnigramTokenizer(XLMR_PIECES, scheme="xlmr")
    theirs = _hf_unigram(XLMR_PIECES)
    assert ours.tokenize_text(text) == theirs.encode(text).tokens


def test_viterbi_parity_randomized_vocab():
    # a larger randomized vocab: substrings of a small alphabet with
    # seeded random scores — stresses tie-free max-sum path selection
    rng = np.random.default_rng(7)
    alphabet = "abcde"
    pieces = [("<unk>", 0.0, UNKNOWN)]
    seen = {"<unk>"}
    for length in (1, 2, 3):
        for _ in range(40):
            piece = "".join(rng.choice(list(alphabet), size=length))
            if piece not in seen:
                seen.add(piece)
                pieces.append(
                    (piece, float(-rng.uniform(1, 12)), NORMAL)
                )
    # every single char must be in-vocab plus the metaspace prefix forms
    for ch in alphabet + SPACE:
        if ch not in seen:
            pieces.append((ch, float(-rng.uniform(1, 12)), NORMAL))
    ours = UnigramTokenizer(pieces, scheme="xlmr")
    theirs = _hf_unigram(pieces)
    texts = [
        "".join(rng.choice(list(alphabet + "  "), size=30)).strip() or "a"
        for _ in range(25)
    ]
    for text in texts:
        assert (
            ours.tokenize_text(text) == theirs.encode(text).tokens
        ), text


def test_xlmr_id_scheme():
    tok = UnigramTokenizer(XLMR_PIECES, scheme="xlmr")
    # fairseq specials
    assert (tok.cls_id, tok.pad_id, tok.sep_id, tok.unk_id) == (0, 1, 2, 3)
    # piece ids shift by +1:  ▁hello is spm id 11 -> 12
    ids, mask = tok.encode_batch(["hello"], max_length=8)
    assert ids[0, 0] == 0 and ids[0, 2] == 2  # <s> ... </s>
    assert ids[0, 1] == 11 + 1
    assert mask[0].sum() == 3
    # vocab_size covers <mask> at the end
    assert tok.vocab_size == len(XLMR_PIECES) + 2


def test_deberta_id_scheme():
    pieces = [
        ("[PAD]", 0.0, CONTROL),
        ("[CLS]", 0.0, CONTROL),
        ("[SEP]", 0.0, CONTROL),
        ("[UNK]", 0.0, UNKNOWN),
        (SPACE + "ab", -2.0, NORMAL),
        ("ab", -3.0, NORMAL),
        ("a", -4.0, NORMAL),
        ("b", -4.5, NORMAL),
        (SPACE, -1.5, NORMAL),
    ]
    tok = UnigramTokenizer(pieces, scheme="deberta")
    assert (tok.pad_id, tok.cls_id, tok.sep_id, tok.unk_id) == (0, 1, 2, 3)
    ids, mask = tok.encode_batch(["ab"], max_length=8)
    # [CLS] ▁ab [SEP] with DIRECT spm ids (no fairseq offset)
    assert list(ids[0, :3]) == [1, 4, 2]
    assert tok.vocab_size == len(pieces)


def test_unknown_chars_map_to_unk_and_fuse():
    tok = UnigramTokenizer(XLMR_PIECES, scheme="xlmr")
    # unknown runs fuse into ONE raw-text token (id = unk)
    pieces = tok.tokenize_text("ab zzz ab")
    assert pieces == [SPACE + "ab", SPACE, "zzz", SPACE + "ab"]
    ids, mask = tok.encode_batch(["zzz"], max_length=8)
    assert tok.unk_id in ids[0]
    assert mask[0].sum() == 4  # <s> ▁ zzz </s>


def test_truncation_and_padding_shapes():
    tok = UnigramTokenizer(XLMR_PIECES, scheme="xlmr")
    ids, mask = tok.encode_batch(
        ["hello world " * 50, "ab"], max_length=16
    )
    assert ids.shape == (2, 16) and mask.shape == (2, 16)
    assert mask[0].sum() == 16  # truncated to cap
    assert ids[0, -1] == tok.sep_id  # sep survives truncation
    assert ids[1, mask[1].sum() - 1] == tok.sep_id
    assert (ids[1][mask[1] == 0] == tok.pad_id).all()


def test_normalize_nfkc_and_controls():
    assert normalize("ｈｅｌｌｏ") == "hello"  # NFKC fullwidth fold
    assert normalize("a\x00b\tc") == "ab c"  # controls dropped, tab->space
    assert "①" not in normalize("①")  # circled digits fold


def test_user_defined_pieces_match():
    pieces = XLMR_PIECES + [("<special>", 0.0, USER_DEFINED)]
    tok = UnigramTokenizer(pieces, scheme="xlmr")
    out = tok.tokenize_text("ab <special>")
    # USER_DEFINED participates in segmentation like a normal piece...
    assert "<special>" in "".join(out)
    # ...while CONTROL pieces never match text
    assert "<s>" not in tok.tokenize_text("ab <s> ab")


def test_control_and_byte_pieces_excluded_from_matching():
    # a BYTE piece must not match its own literal name in text: the run
    # falls through to unknown (raw text token, id = unk)
    pieces = XLMR_PIECES + [("<0x41>", -1.0, BYTE)]
    tok = UnigramTokenizer(pieces, scheme="xlmr")
    ids, _ = tok.encode_batch(["<0x41>"], max_length=8)
    byte_id = len(pieces) - 1 + 1  # spm id + xlmr offset
    assert byte_id not in ids[0]
    assert tok.unk_id in ids[0]


def test_model_file_loading(tmp_path):
    path = tmp_path / "sentencepiece.bpe.model"
    path.write_bytes(serialize_proto(XLMR_PIECES))
    tok = UnigramTokenizer.from_model_file(str(path), scheme="xlmr")
    assert tok.tokenize_text("hello") == [SPACE + "hello"]

    from llm_weighted_consensus_tpu.models.tokenizer import load_tokenizer

    loaded = load_tokenizer(str(path))
    assert isinstance(loaded, UnigramTokenizer)
    assert loaded.tokenize_text("hello") == [SPACE + "hello"]


def test_find_vocab_discovers_spm(tmp_path):
    from llm_weighted_consensus_tpu.models.loading import find_vocab

    (tmp_path / "model.safetensors").write_bytes(b"")
    spm = tmp_path / "sentencepiece.bpe.model"
    spm.write_bytes(serialize_proto(XLMR_PIECES))
    assert find_vocab(str(tmp_path)) == str(spm)
    # vocab.txt wins when both exist (WordPiece checkpoints)
    vocab = tmp_path / "vocab.txt"
    vocab.write_text("[PAD]\n[UNK]\n[CLS]\n[SEP]\na\n")
    assert find_vocab(str(tmp_path)) == str(vocab)


def test_scheme_for_model():
    assert scheme_for_model("bge-m3") == "xlmr"
    assert scheme_for_model("deberta-v3-base") == "deberta"


def test_embedder_integration_bge_m3_shapes(tmp_path):
    """bge-m3 preset + spm tokenizer end-to-end through TpuEmbedder
    (tiny config stands in for the 24-layer real shape)."""
    from llm_weighted_consensus_tpu.models.configs import TEST_TINY
    from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder

    tok = UnigramTokenizer(XLMR_PIECES, scheme="xlmr")
    emb = TpuEmbedder("test-tiny", tokenizer=tok, max_tokens=32)
    out = emb.embed_texts(["hello world", "ab abc"])
    assert out.shape == (2, TEST_TINY.hidden_size)
    norms = np.linalg.norm(out, axis=-1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-3)
