"""Serving micro-batcher: concurrent device work fuses into shared
dispatches with results identical to unbatched execution (VERDICT r2 item 1:
"K concurrent requests produce ≪K entries in the device:* metrics series
with identical results")."""

import asyncio
import random

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

pytest.importorskip("jax")

from llm_weighted_consensus_tpu import archive, registry
from llm_weighted_consensus_tpu.clients.chat import (
    ApiBase,
    BackoffPolicy,
    DefaultChatClient,
)
from llm_weighted_consensus_tpu.clients.multichat import MultichatClient
from llm_weighted_consensus_tpu.clients.score import ScoreClient
from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.models.configs import TEST_TINY
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.serve import build_app
from llm_weighted_consensus_tpu.serve.batcher import DeviceBatcher
from llm_weighted_consensus_tpu.serve.gateway import METRICS_KEY
from llm_weighted_consensus_tpu.serve.metrics import Metrics
from llm_weighted_consensus_tpu.utils import jsonutil

from fakes import FakeTransport, Script, chunk_obj

NO_RETRY = BackoffPolicy(max_elapsed_ms=0)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


@pytest.fixture(scope="module")
def embedder():
    return TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32)


# -- unit: the batcher itself -------------------------------------------------


def test_embed_batches_and_matches_unbatched(embedder):
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)

    async def run():
        return await asyncio.gather(
            batcher.embed(["hello world", "second text"]),
            batcher.embed(["third"]),
            batcher.embed(["fourth", "fifth", "sixth"]),
        )

    results = go(run())
    ref = embedder.embed_texts(["hello world", "second text"])
    np.testing.assert_allclose(results[0][0], ref, atol=1e-5)
    assert results[0][1] == embedder.token_count(
        ["hello world", "second text"]
    )
    assert results[2][0].shape[0] == 3
    # 3 concurrent requests, ONE device dispatch
    series = metrics.snapshot()["series"]
    assert series["device:batch:embed"]["count"] == 1
    util = metrics.snapshot()["device_batcher"]
    assert util["dispatches"] == 1 and util["items"] == 3


def test_consensus_batches_same_shape(embedder):
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)
    texts_a = [f"candidate {i % 3}" for i in range(6)]
    texts_b = list(reversed(texts_a))

    async def run():
        return await asyncio.gather(
            batcher.consensus(texts_a),
            batcher.consensus(texts_b),
            batcher.consensus(texts_a),
        )

    (conf_a, tok_a), (conf_b, tok_b), (conf_a2, _) = go(run())
    ref_a = np.asarray(embedder.consensus_confidence(texts_a))
    ref_b = np.asarray(embedder.consensus_confidence(texts_b))
    np.testing.assert_allclose(conf_a, ref_a, atol=1e-5)
    np.testing.assert_allclose(conf_b, ref_b, atol=1e-5)
    np.testing.assert_allclose(conf_a2, ref_a, atol=1e-5)
    assert tok_a == embedder.token_count(texts_a)
    assert tok_b == embedder.token_count(texts_b)
    # 3 concurrent requests pad to the 4-bucket (25% waste, under the
    # chunking threshold) and stay ONE dispatch
    assert metrics.snapshot()["series"]["device:batch:consensus"]["count"] == 1


def test_consensus_groups_chunk_to_powers_of_two(embedder):
    """The device path buckets the request dim to the next power of two;
    the batcher splits groups whose padding would waste >25% of the
    bucket: 5 concurrent same-shape requests (5 -> 8 would waste 37.5%)
    dispatch as 4+1, all results exact."""
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)
    texts = [f"candidate {i % 3}" for i in range(6)]

    async def run():
        return await asyncio.gather(
            *(batcher.consensus(texts) for _ in range(5))
        )

    results = go(run())
    ref = np.asarray(embedder.consensus_confidence(texts))
    for conf, _tok in results:
        np.testing.assert_allclose(conf, ref, atol=1e-5)
    util = metrics.snapshot()["device_batcher"]
    assert util["dispatches"] == 2 and util["items"] == 5

    def chunk_sizes(n, kind="consensus"):
        return [
            len(c)
            for c in DeviceBatcher._pow2_chunks(
                [type("I", (), {"kind": kind})() for _ in range(n)]
            )
        ]

    # <=25% padding stays whole; worse splits greedily, re-checking the
    # threshold on each remainder
    assert chunk_sizes(13) == [13]  # 16-bucket wastes 18.75%
    assert chunk_sizes(63) == [63]  # one pad slot: never split
    assert chunk_sizes(9) == [8, 1]  # 44% waste: split
    assert chunk_sizes(11) == [8, 3]  # remainder 3 re-kept (25%)
    assert chunk_sizes(8) == [8]
    # stream groups pass through whole: their R bucket has a 16 minimum,
    # chunking would strictly add padding and dispatches
    assert chunk_sizes(5, kind="stream") == [5]


def test_consensus_mixed_shapes_split_groups(embedder):
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)

    async def run():
        return await asyncio.gather(
            batcher.consensus(["a", "b", "c"]),
            batcher.consensus(["d", "e"]),  # different N: its own group
        )

    (c3, t3), (c2, t2) = go(run())
    assert c3.shape == (3,) and c2.shape == (2,)
    assert t3 > 0 and t2 > 0
    assert metrics.snapshot()["series"]["device:batch:consensus"]["count"] == 2


def test_chaos_concurrent_mixed_work_matches_direct_twins(embedder):
    """Fuzz the batcher with a random mix of concurrent embed/consensus/
    stream work (random sizes, shapes, interleavings): whatever grouping,
    chunking, bucketing, and pipelining happens inside, every caller must
    get exactly what a direct unbatched call returns.  This is the net
    under the r4 pow2-chunking change and any future grouping policy."""
    import jax.numpy as jnp
    import random

    rng = random.Random(11)
    metrics = Metrics()
    batcher = DeviceBatcher(
        embedder, metrics, window_ms=5.0, max_batch=7, max_rows=40,
        pipeline_depth=2,
    )
    hidden = embedder.config.hidden_size

    jobs = []
    for i in range(24):
        kind = rng.choice(["embed", "consensus", "stream"])
        if kind == "embed":
            texts = [f"text {i} {j} {rng.randrange(5)}" for j in range(rng.randrange(1, 5))]
            jobs.append(("embed", texts))
        elif kind == "consensus":
            n = rng.randrange(2, 6)
            texts = [f"candidate {i % 3} {j % n}" for j in range(n)]
            jobs.append(("consensus", texts))
        else:
            cap = 16
            buf = jnp.zeros((cap, hidden), jnp.float32)
            valid = jnp.zeros((cap,), jnp.float32)
            jobs.append(("stream", (f"stream text {i}", buf, valid, i % cap)))

    async def run():
        async def one(job):
            kind, payload = job
            if kind == "embed":
                return await batcher.embed(payload)
            if kind == "consensus":
                return await batcher.consensus(payload)
            text, buf, valid, pos = payload
            return await batcher.stream_update(
                text, jnp.array(buf), jnp.array(valid), pos
            )

        async def staggered(j, job):
            # random sub-window stagger so groups form at many sizes
            await asyncio.sleep(rng.random() * 0.01)
            return await one(job)

        return await asyncio.gather(
            *(staggered(j, job) for j, job in enumerate(jobs))
        )

    results = go(run())

    for job, result in zip(jobs, results):
        kind, payload = job
        if kind == "embed":
            emb, tokens = result
            ref = embedder.embed_texts(list(payload))
            np.testing.assert_allclose(np.asarray(emb), ref, atol=1e-5)
            assert tokens == embedder.token_count(list(payload))
        elif kind == "consensus":
            conf, tokens = result
            ref = np.asarray(embedder.consensus_confidence(list(payload)))
            np.testing.assert_allclose(np.asarray(conf), ref, atol=1e-5)
            assert tokens == embedder.token_count(list(payload))
        else:
            text, buf, valid, pos = payload
            out_buf, out_valid, conf = result
            rb, rv, rc = embedder.stream_vote_update(
                text, jnp.array(buf), jnp.array(valid), pos
            )
            np.testing.assert_allclose(
                np.asarray(out_buf), np.asarray(rb), atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(out_valid), np.asarray(rv), atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(conf), np.asarray(rc), atol=1e-5
            )
    util = metrics.snapshot()["device_batcher"]
    assert util["items"] == len(jobs)


def test_stream_updates_batch_across_streams(embedder):
    import jax.numpy as jnp

    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)
    hidden = embedder.config.hidden_size
    buf = jnp.zeros((16, hidden), jnp.float32)
    valid = jnp.zeros((16,), jnp.float32)

    async def run():
        return await asyncio.gather(
            batcher.stream_update("alpha", buf, valid, 0),
            batcher.stream_update("beta", buf, valid, 0),
        )

    (b1, v1, c1), (b2, v2, c2) = go(run())
    # the update jits donate buf/valid, so each reference call gets its
    # own copy (the production contract: a buffer is passed to exactly
    # one dispatch, then rebound from the result)
    rb, rv, rc = embedder.stream_vote_update(
        "alpha", jnp.array(buf), jnp.array(valid), 0
    )
    np.testing.assert_allclose(np.asarray(b1), np.asarray(rb), atol=1e-5)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(rv), atol=1e-5)
    rb2, _, _ = embedder.stream_vote_update(
        "beta", jnp.array(buf), jnp.array(valid), 0
    )
    np.testing.assert_allclose(np.asarray(b2), np.asarray(rb2), atol=1e-5)
    assert metrics.snapshot()["series"]["device:batch:stream"]["count"] == 1


def test_max_batch_chunks_oversized_groups(embedder):
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0, max_batch=2)

    async def run():
        return await asyncio.gather(
            *(batcher.embed([f"text {i}"]) for i in range(5))
        )

    results = go(run())
    assert len(results) == 5
    # 5 items at max_batch=2 -> 3 dispatches
    assert metrics.snapshot()["series"]["device:batch:embed"]["count"] == 3


def test_dispatch_error_propagates_to_all_waiters(embedder):
    class Boom(RuntimeError):
        pass

    class BrokenEmbedder:
        def tokenize(self, texts, max_tokens=None):
            raise Boom("device fell over")

    batcher = DeviceBatcher(BrokenEmbedder(), window_ms=20.0)

    async def run():
        results = await asyncio.gather(
            batcher.embed(["a"]),
            batcher.embed(["b"]),
            return_exceptions=True,
        )
        assert all(isinstance(r, Boom) for r in results)
        # the batcher survives a failed dispatch

    go(run())


def test_utilization_gauge_shape(embedder):
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=0.0)

    async def run():
        await batcher.embed(["one"])

    go(run())
    util = metrics.snapshot()["device_batcher"]
    assert set(util) >= {
        "queue_depth",
        "busy_fraction",
        "dispatches",
        "items",
        "items_per_dispatch",
    }
    assert util["queue_depth"] == 0
    assert 0.0 <= util["busy_fraction"] <= 1.0


# -- gateway: concurrent HTTP requests share device dispatches ----------------


def make_app(scripts, embedder=None, **kwargs):
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    reg = registry.InMemoryModelRegistry()
    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, reg, archive_fetcher=store,
        rng_factory=lambda: random.Random(7),
    )
    multichat = MultichatClient(chat, reg, archive_fetcher=store)
    return build_app(chat, score, multichat, embedder, **kwargs)


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def test_gateway_concurrent_embeddings_coalesce(embedder):
    app = make_app([], embedder=embedder, batch_window_ms=20.0)
    k = 8

    async def run(client):
        responses = await asyncio.gather(
            *(
                client.post(
                    "/embeddings",
                    json={
                        "model": "test-tiny",
                        "input": [f"text number {i}", "shared suffix"],
                    },
                )
                for i in range(k)
            )
        )
        bodies = [await r.json() for r in responses]
        assert all(r.status == 200 for r in responses)
        for i, body in enumerate(bodies):
            assert len(body["data"]) == 2
            ref = embedder.embed_texts([f"text number {i}", "shared suffix"])
            got = np.asarray([d["embedding"] for d in body["data"]])
            np.testing.assert_allclose(got, ref, atol=1e-4)
        series = app[METRICS_KEY].snapshot()["series"]
        # K concurrent requests, far fewer device dispatches
        assert series["device:batch:embed"]["count"] < k
        return series["device:batch:embed"]["count"]

    dispatches = go(with_client(app, run))
    assert dispatches <= 3  # typically 1; allow scheduling jitter


def test_gateway_multichat_unary_consensus(embedder):
    n = 3
    scripts = [
        Script([chunk_obj(text, finish="stop")])
        for text in ("the answer is 4", "the answer is 4", "maybe 5?")
    ]
    app = make_app(scripts, embedder=embedder, batch_window_ms=5.0)
    model = ModelBase.from_json_obj(
        {"llms": [{"model": f"g{i}"} for i in range(n)]}
    ).into_model_validate()

    async def run(client):
        resp = await client.post(
            "/multichat/completions",
            data=jsonutil.dumps(
                {
                    "messages": [{"role": "user", "content": "2+2?"}],
                    "model": {
                        "llms": [llm.base.to_json_obj() for llm in model.llms]
                    },
                    "consensus": True,
                }
            ),
            headers={"content-type": "application/json"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert set(body["consensus"]) == {"0", "1", "2"}
        conf = body["consensus"]
        assert abs(sum(conf.values()) - 1.0) < 1e-5
        # device work went through the batcher
        series = app[METRICS_KEY].snapshot()["series"]
        assert series["device:batch:consensus"]["count"] == 1

    go(with_client(app, run))


def test_gateway_multichat_unary_no_consensus_flag(embedder):
    scripts = [
        Script([chunk_obj("a", finish="stop")]),
        Script([chunk_obj("b", finish="stop")]),
    ]
    app = make_app(scripts, embedder=embedder)

    async def run(client):
        resp = await client.post(
            "/multichat/completions",
            data=jsonutil.dumps(
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": {"llms": [{"model": "g0"}, {"model": "g1"}]},
                }
            ),
            headers={"content-type": "application/json"},
        )
        body = await resp.json()
        assert "consensus" not in body

    go(with_client(app, run))


def test_gateway_streaming_consensus_through_batcher(embedder):
    """Two concurrent consensus multichat streams: frames still correct
    (batched stream updates return per-stream buffers)."""
    n = 2
    scripts = [
        Script([chunk_obj(f"stream one gen {i}", finish="stop")])
        for i in range(n)
    ] + [
        Script([chunk_obj(f"stream two gen {i}", finish="stop")])
        for i in range(n)
    ]
    app = make_app(scripts, embedder=embedder, batch_window_ms=5.0)

    def body():
        return jsonutil.dumps(
            {
                "stream": True,
                "consensus": True,
                "messages": [{"role": "user", "content": "q"}],
                "model": {"llms": [{"model": f"g{i}"} for i in range(n)]},
            }
        )

    async def run(client):
        async def one():
            resp = await client.post(
                "/multichat/completions",
                data=body(),
                headers={"content-type": "application/json"},
            )
            text = await resp.text()
            frames = [
                jsonutil.loads(block[len("data: ") :])
                for block in text.split("\n\n")
                if block.startswith("data: ") and "[DONE]" not in block
            ]
            return [
                f for f in frames if f.get("object") == "multichat.consensus"
            ]

        frames_a, frames_b = await asyncio.gather(one(), one())
        for frames in (frames_a, frames_b):
            assert frames, "expected consensus frames"
            final = frames[-1]["confidence"]
            assert abs(sum(float(v) for v in final.values()) - 1.0) < 1e-5

    go(with_client(app, run))


# -- per-row embedding memoization (cache/EmbeddingCache) ---------------------


def test_embed_cache_memoizes_rows(embedder):
    from llm_weighted_consensus_tpu.cache import EmbeddingCache

    metrics = Metrics()
    batcher = DeviceBatcher(
        embedder,
        metrics,
        window_ms=5.0,
        embed_cache=EmbeddingCache(60, 1 << 20),
    )

    async def run():
        a = await batcher.embed(["alpha text", "beta text"])
        b = await batcher.embed(["alpha text", "beta text"])
        return a, b

    (emb_a, tok_a), (emb_b, tok_b) = go(run())
    # second call is pure cache: identical rows, zero extra dispatches
    np.testing.assert_array_equal(np.asarray(emb_a), np.asarray(emb_b))
    assert tok_a == tok_b == embedder.token_count(["alpha text", "beta text"])
    assert metrics.snapshot()["series"]["device:batch:embed"]["count"] == 1
    stats = metrics.snapshot()["embed_cache"]
    assert stats["hits"] == 2 and stats["entries"] == 2
    # and matches the uncached reference numerically
    ref = embedder.embed_texts(["alpha text", "beta text"])
    np.testing.assert_allclose(np.asarray(emb_a), ref, atol=1e-5)


def test_embed_cache_partial_hit_assembles_correctly(embedder):
    from llm_weighted_consensus_tpu.cache import EmbeddingCache

    batcher = DeviceBatcher(
        embedder, window_ms=5.0, embed_cache=EmbeddingCache(60, 1 << 20)
    )

    async def run():
        await batcher.embed(["x row", "y row"])
        return await batcher.embed(["y row", "z row"])  # hit + miss mix

    emb, tokens = go(run())
    ref = embedder.embed_texts(["y row", "z row"])
    np.testing.assert_allclose(np.asarray(emb), ref, atol=1e-5)
    assert tokens == embedder.token_count(["y row", "z row"])


def test_embed_cache_collapses_inflight_duplicates(embedder):
    from llm_weighted_consensus_tpu.cache import EmbeddingCache

    metrics = Metrics()
    batcher = DeviceBatcher(
        embedder,
        metrics,
        window_ms=20.0,
        embed_cache=EmbeddingCache(60, 1 << 20),
    )

    async def run():
        return await asyncio.gather(
            *(batcher.embed(["same row"]) for _ in range(4))
        )

    results = go(run())
    for emb, tokens in results:
        np.testing.assert_array_equal(
            np.asarray(emb), np.asarray(results[0][0])
        )
        assert tokens == results[0][1]
    # 4 concurrent identical rows -> ONE device row computed
    util = metrics.snapshot()["device_batcher"]
    assert util["items"] == 1
    assert metrics.snapshot()["embed_cache"]["inflight_collapses"] == 3


def test_embed_cache_duplicate_rows_in_one_request(embedder):
    from llm_weighted_consensus_tpu.cache import EmbeddingCache

    metrics = Metrics()
    batcher = DeviceBatcher(
        embedder,
        metrics,
        window_ms=5.0,
        embed_cache=EmbeddingCache(60, 1 << 20),
    )

    async def run():
        return await batcher.embed(["dup row", "dup row"])

    emb, tokens = go(run())
    np.testing.assert_array_equal(np.asarray(emb[0]), np.asarray(emb[1]))
    # token accounting still counts BOTH rows (public contract unchanged)
    assert tokens == embedder.token_count(["dup row", "dup row"])
    assert metrics.snapshot()["device_batcher"]["items"] == 1


# -- overload: bounded queue, deadline shed, departed callers (PR 4) ----------


def test_queue_bound_fails_fast_with_overloaded(embedder):
    from llm_weighted_consensus_tpu.errors import OverloadedError

    metrics = Metrics()
    batcher = DeviceBatcher(
        embedder, metrics, window_ms=100.0, max_queue_depth=2
    )

    async def run():
        t1 = asyncio.ensure_future(batcher.embed(["a"]))
        t2 = asyncio.ensure_future(batcher.embed(["b"]))
        await asyncio.sleep(0)  # both submitted; queue is at its bound
        with pytest.raises(OverloadedError) as ei:
            await batcher.embed(["c"])
        assert ei.value.shed_reason == "batcher_queue_full"
        assert ei.value.status() == 503
        # queued work is unaffected by the shed
        (e1, n1), (e2, n2) = await asyncio.gather(t1, t2)
        assert e1.shape[0] == 1 and e2.shape[0] == 1

    go(run())
    util = batcher.utilization()
    assert util["shed_queue_full"] == 1
    assert util["max_queue_depth"] == 2
    snap = metrics.snapshot()["series"]
    assert snap["device:shed:queue_full"]["errors"] == 1


def test_deadline_shed_before_dispatch_is_504(embedder):
    from llm_weighted_consensus_tpu.errors import DeadlineExceededError
    from llm_weighted_consensus_tpu.resilience import Deadline

    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=20.0)

    async def run():
        token = Deadline(0.0005).activate()  # dead long before the window
        try:
            with pytest.raises(DeadlineExceededError) as ei:
                await batcher.embed(["too late"])
            assert ei.value.status() == 504
        finally:
            Deadline.deactivate(token)
        # no deadline active -> same batcher keeps serving
        emb, tokens = await batcher.embed(["in time"])
        assert emb.shape[0] == 1 and tokens > 0

    go(run())
    assert batcher.shed_deadline == 1
    snap = metrics.snapshot()["series"]
    assert snap["device:shed:deadline"]["errors"] == 1


def test_cancelled_caller_drops_item_before_dispatch(embedder):
    """Regression (ISSUE PR 4 satellite): a departed caller — task
    cancellation, or the GeneratorExit a client disconnect throws into a
    streaming generator — must not leave its item to burn device time.
    ``_submit`` cancels the future on the way out and ``_shed_group``
    drops done futures before the group dispatches."""
    metrics = Metrics()
    batcher = DeviceBatcher(embedder, metrics, window_ms=40.0)

    async def run():
        t = asyncio.ensure_future(batcher.embed(["abandoned"]))
        await asyncio.sleep(0.005)  # submitted, still inside the window
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        assert await batcher.drain(2.0)

    go(run())
    assert batcher.cancelled_items == 1
    # the whole group was cancelled callers: nothing reached the device
    assert batcher.utilization()["dispatches"] == 0
    assert "device:batch:embed" not in metrics.snapshot()["series"]


def test_drain_waits_for_queued_and_inflight_work(embedder):
    batcher = DeviceBatcher(embedder, Metrics(), window_ms=10.0)

    async def run():
        assert batcher.idle()
        t = asyncio.ensure_future(batcher.embed(["queued"]))
        await asyncio.sleep(0)
        assert not batcher.idle()
        assert await batcher.drain(5.0) is True
        assert batcher.idle()
        emb, _ = await t
        assert emb.shape[0] == 1

    go(run())


def test_watchdog_brackets_batcher_dispatches(embedder):
    from llm_weighted_consensus_tpu.resilience import DeviceWatchdog

    wd = DeviceWatchdog(60_000.0)  # generous: must never trip here
    batcher = DeviceBatcher(embedder, Metrics(), window_ms=5.0, watchdog=wd)

    async def run():
        await asyncio.gather(
            batcher.embed(["one"]), batcher.embed(["two"])
        )

    go(run())
    assert wd.dispatches >= 1
    assert wd.snapshot()["active_dispatches"] == 0  # every begin ended
    assert wd.healthy() is True
