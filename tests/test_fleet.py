"""Fleet tier (fleet/): consistent-hash ownership, cross-replica
single-flight leases, peer-to-peer cache fetch over the replay wire
format, drain-time hot-set handoff, and the serialized-executable
(AOT) store that makes a joining replica warm in seconds.

The multi-replica tests run REAL aiohttp servers on localhost ports —
each "replica" is a full gateway app with its own FakeTransport, score
cache, and FleetCoordinator, sharing a static roster — so the
exactly-one-upstream and degrade-to-local acceptance criteria are
asserted against the actual peer protocol, not mocks of it.
"""

import asyncio
import os
import random
import time
from types import SimpleNamespace

import pytest

from llm_weighted_consensus_tpu import archive, registry
from llm_weighted_consensus_tpu.ballot import PrefixTree
from llm_weighted_consensus_tpu.cache import (
    CacheStore,
    ScoreCache,
    score_fingerprint,
)
from llm_weighted_consensus_tpu.clients.chat import (
    ApiBase,
    BackoffPolicy,
    DefaultChatClient,
)
from llm_weighted_consensus_tpu.clients.score import ScoreClient
from llm_weighted_consensus_tpu.fleet import (
    FleetClient,
    FleetConfig,
    FleetCoordinator,
    FleetFaultPlan,
    FleetMembership,
    LeaseTable,
    PeerHealth,
    clean_chunk_objs,
)
from llm_weighted_consensus_tpu.serve import build_app
from llm_weighted_consensus_tpu.serve.config import Config
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.utils import jsonutil

from fakes import FakeTransport, Script, chunk_obj

SEED = 11
NO_RETRY = BackoffPolicy(max_elapsed_ms=0)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def ballot_keys(n):
    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, n, 20)
    return {idx: k for k, idx in tree.key_indices(rng)}


JUDGES = {"llms": [{"model": "j1"}]}


def score_body(**overrides):
    body = {
        "messages": [{"role": "user", "content": "q"}],
        "model": JUDGES,
        "choices": ["first", "second"],
    }
    body.update(overrides)
    return body


def winning_script():
    keys = ballot_keys(2)
    return Script([chunk_obj(f"pick {keys[1]}", finish="stop")])


def fp_of(body):
    return score_fingerprint(ScoreParams.from_json_obj(body))


# -- membership / ownership ring ----------------------------------------------


def fleet_cfg(self_url, peers, **kw):
    return FleetConfig(self_url=self_url, peers=list(peers), **kw)


URLS = ["http://10.0.0.1:5000", "http://10.0.0.2:5000", "http://10.0.0.3:5000"]


def test_ring_agrees_across_replicas():
    # ownership must be a pure function of (roster, key): every replica,
    # hashing independently, routes a fingerprint to the same owner
    rings = [FleetMembership(fleet_cfg(u, URLS)) for u in URLS]
    for i in range(64):
        owners = {m.owner(f"fp-{i}") for m in rings}
        assert len(owners) == 1
        assert owners.pop() in URLS


def test_ring_balance_and_share():
    m = FleetMembership(fleet_cfg(URLS[0], URLS))
    counts = {u: 0 for u in URLS}
    for i in range(600):
        counts[m.owner(f"key-{i}")] += 1
    for u in URLS:
        # 64 vnodes per peer keeps every share well away from 0 and 1
        assert 0.15 < counts[u] / 600 < 0.55, counts
    shares = [
        FleetMembership(fleet_cfg(u, URLS)).owned_share() for u in URLS
    ]
    assert abs(sum(shares) - 1.0) < 1e-9


def test_ring_stability_on_departure():
    # the consistent-hash property the drain handoff relies on: removing
    # one peer only moves the keys that peer owned
    full = FleetMembership(fleet_cfg(URLS[0], URLS))
    gone = URLS[2]
    shrunk = FleetMembership(fleet_cfg(URLS[0], URLS[:2]))
    moved = stayed = 0
    for i in range(300):
        key = f"key-{i}"
        before = full.owner(key)
        if before == gone:
            moved += 1
        else:
            assert shrunk.owner(key) == before
            stayed += 1
    assert moved > 0 and stayed > 0


def test_owner_excluding_self_is_the_post_departure_owner():
    me = URLS[1]
    mine = FleetMembership(fleet_cfg(me, URLS))
    without_me = FleetMembership(
        fleet_cfg(URLS[0], [u for u in URLS if u != me])
    )
    for i in range(200):
        key = f"key-{i}"
        assert mine.owner_excluding_self(key) == without_me.owner(key)
    # a roster of one has nowhere to hand off to
    alone = FleetMembership(fleet_cfg(me, [me]))
    assert alone.owner_excluding_self("any") is None


def test_peers_file_roster_reloads_on_mtime(tmp_path):
    roster = tmp_path / "peers.txt"
    roster.write_text("# fleet roster\nhttp://a:1/\n\nhttp://b:2\n")
    now = [100.0]
    m = FleetMembership(
        FleetConfig(self_url="http://a:1", peers_file=str(roster)),
        clock=lambda: now[0],
    )
    assert m.peers == ["http://a:1", "http://b:2"]
    roster.write_text("http://a:1\nhttp://b:2\nhttp://c:3\n")
    os.utime(roster, (time.time() + 5, time.time() + 5))
    # inside the reload interval the old roster is served
    now[0] += 0.5
    assert m.peers == ["http://a:1", "http://b:2"]
    now[0] += 1.0
    assert m.peers == ["http://a:1", "http://b:2", "http://c:3"]
    assert m.reloads == 1
    # a transiently missing file must NOT empty the fleet
    roster.unlink()
    now[0] += 2.0
    assert m.peers == ["http://a:1", "http://b:2", "http://c:3"]


def test_config_fleet_knobs_and_validation():
    base = {"SCORE_CACHE_TTL": "60", "FLEET_SELF": "http://a:1/"}
    c = Config.from_env(
        dict(base, FLEET_PEERS="http://a:1/, http://b:2 ,")
    )
    fc = c.fleet_config()
    assert fc is not None
    assert fc.self_url == "http://a:1"
    assert fc.peers == ["http://a:1", "http://b:2"]
    assert Config.from_env({}).fleet_config() is None
    with pytest.raises(ValueError, match="mutually exclusive"):
        Config.from_env(
            dict(base, FLEET_PEERS="http://a:1", FLEET_PEERS_FILE="/p")
        )
    with pytest.raises(ValueError, match="FLEET_SELF is not"):
        Config.from_env(
            {"SCORE_CACHE_TTL": "60", "FLEET_PEERS": "http://a:1"}
        )
    with pytest.raises(ValueError, match="no roster"):
        Config.from_env(base)
    with pytest.raises(ValueError, match="not in FLEET_PEERS"):
        Config.from_env(dict(base, FLEET_PEERS="http://b:2"))
    with pytest.raises(ValueError, match="SCORE_CACHE_TTL"):
        Config.from_env(
            {"FLEET_SELF": "http://a:1", "FLEET_PEERS": "http://a:1"}
        )
    with pytest.raises(ValueError, match="FLEET_LEASE_MILLIS"):
        Config.from_env(
            dict(base, FLEET_PEERS="http://a:1", FLEET_LEASE_MILLIS="0")
        )


# -- lease table --------------------------------------------------------------


def test_lease_grant_wait_publish():
    async def run():
        t = LeaseTable(10000)
        granted, fut = t.acquire("fp", "http://a:1")
        assert granted and fut is None
        granted, fut = t.acquire("fp", "http://b:2")
        assert not granted and fut is not None
        t.publish("fp")
        assert await t.wait(fut, 1.0) is True
        assert t.active() == 0
        assert t.stats()["granted"] == 1
        assert t.stats()["waits"] == 1
        assert t.stats()["published"] == 1

    go(run())


def test_lease_release_wakes_waiters_with_none():
    async def run():
        t = LeaseTable(10000)
        t.acquire("fp", "http://a:1")
        _, fut = t.acquire("fp", "http://b:2")
        t.release("fp", "http://nobody:9")  # wrong holder: no-op
        assert not fut.done()
        t.release("fp", "http://a:1")
        assert await t.wait(fut, 1.0) is None
        # the slot is free again
        granted, _ = t.acquire("fp", "http://b:2")
        assert granted

    go(run())


def test_lease_expiry_regrants_and_resolves_old_future():
    async def run():
        now = [0.0]
        t = LeaseTable(1000, clock=lambda: now[0])
        t.acquire("fp", "http://a:1")
        _, fut = t.acquire("fp", "http://b:2")
        now[0] = 1.5
        granted, _ = t.acquire("fp", "http://b:2")
        assert granted  # the dead holder's lease expired
        assert t.expirations == 1
        assert await t.wait(fut, 1.0) is None

    go(run())


def test_lease_same_holder_reclaim_extends():
    async def run():
        now = [0.0]
        t = LeaseTable(1000, clock=lambda: now[0])
        t.acquire("fp", "http://a:1")
        now[0] = 0.8
        granted, fut = t.acquire("fp", "http://a:1")
        assert granted and fut is None  # a retry keeps its own lease
        now[0] = 1.3  # past the ORIGINAL expiry, inside the extension
        assert t.holder_future("fp") is not None
        assert t.remaining_sec("fp") == pytest.approx(0.5)

    go(run())


def test_lease_wait_timeout_does_not_kill_shared_future():
    async def run():
        t = LeaseTable(10000)
        t.acquire("fp", "http://a:1")
        _, fut = t.acquire("fp", "http://b:2")
        assert await t.wait(fut, 0.01) is None  # timed out
        assert not fut.cancelled()  # other waiters still hold it
        t.publish("fp")
        assert await t.wait(fut, 1.0) is True

    go(run())


# -- wire-side replay admission guard -----------------------------------------


def recorded_chunks():
    """A real clean record: run one scored request and read the cache."""
    cache = ScoreCache(60, 1 << 20)
    transport = FakeTransport([winning_script()])
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        cache=cache,
    )
    params = ScoreParams.from_json_obj(score_body())

    async def run():
        stream = await score.create_streaming(None, params)
        async for _ in stream:
            pass

    go(run())
    record = cache.get(score_fingerprint(params))
    assert record is not None
    return record


def test_wire_guard_accepts_a_clean_record():
    record = recorded_chunks()
    assert clean_chunk_objs(record) == record


def test_wire_guard_rejects_degraded_and_errored_records():
    record = recorded_chunks()
    degraded = jsonutil.loads(jsonutil.dumps(record))
    degraded[0]["degraded"] = True
    assert clean_chunk_objs(degraded) is None
    errored = jsonutil.loads(jsonutil.dumps(record))
    errored[0]["choices"][0]["error"] = {"message": "judge failed"}
    assert clean_chunk_objs(errored) is None


def test_wire_guard_rejects_garbage():
    assert clean_chunk_objs(None) is None
    assert clean_chunk_objs([]) is None
    assert clean_chunk_objs("not a list") is None
    assert clean_chunk_objs([1, 2]) is None
    assert clean_chunk_objs([{"not": "a chunk"}]) is None


# -- store: TTL override + hot entries ----------------------------------------


def test_put_ttl_override_clamped_never_extended():
    now = [0.0]
    store = CacheStore(10.0, 1 << 20, clock=lambda: now[0])
    store.put("short", "v", 1, ttl_sec=2.0)
    store.put("long", "v", 1, ttl_sec=99.0)  # clamped to the store TTL
    store.put("dead", "v", 1, ttl_sec=0.0)  # already expired: dropped
    assert store.get("dead") is None
    now[0] = 3.0
    assert store.get("short") is None
    assert store.get("long") == "v"
    now[0] = 11.0
    assert store.get("long") is None


def test_hot_entries_mru_first_and_live_only():
    now = [0.0]
    store = CacheStore(10.0, 1 << 20, clock=lambda: now[0])
    for i in range(4):
        store.put(f"fp{i}", f"v{i}", 1)
    store.get("fp1")  # touch: fp1 becomes MRU
    now[0] = 5.0
    store.put("fresh", "vf", 1)
    entries = store.hot_entries(3)
    assert [fp for fp, _, _ in entries] == ["fresh", "fp1", "fp3"]
    for _, _, remaining in entries:
        assert 0 < remaining <= 10.0
    now[0] = 12.0  # originals expired, "fresh" still live
    assert [fp for fp, _, _ in store.hot_entries(10)] == ["fresh"]


# -- coordinator (no HTTP) ----------------------------------------------------


def make_coordinator(self_url, peers, **kw):
    fleet = FleetCoordinator(fleet_cfg(self_url, peers, **kw))
    fleet.cache = ScoreCache(60, 1 << 20)
    return fleet


def test_begin_with_empty_roster_is_local():
    async def run():
        fleet = make_coordinator("http://a:1", [])
        assert await fleet.begin("fp") == ("local", None)
        assert fleet.local_fallbacks == 1

    go(run())


def test_owner_lease_lifecycle():
    async def run():
        fleet = make_coordinator("http://a:1", ["http://a:1"])
        assert await fleet.begin("fp") == ("lease", None)
        assert fleet.leases.active() == 1
        fleet.publish("fp", [{"any": "chunks"}])
        assert fleet.leases.active() == 0
        assert fleet.publishes == 1
        # abandon releases too
        assert await fleet.begin("fp2") == ("lease", None)
        fleet.abandon("fp2")
        assert fleet.leases.active() == 0

    go(run())


def test_owner_waits_out_dead_holder_then_takes_the_lease():
    async def run():
        fleet = make_coordinator(
            "http://a:1", ["http://a:1"], lease_millis=80.0
        )
        granted, _ = fleet.leases.acquire("fp", "http://dead:9")
        assert granted
        t0 = time.monotonic()
        plan, chunks = await fleet.begin("fp")
        # the dead remote holder's lease expired; we compute with a
        # fresh lease of our own — today's behavior, one TTL later
        assert (plan, chunks) == ("lease", None)
        assert time.monotonic() - t0 < 2.0
        assert fleet.leases.expirations == 1

    go(run())


def test_owner_waiter_wakes_on_publish_with_a_hit():
    async def run():
        fleet = make_coordinator("http://a:1", ["http://a:1"])
        fleet.leases.acquire("fp", "http://peer:2")
        record = [{"fake": "record"}]

        async def remote_publishes():
            await asyncio.sleep(0.02)
            fleet.cache.put("fp", record, 64)
            fleet.leases.publish("fp")

        task = asyncio.get_event_loop().create_task(remote_publishes())
        plan, chunks = await fleet.begin("fp")
        await task
        assert plan == "hit"
        assert chunks == record
        assert fleet.peer_hits == 1

    go(run())


def test_unreachable_owner_degrades_to_local_and_breaks():
    async def run():
        from aiohttp.test_utils import unused_port

        dead = f"http://127.0.0.1:{unused_port()}"
        me = "http://127.0.0.2:1"
        fleet = make_coordinator(
            me, [me, dead], fetch_timeout_millis=300.0
        )
        # a fingerprint the dead peer owns
        fp = next(
            f"fp-{i}"
            for i in range(1000)
            if fleet.membership.owner(f"fp-{i}") == dead
        )
        try:
            for _ in range(3):
                assert await fleet.begin(fp) == ("local", None)
            assert fleet.peer_errors >= 1
            assert fleet.local_fallbacks >= 3
            # connect failures trip the per-peer breaker: later begins
            # stop paying the connect attempt entirely
            snap = fleet.client.breakers.snapshot()
            assert any(b.get("state") == "open" for b in snap.values()), snap
            # ...and the third consecutive failure QUARANTINES the dead
            # peer: its keys re-home, so the next begin owns fp locally
            # (a lease, not a fallback) instead of paying for the corpse
            assert fleet.health.quarantined() == [dead]
            status, _ = await fleet.begin(fp)
            assert status == "lease"
            assert fleet.membership.owner(fp) == me
        finally:
            await fleet.close()

    go(run())


# -- multi-replica integration (real servers, real peer protocol) -------------


def make_node(scripts, self_url, peers, lease_ms, fetch_ms, **cfg_kw):
    cache = ScoreCache(60, 1 << 20)
    cfg = fleet_cfg(
        self_url,
        peers,
        lease_millis=lease_ms,
        fetch_timeout_millis=fetch_ms,
        **cfg_kw,
    )
    fleet = FleetCoordinator(cfg)
    fleet.cache = cache
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        cache=cache,
        fleet=fleet,
    )
    app = build_app(chat, score, fleet=fleet)
    return SimpleNamespace(
        url=self_url, cache=cache, fleet=fleet, transport=transport, app=app
    )


async def start_cluster(
    scripts_by_node, lease_ms=10000.0, fetch_ms=2000.0, **cfg_kw
):
    from aiohttp.test_utils import TestClient, TestServer, unused_port

    ports = [unused_port() for _ in scripts_by_node]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    nodes = []
    for i, scripts in enumerate(scripts_by_node):
        node = make_node(
            scripts, urls[i], urls, lease_ms, fetch_ms, **cfg_kw
        )
        node.client = TestClient(TestServer(node.app, port=ports[i]))
        await node.client.start_server()
        nodes.append(node)
    return nodes


async def stop_cluster(nodes):
    for node in nodes:
        await node.fleet.close()
        await node.client.close()


def post_json(client, path, obj, headers=None):
    h = {"content-type": "application/json"}
    h.update(headers or {})
    return client.post(path, data=jsonutil.dumps(obj), headers=h)


def owner_of(nodes, body):
    url = nodes[0].fleet.membership.owner(fp_of(body))
    return next(n for n in nodes if n.url == url)


def body_owned_by(nodes, node):
    for i in range(1000):
        body = score_body(
            messages=[{"role": "user", "content": f"q-{i}"}]
        )
        if owner_of(nodes, body) is node:
            return body
    raise AssertionError("no fingerprint landed on the node")


def test_peer_fetch_serves_owner_hit_without_upstream():
    async def run():
        nodes = await start_cluster([[winning_script()], []])
        try:
            body = body_owned_by(nodes, nodes[0])
            body["stream"] = True
            resp = await post_json(
                nodes[0].client, "/score/completions", body
            )
            assert resp.status == 200
            miss = await resp.read()
            # the non-owner fetches the record from the owner and
            # replays it byte-identically — zero upstream calls
            resp = await post_json(
                nodes[1].client, "/score/completions", body
            )
            assert resp.status == 200
            assert await resp.read() == miss
            assert nodes[1].transport.requests == []
            assert nodes[1].fleet.peer_hits == 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_hot_key_stampede_hits_upstream_exactly_once():
    async def run():
        # every replica COULD serve upstream (each has a script), so
        # only the lease protocol explains a fan-out count of one
        nodes = await start_cluster(
            [[winning_script()] for _ in range(3)]
        )
        try:
            body = score_body(stream=True)
            resps = await asyncio.gather(
                *(
                    post_json(n.client, "/score/completions", body)
                    for n in nodes
                )
            )
            bodies = [await r.read() for r in resps]
            assert all(r.status == 200 for r in resps)
            assert bodies[0] == bodies[1] == bodies[2]
            upstream = sum(len(n.transport.requests) for n in nodes)
            assert upstream == 1, upstream
        finally:
            await stop_cluster(nodes)

    go(run())


def test_owner_death_degrades_to_local_compute():
    async def run():
        nodes = await start_cluster(
            [[], [winning_script()]], fetch_ms=500.0
        )
        try:
            body = body_owned_by(nodes, nodes[0])
            await nodes[0].client.close()  # the owner dies
            resp = await post_json(
                nodes[1].client, "/score/completions", body
            )
            assert resp.status == 200
            assert len(nodes[1].transport.requests) == 1
            assert nodes[1].fleet.local_fallbacks >= 1
            assert nodes[1].fleet.peer_errors >= 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_dead_lease_holder_expires_to_local_compute():
    async def run():
        nodes = await start_cluster(
            [[], [winning_script()]], lease_ms=150.0
        )
        try:
            body = body_owned_by(nodes, nodes[0])
            fp = fp_of(body)
            # a replica claimed the lease on the owner, then died
            # without publishing or releasing
            granted, _ = nodes[0].fleet.leases.acquire(fp, "http://dead:9")
            assert granted
            t0 = time.monotonic()
            resp = await post_json(
                nodes[1].client, "/score/completions", body
            )
            assert resp.status == 200
            # bounded by the lease TTL, then local compute — never stuck
            assert time.monotonic() - t0 < 5.0
            assert len(nodes[1].transport.requests) == 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_peer_wait_sheds_on_propagated_deadline():
    async def run():
        # the lease TTL is 20s; the request deadline is 1.5s.  The
        # "sheds instead of blocking" contract: peer legs spend at most
        # half the remaining budget, so local compute still fits
        nodes = await start_cluster(
            [[], [winning_script()]], lease_ms=20000.0
        )
        try:
            body = body_owned_by(nodes, nodes[0])
            fp = fp_of(body)
            nodes[0].fleet.leases.acquire(fp, "http://hung:9")
            t0 = time.monotonic()
            resp = await post_json(
                nodes[1].client,
                "/score/completions",
                body,
                headers={"x-deadline-ms": "1500"},
            )
            assert resp.status == 200
            assert time.monotonic() - t0 < 3.0  # nowhere near the TTL
            assert len(nodes[1].transport.requests) == 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_degraded_publish_is_rejected_at_the_wire():
    record = recorded_chunks()

    async def run():
        nodes = await start_cluster([[]])
        try:
            node = nodes[0]
            dirty = jsonutil.loads(jsonutil.dumps(record))
            dirty[0]["degraded"] = True
            resp = await node.client.put(
                "/fleet/v1/entry/fp-dirty",
                data=jsonutil.dumps(
                    {"holder": "http://evil:1", "chunks": dirty}
                ),
            )
            assert resp.status == 422
            assert node.fleet.rejected_publishes == 1
            assert node.cache.get("fp-dirty") is None
            # the clean original is accepted and servable
            resp = await node.client.put(
                "/fleet/v1/entry/fp-clean",
                data=jsonutil.dumps(
                    {"holder": "http://peer:1", "chunks": record}
                ),
            )
            assert resp.status == 200
            resp = await node.client.get("/fleet/v1/entry/fp-clean")
            assert resp.status == 200
            assert (await resp.json())["chunks"] == record
        finally:
            await stop_cluster(nodes)

    go(run())


def test_dirty_publish_releases_the_lease():
    record = recorded_chunks()
    dirty = jsonutil.loads(jsonutil.dumps(record))
    dirty[0]["choices"][0]["error"] = {"message": "boom"}

    async def run():
        nodes = await start_cluster([[]])
        try:
            node = nodes[0]
            node.fleet.leases.acquire("fp", "http://peer:1")
            resp = await node.client.put(
                "/fleet/v1/entry/fp",
                data=jsonutil.dumps(
                    {"holder": "http://peer:1", "chunks": dirty}
                ),
            )
            assert resp.status == 422
            # waiters must not ride out the TTL hoping for a record
            # that was refused
            assert node.fleet.leases.active() == 0
        finally:
            await stop_cluster(nodes)

    go(run())


def test_drain_handoff_moves_hot_set_to_new_owner():
    async def run():
        from llm_weighted_consensus_tpu.serve.lifecycle import (
            Lifecycle,
            health_handlers,
        )

        nodes = await start_cluster([[winning_script()], []])
        try:
            a, b = nodes
            body = body_owned_by(nodes, a)
            fp = fp_of(body)
            resp = await post_json(a.client, "/score/completions", body)
            assert resp.status == 200
            assert b.cache.get(fp) is None
            lifecycle = Lifecycle(
                fleet=a.fleet, caches=[a.cache], drain_timeout_ms=2000.0
            )
            clean = await lifecycle.begin_drain()
            assert clean
            # the hot entry now lives on its post-drain owner, which
            # can serve it without ever seeing the original request
            assert b.cache.get(fp) is not None
            assert b.fleet.handoff_received == 1
            assert a.fleet.handoff_sent == 1
            assert a.fleet.handoff_accepted == 1
            assert lifecycle.snapshot()["fleet_handoff_entries"] == 1
            resp = await post_json(b.client, "/score/completions", body)
            assert resp.status == 200
            assert b.transport.requests == []
            # /readyz surfaces membership while READY (checked on a
            # fresh lifecycle: the drained one reports 503/stopped)
            _, readyz = health_handlers(
                Lifecycle(fleet=b.fleet, caches=[b.cache])
            )
            ready_body = jsonutil.loads((await readyz(None)).text)
            assert ready_body["fleet"]["self"] == b.url
            assert set(ready_body["fleet"]["peers"]) == {a.url, b.url}
        finally:
            await stop_cluster(nodes)

    go(run())


def test_handoff_rejects_dirty_and_expired_entries():
    record = recorded_chunks()
    dirty = jsonutil.loads(jsonutil.dumps(record))
    dirty[0]["degraded"] = True

    async def run():
        nodes = await start_cluster([[]])
        try:
            node = nodes[0]
            resp = await node.client.post(
                "/fleet/v1/handoff",
                data=jsonutil.dumps(
                    {
                        "from": "http://peer:1",
                        "entries": [
                            {"fp": "ok", "chunks": record, "ttl_sec": 5.0},
                            {"fp": "bad", "chunks": dirty, "ttl_sec": 5.0},
                            {"fp": "old", "chunks": record, "ttl_sec": 0},
                        ],
                    }
                ),
            )
            assert resp.status == 200
            assert (await resp.json())["accepted"] == 1
            assert node.fleet.handoff_received == 1
            assert node.fleet.handoff_rejected == 2
            assert node.cache.get("ok") is not None
            assert node.cache.get("bad") is None
            assert node.cache.get("old") is None
        finally:
            await stop_cluster(nodes)

    go(run())


def test_fleet_metrics_sections_and_prom_families():
    async def run():
        nodes = await start_cluster([[winning_script()], []])
        try:
            body = body_owned_by(nodes, nodes[0])
            await post_json(nodes[0].client, "/score/completions", body)
            resp = await post_json(
                nodes[1].client, "/score/completions", body
            )
            assert resp.status == 200
            metrics = await (await nodes[1].client.get("/metrics")).json()
            fleet = metrics["fleet"]
            assert fleet["peer_fetch"]["hits"] == 1
            assert fleet["membership"]["self"] == nodes[1].url
            assert 0.0 < fleet["membership"]["owned_share"] < 1.0
            prom = await (
                await nodes[1].client.get("/metrics?format=prometheus")
            ).text()
            assert 'lwc_fleet_peer_fetches_total{result="hits"} 1' in prom
            assert "lwc_fleet_leases " in prom
        finally:
            await stop_cluster(nodes)

    go(run())


# -- failure plane: fault plan, ring epochs, takeover, quarantine -------------


def test_fault_plan_seeded_determinism_is_pair_local():
    # the contract the split-brain drill's replay leans on: each ordered
    # pair's fault sequence depends only on (seed, pair, pair-ordinal),
    # never on how the event loop interleaves other pairs
    probs = {"blackhole": 0.25, "slow": 0.25, "5xx": 0.2}
    a, b, c = "http://a:1", "http://b:1", "http://c:1"
    grouped = FleetFaultPlan(seed=7, probabilities=probs)
    seq_ab = [grouped.next_fault(a, b) for _ in range(8)]
    seq_ac = [grouped.next_fault(a, c) for _ in range(8)]
    interleaved = FleetFaultPlan(seed=7, probabilities=probs)
    mixed = [
        interleaved.next_fault(*pair) for _ in range(8) for pair in ((a, b), (a, c))
    ]
    assert seq_ab == mixed[0::2]
    assert seq_ac == mixed[1::2]
    # and a different seed draws a different sequence
    reseeded = FleetFaultPlan(seed=8, probabilities=probs)
    assert [reseeded.next_fault(a, b) for _ in range(8)] != seq_ab


def test_fault_plan_parse_script_scope_and_errors():
    plan = FleetFaultPlan.parse("seed=3,blackhole=0.5,slow_ms=40,to=http://b:1")
    assert plan.seed == 3 and plan.slow_ms == 40.0
    assert plan.probabilities["blackhole"] == 0.5
    # to= scopes sampled faults to legs TOWARD the listed peers
    assert all(
        plan.next_fault("http://a:1", "http://c:1") is None for _ in range(20)
    )

    scripted = FleetFaultPlan.parse("script=connect|ok|5xx")
    assert scripted.next_fault("http://a:1", "http://b:1") == "connect"
    assert scripted.next_fault("http://a:1", "http://b:1") is None
    assert scripted.next_fault("http://a:1", "http://b:1") == "5xx"
    assert scripted.next_fault("http://a:1", "http://b:1") is None  # past end
    # the script replays PER PAIR: a fresh pair starts from slot 0
    assert scripted.next_fault("http://a:1", "http://c:1") == "connect"

    for bad in ("nope", "bogus=1", "script=warp", "seed"):
        with pytest.raises(ValueError):
            FleetFaultPlan.parse(bad)


def test_fault_plan_partition_and_heal():
    a, b, c = "http://a:1", "http://b:1", "http://c:1"
    plan = FleetFaultPlan()
    plan.partition([[a], [b, c]])
    assert plan.next_fault(a, b) == "blackhole"
    assert plan.next_fault(b, a) == "blackhole"
    assert plan.next_fault(c, a) == "blackhole"
    assert plan.next_fault(b, c) is None  # same component: healthy
    plan.heal()
    assert plan.next_fault(a, b) is None
    assert plan.injected["blackhole"] == 3
    assert plan.snapshot()["rules"] == 0


def test_peer_5xx_opens_breaker():
    # regression: _request used to record breaker SUCCESS for any
    # answered request, so a peer stuck returning 500s never tripped it
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    async def run():
        app = web.Application()

        async def boom(request):
            return web.json_response(
                {"error": {"kind": "internal"}}, status=500
            )

        app.router.add_get("/fleet/v1/entry/{fp}", boom)
        server_client = TestClient(TestServer(app))
        await server_client.start_server()
        peer = str(server_client.make_url("")).rstrip("/")
        fc = FleetClient("http://me:1", fetch_timeout_ms=500.0)
        try:
            for _ in range(3):
                assert await fc.fetch_entry(peer, "fp") == ("error", None)
            assert fc.peer_5xx == 3
            states = {
                s["state"] for s in fc.breakers.snapshot().values()
            }
            assert "open" in states, states
            # open breaker: the next leg sheds without touching the wire
            assert await fc.fetch_entry(peer, "fp") == ("error", None)
            assert fc.peer_5xx == 3
        finally:
            await fc.close()
            await server_client.close()

    go(run())


def test_publish_without_running_loop_closes_coro_quietly():
    # _spawn must not call the deprecated get_event_loop() fallback: with
    # no running loop the coroutine is closed, not leaked or crashed
    fleet = make_coordinator(URLS[0], URLS)
    fp = next(
        f"fp-{i}"
        for i in range(1000)
        if fleet.membership.owner(f"fp-{i}") != URLS[0]
    )
    fleet.publish(fp, [])
    fleet.abandon(fp)
    assert fleet._tasks == set()


def test_publish_routes_on_pinned_view_across_roster_reload(tmp_path):
    async def run():
        me = "http://10.0.0.1:5000"
        other = "http://10.0.0.2:5000"
        peers_file = tmp_path / "peers.txt"
        peers_file.write_text(f"{me}\n")
        now = [0.0]
        cfg = FleetConfig(self_url=me, peers_file=str(peers_file))
        fleet = FleetCoordinator(cfg, clock=lambda: now[0])
        fleet.cache = ScoreCache(60, 1 << 20)
        # a fingerprint that moves to `other` once the roster grows
        probe = FleetMembership(fleet_cfg(me, [me, other]))
        fp = next(
            f"fp-{i}" for i in range(1000) if probe.owner(f"fp-{i}") == other
        )
        epoch0 = fleet.membership.epoch
        assert await fleet.begin(fp) == ("lease", None)
        assert fleet.leases.active() == 1
        # the roster grows MID-REQUEST: the live owner flips away from us
        peers_file.write_text(f"{me}\n{other}\n")
        os.utime(peers_file, (1e9, 1e9))
        now[0] += 2.0
        assert fleet.membership.owner(fp) == other
        assert fleet.membership.epoch > epoch0
        # the publish still routes on the view PINNED at begin: the local
        # lease retires (waiters wake) and nothing is pushed at the new
        # owner, who never granted anything
        fleet.publish(fp, [])
        assert fleet.leases.active() == 0
        assert fleet.leases.published == 1
        assert fleet._tasks == set()
        await fleet.close()

    go(run())


def test_split_roster_divergence_degrades_to_local():
    async def run():
        nodes = await start_cluster([[winning_script()], [winning_script()]])
        try:
            a, b = nodes
            body = body_owned_by(nodes, b)  # a must cross the wire
            # b's roster diverges (a staggered peers-file read: b now
            # believes it is alone) — its digest no longer matches a's
            b.fleet.membership._set_peers([b.url])
            resp = await post_json(a.client, "/score/completions", body)
            assert resp.status == 200
            assert a.fleet.ring_divergences == 1
            assert b.fleet.ring_rejects >= 1
            # a served the request itself rather than trusting the
            # divergent owner's lease table
            assert len(a.transport.requests) == 1
            assert a.fleet.local_fallbacks >= 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_dead_holder_early_takeover_and_late_publish_reconciled():
    record = recorded_chunks()

    async def run():
        from aiohttp.test_utils import unused_port

        nodes = await start_cluster(
            [[winning_script()]],
            lease_ms=30000.0,
            fetch_ms=300.0,
            probe_millis=60.0,
        )
        try:
            node = nodes[0]
            dead = f"http://127.0.0.1:{unused_port()}"
            body = body_owned_by(nodes, node)
            fp = fp_of(body)
            granted, _ = node.fleet.leases.acquire(fp, dead)
            assert granted
            t0 = time.monotonic()
            resp = await post_json(node.client, "/score/completions", body)
            assert resp.status == 200
            elapsed = time.monotonic() - t0
            # the waiter probed the dead holder and stole the lease in
            # ~one probe interval — nowhere near the 30 s TTL
            assert elapsed < 5.0, elapsed
            assert node.fleet.early_takeovers == 1
            assert node.fleet.leases.takeovers == 1
            assert len(node.transport.requests) == 1
            # the "dead" holder's publish arrives after the steal: a
            # LATE publish — cached and counted, but it must not retire
            # the live claimant's state or be double-counted as fresh
            resp = await node.client.put(
                f"/fleet/v1/entry/{fp}",
                data=jsonutil.dumps({"holder": dead, "chunks": record}),
                headers={"content-type": "application/json"},
            )
            assert resp.status == 200
            out = await resp.json()
            assert out["accepted"] is True
            assert out["retired"] is False
            assert node.fleet.leases.late_publishes == 1
            assert node.cache.get(fp) is not None
        finally:
            await stop_cluster(nodes)

    go(run())


def test_breaker_open_holder_is_taken_over_without_probe():
    async def run():
        nodes = await start_cluster(
            [[winning_script()], []], lease_ms=30000.0, probe_millis=60.0
        )
        try:
            a, b = nodes
            body = body_owned_by(nodes, a)
            fp = fp_of(body)
            # b holds a's lease while a's breaker for b is OPEN from
            # recent transport failures; b's server is alive and would
            # answer a ping, so only the breaker verdict explains a
            # takeover this fast
            granted, _ = a.fleet.leases.acquire(fp, b.url)
            assert granted
            breaker = a.fleet.client.breakers.get(b.url, "fleet")
            for _ in range(3):
                breaker.record_failure()
            t0 = time.monotonic()
            resp = await post_json(a.client, "/score/completions", body)
            assert resp.status == 200
            assert time.monotonic() - t0 < 5.0
            assert a.fleet.early_takeovers == 1
            assert len(a.transport.requests) == 1
        finally:
            await stop_cluster(nodes)

    go(run())


def test_peer_health_flap_quarantine_and_probe_readmission():
    now = [0.0]
    health = PeerHealth(3, 100.0, clock=lambda: now[0])
    peer = "http://p:1"
    # an up/down/up flapper never reaches 3 CONSECUTIVE failures, but
    # the transition count in the window trips the flap detector
    for ok in [True, False, True, False, True, False, True]:
        health.record(peer, ok)
    assert health.quarantined() == [peer]
    # once quarantined, traffic outcomes stop mattering — only probes
    # gate re-admission, at most one per interval
    health.record(peer, True)
    assert health.quarantined() == [peer]
    assert health.probes_due() == []  # interval not yet elapsed
    now[0] += 0.2
    assert health.probes_due() == [peer]
    assert health.probes_due() == []  # stamped: no double probe
    health.record_probe(peer, False)
    assert health.quarantined() == [peer]
    now[0] += 0.2
    assert health.probes_due() == [peer]
    health.record_probe(peer, True)
    assert health.quarantined() == []
    assert health.stats()["readmissions"] == 1
    # disabled (FLEET_QUARANTINE_FAILURES=0): inert, never quarantines
    off = PeerHealth(0, 100.0, clock=lambda: now[0])
    for _ in range(10):
        off.record(peer, False)
    assert off.quarantined() == []


def test_ring_digest_agrees_and_quarantine_stays_local():
    rings = [FleetMembership(fleet_cfg(u, URLS)) for u in URLS]
    assert len({m.ring_digest() for m in rings}) == 1
    m = rings[0]
    epoch0 = m.epoch
    m.set_quarantined({URLS[1]})
    assert m.epoch == epoch0 + 1
    # quarantine re-homes the sick peer's keys but does NOT change the
    # digest: it is local knowledge, not roster disagreement — otherwise
    # noticing a sick peer would make every healthy pair look divergent
    assert m.ring_digest() == rings[1].ring_digest()
    assert URLS[1] not in {m.owner(f"fp-{i}") for i in range(256)}
    m.set_quarantined({URLS[1]})  # no change: no rebuild, same epoch
    assert m.epoch == epoch0 + 1
    m.set_quarantined(set())
    assert m.epoch == epoch0 + 2
    assert URLS[1] in {m.owner(f"fp-{i}") for i in range(256)}


def test_departure_view_matches_bruteforce_ring_removal():
    import xxhash

    me = URLS[0]
    m = FleetMembership(fleet_cfg(me, URLS))

    def brute(fp):
        # the pre-optimization algorithm: nearest clockwise vnode over
        # every peer but self, O(peers x vnodes) per key
        key = xxhash.xxh3_64_intdigest(fp.encode())
        best = None
        for peer in m.peers:
            if peer == me:
                continue
            for i in range(m.config.vnodes):
                point = xxhash.xxh3_64_intdigest(f"{peer}#{i}".encode())
                distance = (point - key) % (1 << 64)
                if best is None or distance < best[0]:
                    best = (distance, peer)
        return best[1]

    for i in range(128):
        assert m.owner_excluding_self(f"fp-{i}") == brute(f"fp-{i}")


def test_handoff_pushes_targets_concurrently_under_partition():
    record = recorded_chunks()

    async def run():
        nodes = await start_cluster([[], [], []], fetch_ms=400.0)
        try:
            a = nodes[0]
            departure = a.fleet.membership.departure_view()
            fps = {}
            for i in range(1000):
                fps.setdefault(departure.owner(f"fp-{i}"), f"fp-{i}")
                if len(fps) == 2:
                    break
            for fp in fps.values():
                a.cache.put_chunks(fp, record)
            # both targets blackholed: each push burns the full fetch
            # budget — concurrently, or the drain pays it once per target
            plan = FleetFaultPlan()
            plan.partition([[a.url], [nodes[1].url, nodes[2].url]])
            a.fleet.client.fault_plan = plan
            t0 = time.monotonic()
            accepted = await a.fleet.handoff(a.cache)
            elapsed = time.monotonic() - t0
            assert accepted == 0
            assert elapsed < 0.75, elapsed  # ~one budget, not two
            assert plan.injected["blackhole"] == 2
        finally:
            await stop_cluster(nodes)

    go(run())


# -- AOT executable store -----------------------------------------------------


def test_aot_store_digest_namespaces_and_fail_open(tmp_path):
    from llm_weighted_consensus_tpu.models.aot_store import (
        AotStore,
        _key_name,
    )

    a = AotStore(str(tmp_path), meta={"jax": "1", "backend": "cpu"})
    b = AotStore(str(tmp_path), meta={"jax": "2", "backend": "cpu"})
    # any environment difference lands in a fresh namespace:
    # invalidation by construction
    assert a.dir != b.dir
    key = ("vote1", 4, 16, True)
    assert a.load(key) is None  # missing: silent miss, not a failure
    assert a.load_failures == 0
    os.makedirs(a.dir, exist_ok=True)
    with open(os.path.join(a.dir, _key_name(key)), "wb") as f:
        f.write(b"not a pickle")
    assert a.load(key) is None  # corrupt: fail open, count it
    assert a.load_failures == 1


def test_aot_warmup_serializes_then_restores_without_compiling(tmp_path):
    jax = pytest.importorskip("jax")
    import numpy as np

    from llm_weighted_consensus_tpu.models import configs
    from llm_weighted_consensus_tpu.models.aot_store import AotStore
    from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder

    N, S, R = 4, 16, 2

    def make(store_root):
        e = TpuEmbedder(
            "test-tiny", config=configs.TEST_TINY, max_tokens=32, seed=3
        )
        e.aot_store = AotStore(str(store_root), meta=e.aot_cache_meta())
        return e

    e1 = make(tmp_path)
    timings1 = e1.aot_warmup([(N, S)], r_buckets=[R])
    assert len(timings1) == 4
    assert e1.aot_store.saves == 4
    assert e1.jit_stats()["aot_restored"] == 0

    # a new replica sharing the artifact dir deserializes every bucket
    e2 = make(tmp_path)
    timings2 = e2.aot_warmup([(N, S)], r_buckets=[R])
    assert e2.aot_store.loads == 4
    assert e2.jit_stats()["aot_restored"] == 4
    assert all("[deserialized]" in label for label, _ in timings2)

    # the acceptance: deserialize-only warmup serves warmed buckets with
    # ZERO new jit specializations, and computes the same numbers
    stats0 = e2.jit_stats()
    rng = np.random.default_rng(12)
    ids = rng.integers(3, configs.TEST_TINY.vocab_size, (N, S)).astype(
        np.int32
    )
    mask = np.ones((N, S), np.int32)
    got = np.asarray(e2.consensus_confidence_tokens(ids, mask))
    ref = np.asarray(e1.consensus_confidence_tokens(ids, mask))
    stats1 = e2.jit_stats()
    assert stats1["specializations"] == stats0["specializations"]
    np.testing.assert_allclose(got, ref, atol=1e-6)
