"""Hostile-upstream ingest hardening (ISSUE 19): the byte-budget plane
and the host memory governor, drilled end to end.

Three layers, seeded and deterministic throughout:

* the SSE parser byte budgets against the committed corpus under
  ``tests/fixtures/ingest/`` (trip boundaries, usable-after-trip);
* the four hostile fault kinds (``giant_line``, ``newline_less_flood``,
  ``oversized_unary``, ``binary_garbage``) through the real client
  stack, including how cap trips compose with the breaker, hedging and
  quorum (a capped leg degrades like a 499 — excluded, never fatal);
* the J=8 x N=64 gateway drill: a seeded hostile fault matrix against
  ``POST /score/completions`` asserting zero crashes, degraded final
  frames with per-judge cap-trip entries, and bounded RSS while the
  offered hostile payload is orders of magnitude larger;
* the MemGuard drills named by the acceptance criteria: soft pressure
  shrinks budgets, hard pressure sheds ``503 shed_reason=memory``,
  recovery is hysteretic, and /readyz carries ``degraded_mem``.
"""

import asyncio
import json
import pathlib
import random
from types import SimpleNamespace

import pytest

from llm_weighted_consensus_tpu import archive, registry
from llm_weighted_consensus_tpu.ballot import PrefixTree, branch_limit
from llm_weighted_consensus_tpu.cache import ScoreCache
from llm_weighted_consensus_tpu.clients import sse
from llm_weighted_consensus_tpu.clients.chat import (
    ApiBase,
    BackoffPolicy,
    DefaultChatClient,
)
from llm_weighted_consensus_tpu.clients.score import ScoreClient
from llm_weighted_consensus_tpu.errors import (
    BreakerOpenError,
    IngestCapError,
)
from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.resilience import (
    BreakerConfig,
    BreakerRegistry,
    FaultInjectionTransport,
    FaultPlan,
    HedgePolicy,
    ResiliencePolicy,
)
from llm_weighted_consensus_tpu.resilience import memguard as memguard_mod
from llm_weighted_consensus_tpu.resilience.admission import (
    AdmissionConfig,
    AdmissionController,
    shed_response,
)
from llm_weighted_consensus_tpu.resilience.memguard import (
    LEVEL_HARD,
    LEVEL_OK,
    LEVEL_SOFT,
    MemGuard,
    read_rss_bytes,
    resolve_watermarks,
)
from llm_weighted_consensus_tpu.serve import build_app
from llm_weighted_consensus_tpu.serve.config import Config
from llm_weighted_consensus_tpu.serve.lifecycle import (
    Lifecycle,
    health_handlers,
)
from llm_weighted_consensus_tpu.serve.metrics import (
    Metrics,
    register_overload,
    render_prometheus,
)
from llm_weighted_consensus_tpu.types.chat_request import (
    ChatCompletionCreateParams,
    UserMessage,
)
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.utils import jsonutil

from fakes import FakeTransport, Script, chunk_obj

SEED = 19
NO_RETRY = BackoffPolicy(max_elapsed_ms=0)
AB1 = [ApiBase("https://a.example", "key-a")]
AB = [
    ApiBase("https://a.example", "key-a"),
    ApiBase("https://b.example", "key-b"),
]

CORPUS = pathlib.Path(__file__).parent / "fixtures" / "ingest"

TEXTS = ["answer alpha", "answer beta", "answer gamma"]


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def corpus_bytes(name):
    return (CORPUS / name).read_bytes()


def chat_params():
    return ChatCompletionCreateParams(
        messages=[UserMessage(content="hi")], model="fake-model"
    )


def healthy_script():
    return Script([chunk_obj("a"), chunk_obj("b", finish="stop")])


def ballot_keys(n):
    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, n, branch_limit(None))
    return {idx: key for key, idx in tree.key_indices(rng)}


def judge_script(key, **kw):
    return Script(
        [
            chunk_obj("I pick ", model="up-model"),
            chunk_obj(f"{key} as best.", model="up-model", finish="stop"),
        ],
        **kw,
    )


def score_params(model_json):
    return ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": "pick the best"}],
            "model": model_json,
            "choices": TEXTS,
        }
    )


def inline_model(judges):
    model = ModelBase.from_json_obj({"llms": judges}).into_model_validate()
    return model, {"llms": [llm.base.to_json_obj() for llm in model.llms]}


async def _stream_items(c, p=None):
    stream = await c.create_streaming(None, p or chat_params())
    return [item async for item in stream]


def is_cap_error_obj(error) -> bool:
    """Walk a per-judge ResponseError (attr form) or a JSON error entry
    (dict form) for the nested ``kind == "ingest_cap"`` marker — the
    same walk clients/score.py does when flagging a frame degraded."""
    msg = error.get("message") if isinstance(error, dict) else getattr(
        error, "message", None
    )
    while isinstance(msg, dict):
        if msg.get("kind") == "ingest_cap":
            return True
        msg = msg.get("error")
    return False


# -- corpus x SSEParser byte budgets ------------------------------------------


def parse_all(raw, **caps):
    parser = sse.SSEParser(**caps)
    events = list(parser.feed(raw))
    tail = parser.flush()
    if tail is not None:
        events.append(tail)
    return events


def feed_until_trip(parser, raw):
    events = []
    try:
        for event in parser.feed(raw):
            events.append(event)
    except IngestCapError as e:
        return events, e
    return events, None


def test_corpus_giant_line_trips_event_cap_after_completed_events():
    raw = corpus_bytes("giant_line.sse")
    ref = parse_all(raw)  # uncapped reference: e1, e2, giant, e3
    assert len(ref) == 4 and len(ref[2]) >= 8192

    parser = sse.SSEParser(max_event_bytes=4096)
    events, trip = feed_until_trip(parser, raw)
    # events completed before the offending line still surface
    assert events == ref[:2]
    assert trip is not None and trip.what == "sse_event"
    assert trip.observed_bytes >= 8192
    assert parser.cap_trips == 1
    # the oversized open event was dropped, the rest of the buffered
    # stream parses cleanly: the parser stays usable after a trip
    more, trip2 = feed_until_trip(parser, b"")
    assert trip2 is None
    assert more == [ref[3]]


def test_corpus_newline_less_flood_trips_buffer_cap():
    raw = corpus_bytes("newline_less_flood.bin")
    parser = sse.SSEParser(max_buffer_bytes=4096)
    events, trip = feed_until_trip(parser, raw)
    assert events == []
    assert trip is not None and trip.what == "sse_buffer"
    assert trip.observed_bytes == len(raw)
    # residue dropped: bounded AND usable for subsequent feeds
    assert len(parser._buffer) == 0
    assert list(parser.feed(b"data: ok\n\n")) == ["ok"]


def test_corpus_binary_garbage_is_bounded_and_survivable():
    raw = corpus_bytes("binary_garbage.bin")
    parser = sse.SSEParser(max_buffer_bytes=4096, max_event_bytes=4096)
    events, trip = feed_until_trip(parser, raw)
    # 4 KiB of seeded garbage never exceeds a 4 KiB budget (trips are
    # strictly-greater); whatever junk lines it forms are not data:
    # fields, so nothing hostile surfaces as an event either
    assert trip is None
    assert parser.cap_trips == 0
    assert len(parser._buffer) <= 4096
    # a real stream resumes after the garbage residue flushes through
    list(parser.feed(b"\n"))
    assert list(parser.feed(b"data: ok\n\n"))[-1] == "ok"


def test_corpus_interleaved_parser_usable_after_trip():
    raw = corpus_bytes("interleaved.sse")
    ref = parse_all(raw)
    giant = next(i for i, e in enumerate(ref) if len(e) >= 8192)

    parser = sse.SSEParser(max_event_bytes=4096)
    events, trip = feed_until_trip(parser, raw)
    assert events == ref[:giant]
    assert trip is not None and trip.what == "sse_event"
    more, trip2 = feed_until_trip(parser, b"")
    assert trip2 is None
    # everything after the giant line — including [DONE] — still parses
    assert more == ref[giant + 1 :]
    assert more[-1] == "[DONE]"


def test_make_parser_caps_flow_through():
    p = sse.make_parser(max_buffer_bytes=5, max_event_bytes=7)
    assert p.max_buffer_bytes == 5
    assert p.max_event_bytes == 7


# -- hostile fault kinds through the chat client ------------------------------


def hostile_client(faults, *, flood_bytes, n_scripts=1, **kw):
    plan = FaultPlan(script=list(faults), flood_bytes=flood_bytes)
    transport = FakeTransport([healthy_script() for _ in range(n_scripts)])
    kw.setdefault("backoff", NO_RETRY)
    client = DefaultChatClient(
        FaultInjectionTransport(transport, plan), AB1, **kw
    )
    return client, transport, plan


def test_giant_line_fault_trips_event_cap_mid_stream():
    client, _, _ = hostile_client(
        ["giant_line"],
        flood_bytes=8192,
        sse_max_event_bytes=4096,
        judge_stream_max_bytes=1 << 20,
    )
    items = go(_stream_items(client))
    assert items[0].choices[0].delta.content == "a"  # stream committed
    assert isinstance(items[-1], IngestCapError)
    assert items[-1].what == "sse_event"


def test_newline_less_flood_fault_trips_stream_budget():
    # the cumulative per-judge budget is checked before the parser sees
    # the bytes: a flood bigger than the leg budget trips judge_stream
    client, _, _ = hostile_client(
        ["newline_less_flood"],
        flood_bytes=8192,
        judge_stream_max_bytes=4096,
    )
    items = go(_stream_items(client))
    assert isinstance(items[-1], IngestCapError)
    assert items[-1].what == "judge_stream"


def test_newline_less_flood_fault_trips_residue_cap():
    # with the leg budget generous, the same flood accumulates as
    # newline-less parser residue and trips sse_buffer instead
    client, _, _ = hostile_client(
        ["newline_less_flood"],
        flood_bytes=8192,
        sse_max_event_bytes=4096,
        judge_stream_max_bytes=1 << 20,
    )
    items = go(_stream_items(client))
    assert isinstance(items[-1], IngestCapError)
    assert items[-1].what == "sse_buffer"


def test_oversized_unary_fault_trips_body_cap():
    client, _, _ = hostile_client(
        ["oversized_unary"],
        flood_bytes=8192,
        judge_stream_max_bytes=4096,
    )
    with pytest.raises(IngestCapError) as ei:
        go(_stream_items(client))
    assert ei.value.what == "unary_body"
    assert ei.value.observed_bytes == 8192


def test_oversized_unary_corpus_body_trips_without_fault_plan():
    # same trip straight off the wire: a non-2xx whose body is the
    # committed 8 KiB blob, no injector involved
    transport = FakeTransport(
        [Script(status=503, body=corpus_bytes("oversized_unary.bin"))]
    )
    client = DefaultChatClient(
        transport, AB1, backoff=NO_RETRY, judge_stream_max_bytes=4096
    )
    with pytest.raises(IngestCapError) as ei:
        go(_stream_items(client))
    assert ei.value.what == "unary_body"


def test_binary_garbage_fault_stream_survives():
    def run_once():
        client, _, _ = hostile_client(
            ["binary_garbage"], flood_bytes=8192
        )
        return go(_stream_items(client))

    items = run_once()
    assert items[0].choices[0].delta.content == "a"
    # garbage decodes to junk (ignored lines / replacement chars), never
    # a cap trip at the serving budgets, and never a crash: the stream
    # reaches its terminator
    assert not any(isinstance(i, IngestCapError) for i in items)
    # seeded: a second run produces the identical item shape
    again = run_once()
    assert [type(i).__name__ for i in again] == [
        type(i).__name__ for i in items
    ]


def test_fault_plan_parses_flood_bytes_key():
    plan = FaultPlan.parse("seed=7,giant_line=0.5,flood_bytes=1024")
    assert plan.flood_bytes == 1024
    assert plan.probabilities["giant_line"] == 0.5


def test_hostile_fault_matrix_is_deterministic():
    probs = {
        "giant_line": 0.25,
        "newline_less_flood": 0.25,
        "oversized_unary": 0.2,
        "binary_garbage": 0.2,
    }
    def draw(seed):
        plan = FaultPlan(seed=seed, probabilities=probs)
        return [plan.next_fault() for _ in range(100)]

    draws = [draw(9), draw(9)]
    assert draws[0] == draws[1]
    assert set(probs) <= set(filter(None, draws[0]))
    assert draw(10) != draws[0]


def test_mid_utf8_cuts_never_corrupt_events():
    # byte-at-a-time feeding cuts every multi-byte character: the parser
    # buffers raw bytes and decodes per completed line, so the event
    # survives intact (and errors="replace" bounds the worst case)
    raw = "data: voilà ✓ — ¡hostile! ✓\n\n".encode("utf-8")
    parser = sse.SSEParser(max_buffer_bytes=4096, max_event_bytes=4096)
    events = []
    for i in range(len(raw)):
        events.extend(parser.feed(raw[i : i + 1]))
    assert events == ["voilà ✓ — ¡hostile! ✓"]
    assert parser.cap_trips == 0


def test_redos_shaped_ballot_content_scans_linearly():
    import re
    import time as time_mod

    from llm_weighted_consensus_tpu.ballot.vote import extract_vote
    from llm_weighted_consensus_tpu.errors import InvalidContentError

    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, 64, branch_limit(None))
    key_indices = tree.key_indices(rng)
    keys = [k for k, _ in key_indices]
    with_ticks, without_ticks = PrefixTree.regex_patterns(keys)
    w = re.compile(with_ticks)
    wo = re.compile(without_ticks)
    # adversarial judge output: ~200 KiB of near-miss key prefixes (the
    # shape that would explode a backtracking alternation); the patterns
    # are pure literal alternations, so the scan must stay linear
    near_miss = "`" + keys[0][1:-1][:-1] + "X` "
    hostile = near_miss * (200_000 // len(near_miss))
    t0 = time_mod.perf_counter()
    try:
        extract_vote(tree, w, wo, 64, hostile)
    except InvalidContentError:
        pass  # near-misses may legitimately match no key at all
    elapsed = time_mod.perf_counter() - t0
    assert elapsed < 1.0, f"ballot scan took {elapsed:.3f}s on 200KiB"
    # a real backticked key after the hostile prefix still lands one-hot
    idx = key_indices[0][1]
    vote = extract_vote(tree, w, wo, 64, hostile + f"I pick {keys[0]}.")
    assert vote[idx] == 1


# -- cap trips x resilience machinery -----------------------------------------


def test_cap_trips_count_against_the_breaker():
    # a pre-commit trip (the oversized unary body) is an attempt-level
    # failure, so it lands on the upstream's breaker exactly like a
    # transport error; post-commit mid-stream trips ride the degraded
    # final-frame path instead (the quorum test below)
    t = {"now": 0.0}
    policy = ResiliencePolicy(
        breakers=BreakerRegistry(
            BreakerConfig(
                threshold=1.0, window=2, min_samples=2, cooldown_ms=5000
            ),
            clock=lambda: t["now"],
        )
    )
    plan = FaultPlan(
        script=["oversized_unary", "oversized_unary"], flood_bytes=8192
    )
    transport = FakeTransport([healthy_script()])
    client = DefaultChatClient(
        FaultInjectionTransport(transport, plan),
        AB1,
        backoff=NO_RETRY,
        resilience=policy,
        judge_stream_max_bytes=4096,
    )
    for _ in range(2):
        with pytest.raises(IngestCapError):
            go(_stream_items(client))
    assert plan.requests == 2
    # a flooding upstream is a failing upstream: the breaker opens and
    # the third attempt is refused locally (the plan sees no request)
    with pytest.raises(BreakerOpenError):
        go(_stream_items(client))
    assert plan.requests == 2
    key = "https://a.example|fake-model"
    assert policy.snapshot()["breakers"][key]["state"] == "open"
    # cooldown -> half-open probe -> healthy slot -> closed again
    t["now"] += 6.0
    items = go(_stream_items(client))
    assert items[0].choices[0].delta.content == "a"
    assert policy.snapshot()["breakers"][key]["state"] == "closed"


def make_hostile_score_client(scripts, plan, policy, api_bases=None, **kw):
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        FaultInjectionTransport(transport, plan),
        api_bases or AB1,
        backoff=NO_RETRY,
        resilience=policy,
        **kw,
    )
    client = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        resilience=policy,
    )
    return client, transport


async def collect(client, params):
    stream = await client.create_streaming(None, params)
    return [item async for item in stream]


def test_capped_judge_is_excluded_like_a_499_and_frame_degrades():
    keys = ballot_keys(3)
    policy = ResiliencePolicy()
    model, model_json = inline_model(
        [
            {"model": "judge-a", "weight": {"type": "static", "weight": 2}},
            {"model": "judge-b", "weight": {"type": "static", "weight": 1}},
            {"model": "judge-c", "weight": {"type": "static", "weight": 1}},
        ]
    )
    # the plan is positional (one slot per upstream request, in fan-out
    # order); flood a WEIGHT-1 judge so the surviving 2+1 decide alone
    flood_pos = next(
        i
        for i, llm in enumerate(model.llms)
        if llm.base.model in ("judge-b", "judge-c")
    )
    faults = [None] * len(model.llms)
    faults[flood_pos] = "giant_line"
    plan = FaultPlan(script=faults, flood_bytes=8192)
    client, transport = make_hostile_score_client(
        [judge_script(keys[1]) for _ in model.llms],
        plan,
        policy,
        sse_max_event_bytes=4096,
        judge_stream_max_bytes=1 << 20,
    )
    items = go(collect(client, score_params(model_json)))
    final = items[-1]
    # the two surviving judges settle the vote; the capped leg is an
    # error entry, not a fatality
    cand = {c.index: c for c in final.choices if c.index < 3}
    assert cand[1].confidence is not None
    tail_errors = [
        c.error
        for c in final.choices
        if c.index >= 3 and c.error is not None
    ]
    assert len(tail_errors) == 1
    assert is_cap_error_obj(tail_errors[0])
    # marked degraded (never cacheable) and counted
    assert getattr(final, "degraded", False) is True
    assert policy.counters["ingest_cap_degraded"] == 1


def test_flooding_slow_primary_loses_hedge_race_cleanly():
    keys = ballot_keys(3)
    policy = ResiliencePolicy(hedge=HedgePolicy(delay_ms=30.0))
    _, model_json = inline_model(
        [{"model": "judge-a", "weight": {"type": "static", "weight": 1}}]
    )
    # primary: slow first chunk AND a giant-line flood behind it; the
    # hedged backup (next api base) wins long before the flood lands
    plan = FaultPlan(script=["giant_line", None], flood_bytes=8192)
    client, transport = make_hostile_score_client(
        [judge_script(keys[1], delays={0: 1.0}), judge_script(keys[1])],
        plan,
        policy,
        api_bases=AB,
        sse_max_event_bytes=4096,
    )
    items = go(collect(client, score_params(model_json)))
    assert len(transport.requests) == 2  # primary + one hedged backup
    assert policy.counters["hedge_launched"] == 1
    assert policy.counters["hedge_won"] == 1
    final = items[-1]
    # the losing primary's flood was discarded with the race: the final
    # frame is clean, the vote tallied once
    assert not getattr(final, "degraded", False)
    assert not any(
        c.error is not None and is_cap_error_obj(c.error)
        for c in final.choices
    )


# -- the seeded J=8 x N=64 hostile gateway drill ------------------------------

DRILL_JUDGES = 8
DRILL_REQUESTS = 64


def sse_events(text):
    return [
        block[len("data: ") :]
        for block in text.split("\n\n")
        if block.startswith("data: ")
    ]


def frame_cap_entries(frame):
    """Per-judge cap-trip error entries in a final-frame JSON object."""
    return [
        c
        for c in frame.get("choices", [])
        if isinstance(c.get("error"), dict) and is_cap_error_obj(c["error"])
    ]


def test_hostile_ingest_drill_j8_n64_bounded_and_degraded():
    keys = ballot_keys(3)
    plan = FaultPlan(
        seed=SEED,
        probabilities={"giant_line": 0.35, "newline_less_flood": 0.35},
        flood_bytes=1 << 20,
    )
    _, model_json = inline_model(
        [
            {"model": f"judge-{i}", "weight": {"type": "static", "weight": 1}}
            for i in range(DRILL_JUDGES)
        ]
    )
    scripts = [
        judge_script(keys[1])
        for _ in range(DRILL_JUDGES * DRILL_REQUESTS)
    ]
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        FaultInjectionTransport(transport, plan),
        AB1,
        backoff=NO_RETRY,
        # caps sized for trip diversity against the 1 MiB floods: a
        # complete giant line trips the event cap, a newline-less flood
        # trips the residue cap, both far under the leg stream budget
        sse_max_event_bytes=64 * 1024,
        judge_stream_max_bytes=16 * 1024 * 1024,
    )
    policy = ResiliencePolicy()
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        resilience=policy,
    )
    app = build_app(chat, score)

    body = {
        "stream": True,
        "messages": [{"role": "user", "content": "pick the best"}],
        "model": model_json,
        "choices": TEXTS,
    }

    async def run(client):
        statuses, degraded, cap_entries, vote_failures = [], 0, 0, 0
        for _ in range(DRILL_REQUESTS):
            resp = await client.post(
                "/score/completions",
                data=jsonutil.dumps(body),
                headers={"content-type": "application/json"},
            )
            statuses.append(resp.status)
            events = sse_events(await resp.text())
            assert events[-1] == "[DONE]"
            frames = [json.loads(e) for e in events[:-1]]
            final = frames[-1]
            if "choices" not in final:
                # every judge leg faulted: a valid all_votes_failed
                # error envelope, which is degradation, not a crash
                assert final["message"]["error"]["kind"] == "all_votes_failed"
                vote_failures += 1
                continue
            entries = frame_cap_entries(final)
            if entries:
                cap_entries += len(entries)
                # cap-tripped legs always mark the frame degraded
                assert final.get("degraded") is True
            if final.get("degraded"):
                degraded += 1
        return statuses, degraded, cap_entries, vote_failures

    rss_before = read_rss_bytes()

    async def drive():
        from aiohttp.test_utils import TestClient, TestServer

        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await run(client)
        finally:
            await client.close()

    statuses, degraded, cap_entries, vote_failures = go(drive())
    rss_after = read_rss_bytes()

    # zero crashes: every request answered the streaming protocol
    assert statuses == [200] * DRILL_REQUESTS
    # the matrix bit: both hostile kinds fired, every leg consumed
    snap = plan.snapshot()
    assert snap["requests"] == DRILL_JUDGES * DRILL_REQUESTS
    assert snap["injected"]["giant_line"] >= 1
    assert snap["injected"]["newline_less_flood"] >= 1
    # degraded frames with per-judge cap-trip entries actually happened
    assert degraded >= 1
    assert cap_entries >= DRILL_JUDGES  # many legs tripped across the run
    assert vote_failures < DRILL_REQUESTS  # plenty of requests settled
    # bounded ingest: the injectors OFFERED hundreds of MiB of hostile
    # bytes; the byte budgets stopped reading instead of buffering, so
    # peak RSS growth stays a small constant
    offered = sum(snap["injected"].values()) * plan.flood_bytes
    assert offered > 100 * (1 << 20)
    if rss_before is not None and rss_after is not None:
        assert rss_after - rss_before < 256 * (1 << 20)
    # cache can never hold a degraded result (regression vs replay.py)
    assert policy.counters["ingest_cap_degraded"] == degraded


# -- MemGuard drills (named by the acceptance criteria) -----------------------


def fed(values):
    """An rss_fn fed from a list (the DeviceWatchdog fake-clock idiom)."""
    seq = iter(values)
    return lambda: next(seq)


def test_memguard_drill_soft_pressure_shrinks_budgets():
    cache = ScoreCache(60, 1 << 20)
    sink = SimpleNamespace(capacity=4096)
    adm = AdmissionController(
        AdmissionConfig(max_inflight=8, adaptive=True, min_limit=2)
    )
    mg = MemGuard(1000, 2000, rss_fn=fed([500, 1500]))
    mg.govern(caches=[cache], sinks=[sink], admission=adm)

    assert mg.check() == LEVEL_OK
    assert cache.max_bytes == 1 << 20  # untouched below the watermark

    assert mg.check() == LEVEL_SOFT
    assert cache.max_bytes == (1 << 20) // 2
    assert sink.capacity == 2048
    assert adm.limit == 4.0  # AIMD limit decayed once
    snap = mg.snapshot()
    assert snap["level"] == "soft"
    assert snap["soft_trips"] == 1
    assert mg.degraded is True
    assert mg.gate() is None  # soft pressure still admits everything


def test_memguard_drill_hard_pressure_sheds_503_shed_reason_memory():
    mg = MemGuard(1000, 2000, rss_fn=fed([2500]))
    assert mg.check() == LEVEL_HARD
    assert mg.gate() == "memory"
    assert mg.shedding is True

    adm = AdmissionController(AdmissionConfig(), mem_gate=mg.gate)
    # ALL new work sheds under hard pressure, device-bound or not
    assert adm.try_acquire() == "memory"
    assert adm.try_acquire(device_work=True) == "memory"
    assert adm.shed == {"memory": 2}

    resp = shed_response("memory", 1000.0)
    assert resp.status == 503
    assert resp.headers["Retry-After"] == "1"
    envelope = json.loads(resp.text)
    assert envelope["code"] == 503
    assert envelope["message"]["kind"] == "overloaded"
    assert envelope["message"]["shed_reason"] == "memory"


def test_memguard_drill_hysteretic_recovery():
    cache = SimpleNamespace(max_bytes=1000)
    # soft=1000 hard=2000, recover_fraction=0.9: recovery needs RSS
    # strictly below 900 / 1800 — hovering at the boundary never flaps
    mg = MemGuard(
        1000,
        2000,
        rss_fn=fed([1500, 950, 899, 2500, 1850, 1750, 100]),
        recover_fraction=0.9,
    )
    mg.govern(caches=[cache])

    assert mg.check() == LEVEL_SOFT  # 1500: tripped
    assert cache.max_bytes == 500
    assert mg.check() == LEVEL_SOFT  # 950: below soft, above 0.9*soft
    assert cache.max_bytes == 500  # ...so still shrunk
    assert mg.check() == LEVEL_OK  # 899: truly recovered
    assert cache.max_bytes == 1000  # budget restored
    assert mg.snapshot()["recoveries"] == 1

    assert mg.check() == LEVEL_HARD  # 2500: straight to shedding
    assert mg.gate() == "memory"
    assert mg.check() == LEVEL_HARD  # 1850: above 0.9*hard, still sheds
    assert mg.check() == LEVEL_SOFT  # 1750: admits again, still degraded
    assert mg.gate() is None
    assert mg.check() == LEVEL_OK  # 100: fully recovered
    snap = mg.snapshot()
    assert snap["soft_trips"] == 2
    assert snap["hard_trips"] == 1
    assert snap["recoveries"] == 2


def test_memguard_drill_degraded_mem_on_readyz():
    rss = {"v": 500}
    mg = MemGuard(1000, 2000, rss_fn=lambda: rss["v"])
    lifecycle = Lifecycle(memguard=mg)
    _livez, readyz = health_handlers(lifecycle)

    async def body():
        return json.loads((await readyz(None)).body)

    assert go(body()) == {"ready": True}
    rss["v"] = 1500
    mg.check()
    out = go(body())
    # still 200/ready: in-flight work is finishing, probes keep passing
    assert out["ready"] is True
    assert out["degraded_mem"] is True
    assert out["mem_level"] == "soft"
    rss["v"] = 100
    mg.check()
    assert go(body()) == {"ready": True}


def test_memguard_watermark_resolution(monkeypatch):
    # explicit pair passes through (hard clamped >= soft)
    assert resolve_watermarks(100, 200) == (100, 200)
    assert resolve_watermarks(300, 200) == (300, 300)
    # auto = 80% / 90% of MemTotal
    monkeypatch.setattr(
        memguard_mod, "read_mem_total_bytes", lambda: 1000
    )
    assert resolve_watermarks(0, 0) == (800, 900)
    assert resolve_watermarks(0, 950) == (800, 950)
    # unreadable MemTotal: disabled, never guessed
    monkeypatch.setattr(
        memguard_mod, "read_mem_total_bytes", lambda: None
    )
    assert resolve_watermarks(0, 0) is None


def test_config_memguard_factory():
    assert Config(memguard_enabled=False).memguard() is None
    mg = Config(mem_soft_bytes=100, mem_hard_bytes=200).memguard()
    assert isinstance(mg, MemGuard)
    assert (mg.soft_bytes, mg.hard_bytes) == (100, 200)


def test_memguard_rss_source_reads_this_process():
    rss = read_rss_bytes()
    assert rss is not None and rss > 0


def test_memguard_rides_the_metrics_plane():
    metrics = Metrics()
    mg = MemGuard(1000, 2000, rss_fn=fed([1500]))
    register_overload(metrics, memguard=mg)
    mg.check()
    section = metrics.provider_section("memguard")
    assert section["level"] == "soft"
    assert section["rss_bytes"] == 1500
    text = render_prometheus(metrics)
    assert "lwc_memguard_rss_bytes 1500" in text
    assert "lwc_memguard_level" in text
    assert "lwc_memguard_trips" in text


# -- gateway body caps (client_max_size + 413 envelope) -----------------------


def make_capped_app(max_body_bytes, fleet=None):
    transport = FakeTransport([])
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
    )
    return build_app(
        chat, score, fleet=fleet, max_body_bytes=max_body_bytes
    )


async def with_client(app, fn):
    from aiohttp.test_utils import TestClient, TestServer

    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def test_gateway_oversized_body_413_payload_too_large_envelope():
    app = make_capped_app(1024)

    async def run(client):
        resp = await client.post(
            "/score/completions",
            data=b"x" * 4096,
            headers={"content-type": "application/json"},
        )
        assert resp.status == 413
        envelope = await resp.json()
        assert envelope["code"] == 413
        assert envelope["message"]["kind"] == "payload_too_large"
        # a right-sized (if malformed) body still reaches the handler
        resp = await client.post(
            "/score/completions",
            data=b"{}",
            headers={"content-type": "application/json"},
        )
        assert resp.status == 400

    go(with_client(app, run))


def test_huge_n_body_trips_the_default_cap_with_envelope():
    # MAX_BODY_BYTES=0 keeps aiohttp's own 1 MiB client_max_size rather
    # than lifting the cap — a huge-N request body (thousands of
    # candidates) still gets the structured 413 envelope, never an
    # unbounded read or a stock HTML error page
    app = make_capped_app(0)

    async def run(client):
        huge_n = jsonutil.dumps(
            {
                "model": "fake-model",
                "messages": [{"role": "user", "content": "q"}],
                "choices": [{"text": "c" * 512} for _ in range(4096)],
            }
        ).encode()
        assert len(huge_n) > 1024**2
        resp = await client.post(
            "/score/completions",
            data=huge_n,
            headers={"content-type": "application/json"},
        )
        assert resp.status == 413
        envelope = await resp.json()
        assert envelope["code"] == 413
        assert envelope["message"]["kind"] == "payload_too_large"

    go(with_client(app, run))


def test_fleet_routes_ride_the_same_body_cap():
    from llm_weighted_consensus_tpu.fleet import (
        FleetConfig,
        FleetCoordinator,
    )

    me = "http://127.0.0.1:1"
    fleet = FleetCoordinator(FleetConfig(self_url=me, peers=[me]))
    fleet.cache = ScoreCache(60, 1 << 20)
    app = make_capped_app(1024, fleet=fleet)

    async def run(client):
        resp = await client.post(
            "/fleet/v1/handoff",
            data=b"y" * 4096,
            headers={"content-type": "application/json"},
        )
        assert resp.status == 413
        envelope = await resp.json()
        assert envelope["message"]["kind"] == "payload_too_large"

    try:
        go(with_client(app, run))
    finally:
        go(fleet.close())
