"""Consensus-quality observability (ISSUE 12): per-judge scorecards
(agreement, entropy, hedging, top-1 calibration/ECE, exact weight
share), pairwise Cohen's kappa, windowed drift detection, the
JUDGE_BIAS_PLAN drill seam, the persistent outcome ledger, the
all-judges-failed forced-trace regression, the ledger -> training
round trip, and the seeded end-to-end bias drill over the gateway."""

import asyncio
import json
import math
import random
from decimal import Decimal

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from llm_weighted_consensus_tpu import archive, obs, registry
from llm_weighted_consensus_tpu.clients.chat import (
    ApiBase,
    BackoffPolicy,
    DefaultChatClient,
)
from llm_weighted_consensus_tpu.clients.multichat import MultichatClient
from llm_weighted_consensus_tpu.clients.score import (
    AllVotesFailed,
    ScoreClient,
)
from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.obs import (
    LEDGER_SCHEMA,
    JudgeBallot,
    Outcome,
    OutcomeLedger,
    QualityAggregator,
)
from llm_weighted_consensus_tpu.obs.quality import N_CALIBRATION_BINS
from llm_weighted_consensus_tpu.resilience import JudgeBiasPlan
from llm_weighted_consensus_tpu.serve import Config, build_app
from llm_weighted_consensus_tpu.serve.metrics import (
    KNOWN_PROM_FAMILIES,
    KNOWN_SECTIONS,
    Metrics,
    register_quality,
    render_prometheus,
)
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.utils import jsonutil
from llm_weighted_consensus_tpu.weights.training_table import (
    TrainingTableStore,
)

from fakes import FakeTransport, Script, chunk_obj

SEED = 42
NO_RETRY = BackoffPolicy(max_elapsed_ms=0)
AB = [ApiBase("https://a.example", "key-a")]
TEXTS = ["answer alpha", "answer beta"]


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


@pytest.fixture(autouse=True)
def _fresh_quality():
    # the aggregator is a process-global singleton (like phases); every
    # test starts and ends from a clean, default-configured slate
    obs.reset_quality()
    obs.configure_quality(window=64, drift_threshold=0.25)
    yield
    obs.reset_quality()
    obs.configure_quality(window=64, drift_threshold=0.25)


# -- synthetic outcome helpers ------------------------------------------------


def ballot(model, vote, weight=1, error_code=None, index=0):
    return JudgeBallot(
        model=model,
        model_index=index,
        weight=Decimal(weight),
        vote=vote,
        error_code=error_code,
    )


def outcome(ballots, winner=0, margin=0.5, n=2, **kw):
    weight_sum = sum(
        (b.weight for b in ballots if b.vote is not None), Decimal(0)
    )
    flags = {
        "degraded": False,
        "quorum_degraded": False,
        "all_failed": False,
    }
    flags.update(kw)
    return Outcome(
        winner=winner,
        margin=margin,
        weight_sum=weight_sum,
        n_choices=n,
        trace_id="trace-1",
        judges=ballots,
        **flags,
    )


# -- scorecard math -----------------------------------------------------------


def test_scorecard_rates_and_exact_weight_share():
    agg = QualityAggregator()
    # a (weight 2) always agrees with the winner; b (weight 1) never
    for _ in range(3):
        agg.observe_outcome(
            outcome(
                [
                    ballot("a", [0.8, 0.2], weight=2),
                    ballot("b", [0.1, 0.9], weight=1, index=1),
                ]
            )
        )
    # b additionally abstains, errors, and is cancelled once each
    for code in (None, 500, 499):
        agg.observe_outcome(
            outcome(
                [
                    ballot("a", [0.8, 0.2], weight=2),
                    ballot("b", None, error_code=code, index=1),
                ]
            )
        )
    a = agg.scorecard("a")
    b = agg.scorecard("b")
    assert a["ballots"] == 6 and a["voted"] == 6
    assert a["agreement_rate"] == 1.0
    assert a["hedge_rate"] == 0.0
    assert b["ballots"] == 6 and b["voted"] == 3
    assert b["agreement_rate"] == 0.0
    assert b["abstain_rate"] == round(1 / 6, 4)
    assert b["error_rate"] == round(1 / 6, 4)
    assert b["cancelled_rate"] == round(1 / 6, 4)
    # weight share is Decimal-exact: a contributed 2 of each 3-weight
    # shared panel plus 2 of each 2-weight solo panel
    assert a["weight_share"] == float(
        Decimal(2 * 6) / Decimal(3 * 3 + 2 * 3)
    )
    assert b["weight_share"] == float(Decimal(3) / Decimal(9))
    assert agg.scorecard("nope") is None


def test_entropy_and_hedge_detection():
    agg = QualityAggregator()
    agg.observe_outcome(
        outcome(
            [
                # uniform vote: maximal entropy, and a hedge (top < 0.5)
                ballot("fence-sitter", [0.25, 0.25, 0.25, 0.25]),
                # one-hot vote: zero entropy, no hedge
                ballot("decisive", [1.0, 0.0, 0.0, 0.0], index=1),
            ],
            n=4,
        )
    )
    fence = agg.scorecard("fence-sitter")
    decisive = agg.scorecard("decisive")
    assert fence["entropy_mean"] == 1.0
    assert fence["hedge_rate"] == 1.0
    assert decisive["entropy_mean"] == 0.0
    assert decisive["hedge_rate"] == 0.0


def test_top1_calibration_bins_and_ece():
    agg = QualityAggregator()
    # two confident picks that win, two mild picks that lose
    for _ in range(2):
        agg.observe_outcome(outcome([ballot("j", [0.95, 0.05])], winner=0))
    for _ in range(2):
        agg.observe_outcome(outcome([ballot("j", [0.45, 0.55])], winner=0))
    cal = agg.scorecard("j")["calibration"]
    assert cal["samples"] == 4
    by_le = {row["le"]: row for row in cal["bins"]}
    assert by_le[1.0]["count"] == 2
    assert by_le[1.0]["p_avg"] == 0.95
    assert by_le[1.0]["win_rate"] == 1.0
    assert by_le[0.6]["count"] == 2
    assert by_le[0.6]["p_avg"] == 0.55
    assert by_le[0.6]["win_rate"] == 0.0
    # ECE = 0.5*|0.95-1.0| + 0.5*|0.55-0.0|
    assert cal["ece"] == round(0.5 * 0.05 + 0.5 * 0.55, 4)
    assert len(cal["bins"]) <= N_CALIBRATION_BINS


def test_pairwise_kappa_corrects_for_chance():
    agg = QualityAggregator()
    # a and b always agree, varying their pick; c always disagrees
    for pick in (0, 1, 0, 1):
        votes = {0: [0.9, 0.1], 1: [0.1, 0.9]}
        agg.observe_outcome(
            outcome(
                [
                    ballot("a", votes[pick]),
                    ballot("b", votes[pick], index=1),
                    ballot("c", votes[1 - pick], index=2),
                ],
                winner=pick,
            )
        )
    kappa = agg.snapshot()["pairwise_kappa"]
    assert kappa["a|b"]["ballots"] == 4
    assert kappa["a|b"]["kappa"] == 1.0
    # both picked both candidates half the time but never together:
    # chance predicts 0.5 agreement, observed is 0 -> perfect discord
    assert kappa["a|c"]["kappa"] == -1.0
    assert kappa["b|c"]["kappa"] == -1.0


def test_pairwise_kappa_degenerate_unanimous_panel():
    agg = QualityAggregator()
    for _ in range(3):
        agg.observe_outcome(
            outcome(
                [ballot("a", [1.0, 0.0]), ballot("b", [1.0, 0.0], index=1)]
            )
        )
    # both always pick candidate 0: chance predicts total agreement,
    # the degenerate branch reports perfect (not 0/0) kappa
    assert agg.snapshot()["pairwise_kappa"]["a|b"]["kappa"] == 1.0


# -- drift --------------------------------------------------------------------


def drifted_agg(healthy, sour, window=4, threshold=0.3):
    agg = QualityAggregator(window=window, drift_threshold=threshold)
    for _ in range(healthy):
        agg.observe_outcome(outcome([ballot("j", [1.0, 0.0])]))
    for _ in range(sour):
        agg.observe_outcome(outcome([ballot("j", [0.0, 1.0])]))
    return agg


def test_drift_needs_full_window_and_full_baseline():
    # a cold judge is never flagged, however bad the start looks
    agg = QualityAggregator(window=4, drift_threshold=0.3)
    for _ in range(6):
        agg.observe_outcome(outcome([ballot("j", [0.0, 1.0])]))
    drift = agg.scorecard("j")["drift"]
    assert drift["flagged"] is False
    assert drift["recent_agreement"] == 0.0
    # healthy history but the baseline is still inside the window
    assert drifted_agg(4, 3).scorecard("j")["drift"]["flagged"] is False


def test_drift_flags_agreement_collapse_against_baseline():
    agg = drifted_agg(8, 4)
    drift = agg.scorecard("j")["drift"]
    assert drift["flagged"] is True
    assert drift["recent_agreement"] == 0.0
    assert drift["baseline_agreement"] == 1.0
    assert drift["agreement_drop"] == 1.0
    assert agg.snapshot()["flagged"] == ["j"]
    assert agg.summary()["flagged_judges"] == ["j"]


def test_drift_healthy_judge_stays_unflagged():
    agg = drifted_agg(12, 0)
    drift = agg.scorecard("j")["drift"]
    assert drift["flagged"] is False
    assert drift["agreement_drop"] == 0.0


def test_configure_rebounds_existing_windows():
    agg = drifted_agg(8, 4, window=4)
    agg.configure(window=2, drift_threshold=0.7)
    drift = agg.scorecard("j")["drift"]
    assert drift["window"] == 2 and drift["window_fill"] == 2
    # the shrunken window keeps the 2 newest (sour) ballots; baseline
    # is now 8 healthy + 2 sour = 0.8 agreement, a 0.8 drop
    assert drift["agreement_drop"] == 0.8
    assert drift["flagged"] is True


# -- outcome counters / snapshot / summary ------------------------------------


def test_outcome_counters_and_margin_histogram():
    agg = QualityAggregator()
    agg.observe_outcome(outcome([ballot("j", [1.0, 0.0])], margin=0.4))
    agg.observe_outcome(
        outcome([ballot("j", [1.0, 0.0])], margin=0.2, degraded=True)
    )
    agg.observe_outcome(
        outcome(
            [ballot("j", [1.0, 0.0])],
            margin=0.2,
            degraded=True,
            quorum_degraded=True,
        )
    )
    agg.observe_outcome(
        outcome(
            [ballot("j", None, error_code=500)],
            winner=None,
            margin=None,
            all_failed=True,
        )
    )
    snap = agg.snapshot()
    assert snap["requests"] == 4
    assert snap["outcomes"] == {
        "scored": 3,
        "degraded": 2,
        "quorum_degraded": 1,
        "all_failed": 1,
    }
    assert snap["degraded_rate"] == 0.5
    assert snap["all_failed_rate"] == 0.25
    # only real margins land in the histogram (the all-failed request
    # has no consensus to measure)
    assert snap["confidence_margin"]["count"] == 3
    summary = agg.summary()
    assert summary["requests"] == 4
    assert summary["median_confidence_margin"] is not None
    assert summary["flagged_judges"] == []


def test_prom_snapshot_is_cloned_and_flat():
    agg = QualityAggregator()
    agg.observe_outcome(outcome([ballot("j", [1.0, 0.0])], margin=0.4))
    psnap = agg.prom_snapshot()
    assert psnap["margin"].count == 1
    assert psnap["exemplar"][0] == "trace-1"
    assert psnap["agreement"] == {"j": 1.0}
    assert psnap["drift_flagged"] == {"j": 0.0}
    # the clone must not alias live state
    psnap["margin"].observe(0.1)
    assert agg.prom_snapshot()["margin"].count == 1


# -- JUDGE_BIAS_PLAN ----------------------------------------------------------


def test_bias_plan_parse_round_trip():
    plan = JudgeBiasPlan.parse("judge=2,after=16,flip=1.0,seed=7")
    assert plan.judge == 2 and plan.after == 16 and plan.seed == 7
    assert plan.probabilities["flip"] == 1.0
    scripted = JudgeBiasPlan.parse("judge=1,script=ok|flip|uniform")
    assert scripted._script == [None, "flip", "uniform"]


@pytest.mark.parametrize(
    "spec",
    [
        "bogus=1",
        "judge",
        "judge=2,script=flip|warp",
    ],
)
def test_bias_plan_parse_rejects_bad_specs(spec):
    with pytest.raises(ValueError, match="JUDGE_BIAS_PLAN"):
        JudgeBiasPlan.parse(spec)


def test_bias_plan_after_warmup_and_determinism():
    vote = [Decimal(1), Decimal(0)]

    def run():
        plan = JudgeBiasPlan(
            judge=1, seed=3, after=2, probabilities={"flip": 1.0}
        )
        out = []
        for _ in range(5):
            plan.perturb(0, list(vote))  # untargeted judge interleaved
            out.append(plan.perturb(1, list(vote)))
        return plan, out

    plan, first = run()
    _, second = run()
    # warm-up ballots pass through untouched, then the flip begins;
    # the sequence is identical across runs (seeded, ordinal-keyed)
    assert first[:2] == [vote, vote]
    assert all(v == [Decimal(0), Decimal(1)] for v in first[2:])
    assert first == second
    assert plan.injected["flip"] == 3
    assert plan.snapshot()["injected"] == {"flip": 3}
    # the untargeted judge was never perturbed
    assert plan.snapshot()["ballots"] == {0: 5, 1: 5}


def test_bias_kinds_permute_or_flatten():
    v = [Decimal("0.5"), Decimal("0.3"), Decimal("0.2")]
    flip = JudgeBiasPlan(judge=0, probabilities={"flip": 1.0})
    assert flip.perturb(0, list(v)) == [v[1], v[2], v[0]]
    invert = JudgeBiasPlan(judge=0, probabilities={"invert": 1.0})
    assert invert.perturb(0, list(v)) == list(reversed(v))
    uniform = JudgeBiasPlan(judge=0, probabilities={"uniform": 1.0})
    third = Decimal(1) / Decimal(3)
    assert uniform.perturb(0, list(v)) == [third, third, third]
    # a single-entry vote has nothing to perturb
    solo = JudgeBiasPlan(judge=0, probabilities={"flip": 1.0})
    assert solo.perturb(0, [Decimal(1)]) == [Decimal(1)]


# -- outcome ledger -----------------------------------------------------------


def test_ledger_ring_bound_and_newest_first():
    ledger = OutcomeLedger(capacity=3)
    for i in range(5):
        ledger.offer({"id": f"r{i}", "winner": i})
    snap = ledger.snapshot()
    assert snap["size"] == 3 and snap["kept"] == 5
    assert snap["schema"] == LEDGER_SCHEMA
    assert [r["id"] for r in ledger.index()] == ["r4", "r3", "r2"]
    assert [r["id"] for r in ledger.index(limit=1)] == ["r4"]
    assert ledger.get("r0") is None  # evicted
    record = ledger.get("r4")
    assert record["winner"] == 4
    assert record["schema"] == LEDGER_SCHEMA  # stamped on offer


def test_ledger_disk_jsonl(tmp_path):
    ledger = OutcomeLedger(capacity=2, disk_dir=str(tmp_path))
    for i in range(4):
        ledger.offer({"id": f"r{i}"})
    # the ring is bounded but the JSONL tier keeps everything
    lines = [
        json.loads(ln)
        for ln in open(ledger.snapshot()["disk_path"], encoding="utf-8")
    ]
    assert [r["id"] for r in lines] == ["r0", "r1", "r2", "r3"]
    assert all(r["schema"] == LEDGER_SCHEMA for r in lines)
    assert ledger.snapshot()["disk_errors"] == 0


def test_ledger_disk_error_never_raises(tmp_path):
    ledger = OutcomeLedger(capacity=2, disk_dir=str(tmp_path))
    # a directory in place of the file: every append fails with OSError,
    # which must be swallowed and counted, never raised into the tally
    ledger._disk_path = str(tmp_path)
    ledger.offer({"id": "r0"})
    assert ledger.snapshot()["disk_errors"] == 1
    assert ledger.get("r0") is not None


# -- registries ---------------------------------------------------------------


def test_quality_sections_and_families_registered():
    assert "quality" in KNOWN_SECTIONS and "ledger" in KNOWN_SECTIONS
    for family in (
        "lwc_confidence_margin",
        "lwc_consensus_outcomes",
        "lwc_judge_agreement",
        "lwc_judge_drift",
    ):
        assert family in KNOWN_PROM_FAMILIES, family
    metrics = Metrics()
    register_quality(metrics, OutcomeLedger(capacity=2))
    snap = metrics.snapshot()
    assert snap["quality"]["requests"] == 0
    assert snap["ledger"]["capacity"] == 2


# -- config knobs -------------------------------------------------------------


def test_config_quality_knobs_and_validation():
    c = Config.from_env({})
    assert c.quality_window == 64 and c.quality_drift_threshold == 0.25
    assert c.outcome_ledger() is None
    assert c.judge_bias_injection_plan() is None
    c = Config.from_env(
        {"QUALITY_WINDOW": "8", "QUALITY_DRIFT_THRESHOLD": "0.5"}
    )
    assert c.quality_window == 8 and c.quality_drift_threshold == 0.5
    with pytest.raises(ValueError, match="QUALITY_WINDOW"):
        Config.from_env({"QUALITY_WINDOW": "0"})
    for bad in ("0", "1.5", "-0.1"):
        with pytest.raises(ValueError, match="QUALITY_DRIFT_THRESHOLD"):
            Config.from_env({"QUALITY_DRIFT_THRESHOLD": bad})


def test_config_ledger_and_bias_factories(tmp_path):
    ledger = Config.from_env({"LEDGER_RING": "4"}).outcome_ledger()
    assert ledger.capacity == 4 and ledger.snapshot()["disk_path"] is None
    # LEDGER_DIR alone arms the ledger at the default ring size
    ledger = Config.from_env(
        {"LEDGER_DIR": str(tmp_path)}
    ).outcome_ledger()
    assert ledger.capacity == 256
    assert ledger.snapshot()["disk_path"].startswith(str(tmp_path))
    plan = Config.from_env(
        {"JUDGE_BIAS_PLAN": "judge=1,flip=1.0"}
    ).judge_bias_injection_plan()
    assert isinstance(plan, JudgeBiasPlan) and plan.judge == 1


# -- the tally seam (ScoreClient integration) ---------------------------------


def make_model(judges):
    return ModelBase.from_json_obj({"llms": judges}).into_model_validate()


def inline_model_json(model):
    return {"llms": [llm.base.to_json_obj() for llm in model.llms]}


def ballot_keys(n):
    from llm_weighted_consensus_tpu.ballot import PrefixTree, branch_limit

    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, n, branch_limit(None))
    return {idx: key for key, idx in tree.key_indices(rng)}


def judge_script(key, **kw):
    return Script(
        [chunk_obj(f"I pick {key} as best.", finish="stop")], **kw
    )


def score_params(choices, model, **kw):
    return ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": "pick the best"}],
            "model": model,
            "choices": choices,
            **kw,
        }
    )


def make_score_client(scripts, **kw):
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(transport, AB, backoff=NO_RETRY)
    client = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        **kw,
    )
    return client, transport


async def collect(client, params):
    stream = await client.create_streaming(None, params)
    return [item async for item in stream]


def test_tally_seam_populates_scorecards_and_ledger():
    keys = ballot_keys(2)
    model = make_model([{"model": "judge-a"}, {"model": "judge-b"}])
    # scorecards are keyed by the deterministic judge id (llm.id), and
    # judges dispatch in sorted-by-id order — the transport pops
    # scripts in that order
    first, second = (llm.id for llm in model.llms)
    ledger = OutcomeLedger(capacity=8)
    client, _ = make_score_client(
        [judge_script(keys[0]), judge_script(keys[1])], ledger=ledger
    )
    go(collect(client, score_params(TEXTS, inline_model_json(model))))

    snap = obs.quality_snapshot()
    assert snap["requests"] == 1
    assert snap["outcomes"]["scored"] == 1
    assert set(snap["judges"]) == {first, second}
    # split panel: margin (top1 - top2)/weight_sum is exactly 0
    assert snap["confidence_margin"]["count"] == 1
    assert f"{min(first, second)}|{max(first, second)}" in (
        snap["pairwise_kappa"]
    )

    [record] = ledger.index()
    assert record["schema"] == LEDGER_SCHEMA
    assert record["n_choices"] == 2
    assert record["margin"] == 0.0
    assert record["weight_sum"] == 2.0
    assert len(record["confidence"]) == 2
    rows = {row["model"]: row for row in record["judges"]}
    assert rows[first]["vote"] == [1.0, 0.0]
    assert rows[second]["vote"] == [0.0, 1.0]
    # alignment is the judge's share-weighted confidence: with equal
    # weights each judge's one-hot vote aligns 0.5 with the consensus
    assert rows[first]["alignment"] == 0.5
    assert rows[second]["error"] is None


def test_all_failed_forces_trace_retention():
    # merged-4xx all-failed: the unary surface is a 4xx, below the >=500
    # middleware forcing threshold — the tally must force retention
    model = make_model([{"model": "judge-a"}, {"model": "judge-b"}])
    client, _ = make_score_client(
        [Script(status=418, body=b"{}"), Script(status=418, body=b"{}")]
    )

    async def run():
        root = obs.start_trace("test:root", sampled=False)
        token = root.activate()
        try:
            items = await collect(
                client, score_params(TEXTS, inline_model_json(model))
            )
        finally:
            obs.Span.deactivate(token)
            root.finish()
        return root.trace, items

    trace, items = go(run())
    assert isinstance(items[-1], AllVotesFailed)
    assert not trace.sampled
    assert trace.forced and trace.force_reason == "all_failed"
    snap = obs.quality_snapshot()
    assert snap["outcomes"]["all_failed"] == 1
    cards = snap["judges"]
    assert cards[model.llms[0].id]["error_rate"] == 1.0


def test_ledger_rows_train_without_transformation(tmp_path):
    # the round trip ROADMAP items 4-5 rely on: ledger vote vectors are
    # the embeddings, alignment scores the labels — no transformation
    keys = ballot_keys(2)
    model = make_model([{"model": "judge-a"}, {"model": "judge-b"}])
    ledger = OutcomeLedger(capacity=8)
    client, _ = make_score_client(
        [judge_script(keys[0]), judge_script(keys[0])] * 3, ledger=ledger
    )
    params = score_params(TEXTS, inline_model_json(model))
    for _ in range(3):
        go(collect(client, params))

    store = TrainingTableStore()
    for record in ledger.index():
        for row in record["judges"]:
            if row["vote"] is None:
                continue
            store.add_rows(
                row["model"],
                np.asarray([row["vote"]]),
                np.asarray([row["alignment"]]),
            )
    path = str(tmp_path / "tables.npz")
    store.save(path)
    loaded = TrainingTableStore.load(path)
    embeddings, scores = loaded.get(model.llms[0].id)
    assert embeddings.shape == (3, 2) and embeddings.dtype == np.float32
    # unanimous one-hot panel: full alignment with the consensus
    assert np.array_equal(embeddings, np.asarray([[1, 0]] * 3, np.float32))
    assert np.array_equal(scores, np.ones(3, np.float32))


# -- the seeded end-to-end drill ----------------------------------------------


def post_json(client, path, obj):
    return client.post(
        path,
        data=jsonutil.dumps(obj),
        headers={"content-type": "application/json"},
    )


def test_bias_drill_flags_judge_over_gateway():
    """ISSUE 12 acceptance: a JUDGE_BIAS_PLAN-miscalibrated judge is
    flagged by the drift detector within a bounded request count, the
    scorecard is visible on /v1/judges and in the quality section of
    both /metrics forms, with zero request errors."""
    n_requests = 16
    obs.configure_quality(window=4, drift_threshold=0.3)
    keys = ballot_keys(2)
    model = make_model(
        [{"model": "judge-a"}, {"model": "judge-b"}, {"model": "judge-c"}]
    )
    model_json = inline_model_json(model)
    # the target of the bias plan is a judge *index* (deterministic:
    # position in the sorted-by-id panel), exactly how the env spec
    # would name it; the drift detector sees only the opaque judge id
    biased = next(l for l in model.llms if l.base.model == "judge-c")
    honest_ids = sorted(l.id for l in model.llms if l is not biased)
    # every judge honestly picks candidate 0, every request; the plan
    # flips judge-c after 8 healthy warm-up ballots
    scripts = [judge_script(keys[0]) for _ in range(3 * n_requests)]
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(transport, AB, backoff=NO_RETRY)
    ledger = OutcomeLedger(capacity=64)
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(SEED),
        bias_plan=JudgeBiasPlan.parse(
            f"judge={biased.index},after=8,flip=1.0,seed=7"
        ),
        ledger=ledger,
    )
    multichat = MultichatClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
    )
    app = build_app(chat, score, multichat, ledger=ledger)

    async def run(client):
        for _ in range(n_requests):
            resp = await post_json(
                client,
                "/score/completions",
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": model_json,
                    "choices": TEXTS,
                },
            )
            assert resp.status == 200  # zero request errors
            body = await resp.json()
            assert "error" not in body

        resp = await client.get("/v1/judges")
        assert resp.status == 200
        listing = await resp.json()
        assert listing["window"] == 4
        cards = {c["model"]: c for c in listing["judges"]}
        assert set(cards) == {biased.id, *honest_ids}
        assert cards[biased.id]["drift"]["flagged"] is True
        for honest in honest_ids:
            assert cards[honest]["drift"]["flagged"] is False
            # the panel's honest members agree with every consensus
            assert cards[honest]["agreement_rate"] == 1.0
        # the biased judge's windowed agreement collapsed
        assert cards[biased.id]["drift"]["recent_agreement"] == 0.0

        resp = await client.get(f"/v1/judges/{biased.id}")
        assert resp.status == 200
        card = await resp.json()
        assert card["drift"]["flagged"] is True
        assert (await client.get("/v1/judges/nope")).status == 404

        resp = await client.get("/metrics")
        snap = await resp.json()
        assert snap["quality"]["requests"] == n_requests
        assert snap["quality"]["flagged"] == [biased.id]
        assert snap["quality"]["outcomes"]["scored"] == n_requests
        assert snap["ledger"]["kept"] == n_requests

        resp = await client.get("/metrics?format=prometheus")
        text = await resp.text()
        assert f'lwc_judge_drift{{judge="{biased.id}"}} 1' in text
        assert f'lwc_judge_drift{{judge="{honest_ids[0]}"}} 0' in text
        assert (
            f'lwc_consensus_outcomes_total{{outcome="scored"}} {n_requests}'
            in text
        )
        assert f'lwc_judge_agreement{{judge="{honest_ids[1]}"}} 1' in text
        assert "lwc_confidence_margin_bucket" in text

    async def with_client():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            await run(client)
        finally:
            await client.close()

    go(with_client())
    # the ledger recorded the whole drill for later training
    assert ledger.snapshot()["kept"] == n_requests
    flipped = [
        row
        for record in ledger.index(limit=n_requests)
        for row in record["judges"]
        if row["model"] == biased.id and row["vote"] == [0.0, 1.0]
    ]
    assert len(flipped) == n_requests - 8
