"""Mesh fault domains (ISSUE PR 10): classify → downsize → re-dispatch
→ probe → upsize, on the tier-1 8-virtual-device CPU mesh.

What this pins:

* fault classification at the embedder/batcher seam — injected and
  XlaRuntimeError-shaped faults sort transient/persistent, ordinary
  application errors stay on the fail-the-group path, and the
  transient-streak / watchdog-overdue escalations fire;
* the downsize ladder — dp halving with tp preserved, every rung's mesh
  a device-prefix submesh, every rung AOT-warmed under its own
  ``("mesh", dp, tp)`` key namespace at startup;
* the batcher's re-dispatch contract — a faulted group re-queues onto
  the downsized shape and the answers are numerically identical to a
  fault-free run; past-deadline items shed 504 exactly like the PR 4
  drain path; admission and batcher capacity rescale to the surviving
  chip fraction;
* recovery — ``try_recover`` re-validates the full mesh and upsizes
  back (or keeps the mesh down while the plan still faults);
* the acceptance drill — seeded ``DEVICE_FAULT_PLAN``, persistent fault
  mid-traffic, exactly one downsize, zero non-504 request errors,
  ``/readyz`` flying the ``degraded_mesh`` flag until the upsize;
* identity — no manager attached (MESH_FAULT_ENABLED unset) and
  manager-attached-but-healthy both serve byte-identically, and the
  config validation refuses the nonsensical knob combos.
"""

import asyncio
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llm_weighted_consensus_tpu.errors import DeadlineExceededError
from llm_weighted_consensus_tpu.models import configs
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.parallel.mesh import make_mesh
from llm_weighted_consensus_tpu.parallel.sharding import shard_embedder_mesh
from llm_weighted_consensus_tpu.resilience import (
    Deadline,
    DeviceFaultPlan,
    InjectedHangError,
    InjectedPersistentError,
    InjectedTransientError,
    MeshFaultManager,
    classify_dispatch_error,
)
from llm_weighted_consensus_tpu.serve.batcher import DeviceBatcher
from llm_weighted_consensus_tpu.serve.config import Config
from llm_weighted_consensus_tpu.serve.metrics import Metrics

TINY = configs.TEST_TINY
DP, TP = 4, 2
N, S, R = 4, 16, 2

TEXTS = [f"candidate number {i % 3} under fault" for i in range(6)]


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_embedder(**kw):
    kw.setdefault("config", TINY)
    return TpuEmbedder("test-tiny", max_tokens=32, seed=3, **kw)


def mesh_embedder(dp=DP, tp=TP, **kw):
    emb = make_embedder(**kw)
    shard_embedder_mesh(emb, make_mesh(dp=dp, tp=tp))
    return emb


def manager_for(emb, dp=DP, tp=TP, **kw):
    mgr = MeshFaultManager(emb, shape=(dp, tp), **kw)
    mgr.build_ladder()
    return mgr


class _FakeXlaRuntimeError(Exception):
    pass


_FakeXlaRuntimeError.__name__ = "XlaRuntimeError"


# -- classification -----------------------------------------------------------


def test_classify_dispatch_error_sorts_kinds():
    assert classify_dispatch_error(InjectedTransientError("x")) == "transient"
    assert (
        classify_dispatch_error(InjectedPersistentError("x")) == "persistent"
    )
    # a hang surfaces transient; the watchdog note escalates it
    assert classify_dispatch_error(InjectedHangError("x")) == "transient"
    # ordinary application errors are NOT device faults
    assert classify_dispatch_error(ValueError("bad input")) is None
    assert classify_dispatch_error(RuntimeError("app bug")) is None
    # XlaRuntimeError statuses, matched by type name (no jaxlib import)
    err = _FakeXlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")
    assert classify_dispatch_error(err) == "transient"
    err = _FakeXlaRuntimeError("INTERNAL: device halted")
    assert classify_dispatch_error(err) == "persistent"
    # unknown XLA status: one free retry beats losing half the mesh
    err = _FakeXlaRuntimeError("something new")
    assert classify_dispatch_error(err) == "transient"


def test_manager_classify_escalates_transient_streak():
    emb = mesh_embedder()
    mgr = manager_for(emb, transient_retries=2)
    t = InjectedTransientError("blip")
    assert mgr.classify(t) == "transient"
    assert mgr.classify(t) == "transient"
    # streak 3 > retries 2: the "transient" fault is a wedge in disguise
    assert mgr.classify(t) == "persistent"
    # a clean dispatch resets the streak
    assert mgr.classify(t) == "transient"
    mgr.note_dispatch_ok()
    assert mgr.classify(t) == "transient"
    # application errors pass through unclassified regardless of state
    assert mgr.classify(ValueError("app")) is None


def test_manager_classify_watchdog_overdue_escalates():
    mgr = manager_for(mesh_embedder())
    mgr.note_watchdog_trip()
    assert mgr.classify(InjectedHangError("wedge")) == "persistent"
    # the note is consumed — the next blip is just a blip
    assert mgr.classify(InjectedTransientError("blip")) == "transient"


# -- DEVICE_FAULT_PLAN --------------------------------------------------------


def test_device_fault_plan_seeded_is_deterministic():
    a = DeviceFaultPlan(seed=7, probabilities={"transient": 0.5})
    b = DeviceFaultPlan(seed=7, probabilities={"transient": 0.5})
    draws_a = [a.next_fault() for _ in range(64)]
    draws_b = [b.next_fault() for _ in range(64)]
    assert draws_a == draws_b
    assert a.snapshot() == b.snapshot()
    assert a.snapshot()["requests"] == 64


def test_device_fault_plan_parse_and_script():
    plan = DeviceFaultPlan.parse(
        "seed=3,hang_ms=10,script=persistent|ok|transient"
    )
    assert plan.hang_ms == 10.0
    assert plan.next_fault() == "persistent"
    assert plan.next_fault() is None
    assert plan.next_fault() == "transient"
    # healthy after script exhaustion
    assert plan.next_fault() is None
    assert plan.snapshot() == {
        "requests": 4,
        "injected": {"transient": 1, "persistent": 1},
    }
    with pytest.raises(ValueError, match="unknown key"):
        DeviceFaultPlan.parse("sneed=3")
    with pytest.raises(ValueError, match="unknown fault"):
        DeviceFaultPlan.parse("script=kaboom")
    with pytest.raises(ValueError, match="key=value"):
        DeviceFaultPlan.parse("persistent")


def test_maybe_inject_raises_per_script():
    mgr = manager_for(
        mesh_embedder(),
        fault_plan=DeviceFaultPlan.scripted(
            ["transient", None, "hang"], hang_ms=1.0
        ),
    )
    with pytest.raises(InjectedTransientError):
        mgr.maybe_inject()
    mgr.maybe_inject()  # healthy slot
    # the hang sleeps its bounded hang_ms then raises — never blocks
    with pytest.raises(InjectedHangError):
        mgr.maybe_inject()


# -- the ladder ---------------------------------------------------------------


def test_ladder_walk_8_to_1_dp_halving_tp_preserved():
    emb = mesh_embedder(dp=8, tp=1)
    mgr = manager_for(emb, dp=8, tp=1)
    assert mgr.build_ladder() == [(8, 1), (4, 1), (2, 1), (1, 1)]
    assert mgr.current_shape == (8, 1)
    assert not mgr.degraded and not mgr.exhausted
    devices0 = list(emb.mesh.devices.reshape(-1))
    for expect in [(4, 1), (2, 1), (1, 1)]:
        assert mgr.downsize() is True
        assert mgr.current_shape == expect
        assert emb.mesh_shape == expect
        # every rung is a PREFIX submesh of the full device list
        assert (
            list(emb.mesh.devices.reshape(-1))
            == devices0[: expect[0] * expect[1]]
        )
        assert mgr.degraded
    assert mgr.exhausted
    # past the last rung: the caller's cue to flip the CPU twin
    assert mgr.downsize() is False
    snap = mgr.snapshot()
    assert snap["downsizes"] == 3
    assert snap["epoch"] == 3
    # the dropped tails accumulate as the faulted domain: 7 of 8 devices
    assert len(snap["faulted_devices"]) == 7


def test_ladder_preserves_tp():
    mgr = manager_for(mesh_embedder())
    assert mgr.build_ladder() == [(4, 2), (2, 2), (1, 2)]


def test_warm_ladder_aot_covers_every_rung():
    emb = mesh_embedder()
    mgr = manager_for(emb)
    timings = mgr.warm_ladder([(N, S)], [R], [(4, 64, 8)])
    # 4 executables (vote1/embed/many/packed) x 3 rungs
    assert len(timings) == 12
    assert emb.aot_mesh_shapes() == [(4, 2), (2, 2), (1, 2)]
    # the embedder exits warmed AND sharded at the full shape
    assert emb.mesh_shape == (DP, TP)
    # warm again: idempotent, nothing recompiles
    assert mgr.warm_ladder([(N, S)], [R], [(4, 64, 8)]) == []


def test_downsized_rung_serves_warmed_zero_new_specializations():
    """The executable-table swap: post-downsize traffic on the surviving
    submesh hits the rung's precompiled executables — no compile storm."""
    emb = mesh_embedder()
    mgr = manager_for(emb)
    mgr.warm_ladder([(N, S)], [R])
    assert mgr.downsize() is True
    rng = np.random.default_rng(5)
    ids = rng.integers(3, TINY.vocab_size, (N, S)).astype(np.int32)
    mask = np.ones((N, S), np.int32)
    stats0 = emb.jit_stats()["specializations"]
    out = np.asarray(emb.consensus_confidence_tokens(ids, mask))
    assert np.all(np.isfinite(out))
    assert emb.jit_stats()["specializations"] == stats0


# -- shape-transition serialization -------------------------------------------


def test_shape_transition_waits_for_inflight_dispatch():
    """The shape gate: downsize() must drain in-flight dispatches before
    re-sharding — the batcher's executor has pipeline_depth (default 2)
    workers, so a concurrent dispatch thread can be mid-PJRT on the old
    params when the fault handler runs."""
    emb = mesh_embedder()
    mgr = manager_for(emb)
    dispatching = threading.Event()
    finish_dispatch = threading.Event()
    order = []

    def dispatch_thread():
        with mgr.dispatch_guard():
            dispatching.set()
            finish_dispatch.wait(5.0)
            order.append("dispatch")

    def downsize_thread():
        mgr.downsize()
        order.append("downsize")

    t = threading.Thread(target=dispatch_thread)
    t.start()
    assert dispatching.wait(5.0)
    w = threading.Thread(target=downsize_thread)
    w.start()
    time.sleep(0.05)
    # the re-shard is parked behind the in-flight dispatch
    assert order == []
    assert mgr.current_shape == (DP, TP)
    finish_dispatch.set()
    t.join(5.0)
    w.join(5.0)
    assert order == ["dispatch", "downsize"]
    assert mgr.current_shape == (2, 2)


def test_stale_epoch_fault_skips_ladder_step():
    """Pipelined groups faulting on the SAME dead device must cost one
    rung: a downsize carrying a pre-transition epoch stamp re-queues
    without stepping the ladder again."""
    mgr = manager_for(mesh_embedder())
    epoch0 = mgr.epoch
    assert mgr.downsize(observed_epoch=epoch0) is True
    assert mgr.current_shape == (2, 2)
    # the second in-flight group observed the same pre-downsize epoch:
    # its fault is old news — True (re-queue) but no rung spent
    assert mgr.downsize(observed_epoch=epoch0) is True
    assert mgr.current_shape == (2, 2)
    snap = mgr.snapshot()
    assert snap["downsizes"] == 1
    assert snap["epoch"] == 1


def test_concurrent_persistent_faults_downsize_once_through_batcher():
    """End to end: two pipelined dispatch groups both drawing persistent
    faults from one fault event step the ladder exactly once, and every
    re-dispatched answer still matches the fault-free run."""
    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(
        emb,
        fault_plan=DeviceFaultPlan.scripted(["persistent", "persistent"]),
    )
    mgr.warm_ladder([(N, S)], [R])
    batcher = DeviceBatcher(
        emb, Metrics(), window_ms=20.0, pipeline_depth=2, meshfault=mgr
    )
    short = TEXTS[:4]

    async def run():
        # different candidate counts -> different keys -> two groups,
        # dispatched concurrently on the 2-deep pipeline; both draw a
        # persistent fault before either handler can downsize
        return await asyncio.gather(
            batcher.consensus(TEXTS), batcher.consensus(short)
        )

    (conf_a, _), (conf_b, _) = go(run())
    np.testing.assert_allclose(
        conf_a, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )
    np.testing.assert_allclose(
        conf_b, np.asarray(ref.consensus_confidence(short)), atol=1e-5
    )
    snap = mgr.snapshot()
    # ONE fault event, one rung — not one per in-flight group
    assert snap["downsizes"] == 1
    assert snap["current_shape"] == [2, 2]
    assert snap["re_dispatches"] >= 2


# -- re-dispatch through the batcher ------------------------------------------


def test_persistent_fault_downsizes_once_and_matches_clean_run():
    """The acceptance core: a persistent fault mid-dispatch costs one
    ladder rung and ZERO request errors — the re-dispatched answers are
    numerically identical to a fault-free run."""
    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(
        emb, fault_plan=DeviceFaultPlan.scripted(["persistent"])
    )
    mgr.warm_ladder([(N, S)], [R])
    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=20.0, meshfault=mgr)

    async def run():
        return await asyncio.gather(
            batcher.consensus(TEXTS),
            batcher.consensus(list(reversed(TEXTS))),
        )

    (conf_a, tok_a), (conf_b, _) = go(run())
    np.testing.assert_allclose(
        conf_a, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )
    np.testing.assert_allclose(
        conf_b,
        np.asarray(ref.consensus_confidence(list(reversed(TEXTS)))),
        atol=1e-5,
    )
    assert tok_a == ref.token_count(TEXTS)
    snap = mgr.snapshot()
    assert snap["downsizes"] == 1
    assert snap["current_shape"] == [2, 2]
    assert snap["re_dispatches"] >= 1
    assert mgr.degraded


def test_transient_fault_retries_on_same_shape():
    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(
        emb, fault_plan=DeviceFaultPlan.scripted(["transient"])
    )
    batcher = DeviceBatcher(emb, Metrics(), window_ms=10.0, meshfault=mgr)
    conf, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(
        conf, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )
    snap = mgr.snapshot()
    # retried on the FULL shape: transient faults don't spend rungs
    assert snap["downsizes"] == 0
    assert snap["current_shape"] == [DP, TP]
    assert snap["re_dispatches"] >= 1


def test_redispatch_sheds_expired_deadline_as_504():
    """Re-queue is deadline-bounded: an item past its budget at re-queue
    time sheds 504 (the PR 4 contract) instead of riding the new shape."""
    emb = mesh_embedder()
    mgr = manager_for(
        emb,
        fault_plan=DeviceFaultPlan.scripted(["hang"], hang_ms=60.0),
    )
    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=5.0, meshfault=mgr)

    async def run():
        # 20 ms budget, 60 ms injected hang: expired by re-queue time
        token = Deadline(0.02).activate()
        try:
            with pytest.raises(DeadlineExceededError) as ei:
                await batcher.embed(["too late by redispatch"])
            assert ei.value.status() == 504
        finally:
            Deadline.deactivate(token)

    go(run())
    assert batcher.shed_deadline == 1
    assert (
        metrics.snapshot()["series"]["device:shed:deadline"]["errors"] == 1
    )


def test_redispatch_limit_is_observable_in_metrics():
    """An item failed at REDISPATCH_LIMIT must show in /metrics like the
    adjacent deadline shed — a fault loop exhausting items cannot be
    invisible."""
    emb = mesh_embedder()
    # transient_retries high enough that the streak never escalates to
    # persistent: every fault re-queues on the same shape until the
    # per-item limit trips
    mgr = manager_for(
        emb,
        transient_retries=100,
        fault_plan=DeviceFaultPlan.scripted(
            ["transient"] * (DeviceBatcher.REDISPATCH_LIMIT + 1)
        ),
    )
    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=5.0, meshfault=mgr)

    async def run():
        with pytest.raises(InjectedTransientError):
            await batcher.embed(["recycled until the limit"])

    go(run())
    assert batcher.shed_redispatch_limit == 1
    assert (
        metrics.snapshot()["series"]["device:shed:redispatch"]["errors"]
        == 1
    )
    assert mgr.snapshot()["re_dispatches"] == DeviceBatcher.REDISPATCH_LIMIT


def test_application_errors_keep_fail_the_group_path():
    """A non-device error must NOT touch the ladder: the group fails
    exactly as it did before the fault-domain subsystem existed."""
    emb = mesh_embedder()
    mgr = manager_for(emb)
    batcher = DeviceBatcher(emb, Metrics(), window_ms=5.0, meshfault=mgr)
    boom = ValueError("tokenizer exploded")

    def bad_dispatch(group, embedder):
        raise boom

    # instance attribute shadows the bound method the dispatch getattr
    # resolves — the injected application error, not a device fault
    batcher._dispatch_embed = bad_dispatch

    async def run():
        with pytest.raises(ValueError, match="tokenizer exploded"):
            await batcher.embed(["doomed"])

    go(run())
    assert mgr.snapshot()["downsizes"] == 0
    assert mgr.snapshot()["re_dispatches"] == 0


def test_ladder_exhaustion_flips_cpu_fallback():
    """Satellite precedence, bottom half: when every rung is spent the
    batcher flips to the CPU twin — the last resort, never the first."""
    emb = mesh_embedder(dp=2, tp=1)
    fallback = make_embedder()
    mgr = manager_for(
        emb,
        dp=2,
        tp=1,
        fault_plan=DeviceFaultPlan.scripted(["persistent", "persistent"]),
    )
    batcher = DeviceBatcher(
        emb,
        Metrics(),
        window_ms=10.0,
        meshfault=mgr,
        fallback_embedder=fallback,
    )
    conf, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(
        conf, np.asarray(fallback.consensus_confidence(TEXTS)), atol=1e-5
    )
    assert mgr.exhausted
    assert batcher._use_fallback is True


# -- rescale hooks ------------------------------------------------------------


def test_downsize_rescales_admission_and_batcher_capacity():
    from llm_weighted_consensus_tpu.resilience import (
        AdmissionConfig,
        AdmissionController,
    )

    emb = mesh_embedder()
    mgr = manager_for(emb)
    admission = AdmissionController(
        AdmissionConfig(max_inflight=16, adaptive=True, min_limit=2)
    )
    batcher = DeviceBatcher(
        emb, Metrics(), window_ms=5.0, max_batch=32, max_rows=64
    )
    mgr.rescale_hooks.append(admission.rescale)
    mgr.rescale_hooks.append(batcher.rescale_capacity)

    assert mgr.downsize() is True  # 4x2 -> 2x2: half the chips
    assert admission.snapshot()["mesh_scale"] == 0.5
    assert admission.limit == 8.0
    assert batcher.max_batch == 16 and batcher.max_rows == 32
    # the scaled cap sheds at half the configured in-flight bound
    admission.inflight = 8
    assert admission.try_acquire() == "inflight_limit"

    assert mgr.downsize() is True  # 2x2 -> 1x2: quarter capacity
    assert admission.snapshot()["mesh_scale"] == 0.25
    assert batcher.max_batch == 8 and batcher.max_rows == 16

    mgr.try_recover()  # full shape restores full capacity
    assert "mesh_scale" not in admission.snapshot()
    assert batcher.max_batch == 32 and batcher.max_rows == 64
    admission.inflight = 8
    assert admission.try_acquire() is None


# -- recovery -----------------------------------------------------------------


def test_try_recover_upsizes_and_matches_clean_run():
    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(emb)
    mgr.warm_ladder([(N, S)], [R])
    assert mgr.downsize() is True
    epoch_down = mgr.epoch
    assert mgr.try_recover() is True
    assert mgr.current_shape == (DP, TP)
    assert emb.mesh_shape == (DP, TP)
    assert not mgr.degraded
    assert mgr.epoch == epoch_down + 1
    snap = mgr.snapshot()
    assert snap["upsizes"] == 1
    assert snap["faulted_devices"] == []
    # post-upsize numerics: identical to a never-faulted embedder
    batcher = DeviceBatcher(emb, Metrics(), window_ms=10.0, meshfault=mgr)
    conf, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(
        conf, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )


def test_try_recover_holds_while_plan_still_faulty():
    emb = mesh_embedder()
    mgr = manager_for(
        emb,
        fault_plan=DeviceFaultPlan.scripted(["persistent", None]),
    )
    # downsize() consumes no plan draws — only dispatches and probes do
    assert mgr.downsize() is True
    # probe draw #1 is persistent: the mesh stays down
    assert mgr.try_recover() is False
    assert mgr.degraded
    assert mgr.snapshot()["probe_failures"] == 1
    # probe draw #2 is healthy: upsize proceeds
    assert mgr.try_recover() is True
    assert not mgr.degraded


def test_probe_fn_failure_rolls_back_upsize():
    emb = mesh_embedder()
    mgr = manager_for(emb)
    assert mgr.downsize() is True

    def bad_probe():
        raise InjectedPersistentError("still dead")

    mgr.probe_fn = bad_probe
    assert mgr.try_recover() is False
    assert mgr.degraded
    assert emb.mesh_shape == (2, 2)  # rolled back to the surviving rung
    assert mgr.snapshot()["probe_failures"] == 1


def test_probe_backoff_scales_with_failures_and_resets():
    emb = mesh_embedder()
    mgr = manager_for(
        emb,
        fault_plan=DeviceFaultPlan.scripted(
            ["persistent", "persistent", None]
        ),
    )
    assert mgr.downsize() is True  # consumes no plan draws
    assert mgr.probe_backoff_scale() == 1.0
    assert mgr.try_recover() is False  # probe draw #1 faults
    assert mgr.probe_backoff_scale() == 2.0
    assert mgr.try_recover() is False  # probe draw #2 faults
    assert mgr.probe_backoff_scale() == 4.0
    assert mgr.snapshot()["probe_backoff"] == 4.0
    assert mgr.try_recover() is True  # healthy: upsize resets backoff
    assert mgr.probe_backoff_scale() == 1.0


def test_blind_upsize_warns_once(caplog):
    """No probe_fn and no fault plan: the upsize is unvalidated, which
    deserves a loud (but one-time) warning — production wires a real
    probe_fn in serve/__main__.py."""
    import logging

    mgr = manager_for(mesh_embedder())
    assert mgr.downsize() is True
    with caplog.at_level(logging.WARNING, logger="lwc.resilience"):
        assert mgr.try_recover() is True
        assert mgr.downsize() is True
        assert mgr.try_recover() is True
    warnings = [
        r for r in caplog.records if "no probe_fn" in r.getMessage()
    ]
    assert len(warnings) == 1


def test_not_degraded_try_recover_is_noop():
    mgr = manager_for(mesh_embedder())
    assert mgr.try_recover() is False
    assert mgr.snapshot()["upsizes"] == 0


# -- identity when off --------------------------------------------------------


def test_no_manager_is_todays_behavior():
    """MESH_FAULT_ENABLED unset: the batcher has no manager, dispatch
    errors fail the group exactly as before this PR."""
    ref = make_embedder()
    emb = mesh_embedder()
    batcher = DeviceBatcher(emb, Metrics(), window_ms=10.0)
    assert batcher.meshfault is None
    conf, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(
        conf, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )


def test_healthy_plan_is_identity():
    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(
        emb, fault_plan=DeviceFaultPlan.scripted([None, None, None])
    )
    batcher = DeviceBatcher(emb, Metrics(), window_ms=10.0, meshfault=mgr)
    conf, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(
        conf, np.asarray(ref.consensus_confidence(TEXTS)), atol=1e-5
    )
    snap = mgr.snapshot()
    assert snap["downsizes"] == 0 and snap["re_dispatches"] == 0
    assert not mgr.degraded


# -- config -------------------------------------------------------------------

MESH_ENV = {"MESH_ENABLED": "1", "MESH_SHAPE": f"{DP}x{TP}"}


def test_config_off_by_default():
    config = Config.from_env({})
    assert config.mesh_fault_enabled is False
    assert config.device_fault_plan is None
    assert config.device_fault_injection_plan() is None


def test_config_parses_and_builds_plan():
    config = Config.from_env(
        dict(
            MESH_ENV,
            MESH_FAULT_ENABLED="1",
            MESH_FAULT_TRANSIENT_RETRIES="5",
            MESH_FAULT_PROBE_MILLIS="250",
            DEVICE_FAULT_PLAN="seed=9,transient=0.1",
        )
    )
    assert config.mesh_fault_enabled is True
    assert config.mesh_fault_transient_retries == 5
    assert config.mesh_fault_probe_millis == 250.0
    plan = config.device_fault_injection_plan()
    assert isinstance(plan, DeviceFaultPlan)
    assert plan.seed == 9


def test_config_validation_refuses_nonsense():
    with pytest.raises(ValueError, match="needs MESH_ENABLED"):
        Config.from_env({"MESH_FAULT_ENABLED": "1"})
    with pytest.raises(ValueError, match="MESH_FAULT_ENABLED is not"):
        Config.from_env(dict(MESH_ENV, DEVICE_FAULT_PLAN="seed=1"))
    with pytest.raises(ValueError, match="must be >= 0"):
        Config.from_env(
            dict(
                MESH_ENV,
                MESH_FAULT_ENABLED="1",
                MESH_FAULT_PROBE_MILLIS="-1",
            )
        )


def test_config_cpu_fallback_precedence():
    """Satellite 1: in mesh mode the CPU twin without the ladder is
    refused at startup — it must be the post-exhaustion last resort."""
    with pytest.raises(ValueError, match="last resort AFTER"):
        Config.from_env(
            dict(
                MESH_ENV,
                DEVICE_WATCHDOG_MILLIS="1000",
                DEVICE_WATCHDOG_CPU_FALLBACK="1",
            )
        )
    # with the ladder armed the combo is the documented precedence chain
    config = Config.from_env(
        dict(
            MESH_ENV,
            MESH_FAULT_ENABLED="1",
            DEVICE_WATCHDOG_MILLIS="1000",
            DEVICE_WATCHDOG_CPU_FALLBACK="1",
        )
    )
    assert config.device_watchdog_cpu_fallback is True
    # and off-mesh the twin needs no ladder (single-device semantics)
    config = Config.from_env(
        {
            "DEVICE_WATCHDOG_MILLIS": "1000",
            "DEVICE_WATCHDOG_CPU_FALLBACK": "1",
        }
    )
    assert config.mesh_fault_enabled is False


# -- the acceptance drill -----------------------------------------------------


def test_acceptance_drill_fault_mid_traffic_readyz_and_recovery():
    """The ISSUE acceptance, end to end on the simulated mesh: seeded
    persistent fault mid-traffic → exactly one downsize, zero request
    errors, answers ≡ fault-free, /readyz flies degraded_mesh while
    down and drops it after the recovery upsize."""
    aiohttp = pytest.importorskip("aiohttp")  # noqa: F841
    from llm_weighted_consensus_tpu.serve.lifecycle import (
        Lifecycle,
        health_handlers,
    )
    from llm_weighted_consensus_tpu.utils import jsonutil

    ref = make_embedder()
    emb = mesh_embedder()
    mgr = manager_for(
        emb,
        fault_plan=DeviceFaultPlan.parse("script=ok|persistent"),
    )
    mgr.warm_ladder([(N, S)], [R])
    metrics = Metrics()
    batcher = DeviceBatcher(emb, metrics, window_ms=15.0, meshfault=mgr)
    metrics.register_provider("meshfault", mgr.snapshot)
    lifecycle = Lifecycle(batcher=batcher, meshfault=mgr)
    _livez, readyz = health_handlers(lifecycle)

    def ready_body():
        resp = go(readyz(None))
        assert resp.status == 200
        return jsonutil.loads(resp.text)

    # healthy before the drill
    assert ready_body() == {"ready": True}

    async def traffic():
        return await asyncio.gather(
            batcher.consensus(TEXTS),  # dispatch 1: ok
            batcher.consensus(TEXTS),  # coalesces into dispatch 1
        )

    first = go(traffic())
    # dispatch 2 faults persistent mid-traffic and re-dispatches
    second = go(traffic())
    expect = np.asarray(ref.consensus_confidence(TEXTS))
    for conf, _ in first + second:
        np.testing.assert_allclose(conf, expect, atol=1e-5)

    snap = metrics.snapshot()["meshfault"]
    assert snap["downsizes"] == 1  # exactly one
    assert snap["current_shape"] == [2, 2]
    assert snap["fault_plan"]["injected"] == {"persistent": 1}
    body = ready_body()  # degraded but READY: still 200
    assert body["degraded_mesh"] is True
    assert body["mesh_shape"] == [2, 2]

    # recovery: the prober re-validates the full mesh and upsizes
    assert mgr.try_recover() is True
    assert ready_body() == {"ready": True}
    post, _ = go(batcher.consensus(TEXTS))
    np.testing.assert_allclose(post, expect, atol=1e-5)
    assert metrics.snapshot()["meshfault"]["upsizes"] == 1
