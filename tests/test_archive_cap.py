"""Regression tests for InMemoryArchive capping (_evict_over_cap / enforce_cap).

The cap logic only reads ``completion.id``, so a SimpleNamespace stands in
for real completion objects — these tests pin eviction ORDER and table
consistency, not serialization (test_multichat covers real completions).
"""

from types import SimpleNamespace

from llm_weighted_consensus_tpu.archive import InMemoryArchive


def comp(cid: str) -> SimpleNamespace:
    return SimpleNamespace(id=cid)


def test_fifo_eviction_order():
    # oldest insertion evicted first; dict insertion order is the queue
    store = InMemoryArchive(max_completions=3)
    for cid in ("a", "b", "c", "d", "e"):
        store.put_chat(comp(cid))
    assert store.chat_ids() == ["c", "d", "e"]


def test_reinserting_existing_id_does_not_evict():
    # overwriting a live id keeps len(table) constant: no eviction fires
    store = InMemoryArchive(max_completions=2)
    store.put_score(comp("a"))
    store.put_score(comp("b"))
    store.put_score(comp("a"))
    assert store.score_ids() == ["a", "b"]


def test_cap_zero_holds_nothing():
    store = InMemoryArchive(max_completions=0)
    store.put_chat(comp("a"))
    store.put_score(comp("b"))
    store.put_multichat(comp("c"))
    assert store.chat_ids() == []
    assert store.score_ids() == []
    assert store.multichat_ids() == []


def test_negative_cap_treated_as_zero():
    # max(0, cap): a negative cap must drain to empty, not loop forever
    store = InMemoryArchive(max_completions=-5)
    store.put_chat(comp("a"))
    assert store.chat_ids() == []


def test_unbounded_when_cap_is_none():
    store = InMemoryArchive()
    for i in range(100):
        store.put_chat(comp(f"c{i}"))
    assert len(store.chat_ids()) == 100


def test_score_eviction_drops_ballots_and_request():
    # evicting a score completion must drop its ballot record and its
    # originating request too — both are useless without the completion
    store = InMemoryArchive(max_completions=1)
    store.put_score(comp("old"))
    store.put_ballot("old", 0, [("k", 1)])
    store.put_score_request("old", SimpleNamespace(messages=[]))

    store.put_score(comp("new"))

    assert store.score_ids() == ["new"]
    assert store.score_ballots("old") is None
    assert store.score_request("old") is None


def test_chat_eviction_leaves_score_tables_alone():
    # the ballots/requests cascade is score-table-only; a chat table at
    # cap must not touch score side tables even with colliding ids
    store = InMemoryArchive(max_completions=1)
    store.put_score(comp("x"))
    store.put_ballot("x", 0, [("k", 0)])
    store.put_chat(comp("x"))
    store.put_chat(comp("y"))  # evicts chat "x"
    assert store.chat_ids() == ["y"]
    assert store.score_ids() == ["x"]
    assert store.score_ballots("x") == {0: [("k", 0)]}


def test_enforce_cap_applies_to_all_tables():
    # lowering the cap after the fact (e.g. loading an over-cap snapshot)
    # trims every table, oldest first, and cascades score side tables
    store = InMemoryArchive()
    for cid in ("c1", "c2", "c3"):
        store.put_chat(comp(cid))
    for cid in ("s1", "s2", "s3"):
        store.put_score(comp(cid))
        store.put_ballot(cid, 0, [("k", 2)])
    for cid in ("m1", "m2", "m3"):
        store.put_multichat(comp(cid))

    store.max_completions = 1
    store.enforce_cap()

    assert store.chat_ids() == ["c3"]
    assert store.score_ids() == ["s3"]
    assert store.multichat_ids() == ["m3"]
    assert store.score_ballots("s1") is None
    assert store.score_ballots("s2") is None
    assert store.score_ballots("s3") == {0: [("k", 2)]}


def test_enforce_cap_noop_when_unbounded():
    store = InMemoryArchive()
    store.put_chat(comp("a"))
    store.enforce_cap()
    assert store.chat_ids() == ["a"]


def test_eviction_keeps_ballot_tables_consistent_under_churn():
    # sustained over-cap traffic: side tables must track the score table
    # exactly (no leaked ballots/requests for evicted completions)
    store = InMemoryArchive(max_completions=4)
    for i in range(32):
        cid = f"s{i}"
        store.put_score(comp(cid))
        store.put_ballot(cid, 0, [("k", i)])
        store.put_score_request(cid, SimpleNamespace(messages=[]))
    live = store.score_ids()
    assert live == ["s28", "s29", "s30", "s31"]
    assert sorted(store._ballots) == sorted(live)
    assert sorted(store._score_requests) == sorted(live)


def test_orphan_queue_structure_is_bounded():
    # the deque itself is capped, not just the orphan COUNT: a workload
    # whose completions all get archived leaves zero orphans but used to
    # grow the queue with one stale entry per completion forever
    store = InMemoryArchive()
    assert store._ballot_orphans.maxlen == 2 * store.MAX_BALLOT_COMPLETIONS
    n = store._ballot_orphans.maxlen + 500
    for i in range(n):
        cid = f"s{i}"
        store.put_ballot(cid, 0, [("k", i)])
        store.put_score(comp(cid))  # archived: entry goes stale in-queue
    assert len(store._ballot_orphans) <= store._ballot_orphans.maxlen
    assert store._n_orphan_ballots == 0
    # every displacement was counted
    assert store._orphan_queue_drops == 500
    # archived ballots all survive — displacement only drops stale entries
    assert len(store._ballots) == n


def test_orphan_queue_displacement_evicts_live_head():
    # when the displaced head is still a live orphan its ballots go with
    # it — nothing unreachable by the eviction queue may keep its bytes
    store = InMemoryArchive()
    store.MAX_BALLOT_COMPLETIONS = 4  # shrink the cap; maxlen follows
    from collections import deque

    store._ballot_orphans = deque(maxlen=2 * store.MAX_BALLOT_COMPLETIONS)
    # cap live orphans at the head...
    for i in range(store.MAX_BALLOT_COMPLETIONS):
        store.put_ballot(f"o{i}", 0, [("k", i)])
    # ...then fill the tail with archived-then-balloted cids: queued but
    # never counted as orphans, so the count-cap loop never fires
    for i in range(store.MAX_BALLOT_COMPLETIONS):
        store.put_score(comp(f"a{i}"))
        store.put_ballot(f"a{i}", 0, [("k", i)])
    assert len(store._ballot_orphans) == store._ballot_orphans.maxlen
    assert store._ballot_orphans[0] == "o0"  # live orphan at the head
    before = set(store._ballots)
    store.put_ballot("one-more", 0, [("k", 99)])
    assert store._orphan_queue_drops >= 1
    assert len(store._ballot_orphans) <= store._ballot_orphans.maxlen
    # accounting stays exact: count matches the actual orphan population
    orphans = [c for c in store._ballots if c not in store._score]
    assert store._n_orphan_ballots == len(orphans)
    assert "one-more" in store._ballots
    assert before - set(store._ballots)  # something was really evicted
