"""Identity layer tests: canonicalization, validation, hash stability.

Reference behaviors under test: src/score/llm/mod.rs:76-588 (prepare/validate/
ids) and src/score/model/mod.rs:37-199 (panel assembly).  Includes golden id
strings guarding hash stability of this framework's id space across refactors.
"""

from decimal import Decimal

import pytest

from llm_weighted_consensus_tpu.identity import (
    LlmBase,
    ModelBase,
    WeightStatic,
    base62_encode,
    id_string,
)


def llm(s: str) -> LlmBase:
    return LlmBase.from_json(s)


def test_base62_known_values():
    assert base62_encode(0) == "0"
    assert base62_encode(61) == "z"
    assert base62_encode(62) == "10"
    assert len(id_string((1 << 128) - 1)) == 22


def test_prepare_drops_defaults():
    a = llm('{"model":"m","temperature":1.0,"top_p":1.0,"frequency_penalty":0.0,'
            '"min_p":0.0,"repetition_penalty":1.0,"top_a":0.0,"top_k":0,'
            '"verbosity":"medium","models":[],"logit_bias":{},"top_logprobs":0,'
            '"synthetic_reasoning":false}')
    b = llm('{"model":"m"}')
    a.prepare()
    b.prepare()
    assert a.to_json() == b.to_json()
    assert a.id_string() == b.id_string()


def test_prepare_stop_canonicalization():
    one = llm('{"model":"m","stop":["x"]}')
    one.prepare()
    assert one.stop == "x"
    many = llm('{"model":"m","stop":["b","a"]}')
    many.prepare()
    assert many.stop == ["a", "b"]
    empty = llm('{"model":"m","stop":[]}')
    empty.prepare()
    assert empty.stop is None


def test_prepare_provider_canonicalization():
    a = llm('{"model":"m","provider":{"allow_fallbacks":true,"only":["z","a"],'
            '"data_collection":"allow","require_parameters":false}}')
    a.prepare()
    assert a.provider.allow_fallbacks is None
    assert a.provider.only == ["a", "z"]
    assert a.provider.data_collection is None
    b = llm('{"model":"m","provider":{"allow_fallbacks":true}}')
    b.prepare()
    assert b.provider is None


def test_prepare_reasoning():
    a = llm('{"model":"m","reasoning":{"enabled":true,"effort":"high"}}')
    a.prepare()
    assert a.reasoning.enabled is None and a.reasoning.effort == "high"
    b = llm('{"model":"m","reasoning":{"enabled":false}}')
    b.prepare()
    assert b.reasoning is None
    c = llm('{"model":"m","reasoning":{"max_tokens":0}}')
    c.prepare()
    assert c.reasoning is None


@pytest.mark.parametrize(
    "body",
    [
        '{"model":""}',
        '{"model":"m","temperature":2.5}',
        '{"model":"m","top_p":1.5}',
        '{"model":"m","frequency_penalty":-3.0}',
        '{"model":"m","top_logprobs":21}',
        '{"model":"m","logit_bias":{"abc":1}}',
        '{"model":"m","logit_bias":{"007":1}}',
        '{"model":"m","logit_bias":{"5":101}}',
        '{"model":"m","stop":""}',
        '{"model":"m","stop":["a","a"]}',
        '{"model":"m","models":["m"]}',
        '{"model":"m","models":["x","x"]}',
        '{"model":"m","weight":{"type":"static","weight":0}}',
        '{"model":"m","weight":{"type":"static","weight":-1}}',
        '{"model":"m","output_mode":"instruction","synthetic_reasoning":true}',
        '{"model":"m","reasoning":{"effort":"high","max_tokens":5}}',
        '{"model":"m","reasoning":{"enabled":false,"effort":"high"}}',
    ],
)
def test_validate_rejects(body):
    with pytest.raises(ValueError):
        llm(body).into_llm_without_indices()


def test_three_identities():
    j = llm('{"model":"m","weight":{"type":"training_table","base_weight":1.0,'
            '"min_weight":0.5,"max_weight":2.0},"output_mode":"json_schema",'
            '"top_logprobs":5}')
    v = j.into_llm_without_indices()
    assert len(v.id) == 22 and len(v.multichat_id) == 22
    assert v.training_table_id is not None and len(v.training_table_id) == 22
    # training_table id ignores weight bounds
    j2 = llm('{"model":"m","weight":{"type":"training_table","base_weight":1.0,'
             '"min_weight":0.1,"max_weight":9.0},"output_mode":"json_schema",'
             '"top_logprobs":5}')
    v2 = j2.into_llm_without_indices()
    assert v2.id != v.id
    assert v2.training_table_id == v.training_table_id
    # multichat id additionally ignores output_mode/top_logprobs
    j3 = llm('{"model":"m","weight":{"type":"training_table","base_weight":1.0,'
             '"min_weight":0.1,"max_weight":9.0}}')
    v3 = j3.into_llm_without_indices()
    assert v3.multichat_id == v.multichat_id
    # static judges have no training table id
    s = llm('{"model":"m"}').into_llm_without_indices()
    assert s.training_table_id is None


GOLDEN_IDS = {
    # Hash-stability goldens for this framework's id space ("v1").  If these
    # change, archived model references break — never change them silently.
    '{"model":"openai/gpt-4o"}': "4qbuZ37QDDwn4bFtDBzbK1",
    '{"model":"openai/gpt-4o","weight":{"type":"static","weight":2.5}}':
        "4GN7JNEPA7UT9cm71UiNYv",
}


def test_golden_ids():
    for body, expected in GOLDEN_IDS.items():
        base = llm(body)
        base.prepare()
        got = base.id_string()
        assert got == expected, f"id drift for {body}: {got} != {expected}"


def test_golden_ids_current():
    # regenerate helper: prints current ids when goldens are first created
    base = llm('{"model":"openai/gpt-4o"}')
    base.prepare()
    assert len(base.id_string()) == 22


GOLDEN_PANEL_IDS = {
    # Panel-level hash-stability goldens: (id, multichat_id, training_table_id).
    # These are the strings the completions archive, the consensus cache
    # (cache/fingerprint.py canonicalizes a model param to its panel id), and
    # training-table snapshots key on — drift here silently orphans all three.
    # Never change them without a key-space version bump.
    '{"llms":[{"model":"openai/gpt-4o"}]}': (
        "2MYVV2IGiD5yA8EXq9zFOb", "6pSL3dcSXOgBgnXtm1n5zU", None),
    '{"llms":[{"model":"openai/gpt-4o"},'
    '{"model":"anthropic/claude-3.5-sonnet",'
    '"weight":{"type":"static","weight":2.0}},'
    '{"model":"google/gemini-1.5-pro"}]}': (
        "5P1jfD3R0tcdGqwYa5uYey", "5bdfHhHSbyqUGKasua7rL4", None),
    '{"weight":{"type":"training_table","embeddings":{"model":"bge-small-en",'
    '"max_tokens":512},"top":10},'
    '"llms":[{"model":"a","weight":{"type":"training_table","base_weight":1.0,'
    '"min_weight":0.5,"max_weight":2.0}},'
    '{"model":"b","weight":{"type":"training_table","base_weight":1.0,'
    '"min_weight":0.5,"max_weight":2.0}}]}': (
        "5WgMPWDgklyMlO9deN6uqN", "7hoX5b7QpvUZ1IwfhUitW6",
        "7YTiM3lOZYNn6lxZVj5aiK"),
}


def test_golden_panel_ids():
    for body, (eid, emc, ett) in GOLDEN_PANEL_IDS.items():
        m = ModelBase.from_json(body).into_model_validate()
        assert m.id == eid, f"panel id drift for {body}: {m.id} != {eid}"
        assert m.multichat_id == emc, (
            f"panel multichat_id drift for {body}: {m.multichat_id} != {emc}")
        assert m.training_table_id == ett, (
            f"panel training_table_id drift for {body}: "
            f"{m.training_table_id} != {ett}")


def test_model_assembly_sorted_and_indexed():
    m = ModelBase.from_json(
        '{"llms":[{"model":"zeta"},{"model":"alpha"},{"model":"alpha","weight":'
        '{"type":"static","weight":3.0}}]}'
    ).into_model_validate()
    assert [l.index for l in m.llms] == [0, 1, 2]
    assert m.llms[0].id == sorted(l.id for l in m.llms)[0]
    assert len(m.id) == 22 and len(m.multichat_id) == 22
    # same members, different declaration order -> same panel id
    m2 = ModelBase.from_json(
        '{"llms":[{"model":"alpha","weight":{"type":"static","weight":3.0}},'
        '{"model":"zeta"},{"model":"alpha"}]}'
    ).into_model_validate()
    assert m2.id == m.id
    assert m2.multichat_id == m.multichat_id


def test_model_multichat_duplicate_indices():
    # "alpha" twice with different weights = same generator, distinct slots
    m = ModelBase.from_json(
        '{"llms":[{"model":"alpha"},{"model":"alpha","weight":'
        '{"type":"static","weight":3.0}},{"model":"beta"}]}'
    ).into_model_validate()
    mc = sorted((l.multichat_id, l.multichat_index) for l in m.llms)
    ids = [x[1] for x in mc]
    assert len(set(ids)) == 3, "duplicate generators get distinct consecutive slots"


def test_model_limits():
    with pytest.raises(ValueError):
        ModelBase(llms=[]).into_model_validate()
    from llm_weighted_consensus_tpu.identity.llm import LlmBase as LB

    with pytest.raises(ValueError):
        ModelBase(llms=[LB(model=f"m{i}") for i in range(129)]).into_model_validate()


def test_panel_weight_mode_mismatch():
    with pytest.raises(ValueError):
        ModelBase.from_json(
            '{"llms":[{"model":"m","weight":{"type":"training_table",'
            '"base_weight":1.0,"min_weight":0.5,"max_weight":2.0}}]}'
        ).into_model_validate()


def test_training_table_panel():
    m = ModelBase.from_json(
        '{"weight":{"type":"training_table","embeddings":{"model":"bge-small-en",'
        '"max_tokens":512},"top":10},'
        '"llms":[{"model":"a","weight":{"type":"training_table","base_weight":1.0,'
        '"min_weight":0.5,"max_weight":2.0}},'
        '{"model":"b","weight":{"type":"training_table","base_weight":1.0,'
        '"min_weight":0.5,"max_weight":2.0}}]}'
    ).into_model_validate()
    assert m.training_table_id is not None
    assert [l.training_table_index for l in m.llms] == [0, 1]


def test_static_weights():
    m = ModelBase.from_json(
        '{"llms":[{"model":"a","weight":{"type":"static","weight":2.0}},'
        '{"model":"b"}]}'
    ).into_model_validate()
    ws = m.static_weights()
    assert sorted(ws) == [Decimal("1.0"), Decimal("2.0")]


def test_weight_static_default_frozen():
    # the default weight shape participates in hashing and must never change
    w = WeightStatic()
    assert w.to_json() == '{"type":"static","weight":1.0}'
