"""LockWitness unit tests: the runtime half of the concurrency audit.

The static rules (LWC014-016) judge the call graph; the witness judges
real acquisition order.  These tests drive the proxies directly with
tiny inline models and assert the four behaviours the chaos/soak drills
rely on: inversion detection (direct and through declared transitive
edges), RLock re-entrancy staying legal while plain-Lock re-entry is a
violation, ``Condition.wait`` releasing the held entry for the duration
of the sleep, and the undeclared-edge ledger that closes the registry's
both-ways contract at runtime.
"""

import threading

from llm_weighted_consensus_tpu.analysis.witness import LockWitness


def _model(kinds, order=(), order_runtime=()):
    return {
        "locks": {k: {"kind": v, "guards": ()} for k, v in kinds.items()},
        "order": order,
        "order_runtime": order_runtime,
    }


def _two_lock_witness(order=()):
    w = LockWitness(_model({"A": "lock", "B": "lock"}, order=order))
    a = w.wrap_lock("A", threading.Lock())
    b = w.wrap_lock("B", threading.Lock())
    return w, a, b


def test_declared_order_is_clean():
    w, a, b = _two_lock_witness(order=(("A", "B"),))
    for _ in range(3):
        with a:
            with b:
                pass
    snap = w.snapshot()
    assert snap["violations"] == []
    assert snap["undeclared"] == []
    assert snap["edges"] == [{"edge": ["A", "B"], "count": 3}]
    assert snap["acquisitions"] == 6


def test_inversion_against_declared_edge_is_violation():
    w, a, b = _two_lock_witness(order=(("A", "B"),))
    with b:
        with a:  # reverse of the declared DAG
            pass
    snap = w.snapshot()
    assert [v["kind"] for v in snap["violations"]] == ["inversion"]
    assert snap["violations"][0]["edge"] == ["B", "A"]
    # the inverse edge is also simply undeclared
    assert snap["undeclared"] == [["B", "A"]]


def test_inversion_against_observed_edge_is_violation():
    """No declared order at all: the first observed direction becomes
    the de-facto DAG, and walking it backwards later is the inversion."""
    w, a, b = _two_lock_witness()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    snap = w.snapshot()
    assert [v["kind"] for v in snap["violations"]] == ["inversion"]
    assert snap["violations"][0]["edge"] == ["B", "A"]


def test_inversion_through_declared_transitive_chain():
    """A -> B -> C declared; acquiring A under C closes a cycle through
    edges this process never even executed — reachability, not equality,
    is the check."""
    w = LockWitness(
        _model(
            {"A": "lock", "B": "lock", "C": "lock"},
            order=(("A", "B"), ("B", "C")),
        )
    )
    a = w.wrap_lock("A", threading.Lock())
    c = w.wrap_lock("C", threading.Lock())
    with c:
        with a:
            pass
    snap = w.snapshot()
    assert [v["kind"] for v in snap["violations"]] == ["inversion"]
    assert snap["violations"][0]["edge"] == ["C", "A"]


def test_rlock_reentry_is_legal():
    w = LockWitness(_model({"R": "rlock"}))
    r = w.wrap_lock("R", threading.RLock())
    with r:
        with r:
            pass
    snap = w.snapshot()
    assert snap["violations"] == []
    assert snap["edges"] == []  # self-edges are not order edges
    assert snap["acquisitions"] == 2


def test_plain_lock_reentry_is_violation():
    # the model says non-reentrant Lock; the underlying primitive is an
    # RLock so the test itself doesn't deadlock on the nested acquire —
    # the witness judges by the registry's declared kind
    w = LockWitness(_model({"L": "lock"}))
    lock = w.wrap_lock("L", threading.RLock())
    with lock:
        with lock:
            pass
    snap = w.snapshot()
    assert [v["kind"] for v in snap["violations"]] == ["reentrant"]
    assert snap["violations"][0]["lock"] == "L"


def test_condition_wait_releases_held_entry():
    """``Condition.wait``/``wait_for`` atomically release the condition:
    the proxy pops the held entry before delegating, so order edges are
    judged against what the thread REALLY holds during the sleep."""
    w = LockWitness(_model({"C": "condition"}))
    cond = w.wrap_lock("C", threading.Condition())
    held_during_wait = []

    def pred():
        held_during_wait.append(list(w._stack()))
        return True

    with cond:
        assert w._stack() == ["C"]
        cond.wait_for(pred, timeout=1.0)
        # woke up: the entry is re-pushed
        assert w._stack() == ["C"]
    assert w._stack() == []  # no leak through the pop/re-push dance
    assert held_during_wait and all(
        "C" not in held for held in held_during_wait
    )
    assert w.snapshot()["violations"] == []


def test_condition_wait_handoff_across_threads():
    w = LockWitness(_model({"C": "condition"}, order=()))
    cond = w.wrap_lock("C", threading.Condition())
    state = {"ready": False}

    def waiter():
        with cond:
            cond.wait_for(lambda: state["ready"], timeout=5.0)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    # the notifier can take the condition while the waiter sleeps in
    # wait_for — proof the proxy released it, not just the primitive
    with cond:
        state["ready"] = True
        cond.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert w.snapshot()["violations"] == []


def test_wrap_gate_counts_gate_as_one_logical_lock():
    from llm_weighted_consensus_tpu.resilience.meshfault import _ShapeGate

    w = LockWitness(_model({"G": "condition", "L": "lock"}, order=(("G", "L"),)))
    gate = w.wrap_gate(_ShapeGate(), key="G")
    lock = w.wrap_lock("L", threading.Lock())
    with gate.shared():
        with lock:
            pass
    with gate.exclusive():
        pass
    snap = w.snapshot()
    assert snap["violations"] == []
    assert snap["undeclared"] == []
    assert snap["edges"] == [{"edge": ["G", "L"], "count": 1}]


def test_snapshot_and_summary_line_shape():
    w, a, b = _two_lock_witness(order=(("A", "B"),))
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    line = w.summary_line()
    assert line == (
        "lock witness: 4 acquisitions, 2 edge(s), "
        "1 undeclared, 1 violation(s)"
    )
