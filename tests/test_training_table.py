"""TPU trained-weights path: embedding lookup -> per-judge weights within
[min, max] bounds; evidence echo + usage seeding (SURVEY §2.1 weight seam)."""

import asyncio
from decimal import Decimal

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.models.configs import TEST_TINY
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.types.score_response import TrainingTableData
from llm_weighted_consensus_tpu.weights.training_table import (
    TpuTrainingTableFetcher,
    TrainingTableStore,
)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def tt_model(n_judges=2):
    return ModelBase.from_json_obj(
        {
            "llms": [
                {
                    "model": f"judge-{i}",
                    "weight": {
                        "type": "training_table",
                        "base_weight": 1,
                        "min_weight": 1,
                        "max_weight": 5,
                    },
                }
                for i in range(n_judges)
            ],
            "weight": {
                "type": "training_table",
                "embeddings": {"model": "test-tiny", "max_tokens": 32},
                "top": 3,
            },
        }
    ).into_model_validate()


def params(text="what is the answer?"):
    return ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": text}],
            "model": "x" * 22,
            "choices": ["a", "b"],
        }
    )


@pytest.fixture(scope="module")
def embedder():
    return TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=5)


def test_fallback_to_base_weight_without_table(embedder):
    model = tt_model()
    fetcher = TpuTrainingTableFetcher(embedder)
    weights, data = go(fetcher.fetch(None, params(), model))
    assert weights == [Decimal(1), Decimal(1)]
    assert isinstance(data, TrainingTableData)
    assert data.embeddings_response.usage.total_tokens > 0
    assert len(data.embeddings_response.data) == 1


def test_table_lookup_discriminates_judges(embedder):
    model = tt_model()
    store = TrainingTableStore()
    prompt = "what is the answer?"
    query_vec = embedder.embed_texts([prompt])[0]
    rng = np.random.default_rng(0)
    near = np.stack([query_vec + 0.001 * rng.normal(size=query_vec.shape) for _ in range(5)])
    # judge 0: historically perfect on similar prompts; judge 1: terrible
    good, bad = model.llms[0], model.llms[1]
    store.add_rows(good.training_table_id, near, np.ones(5))
    store.add_rows(bad.training_table_id, near, np.zeros(5))
    fetcher = TpuTrainingTableFetcher(embedder, store)
    weights, _ = go(fetcher.fetch(None, params(prompt), model))
    w = {llm.index: weights[llm.index] for llm in model.llms}
    assert float(w[good.index]) == pytest.approx(5.0, abs=0.3)
    assert float(w[bad.index]) == pytest.approx(1.0, abs=0.3)
    # bounds respected
    assert all(Decimal(1) <= x <= Decimal(5) for x in weights)


def test_store_appends_rows():
    store = TrainingTableStore()
    store.add_rows("t1", np.ones((2, 4)), np.ones(2))
    store.add_rows("t1", np.zeros((3, 4)), np.zeros(3))
    emb, scores = store.get("t1")
    assert emb.shape == (5, 4) and scores.shape == (5,)
    assert store.get("missing") is None
