"""TPU trained-weights path: embedding lookup -> per-judge weights within
[min, max] bounds; evidence echo + usage seeding (SURVEY §2.1 weight seam)."""

import asyncio
from decimal import Decimal

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.models.configs import TEST_TINY
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.types.score_response import TrainingTableData
from llm_weighted_consensus_tpu.weights.training_table import (
    TpuTrainingTableFetcher,
    TrainingTableStore,
)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def tt_model(n_judges=2):
    return ModelBase.from_json_obj(
        {
            "llms": [
                {
                    "model": f"judge-{i}",
                    "weight": {
                        "type": "training_table",
                        "base_weight": 1,
                        "min_weight": 1,
                        "max_weight": 5,
                    },
                }
                for i in range(n_judges)
            ],
            "weight": {
                "type": "training_table",
                "embeddings": {"model": "test-tiny", "max_tokens": 32},
                "top": 3,
            },
        }
    ).into_model_validate()


def params(text="what is the answer?"):
    return ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": text}],
            "model": "x" * 22,
            "choices": ["a", "b"],
        }
    )


@pytest.fixture(scope="module")
def embedder():
    return TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=5)


def test_fallback_to_base_weight_without_table(embedder):
    model = tt_model()
    fetcher = TpuTrainingTableFetcher(embedder)
    weights, data = go(fetcher.fetch(None, params(), model))
    assert weights == [Decimal(1), Decimal(1)]
    assert isinstance(data, TrainingTableData)
    assert data.embeddings_response.usage.total_tokens > 0
    assert len(data.embeddings_response.data) == 1


def test_table_lookup_discriminates_judges(embedder):
    model = tt_model()
    store = TrainingTableStore()
    prompt = "what is the answer?"
    query_vec = embedder.embed_texts([prompt])[0]
    rng = np.random.default_rng(0)
    near = np.stack([query_vec + 0.001 * rng.normal(size=query_vec.shape) for _ in range(5)])
    # judge 0: historically perfect on similar prompts; judge 1: terrible
    good, bad = model.llms[0], model.llms[1]
    store.add_rows(good.training_table_id, near, np.ones(5))
    store.add_rows(bad.training_table_id, near, np.zeros(5))
    fetcher = TpuTrainingTableFetcher(embedder, store)
    weights, _ = go(fetcher.fetch(None, params(prompt), model))
    w = {llm.index: weights[llm.index] for llm in model.llms}
    assert float(w[good.index]) == pytest.approx(5.0, abs=0.3)
    assert float(w[bad.index]) == pytest.approx(1.0, abs=0.3)
    # bounds respected
    assert all(Decimal(1) <= x <= Decimal(5) for x in weights)


def test_store_appends_rows():
    store = TrainingTableStore()
    store.add_rows("t1", np.ones((2, 4)), np.ones(2))
    store.add_rows("t1", np.zeros((3, 4)), np.zeros(3))
    emb, scores = store.get("t1")
    assert emb.shape == (5, 4) and scores.shape == (5,)
    assert store.get("missing") is None


# -- archive batch re-score (BASELINE config 4) -------------------------------


def test_archive_rescore_reweighting():
    import random

    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.archive.rescore import (
        apply_rescore,
        rescore_archive,
        vote_matrix,
    )
    from llm_weighted_consensus_tpu.ballot import PrefixTree
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from fakes import FakeTransport, Script, chunk_obj

    SEED = 13
    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, 2, 20)
    keys = {idx: k for k, idx in tree.key_indices(rng)}

    model = ModelBase.from_json_obj(
        {
            "llms": [
                {"model": "j-a", "weight": {"type": "static", "weight": 1}},
                {"model": "j-b", "weight": {"type": "static", "weight": 1}},
            ]
        }
    ).into_model_validate()
    order = [llm.base.model for llm in model.llms]
    by_model = {
        "j-a": Script([chunk_obj(f"pick {keys[0]}", model="j-a", finish="stop")]),
        "j-b": Script([chunk_obj(f"pick {keys[1]}", model="j-b", finish="stop")]),
    }
    transport = FakeTransport([by_model[m] for m in order])
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, registry.InMemoryModelRegistry(), archive_fetcher=store,
        rng_factory=lambda: random.Random(SEED),
    )
    result = go(
        score.create_unary(
            None,
            ScoreParams.from_json_obj(
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": {"llms": [llm.base.to_json_obj() for llm in model.llms]},
                    "choices": ["a", "b"],
                }
            ),
        )
    )
    store.put_score(result)

    # stored tie: 0.5 / 0.5
    votes, weights, mask = vote_matrix(result)
    assert votes.shape == (2, 2) and mask.tolist() == [1.0, 1.0]
    cand = {c.index: c for c in result.choices if c.index < 2}
    assert float(cand[0].confidence) == pytest.approx(0.5)

    # reweight judge a 3:1 and re-tally on device - no upstream requests
    a_id = next(llm.id for llm in model.llms if llm.base.model == "j-a")
    results = rescore_archive(store, weight_overrides={a_id: 3.0})
    conf = [float(x) for x in results[result.id]["confidence"]]
    assert conf[0] == pytest.approx(0.75) and conf[1] == pytest.approx(0.25)
    assert apply_rescore(store, results) == 1
    assert float(cand[0].confidence) == pytest.approx(0.75)


def test_archive_rescore_mesh_10k_shape():
    """config-4 shape: thousands of archived vote matrices, one mesh batch."""
    from llm_weighted_consensus_tpu.parallel import make_mesh
    from llm_weighted_consensus_tpu.parallel.batch import rescore_batch

    rng = np.random.default_rng(0)
    b, m, n = 2048, 8, 8
    votes = rng.random((b, m, n)).astype(np.float32)
    votes /= votes.sum(axis=2, keepdims=True)
    weights = rng.uniform(0.5, 2.0, (b, m)).astype(np.float32)
    mesh = make_mesh(dp=8, tp=1)
    _, conf = rescore_batch(votes, weights, mesh=mesh)
    assert conf.shape == (b, n)
    np.testing.assert_allclose(np.asarray(conf).sum(axis=1), 1.0, atol=1e-5)


# -- device soft-vote re-extraction (revote) ----------------------------------


def _soft_vote_archive(p0=0.7, seed=13):
    """One-judge (top_logprobs=2) score completion archived WITH its ballot:
    the judge's key token carries a {p0, 1-p0} top_logprobs distribution
    over the two sibling letters."""
    import math
    import random

    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.ballot import PrefixTree
    from llm_weighted_consensus_tpu.ballot.tree import branch_limit
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from fakes import FakeTransport, Script, chunk_obj

    rng = random.Random(seed)
    tree = PrefixTree.build(rng, 2, branch_limit(2))
    pairs = tree.key_indices(rng)
    key0 = pairs[0][0]
    branch = tree.walk(key0)
    letters = list(branch)
    lp = {
        "content": [
            {"token": "`", "logprob": -0.01, "top_logprobs": []},
            {
                "token": key0[1],
                "logprob": math.log(p0),
                "top_logprobs": [
                    {"token": letters[0], "logprob": math.log(p0)},
                    {"token": letters[1], "logprob": math.log(1 - p0)},
                ],
            },
            {"token": "`", "logprob": -0.01, "top_logprobs": []},
        ]
    }
    transport = FakeTransport(
        [Script([chunk_obj(key0, finish="stop", logprobs=lp)])]
    )
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, registry.InMemoryModelRegistry(), archive_fetcher=store,
        rng_factory=lambda: random.Random(seed),
        ballot_sink=store.put_ballot,
    )
    result = go(
        score.create_unary(
            None,
            ScoreParams.from_json_obj(
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": {
                        "llms": [
                            {
                                "model": "judge-a",
                                "top_logprobs": 2,
                                "weight": {"type": "static", "weight": 1},
                            }
                        ]
                    },
                    "choices": ["a", "b"],
                }
            ),
        )
    )
    store.put_score(result)
    return store, result, branch, letters


def test_revote_matches_host_decimal_extraction():
    """Device re-extraction (softmax_votes over stored logprobs) must agree
    with the live host Decimal path that produced the stored votes."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    judge = [c for c in result.choices if c.index >= 2][0]
    host_vote = [float(v) for v in judge.message.vote]
    assert sum(host_vote) == pytest.approx(1.0)

    results = rescore_archive(store, revote=True)
    conf = [float(x) for x in results[result.id]["confidence"]]
    np.testing.assert_allclose(conf, host_vote, atol=1e-6)


def test_revote_recomputes_from_tampered_logprobs():
    """revote re-derives votes from logprobs — it must track a changed
    distribution while the stored-vote path keeps the old one."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    import math
    from decimal import Decimal

    judge = [c for c in result.choices if c.index >= 2][0]
    alts = judge.logprobs.content[1].top_logprobs
    alts[0].logprob = Decimal(str(math.log(0.2)))
    alts[1].logprob = Decimal(str(math.log(0.8)))

    stale = rescore_archive(store, revote=False)[result.id]["confidence"]
    fresh = rescore_archive(store, revote=True)[result.id]["confidence"]
    i0, i1 = branch[letters[0]], branch[letters[1]]
    assert float(stale[i0]) == pytest.approx(0.7, abs=1e-6)
    assert float(fresh[i0]) == pytest.approx(0.2, abs=1e-6)
    assert float(fresh[i1]) == pytest.approx(0.8, abs=1e-6)


def test_revote_without_ballots_falls_back_to_stored_votes():
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, *_ = _soft_vote_archive(p0=0.6)
    store._ballots.clear()  # simulate an archive without ballot records
    with_stored = rescore_archive(store, revote=False)[result.id]
    fallback = rescore_archive(store, revote=True)[result.id]
    assert [float(x) for x in fallback["confidence"]] == pytest.approx(
        [float(x) for x in with_stored["confidence"]]
    )


def test_revote_handles_tick_stripped_content():
    """A judge that answered without backtick quoting still re-extracts:
    find_key returns the stripped key and leaf_branch_of matches it by
    letter sequence (as the live tree.walk does)."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    judge = [c for c in result.choices if c.index >= 2][0]
    judge.message.content = judge.message.content.replace("`", "")
    host_vote = [float(v) for v in judge.message.vote]

    results = rescore_archive(store, revote=True)
    conf = [float(x) for x in results[result.id]["confidence"]]
    np.testing.assert_allclose(conf, host_vote, atol=1e-6)


def test_archive_snapshot_round_trip(tmp_path):
    """save -> load preserves completions (Decimal-exact votes), ballots,
    and keeps device revote working from the reloaded store."""
    from llm_weighted_consensus_tpu import archive
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    path = str(tmp_path / "archive.json")
    store.save(path)
    reloaded = archive.InMemoryArchive.load(path)

    assert reloaded.score_ids() == store.score_ids()
    orig = store._score[result.id]
    copy = reloaded._score[result.id]
    assert copy.to_json_obj() == orig.to_json_obj()
    judge = [c for c in copy.choices if c.index >= 2][0]
    # Decimal-exact vote round trip
    assert judge.message.vote == [
        c.message.vote for c in orig.choices if c.index >= 2
    ][0]
    assert reloaded.score_ballots(result.id) is not None

    before = rescore_archive(store, revote=True)[result.id]["confidence"]
    after = rescore_archive(reloaded, revote=True)[result.id]["confidence"]
    assert [float(x) for x in after] == pytest.approx(
        [float(x) for x in before]
    )


def test_archive_snapshot_rejects_unknown_version(tmp_path):
    from llm_weighted_consensus_tpu import archive

    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write('{"version": 99}')
    with pytest.raises(ValueError, match="version"):
        archive.InMemoryArchive.load(path)


def test_ballot_table_is_bounded():
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    cap = store.MAX_BALLOT_COMPLETIONS
    for i in range(cap + 10):
        store.put_ballot(f"scrcpl-{i}", 0, [("`A`", 0), ("`B`", 1)])
    assert len(store._ballots) == cap
    # FIFO: oldest evicted, newest kept
    assert store.score_ballots("scrcpl-0") is None
    assert store.score_ballots(f"scrcpl-{cap + 9}") is not None


class _ScoreStub:
    def __init__(self, cid):
        self.id = cid


def test_ballot_eviction_prefers_unarchived():
    """FIFO eviction must never drop an ARCHIVED completion's ballots —
    those are exactly the ones revote still needs — and archived entries
    do not count against the orphan cap."""
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    cap = store.MAX_BALLOT_COMPLETIONS
    store.put_ballot("scrcpl-keep", 0, [("`A`", 0)])
    store.put_score(_ScoreStub("scrcpl-keep"))  # archived
    for i in range(cap + 5):
        store.put_ballot(f"scrcpl-{i}", 0, [("`A`", 0)])
    assert store.score_ballots("scrcpl-keep") is not None
    # cap orphans + the archived one
    assert len(store._ballots) == cap + 1


# -- training-table learning from archived outcomes ---------------------------


def _panel_and_archive(embedder, votes_by_judge, prompt="what is 2+2?"):
    """Run one score request through the real client with J judges voting
    per ``votes_by_judge`` (list of candidate indices), archive completion
    + request, return (store, model, result)."""
    import random

    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.ballot import PrefixTree
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from fakes import FakeTransport, Script, chunk_obj

    seed = 17
    rng = random.Random(seed)
    tree = PrefixTree.build(rng, 2, 20)
    keys = {idx: k for k, idx in tree.key_indices(rng)}

    model = ModelBase.from_json_obj(
        {
            "llms": [
                {
                    "model": f"learn-judge-{j}",
                    "weight": {
                        "type": "training_table",
                        "base_weight": 1,
                        "min_weight": 1,
                        "max_weight": 5,
                    },
                }
                for j in range(len(votes_by_judge))
            ],
            "weight": {
                "type": "training_table",
                "embeddings": {"model": "test-tiny", "max_tokens": 32},
                "top": 3,
            },
        }
    ).into_model_validate()
    # scripts are consumed in panel (sorted-by-id) order; map back to the
    # requested vote per judge name
    vote_by_name = {
        f"learn-judge-{j}": v for j, v in enumerate(votes_by_judge)
    }
    scripts = [
        Script(
            [
                chunk_obj(
                    f"pick {keys[vote_by_name[llm.base.model]]}",
                    model=llm.base.model,
                    finish="stop",
                )
            ]
        )
        for llm in model.llms
    ]
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    from llm_weighted_consensus_tpu.weights import WeightFetchers

    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, registry.InMemoryModelRegistry(), archive_fetcher=store,
        rng_factory=lambda: random.Random(seed),
        weight_fetchers=WeightFetchers(
            training_table_fetcher=TpuTrainingTableFetcher(embedder)
        ),
    )
    params = ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": prompt}],
            "model": {
                "llms": [llm.base.to_json_obj() for llm in model.llms],
                "weight": {
                    "type": "training_table",
                    "embeddings": {"model": "test-tiny", "max_tokens": 32},
                    "top": 3,
                },
            },
            "choices": ["four", "five"],
        }
    )
    result = go(score.create_unary(None, params))
    store.put_score(result)
    store.put_score_request(result.id, params)
    return store, model, result


def test_judge_alignment_scores_self_consistency_and_supervised(embedder):
    from llm_weighted_consensus_tpu.weights.learning import (
        judge_alignment_scores,
    )

    # 3 judges: two vote candidate 0, one votes candidate 1 -> conf 2/3, 1/3
    store, model, result = _panel_and_archive(embedder, [0, 0, 1])
    scores = judge_alignment_scores(result)
    by_name = {}
    for choice in result.choices:
        if choice.model_index is not None:
            llm = next(
                l for l in model.llms if l.id == choice.model
            )
            by_name[llm.base.model] = scores[choice.model_index]
    assert by_name["learn-judge-0"] == pytest.approx(2 / 3)
    assert by_name["learn-judge-1"] == pytest.approx(2 / 3)
    assert by_name["learn-judge-2"] == pytest.approx(1 / 3)

    # supervised: candidate 1 was actually correct
    supervised = judge_alignment_scores(result, label=1)
    by_name_sup = {}
    for choice in result.choices:
        if choice.model_index is not None:
            llm = next(l for l in model.llms if l.id == choice.model)
            by_name_sup[llm.base.model] = supervised[choice.model_index]
    assert by_name_sup["learn-judge-0"] == 0.0
    assert by_name_sup["learn-judge-2"] == 1.0


def test_populate_from_archive_closes_the_loop(embedder):
    """serve -> archive -> learn -> the next lookup weights majority judges
    above the dissenter."""
    import asyncio
    from decimal import Decimal

    from llm_weighted_consensus_tpu.weights.learning import (
        populate_from_archive,
    )
    from llm_weighted_consensus_tpu.weights.training_table import (
        TpuTrainingTableFetcher,
        TrainingTableStore,
    )

    prompt = "what is 2+2?"
    store, model, result = _panel_and_archive(
        embedder, [0, 0, 1], prompt=prompt
    )
    tables = TrainingTableStore()
    added = populate_from_archive(store, embedder, model, tables)
    assert added == 3  # one row per judge
    assert len(tables) == 3  # distinct training_table_ids... or fewer

    fetcher = TpuTrainingTableFetcher(embedder, tables)
    request = store.score_request(result.id)
    weights, _ = asyncio.new_event_loop().run_until_complete(
        fetcher.fetch(None, request, model)
    )
    by_name = {
        llm.base.model: float(weights[llm.index]) for llm in model.llms
    }
    assert by_name["learn-judge-0"] > by_name["learn-judge-2"]
    assert by_name["learn-judge-0"] == pytest.approx(
        1 + (5 - 1) * 2 / 3, abs=0.2
    )
    assert all(Decimal(1) <= w <= Decimal(5) for w in weights)


def test_training_table_store_snapshot_round_trip(tmp_path):
    from llm_weighted_consensus_tpu.weights.training_table import (
        TrainingTableStore,
    )

    store = TrainingTableStore()
    rng = np.random.default_rng(0)
    store.add_rows("t1", rng.random((3, 8)), np.asarray([0.1, 0.5, 0.9]))
    store.add_rows("t2", rng.random((2, 8)), np.asarray([1.0, 0.0]))
    path = str(tmp_path / "tables.npz")
    store.save(path)
    loaded = TrainingTableStore.load(path)
    assert len(loaded) == 2
    for tid in ("t1", "t2"):
        e0, s0 = store.get(tid)
        e1, s1 = loaded.get(tid)
        np.testing.assert_array_equal(e0, e1)
        np.testing.assert_array_equal(s0, s1)


def test_populate_is_idempotent(embedder):
    from llm_weighted_consensus_tpu.weights.learning import (
        populate_from_archive,
    )
    from llm_weighted_consensus_tpu.weights.training_table import (
        TrainingTableStore,
    )

    store, model, result = _panel_and_archive(embedder, [0, 1])
    tables = TrainingTableStore()
    assert populate_from_archive(store, embedder, model, tables) == 2
    # a second sync pass over the same archive adds nothing
    assert populate_from_archive(store, embedder, model, tables) == 0
    emb, scores = tables.get(model.llms[0].training_table_id)
    assert emb.shape[0] == 1


def test_weights_learn_endpoint_and_tables_snapshot(embedder, tmp_path):
    """POST /weights/learn over the live service: archive -> rows; the
    tables snapshot persists on shutdown and reloads."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from llm_weighted_consensus_tpu.serve import Config
    from llm_weighted_consensus_tpu.serve.__main__ import (
        ARCHIVE_KEY,
        TABLES_KEY,
        build_service,
    )
    from llm_weighted_consensus_tpu.utils import jsonutil
    from llm_weighted_consensus_tpu.weights.training_table import (
        TrainingTableStore,
    )

    seed_store, model, result = _panel_and_archive(embedder, [0, 0, 1])
    tables_path = str(tmp_path / "tables.npz")
    config = Config.from_env(
        {
            "EMBEDDER_MODEL": "test-tiny",
            "EMBEDDER_MAX_TOKENS": "32",
            "TABLES_PATH": tables_path,
        }
    )
    app = build_service(config, fake_upstream=True)
    # seed the service's archive with the externally-scored history
    app[ARCHIVE_KEY]._score.update(seed_store._score)
    app[ARCHIVE_KEY]._score_requests.update(seed_store._score_requests)

    body = jsonutil.dumps(
        {"model": {
            "llms": [llm.base.to_json_obj() for llm in model.llms],
            "weight": {
                "type": "training_table",
                "embeddings": {"model": "test-tiny", "max_tokens": 32},
                "top": 3,
            },
        }}
    )

    async def run():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.post(
                "/weights/learn",
                data=body,
                headers={"content-type": "application/json"},
            )
            assert resp.status == 200
            assert (await resp.json())["rows_added"] == 3
            # idempotent second pass
            resp = await client.post(
                "/weights/learn",
                data=body,
                headers={"content-type": "application/json"},
            )
            assert (await resp.json())["rows_added"] == 0
            # malformed body is a clean 400
            resp = await client.post(
                "/weights/learn",
                data=b"{}",
                headers={"content-type": "application/json"},
            )
            assert resp.status == 400
        finally:
            await client.close()  # -> on_cleanup -> tables snapshot

    asyncio.new_event_loop().run_until_complete(run())
    assert len(app[TABLES_KEY]) == 3
    reloaded = TrainingTableStore.load(tables_path)
    assert len(reloaded) == 3
    # ingestion keys are table-scoped: one per judge table for this cid
    assert any(
        key.endswith(f"/{result.id}") for key in reloaded._ingested
    )


def test_fetcher_keeps_shared_empty_store(embedder):
    """Regression: an EMPTY shared store is falsy (len 0); the fetcher must
    still use it — learning populates it AFTER the fetcher is built."""
    from llm_weighted_consensus_tpu.weights.training_table import (
        TpuTrainingTableFetcher,
        TrainingTableStore,
    )

    shared = TrainingTableStore()
    fetcher = TpuTrainingTableFetcher(embedder, shared)
    assert fetcher.store is shared
    shared.add_rows("t", np.ones((1, 4)), np.ones(1))
    assert fetcher.store.get("t") is not None


def test_ballot_cap_never_starves_inflight_or_archived():
    """Saturation regression: with the cap full of ARCHIVED ballots, a new
    in-flight request's ballots must survive until its put_score."""
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    cap = store.MAX_BALLOT_COMPLETIONS
    for i in range(cap):
        cid = f"scrcpl-{i}"
        store.put_ballot(cid, 0, [("`A`", 0)])
        store.put_score(_ScoreStub(cid))  # archived
    store.put_ballot("scrcpl-inflight", 0, [("`A`", 0)])
    assert store.score_ballots("scrcpl-inflight") is not None
    # archived ones all retained too (growth beyond cap is the archive's)
    assert store.score_ballots("scrcpl-0") is not None


def test_archived_ballots_beyond_cap_do_not_drain_inflight_orphans():
    """ADVICE r3: an archive holding more than MAX_BALLOT_COMPLETIONS
    archived-with-ballots completions must NOT put the eviction loop
    permanently over cap — concurrent in-flight requests' ballots all
    survive each other's put_ballot calls."""
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    store.MAX_BALLOT_COMPLETIONS = 4  # instance override: cheap test
    for i in range(10):  # 10 archived-with-ballots > cap of 4
        cid = f"scrcpl-{i}"
        store.put_ballot(cid, 0, [("`A`", 0)])
        store.put_score(_ScoreStub(cid))
    # interleaved in-flight requests: under the old total-ballots cap,
    # every put_ballot here drained the OTHER request's orphan ballots
    store.put_ballot("scrcpl-a", 0, [("`A`", 0)])
    store.put_ballot("scrcpl-b", 0, [("`A`", 0)])
    store.put_ballot("scrcpl-a", 1, [("`B`", 1)])
    store.put_ballot("scrcpl-b", 1, [("`B`", 1)])
    assert store.score_ballots("scrcpl-a") == {0: [("`A`", 0)], 1: [("`B`", 1)]}
    assert store.score_ballots("scrcpl-b") == {0: [("`A`", 0)], 1: [("`B`", 1)]}
    assert len(store._ballots) == 12  # 10 archived + 2 orphans


def test_late_ballot_for_oldest_orphan_does_not_wedge_eviction():
    """ADVICE r3: when the FIFO front IS the in-flight completion (a late
    ballot for an old, still-orphaned completion), eviction must rotate
    past it and keep draining newer orphans instead of breaking while
    over cap."""
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    store.put_ballot("scrcpl-x", 0, [("`A`", 0)])  # oldest orphan
    for i in range(5):
        store.put_ballot(f"scrcpl-y{i}", 0, [("`A`", 0)])
    # drop the cap below the live orphan count, then deliver a late
    # ballot for the FIFO-front completion
    store.MAX_BALLOT_COMPLETIONS = 4
    store.put_ballot("scrcpl-x", 1, [("`B`", 1)])
    # the in-flight front survives; the OLDEST other orphans were evicted
    assert store.score_ballots("scrcpl-x") is not None
    assert store.score_ballots("scrcpl-y0") is None
    assert store.score_ballots("scrcpl-y1") is None
    assert store.score_ballots("scrcpl-y4") is not None
    assert len(store._ballots) == 4


def test_second_panel_learns_from_same_archive(embedder):
    """Cross-panel learning semantics: a RE-WEIGHTED panel shares its
    judges' weight-invariant table ids (no duplicate rows, lookups just
    work); a panel with genuinely different judge configs gets its own
    tables populated from the same archived history."""
    from llm_weighted_consensus_tpu.weights.learning import (
        populate_from_archive,
    )
    from llm_weighted_consensus_tpu.weights.training_table import (
        TrainingTableStore,
    )

    def panel(extra=None):
        judges = []
        for j in range(2):
            judge = {
                "model": f"learn-judge-{j}",
                "weight": {
                    "type": "training_table",
                    "base_weight": 1,
                    "min_weight": 1,
                    "max_weight": 5,
                },
            }
            judge.update(extra or {})
            judges.append(judge)
        return ModelBase.from_json_obj(
            {
                "llms": judges,
                "weight": {
                    "type": "training_table",
                    "embeddings": {"model": "test-tiny", "max_tokens": 32},
                    "top": 3,
                },
            }
        ).into_model_validate()

    store, model_a, result = _panel_and_archive(embedder, [0, 1])
    tables = TrainingTableStore()
    assert populate_from_archive(store, embedder, model_a, tables) == 2

    # re-weighted panel: same judges, new weight bounds -> SAME table ids
    # (weight-invariant identity) -> nothing to re-learn, no duplicates
    reweighted = panel({"weight": {
        "type": "training_table", "base_weight": 2,
        "min_weight": 1, "max_weight": 5,
    }})
    # same tt ids (panels sort judges by full id, so compare as sets)
    assert {l.training_table_id for l in reweighted.llms} == {
        l.training_table_id for l in model_a.llms
    }
    assert populate_from_archive(store, embedder, reweighted, tables) == 0
    emb, _ = tables.get(model_a.llms[0].training_table_id)
    assert emb.shape[0] == 1  # still one row per judge

    # genuinely different judge config (temperature) -> new table ids ->
    # the same archived history is learned into the new tables (matched
    # via the archived request's inline panel, weight-invariant ids)
    hotter = panel({"temperature": 0.5})
    assert hotter.llms[0].training_table_id != model_a.llms[0].training_table_id
    # matching falls back to the ARCHIVED judges' ids, so these rows are
    # keyed by the archived tables (already ingested) -> 0 new rows; the
    # hotter panel's own tables stay empty because no archived judge
    # matches its config
    assert populate_from_archive(store, embedder, hotter, tables) == 0
    assert tables.get(hotter.llms[0].training_table_id) is None


def test_populate_duplicate_ids_and_failure_do_not_poison(embedder):
    from llm_weighted_consensus_tpu.weights.learning import (
        populate_from_archive,
    )
    from llm_weighted_consensus_tpu.weights.training_table import (
        TrainingTableStore,
    )

    store, model, result = _panel_and_archive(embedder, [0, 1])
    tables = TrainingTableStore()
    # duplicate ids in one call add rows once
    added = populate_from_archive(
        store, embedder, model, tables, ids=[result.id, result.id]
    )
    assert added == 2
    emb, _ = tables.get(model.llms[0].training_table_id)
    assert emb.shape[0] == 1

    # a failing add_rows must NOT mark anything ingested
    fresh = TrainingTableStore()
    # poison the table with wrong-dim rows so concatenate raises
    for llm in model.llms:
        fresh.add_rows(llm.training_table_id, np.ones((1, 3)), np.ones(1))
    with pytest.raises(ValueError):
        populate_from_archive(store, embedder, model, fresh)
    assert not any(
        key.endswith(f"/{result.id}") for key in fresh._ingested
    )
