"""TPU trained-weights path: embedding lookup -> per-judge weights within
[min, max] bounds; evidence echo + usage seeding (SURVEY §2.1 weight seam)."""

import asyncio
from decimal import Decimal

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.models.configs import TEST_TINY
from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
from llm_weighted_consensus_tpu.types.score_request import (
    ChatCompletionCreateParams as ScoreParams,
)
from llm_weighted_consensus_tpu.types.score_response import TrainingTableData
from llm_weighted_consensus_tpu.weights.training_table import (
    TpuTrainingTableFetcher,
    TrainingTableStore,
)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def tt_model(n_judges=2):
    return ModelBase.from_json_obj(
        {
            "llms": [
                {
                    "model": f"judge-{i}",
                    "weight": {
                        "type": "training_table",
                        "base_weight": 1,
                        "min_weight": 1,
                        "max_weight": 5,
                    },
                }
                for i in range(n_judges)
            ],
            "weight": {
                "type": "training_table",
                "embeddings": {"model": "test-tiny", "max_tokens": 32},
                "top": 3,
            },
        }
    ).into_model_validate()


def params(text="what is the answer?"):
    return ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": text}],
            "model": "x" * 22,
            "choices": ["a", "b"],
        }
    )


@pytest.fixture(scope="module")
def embedder():
    return TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=5)


def test_fallback_to_base_weight_without_table(embedder):
    model = tt_model()
    fetcher = TpuTrainingTableFetcher(embedder)
    weights, data = go(fetcher.fetch(None, params(), model))
    assert weights == [Decimal(1), Decimal(1)]
    assert isinstance(data, TrainingTableData)
    assert data.embeddings_response.usage.total_tokens > 0
    assert len(data.embeddings_response.data) == 1


def test_table_lookup_discriminates_judges(embedder):
    model = tt_model()
    store = TrainingTableStore()
    prompt = "what is the answer?"
    query_vec = embedder.embed_texts([prompt])[0]
    rng = np.random.default_rng(0)
    near = np.stack([query_vec + 0.001 * rng.normal(size=query_vec.shape) for _ in range(5)])
    # judge 0: historically perfect on similar prompts; judge 1: terrible
    good, bad = model.llms[0], model.llms[1]
    store.add_rows(good.training_table_id, near, np.ones(5))
    store.add_rows(bad.training_table_id, near, np.zeros(5))
    fetcher = TpuTrainingTableFetcher(embedder, store)
    weights, _ = go(fetcher.fetch(None, params(prompt), model))
    w = {llm.index: weights[llm.index] for llm in model.llms}
    assert float(w[good.index]) == pytest.approx(5.0, abs=0.3)
    assert float(w[bad.index]) == pytest.approx(1.0, abs=0.3)
    # bounds respected
    assert all(Decimal(1) <= x <= Decimal(5) for x in weights)


def test_store_appends_rows():
    store = TrainingTableStore()
    store.add_rows("t1", np.ones((2, 4)), np.ones(2))
    store.add_rows("t1", np.zeros((3, 4)), np.zeros(3))
    emb, scores = store.get("t1")
    assert emb.shape == (5, 4) and scores.shape == (5,)
    assert store.get("missing") is None


# -- archive batch re-score (BASELINE config 4) -------------------------------


def test_archive_rescore_reweighting():
    import random

    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.archive.rescore import (
        apply_rescore,
        rescore_archive,
        vote_matrix,
    )
    from llm_weighted_consensus_tpu.ballot import PrefixTree
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from fakes import FakeTransport, Script, chunk_obj

    SEED = 13
    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, 2, 20)
    keys = {idx: k for k, idx in tree.key_indices(rng)}

    model = ModelBase.from_json_obj(
        {
            "llms": [
                {"model": "j-a", "weight": {"type": "static", "weight": 1}},
                {"model": "j-b", "weight": {"type": "static", "weight": 1}},
            ]
        }
    ).into_model_validate()
    order = [llm.base.model for llm in model.llms]
    by_model = {
        "j-a": Script([chunk_obj(f"pick {keys[0]}", model="j-a", finish="stop")]),
        "j-b": Script([chunk_obj(f"pick {keys[1]}", model="j-b", finish="stop")]),
    }
    transport = FakeTransport([by_model[m] for m in order])
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, registry.InMemoryModelRegistry(), archive_fetcher=store,
        rng_factory=lambda: random.Random(SEED),
    )
    result = go(
        score.create_unary(
            None,
            ScoreParams.from_json_obj(
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": {"llms": [llm.base.to_json_obj() for llm in model.llms]},
                    "choices": ["a", "b"],
                }
            ),
        )
    )
    store.put_score(result)

    # stored tie: 0.5 / 0.5
    votes, weights, mask = vote_matrix(result)
    assert votes.shape == (2, 2) and mask.tolist() == [1.0, 1.0]
    cand = {c.index: c for c in result.choices if c.index < 2}
    assert float(cand[0].confidence) == pytest.approx(0.5)

    # reweight judge a 3:1 and re-tally on device - no upstream requests
    a_id = next(llm.id for llm in model.llms if llm.base.model == "j-a")
    results = rescore_archive(store, weight_overrides={a_id: 3.0})
    conf = [float(x) for x in results[result.id]["confidence"]]
    assert conf[0] == pytest.approx(0.75) and conf[1] == pytest.approx(0.25)
    assert apply_rescore(store, results) == 1
    assert float(cand[0].confidence) == pytest.approx(0.75)


def test_archive_rescore_mesh_10k_shape():
    """config-4 shape: thousands of archived vote matrices, one mesh batch."""
    from llm_weighted_consensus_tpu.parallel import make_mesh
    from llm_weighted_consensus_tpu.parallel.batch import rescore_batch

    rng = np.random.default_rng(0)
    b, m, n = 2048, 8, 8
    votes = rng.random((b, m, n)).astype(np.float32)
    votes /= votes.sum(axis=2, keepdims=True)
    weights = rng.uniform(0.5, 2.0, (b, m)).astype(np.float32)
    mesh = make_mesh(dp=8, tp=1)
    _, conf = rescore_batch(votes, weights, mesh=mesh)
    assert conf.shape == (b, n)
    np.testing.assert_allclose(np.asarray(conf).sum(axis=1), 1.0, atol=1e-5)


# -- device soft-vote re-extraction (revote) ----------------------------------


def _soft_vote_archive(p0=0.7, seed=13):
    """One-judge (top_logprobs=2) score completion archived WITH its ballot:
    the judge's key token carries a {p0, 1-p0} top_logprobs distribution
    over the two sibling letters."""
    import math
    import random

    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.ballot import PrefixTree
    from llm_weighted_consensus_tpu.ballot.tree import branch_limit
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from fakes import FakeTransport, Script, chunk_obj

    rng = random.Random(seed)
    tree = PrefixTree.build(rng, 2, branch_limit(2))
    pairs = tree.key_indices(rng)
    key0 = pairs[0][0]
    branch = tree.walk(key0)
    letters = list(branch)
    lp = {
        "content": [
            {"token": "`", "logprob": -0.01, "top_logprobs": []},
            {
                "token": key0[1],
                "logprob": math.log(p0),
                "top_logprobs": [
                    {"token": letters[0], "logprob": math.log(p0)},
                    {"token": letters[1], "logprob": math.log(1 - p0)},
                ],
            },
            {"token": "`", "logprob": -0.01, "top_logprobs": []},
        ]
    }
    transport = FakeTransport(
        [Script([chunk_obj(key0, finish="stop", logprobs=lp)])]
    )
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    store = archive.InMemoryArchive()
    score = ScoreClient(
        chat, registry.InMemoryModelRegistry(), archive_fetcher=store,
        rng_factory=lambda: random.Random(seed),
        ballot_sink=store.put_ballot,
    )
    result = go(
        score.create_unary(
            None,
            ScoreParams.from_json_obj(
                {
                    "messages": [{"role": "user", "content": "q"}],
                    "model": {
                        "llms": [
                            {
                                "model": "judge-a",
                                "top_logprobs": 2,
                                "weight": {"type": "static", "weight": 1},
                            }
                        ]
                    },
                    "choices": ["a", "b"],
                }
            ),
        )
    )
    store.put_score(result)
    return store, result, branch, letters


def test_revote_matches_host_decimal_extraction():
    """Device re-extraction (softmax_votes over stored logprobs) must agree
    with the live host Decimal path that produced the stored votes."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    judge = [c for c in result.choices if c.index >= 2][0]
    host_vote = [float(v) for v in judge.message.vote]
    assert sum(host_vote) == pytest.approx(1.0)

    results = rescore_archive(store, revote=True)
    conf = [float(x) for x in results[result.id]["confidence"]]
    np.testing.assert_allclose(conf, host_vote, atol=1e-6)


def test_revote_recomputes_from_tampered_logprobs():
    """revote re-derives votes from logprobs — it must track a changed
    distribution while the stored-vote path keeps the old one."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    import math
    from decimal import Decimal

    judge = [c for c in result.choices if c.index >= 2][0]
    alts = judge.logprobs.content[1].top_logprobs
    alts[0].logprob = Decimal(str(math.log(0.2)))
    alts[1].logprob = Decimal(str(math.log(0.8)))

    stale = rescore_archive(store, revote=False)[result.id]["confidence"]
    fresh = rescore_archive(store, revote=True)[result.id]["confidence"]
    i0, i1 = branch[letters[0]], branch[letters[1]]
    assert float(stale[i0]) == pytest.approx(0.7, abs=1e-6)
    assert float(fresh[i0]) == pytest.approx(0.2, abs=1e-6)
    assert float(fresh[i1]) == pytest.approx(0.8, abs=1e-6)


def test_revote_without_ballots_falls_back_to_stored_votes():
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, *_ = _soft_vote_archive(p0=0.6)
    store._ballots.clear()  # simulate an archive without ballot records
    with_stored = rescore_archive(store, revote=False)[result.id]
    fallback = rescore_archive(store, revote=True)[result.id]
    assert [float(x) for x in fallback["confidence"]] == pytest.approx(
        [float(x) for x in with_stored["confidence"]]
    )


def test_revote_handles_tick_stripped_content():
    """A judge that answered without backtick quoting still re-extracts:
    find_key returns the stripped key and leaf_branch_of matches it by
    letter sequence (as the live tree.walk does)."""
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    judge = [c for c in result.choices if c.index >= 2][0]
    judge.message.content = judge.message.content.replace("`", "")
    host_vote = [float(v) for v in judge.message.vote]

    results = rescore_archive(store, revote=True)
    conf = [float(x) for x in results[result.id]["confidence"]]
    np.testing.assert_allclose(conf, host_vote, atol=1e-6)


def test_archive_snapshot_round_trip(tmp_path):
    """save -> load preserves completions (Decimal-exact votes), ballots,
    and keeps device revote working from the reloaded store."""
    from llm_weighted_consensus_tpu import archive
    from llm_weighted_consensus_tpu.archive.rescore import rescore_archive

    store, result, branch, letters = _soft_vote_archive(p0=0.7)
    path = str(tmp_path / "archive.json")
    store.save(path)
    reloaded = archive.InMemoryArchive.load(path)

    assert reloaded.score_ids() == store.score_ids()
    orig = store._score[result.id]
    copy = reloaded._score[result.id]
    assert copy.to_json_obj() == orig.to_json_obj()
    judge = [c for c in copy.choices if c.index >= 2][0]
    # Decimal-exact vote round trip
    assert judge.message.vote == [
        c.message.vote for c in orig.choices if c.index >= 2
    ][0]
    assert reloaded.score_ballots(result.id) is not None

    before = rescore_archive(store, revote=True)[result.id]["confidence"]
    after = rescore_archive(reloaded, revote=True)[result.id]["confidence"]
    assert [float(x) for x in after] == pytest.approx(
        [float(x) for x in before]
    )


def test_archive_snapshot_rejects_unknown_version(tmp_path):
    from llm_weighted_consensus_tpu import archive

    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write('{"version": 99}')
    with pytest.raises(ValueError, match="version"):
        archive.InMemoryArchive.load(path)


def test_ballot_table_is_bounded():
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    cap = store.MAX_BALLOT_COMPLETIONS
    for i in range(cap + 10):
        store.put_ballot(f"scrcpl-{i}", 0, [("`A`", 0), ("`B`", 1)])
    assert len(store._ballots) == cap
    # FIFO: oldest evicted, newest kept
    assert store.score_ballots("scrcpl-0") is None
    assert store.score_ballots(f"scrcpl-{cap + 9}") is not None


def test_ballot_eviction_prefers_unarchived():
    """FIFO eviction must never drop an ARCHIVED completion's ballots —
    those are exactly the ones revote still needs."""
    from llm_weighted_consensus_tpu import archive

    store = archive.InMemoryArchive()
    cap = store.MAX_BALLOT_COMPLETIONS
    store.put_ballot("scrcpl-keep", 0, [("`A`", 0)])
    store._score["scrcpl-keep"] = object()  # archived (stub is enough)
    for i in range(cap + 5):
        store.put_ballot(f"scrcpl-{i}", 0, [("`A`", 0)])
    assert store.score_ballots("scrcpl-keep") is not None
    assert len(store._ballots) == cap
