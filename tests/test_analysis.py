"""The analysis gate: first-party lint rules + the jaxpr serving-path
audit (ISSUE 6).

Three layers:

* per-rule fixture tests — every rule fires on its violating fixture
  (exact count: the fixtures enumerate the shapes the rule knows) and
  stays silent on the conforming one; all rules together stay silent on
  every conforming fixture (no cross-rule false positives);
* the baseline machinery — suppression round-trip, mandatory reasons,
  stale-entry detection;
* THE gate — the whole package lints clean against the committed
  baseline, and the jaxpr audit of the int8 serving path (structure +
  AOT coverage + specialization guard) returns zero findings on CPU;
  plus injected-regression tests proving the auditor actually catches
  host transfers / dequant upcasts and names the offending primitive.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from llm_weighted_consensus_tpu.analysis import (
    apply_baseline,
    baseline_entry,
    default_baseline_path,
    load_baseline,
    run_lint,
)
from llm_weighted_consensus_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"

# rule -> number of violations its bad fixture enumerates
EXPECTED_BAD = {
    "LWC001": 3,  # bare / BaseException / CancelledError-no-reraise
    "LWC002": 1,
    "LWC003": 1,
    "LWC004": 2,  # ContextVar.set + .activate() tokens
    "LWC005": 3,  # BinOp + AugAssign + Decimal(float)
    "LWC006": 2,  # time.sleep + open
    "LWC007": 2,  # message() + wire envelope
    "LWC008": 3,  # environ.get + getenv + environ[...] subscript
    "LWC009": 2,  # jnp call + jax call inside one coroutine
    "LWC010": 3,  # undeclared section + dead registry row + rogue span
    "LWC011": 2,  # undocumented from_env knob + stale README token
    "LWC012": 5,  # undeclared family + dead registry row + non-literal
    # name + the _total-suffixed counter header (undeclared + dead row)
    "LWC013": 2,  # jax.block_until_ready + .block_until_ready() method
    "LWC014": 6,  # unregistered lock + stale registry row + 2 unguarded
    # cross-thread accesses + reasonless exemption + exempted method
    # called without the lock held
    "LWC015": 4,  # undeclared observed edge + stale declared edge +
    # order cycle + lexical re-acquire of a non-reentrant Lock
    "LWC016": 5,  # await + wait_device_ready + upstream HTTP +
    # cross-condition wait + call-mediated blocking, all under a held lock
    "LWC017": 2,  # to_json_obj + jsonutil.dumps per merged chunk
    "LWC018": 4,  # 2 capless deques + unguarded bytes growth +
    # raw byte_stream chunks drained into a list
}


def lint_fixture(name: str, rule: str = None):
    rules = [RULES_BY_NAME[rule]] if rule else None
    return run_lint(paths=[FIXTURES / name], rules=rules)


# -- per-rule fixtures -------------------------------------------------------


def test_registry_covers_expected_rules():
    assert sorted(r.name for r in ALL_RULES) == sorted(EXPECTED_BAD)


@pytest.mark.parametrize("rule", sorted(EXPECTED_BAD))
def test_rule_fires_on_bad_fixture(rule):
    findings = lint_fixture(f"{rule.lower()}_bad.py", rule)
    assert len(findings) == EXPECTED_BAD[rule], [
        f.render() for f in findings
    ]
    assert all(f.rule == rule for f in findings)
    # findings carry the symbol (the baseline matching key)
    assert all(f.symbol for f in findings)


@pytest.mark.parametrize("rule", sorted(EXPECTED_BAD))
def test_rule_silent_on_good_fixture(rule):
    assert lint_fixture(f"{rule.lower()}_good.py", rule) == []


@pytest.mark.parametrize("rule", sorted(EXPECTED_BAD))
def test_good_fixtures_clean_under_all_rules(rule):
    """A conforming fixture must not trip ANY rule — the conforming
    idioms are exactly the repo's own, so a cross-rule false positive
    here means the gate would fight real code."""
    findings = lint_fixture(f"{rule.lower()}_good.py")
    assert findings == [], [f.render() for f in findings]


# -- baseline machinery ------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    findings = run_lint(paths=[FIXTURES / "lwc001_bad.py"])
    assert findings
    entries = [baseline_entry(f, "fixture: intentionally bad") for f in findings]
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"suppressions": entries}))

    kept, suppressed, stale = apply_baseline(findings, load_baseline(path))
    assert kept == []
    assert len(suppressed) == len(findings)
    assert stale == []

    # "the code got fixed": the same baseline against a clean file makes
    # every entry stale — the CLI fails until they're deleted
    clean = run_lint(paths=[FIXTURES / "lwc001_good.py"])
    kept2, _, stale2 = apply_baseline(clean, load_baseline(path))
    assert kept2 == clean
    assert len(stale2) == len(entries)


def test_baseline_reason_is_mandatory(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(
        json.dumps(
            {"suppressions": [{"rule": "LWC001", "path": "x.py", "symbol": None}]}
        )
    )
    with pytest.raises(ValueError, match="reason"):
        load_baseline(path)


def test_committed_baseline_is_small_and_reasoned():
    entries = load_baseline(default_baseline_path())
    assert len(entries) <= 10
    assert all(str(e["reason"]).strip() for e in entries)


# -- THE gate: the package itself --------------------------------------------


def test_package_lints_clean_against_baseline():
    kept, _suppressed, stale = apply_baseline(run_lint(), load_baseline())
    assert stale == [], stale
    assert kept == [], "\n".join(f.render() for f in kept)


# -- CLI ---------------------------------------------------------------------


def test_cli_exit_codes(tmp_path, capsys):
    from llm_weighted_consensus_tpu.analysis.__main__ import main

    # single-file lint: the package-wide suppressions don't apply (and
    # would read stale), so scope the run to an empty baseline; the
    # jaxpr/mesh audits are package-level, not per-file — skip them
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"suppressions": []}))
    base = ["--no-jaxpr", "--no-mesh", "--baseline", str(empty)]
    assert main([str(FIXTURES / "lwc002_good.py"), *base]) == 0
    rc = main([str(FIXTURES / "lwc002_bad.py"), *base])
    assert rc == 1
    assert "LWC002" in capsys.readouterr().out

    stale = tmp_path / "stale.json"
    stale.write_text(
        json.dumps(
            {
                "suppressions": [
                    {
                        "rule": "LWC001",
                        "path": "gone.py",
                        "symbol": None,
                        "reason": "covered code was deleted",
                    }
                ]
            }
        )
    )
    assert (
        main(
            [
                str(FIXTURES / "lwc002_good.py"),
                "--no-jaxpr",
                "--no-mesh",
                "--baseline",
                str(stale),
            ]
        )
        == 2
    )


def test_cli_module_entry_point():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "llm_weighted_consensus_tpu.analysis",
            "--no-jaxpr",
            "--json",
        ],
        capture_output=True,
        text=True,
        cwd=Path(__file__).parent.parent,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []


# -- concurrency audit: injected regressions + registry drift ----------------
#
# Each test plants exactly the regression the rule exists to catch in a
# copy of the conforming fixture and asserts the NAMED rule reports it —
# the auditor must not just pass clean code, it must fail broken code.


def _mutated(tmp_path, fixture, old, new):
    src = (FIXTURES / fixture).read_text()
    assert old in src, f"mutation anchor drifted in {fixture}"
    path = tmp_path / fixture  # same filename: the inline model's
    path.write_text(src.replace(old, new))  # module suffix still matches
    return path


def _conc_lint(path, rule):
    return run_lint(paths=[path], rules=[RULES_BY_NAME[rule]])


def test_lwc014_catches_deleted_with_guard(tmp_path):
    """Injected regression: strip the ``with self._lock`` bracket off a
    guarded-field read reachable from two threads."""
    path = _mutated(
        tmp_path,
        "lwc014_good.py",
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._count\n",
        "    def read(self):\n        return self._count\n",
    )
    findings = _conc_lint(path, "LWC014")
    assert [f.rule for f in findings] == ["LWC014"]
    assert findings[0].symbol == "Worker.read"
    assert "_count" in findings[0].message


def test_lwc015_catches_reversed_lock_order(tmp_path):
    """Injected regression: reverse the two-lock nesting in ``forward``
    while ``outer``/``helper`` still walk the declared direction — the
    undeclared inverse edge AND the resulting cycle both surface."""
    path = _mutated(
        tmp_path,
        "lwc015_good.py",
        "    with LOCK_A:\n"
        "        with LOCK_B:\n"
        "            return list(items)\n",
        "    with LOCK_B:\n"
        "        with LOCK_A:\n"
        "            return list(items)\n",
    )
    findings = _conc_lint(path, "LWC015")
    assert findings and all(f.rule == "LWC015" for f in findings)
    assert any(
        "`LOCK_B` -> `LOCK_A`" in f.message and "not declared" in f.message
        for f in findings
    )
    assert any("cycle" in f.message for f in findings)


def test_lwc016_catches_await_under_held_lock(tmp_path):
    """Injected regression: append a coroutine to ``Pump`` that awaits
    while holding the registered lock."""
    src = (FIXTURES / "lwc016_good.py").read_text()
    src += (
        "\n    async def injected(self):\n"
        "        with self._lock:\n"
        "            await self.nothing()\n"
    )
    path = tmp_path / "lwc016_good.py"
    path.write_text(src)
    findings = _conc_lint(path, "LWC016")
    assert [f.rule for f in findings] == ["LWC016"]
    assert findings[0].symbol == "Pump.injected"
    assert "await" in findings[0].message


def test_lwc014_registry_drift_unregistered_lock(tmp_path):
    """Both-ways check, way one: a new threading primitive without a
    registry row fails the lint."""
    path = _mutated(
        tmp_path,
        "lwc014_good.py",
        "        self._lock = threading.Lock()\n",
        "        self._lock = threading.Lock()\n"
        "        self._extra = threading.Lock()\n",
    )
    findings = _conc_lint(path, "LWC014")
    assert [f.rule for f in findings] == ["LWC014"]
    assert "Worker._extra" in findings[0].message
    assert "not in the lock-model registry" in findings[0].message


def test_lwc014_registry_drift_stale_row(tmp_path):
    """Both-ways check, way two: deleting the lock's creation site makes
    its registry row stale — the row must be pruned, not left to rot."""
    path = _mutated(
        tmp_path,
        "lwc014_good.py",
        "        self._lock = threading.Lock()\n",
        "",
    )
    findings = _conc_lint(path, "LWC014")
    assert [f.rule for f in findings] == ["LWC014"]
    assert findings[0].symbol == "Worker._lock"
    assert "no creation site" in findings[0].message


def test_lwc015_registry_drift_stale_order_edge(tmp_path):
    """Both-ways check for the order DAG: if no code path walks the
    declared edge any more, the declaration itself fails the lint."""
    src = (FIXTURES / "lwc015_good.py").read_text()
    for old, new in (
        (
            "    with LOCK_A:\n"
            "        with LOCK_B:\n"
            "            return list(items)\n",
            "    with LOCK_B:\n        return list(items)\n",
        ),
        (
            "    with LOCK_A:\n        return helper(items)\n",
            "    return helper(items)\n",
        ),
    ):
        assert old in src, "mutation anchor drifted in lwc015_good.py"
        src = src.replace(old, new)
    path = tmp_path / "lwc015_good.py"
    path.write_text(src)
    findings = _conc_lint(path, "LWC015")
    assert [f.rule for f in findings] == ["LWC015"]
    assert findings[0].symbol == "LOCK_A->LOCK_B"
    assert "no longer observed" in findings[0].message


def test_package_model_covers_every_primitive_both_ways():
    """The acceptance: 100% registry coverage over the package's real
    threading primitives, in both directions — every creation site has a
    row (no LWC014 unregistered findings) and every row has a creation
    site (no stale findings) on the committed tree."""
    findings = run_lint(rules=[RULES_BY_NAME["LWC014"]])
    drift = [
        f
        for f in findings
        if "not in the lock-model registry" in f.message
        or "no creation site" in f.message
    ]
    assert drift == [], "\n".join(f.render() for f in drift)


# -- jaxpr audit -------------------------------------------------------------

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from llm_weighted_consensus_tpu.analysis.jaxpr_audit import (  # noqa: E402
    audit_traced,
    run_jaxpr_audit,
)

SDS = jax.ShapeDtypeStruct


def test_jaxpr_audit_serving_path_clean():
    """The acceptance: the int8 serving path (every AOT bucket,
    structure + coverage + specialization guard) audits clean on CPU."""
    findings = run_jaxpr_audit()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_audit_clean_toy_fn_passes():
    w = jnp.ones((4, 4), jnp.float32)
    assert (
        audit_traced(lambda x: jnp.dot(x, w), (SDS((4, 4), jnp.float32),), "ok")
        == []
    )


def test_audit_names_injected_device_put():
    w = jnp.ones((4, 4), jnp.float32)
    findings = audit_traced(
        lambda x: jnp.dot(jax.device_put(x), w),
        (SDS((4, 4), jnp.float32),),
        "toy",
    )
    assert [f.rule for f in findings] == ["JXA001"]
    assert "device_put" in findings[0].message


def test_audit_names_injected_callback():
    findings = audit_traced(
        lambda x: jax.pure_callback(
            lambda v: np.asarray(v), SDS((4,), jnp.float32), x
        ),
        (SDS((4,), jnp.float32),),
        "toy",
    )
    assert [f.rule for f in findings] == ["JXA001"]
    assert "pure_callback" in findings[0].message


def test_audit_catches_trace_time_device_get():
    """jax.device_get/np.asarray on a tracer never reaches the jaxpr —
    the auditor reports the trace-time concretization as JXA001."""
    findings = audit_traced(
        lambda x: jnp.asarray(np.asarray(x)) + 1.0,
        (SDS((4,), jnp.float32),),
        "toy",
    )
    assert [f.rule for f in findings] == ["JXA001"]
    assert "trace time" in findings[0].message


def test_audit_names_injected_int8_upcast():
    w = jnp.ones((4, 4), jnp.float32)
    findings = audit_traced(
        lambda q: jnp.dot(q.astype(jnp.float32), w),
        (SDS((4, 4), jnp.int8),),
        "toy",
    )
    assert [f.rule for f in findings] == ["JXA002"]
    assert "convert_element_type" in findings[0].message


def test_audit_flags_missing_pallas_kernel():
    """expect_pallas asserts the fused kernel is still in the forward."""
    w = jnp.ones((4, 4), jnp.float32)
    findings = audit_traced(
        lambda x: jnp.dot(x, w),
        (SDS((4, 4), jnp.float32),),
        "toy",
        expect_pallas=True,
    )
    assert [f.rule for f in findings] == ["JXA002"]
    assert "pallas" in findings[0].message


# -- mesh audit (JXA006-011) -------------------------------------------------

from jax.sharding import PartitionSpec as P  # noqa: E402

from llm_weighted_consensus_tpu.analysis.budgets import (  # noqa: E402
    check_allowlist_stale,
    compare_budgets,
)
from llm_weighted_consensus_tpu.analysis.mesh_audit import (  # noqa: E402
    audit_hlo_collectives,
    audit_replication,
    audit_rule_coverage,
    audit_serving_executables,
    run_mesh_audit,
)

_TOY_TREE = {
    "embed": SDS((512, 1024), jnp.float32),  # 2 MiB: above threshold
    "layers": {"kernel": SDS((8, 8), jnp.float32)},
}
_TOY_RULES = (
    ("embed", r"embed", P()),
    ("kernel", r"layers/kernel", P(None, "tp")),
)


def test_mesh_audit_serving_path_clean():
    """The acceptance: coverage, replication policy, collective plan,
    committed budgets, and sharded-vs-single-device equivalence of every
    serving bucket on the simulated mesh — zero findings."""
    findings = run_mesh_audit()
    assert findings == [], "\n".join(f.render() for f in findings)


def test_mesh_audit_catches_wrong_math_in_committed_executable():
    """Injected regression: the audit runs against the embedder's ACTUAL
    AOT table, so an executable whose math is wrong — here the warmed
    vote1 entry swapped for a same-aval compile that scales the vote —
    must surface as JXA011 naming the bucket."""
    from llm_weighted_consensus_tpu.models.embedder import (
        TpuEmbedder,
        _mesh_embed_and_vote,
    )
    from llm_weighted_consensus_tpu.parallel.mesh import make_mesh
    from llm_weighted_consensus_tpu.parallel.sharding import (
        shard_embedder_mesh,
    )

    mesh = make_mesh(dp=4, tp=2)
    ref = TpuEmbedder("test-tiny", max_tokens=64, seed=0, quantize="none")
    emb = TpuEmbedder("test-tiny", max_tokens=64, seed=0, quantize="none")
    shard_embedder_mesh(emb, mesh)
    n, s = 4, 16
    emb.aot_warmup([(n, s)])

    pad_n = n + (-n) % emb.batch_multiple
    iav = SDS((pad_n, s), jnp.int32, sharding=emb.batch_sharding)
    temp_av = SDS((), jnp.float32, sharding=emb.repl_sharding)
    wrong = (
        jax.jit(
            lambda p, i, m, t: 1.5
            * _mesh_embed_and_vote(
                p, i, m, t, n, emb.config, emb.pooling, emb.mesh
            )
        )
        .lower(emb.params, iav, iav, temp_av)
        .compile()
    )
    emb._aot[emb._aot_key(("vote1", n, s))] = wrong

    findings, _ = audit_serving_executables(
        emb, ref, specs=((n, s),), r_buckets=(), packed_buckets=()
    )
    hits = [
        f for f in findings if f.rule == "JXA011" and "vote1" in f.path
    ]
    assert hits, "\n".join(f.render() for f in findings)


def test_mesh_audit_catches_unwarmed_fault_ladder_rung():
    """Injected regression for JXA012: with warm_ladder neutered, every
    fallback rung is missing its AOT buckets — the ladder audit must name
    the uncovered buckets AND flag that rung dispatches lazily jitted
    instead of riding committed executables."""
    from llm_weighted_consensus_tpu.analysis.mesh_audit import (
        _audit_fault_ladder,
    )
    from llm_weighted_consensus_tpu.resilience import MeshFaultManager

    real = MeshFaultManager.warm_ladder
    MeshFaultManager.warm_ladder = lambda self, *a, **k: []
    try:
        findings = _audit_fault_ladder(
            "test-tiny", 4, 2, ((4, 16),), (), ()
        )
    finally:
        MeshFaultManager.warm_ladder = real
    missing = [
        f
        for f in findings
        if f.rule == "JXA012" and "no AOT executable" in f.message
    ]
    lazy = [
        f
        for f in findings
        if f.rule == "JXA012" and "lazily jitted" in f.message
    ]
    assert missing and lazy, "\n".join(f.render() for f in findings)
    # both fallback rungs of the 4x2 ladder are implicated
    assert {f.path for f in missing} == {
        "mesh:ladder:2x2",
        "mesh:ladder:1x2",
    }


def test_coverage_clean_on_toy_tree():
    assert audit_rule_coverage(_TOY_RULES, _TOY_TREE, "toy") == []


def test_coverage_flags_uncovered_param_leaf():
    """Injected regression: a param leaf no rule matches is JXA006."""
    rules = (_TOY_RULES[0],)  # drop the kernel rule
    findings = audit_rule_coverage(rules, _TOY_TREE, "toy")
    assert [f.rule for f in findings] == ["JXA006"]
    assert findings[0].symbol == "layers/kernel"
    assert "NO partition rule" in findings[0].message


def test_coverage_flags_unused_rule():
    """Injected regression: a rule matching no leaf is JXA006 too."""
    rules = _TOY_RULES + (("ghost", r"layers/nonexistent", P()),)
    findings = audit_rule_coverage(rules, _TOY_TREE, "toy")
    assert [f.rule for f in findings] == ["JXA006"]
    assert findings[0].symbol == "ghost"
    assert "no param leaf" in findings[0].message


def test_coverage_flags_ambiguous_leaf():
    rules = _TOY_RULES + (("dup", r"emb.*", P()),)
    findings = audit_rule_coverage(rules, _TOY_TREE, "toy")
    assert {f.rule for f in findings} == {"JXA006"}
    assert any("ambiguous" in f.message for f in findings)


def test_replication_flags_oversized_replicated_tensor():
    """Injected regression: a >threshold leaf left fully replicated
    without an allowlist entry is JXA007."""
    findings, matched = audit_replication(
        _TOY_RULES, _TOY_TREE, "toy", threshold_bytes=1 << 20, allowlist=[]
    )
    assert [f.rule for f in findings] == ["JXA007"]
    assert findings[0].symbol == "embed"
    assert matched == set()


def test_replication_allowlist_and_stale_detection():
    allow = [{"pattern": "embed", "reason": "gather beats all-to-all"}]
    findings, matched = audit_replication(
        _TOY_RULES, _TOY_TREE, "toy", threshold_bytes=1 << 20, allowlist=allow
    )
    assert findings == []
    assert matched == {"embed"}
    assert check_allowlist_stale(allow, matched) == []
    # the allowlisted tensor got sharded/removed: the entry is stale
    stale = check_allowlist_stale(allow, set())
    assert [f.rule for f in stale] == ["JXA010"]
    assert "stale replicated_allowlist" in stale[0].message


_SHARDED_HLO = """\
ENTRY %main { %ar = f32[8,16] all-reduce(f32[8,16] %x) }
"""


def test_hlo_collectives_clean():
    assert audit_hlo_collectives(_SHARDED_HLO, "toy") == []


def test_hlo_flags_missing_expected_collective():
    """Injected regression: HLO without the TP reduction is JXA008 (the
    layout degenerated to replication)."""
    findings = audit_hlo_collectives(
        "ENTRY %main { %d = f32[8,16] dot(%x, %w) }", "toy"
    )
    assert [f.rule for f in findings] == ["JXA008"]
    assert "expected collective" in findings[0].message


def test_hlo_flags_forbidden_collective():
    """Injected regression: an all-to-all (or host transfer) in the hot
    path is JXA008."""
    findings = audit_hlo_collectives(
        _SHARDED_HLO + "%a2a = f32[8,16] all-to-all(%x)\n", "toy"
    )
    assert [f.rule for f in findings] == ["JXA008"]
    assert "all-to-all" in findings[0].message
    findings = audit_hlo_collectives(
        _SHARDED_HLO + "%s = f32[] send(%x), is_host_transfer=true\n", "toy"
    )
    assert [f.rule for f in findings] == ["JXA008"]


_BUDGETS = {
    "scope": {"model": "toy"},
    "tolerance": {"hbm_bytes": 0.25, "flops": 0.25, "bytes_accessed": 0.25},
    "buckets": {
        "vote1(n=8,s=16)": {
            "hbm_bytes": 1000.0,
            "flops": 2000.0,
            "bytes_accessed": 3000.0,
        }
    },
}
_IN_BAND = {
    "vote1(n=8,s=16)": {
        "hbm_bytes": 1100.0,
        "flops": 2100.0,
        "bytes_accessed": 2900.0,
    }
}


def test_budgets_in_band_clean():
    assert (
        compare_budgets(_IN_BAND, _BUDGETS, scope={"model": "toy"}) == []
    )


def test_budgets_flag_breach():
    """Injected regression: a measured figure past the tolerance band is
    JXA009, naming the bucket and metric."""
    over = {
        "vote1(n=8,s=16)": {
            "hbm_bytes": 1500.0,  # 1.5x vs ±25%
            "flops": 2000.0,
            "bytes_accessed": 3000.0,
        }
    }
    findings = compare_budgets(over, _BUDGETS, scope={"model": "toy"})
    assert [f.rule for f in findings] == ["JXA009"]
    assert findings[0].symbol == "vote1(n=8,s=16)"
    assert "hbm_bytes" in findings[0].message
    assert "outgrew" in findings[0].message


def test_budgets_flag_missing_and_stale_entries():
    """Injected regressions: an audited bucket with no committed entry,
    and a committed bucket the audit no longer lowers — both JXA010."""
    measured = dict(_IN_BAND)
    measured["packed(b=8,l=64,k=8)"] = {"hbm_bytes": 1.0}
    findings = compare_budgets(measured, _BUDGETS, scope={"model": "toy"})
    assert [f.rule for f in findings] == ["JXA010"]
    assert "no committed budget" in findings[0].message

    findings = compare_budgets({}, _BUDGETS, scope={"model": "toy"})
    assert [f.rule for f in findings] == ["JXA010"]
    assert "stale budget entry" in findings[0].message


def test_budgets_flag_scope_mismatch_and_missing_file():
    findings = compare_budgets(_IN_BAND, _BUDGETS, scope={"model": "other"})
    assert [f.rule for f in findings] == ["JXA010"]
    assert "scope" in findings[0].message
    findings = compare_budgets(_IN_BAND, {}, scope={"model": "toy"})
    assert [f.rule for f in findings] == ["JXA010"]
    assert "--write-budgets" in findings[0].message
