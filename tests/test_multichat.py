"""Multichat fan-out client: slot semantics, dedup identity, error
isolation, unary fold, streaming incremental consensus (SURVEY §2.10,
BASELINE configs 2 and 5)."""

import asyncio
from decimal import Decimal

import pytest

from llm_weighted_consensus_tpu import registry
from llm_weighted_consensus_tpu.clients.chat import (
    ApiBase,
    BackoffPolicy,
    DefaultChatClient,
)
from llm_weighted_consensus_tpu.clients.multichat import (
    MultichatClient,
    generator_slots,
)
from llm_weighted_consensus_tpu.identity.model import ModelBase
from llm_weighted_consensus_tpu.types.multichat_request import (
    ChatCompletionCreateParams as MultichatParams,
)
from llm_weighted_consensus_tpu.types.multichat_response import ChatCompletion

from fakes import FakeTransport, Script, chunk_obj

NO_RETRY = BackoffPolicy(max_elapsed_ms=0)


def go(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def make_model(judges):
    return ModelBase.from_json_obj({"llms": judges}).into_model_validate()


def inline(model):
    return {"llms": [llm.base.to_json_obj() for llm in model.llms]}


def make_client(scripts):
    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    return MultichatClient(chat, registry.InMemoryModelRegistry()), transport


def params(model, **kw):
    return MultichatParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": "answer the question"}],
            "model": inline(model),
            **kw,
        }
    )


def test_generator_slots_dedup_and_duplicates():
    # two judges = same generator (weight/output_mode reset), one distinct
    model = make_model(
        [
            {"model": "gen-a", "weight": {"type": "static", "weight": 2}},
            {"model": "gen-a", "weight": {"type": "static", "weight": 5}},
            {"model": "gen-b"},
        ]
    )
    slots = generator_slots(model)
    assert [s for s, _ in slots] == [0, 1, 2]
    ids = [llm.multichat_id for _, llm in slots]
    assert len(set(ids)) == 2  # two distinct generators across three slots
    # duplicates share identity and occupy consecutive slots
    dup_slots = [s for (s, llm) in slots if ids.count(llm.multichat_id) == 2]
    assert dup_slots[1] == dup_slots[0] + 1


def test_fanout_unary_fold_and_identity():
    model = make_model([{"model": "gen-a"}, {"model": "gen-b"}])
    order = [llm.base.model for _, llm in generator_slots(model)]
    by_model = {
        "gen-a": Script([chunk_obj("alpha ", model="gen-a"), chunk_obj("answer", model="gen-a", finish="stop")]),
        "gen-b": Script([chunk_obj("beta answer", model="gen-b", finish="stop")]),
    }
    client, t = make_client([by_model[m] for m in order])
    result = go(client.create_unary(None, params(model)))
    assert isinstance(result, ChatCompletion)
    assert len(result.choices) == 2
    by_slot = {c.index: c for c in result.choices}
    texts = {by_slot[0].message.content, by_slot[1].message.content}
    assert texts == {"alpha answer", "beta answer"}
    for c in result.choices:
        assert c.model is not None and len(c.model) == 22  # multichat_id
        assert c.completion_metadata is not None
    assert result.id.startswith("mchcpl-")


def test_slot_error_isolation():
    model = make_model([{"model": "gen-a"}, {"model": "gen-b"}])
    order = [llm.base.model for _, llm in generator_slots(model)]
    by_model = {
        "gen-a": Script(status=500, body=b'{"err": 1}'),
        "gen-b": Script([chunk_obj("ok", model="gen-b", finish="stop")]),
    }
    client, _ = make_client([by_model[m] for m in order])
    result = go(client.create_unary(None, params(model)))
    by_err = {c.index: c.error for c in result.choices}
    errors = [e for e in by_err.values() if e is not None]
    assert len(errors) == 1
    assert errors[0].code == 500
    ok = [c for c in result.choices if c.error is None][0]
    assert ok.message.content == "ok"


def test_seed_offset_per_slot():
    # identical generators: seeds offset so samples differ
    model = make_model([{"model": "gen-a"}, {"model": "gen-a"}])
    client, t = make_client(
        [
            Script([chunk_obj("s0", finish="stop")]),
            Script([chunk_obj("s1", finish="stop")]),
        ]
    )
    go(client.create_unary(None, params(model, seed=100)))
    seeds = sorted(b["seed"] for _, _, b in t.requests)
    assert seeds == [100, 101]


def test_multichat_as_score_candidates():
    """config 2 shape: multichat generates candidates, score judges them."""
    from llm_weighted_consensus_tpu import archive
    from llm_weighted_consensus_tpu.clients.score import ScoreClient
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )
    import random

    gen_model = make_model([{"model": "gen-a"}, {"model": "gen-b"}])
    order = [llm.base.model for _, llm in generator_slots(gen_model)]
    by_model = {
        "gen-a": Script([chunk_obj("it is 42", model="gen-a", finish="stop")]),
        "gen-b": Script([chunk_obj("it is 41", model="gen-b", finish="stop")]),
    }
    client, _ = make_client([by_model[m] for m in order])
    mc = go(client.create_unary(None, params(gen_model)))

    store = archive.InMemoryArchive()
    store.put_multichat(mc)

    # score the archived multichat candidates
    from llm_weighted_consensus_tpu.ballot import PrefixTree, branch_limit

    SEED = 7
    rng = random.Random(SEED)
    tree = PrefixTree.build(rng, 2, 20)
    keys = {idx: k for k, idx in tree.key_indices(rng)}
    # find which slot said 42
    slot42 = next(c.index for c in mc.choices if "42" in c.message.content)

    judge_model = make_model([{"model": "judge-x"}])
    transport = FakeTransport(
        [Script([chunk_obj(f"the answer {keys[slot42]}", finish="stop")])]
    )
    chat = DefaultChatClient(
        transport, [ApiBase("https://up.example", "k")], backoff=NO_RETRY
    )
    score = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=store,
        rng_factory=lambda: random.Random(SEED),
    )
    sp = ScoreParams.from_json_obj(
        {
            "messages": [{"role": "user", "content": "which?"}],
            "model": {"llms": [llm.base.to_json_obj() for llm in judge_model.llms]},
            "choices": [
                {"type": "multichat_completion", "id": mc.id, "choice_index": 0},
                {"type": "multichat_completion", "id": mc.id, "choice_index": 1},
            ],
        }
    )
    result = go(score.create_unary(None, sp))
    cand = {c.index: c for c in result.choices if c.index < 2}
    assert cand[slot42].confidence == Decimal(1)
    # provenance: candidate carries the multichat generator id
    assert cand[0].model is not None


def test_streaming_self_consistency_incremental():
    jax = pytest.importorskip("jax")
    from llm_weighted_consensus_tpu.clients.multichat import (
        StreamingSelfConsistency,
    )
    from llm_weighted_consensus_tpu.models.configs import TEST_TINY
    from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
    from llm_weighted_consensus_tpu.types.multichat_response import (
        ChatCompletionChunk,
    )

    emb = TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=3)
    sc = StreamingSelfConsistency(emb)

    def chunk(slot, content=None, finish=None):
        return ChatCompletionChunk.from_json_obj(
            {
                "id": "mc",
                "object": "chat.completion.chunk",
                "created": 1,
                "model": "m",
                "choices": [
                    {
                        "index": slot,
                        "delta": {"content": content} if content else {},
                        "finish_reason": finish,
                    }
                ],
            }
        )

    assert sc.push_chunk(chunk(0, "the answer is 42")) is None
    assert sc.push_chunk(chunk(0, finish="stop")) is None  # only 1 finished
    sc.push_chunk(chunk(1, "the answer is 42"))
    conf2 = sc.push_chunk(chunk(1, finish="stop"))
    assert conf2 is not None and set(conf2) == {0, 1}
    sc.push_chunk(chunk(2, "bananas bananas bananas"))
    conf3 = sc.push_chunk(chunk(2, finish="stop"))
    assert set(conf3) == {0, 1, 2}
    assert sum(conf3.values()) == pytest.approx(1.0, abs=1e-5)
    # the two agreeing candidates outrank the outlier
    assert conf3[0] > conf3[2] and conf3[1] > conf3[2]
    # errored slots never enter the consensus
    err = ChatCompletionChunk.from_json_obj(
        {
            "id": "mc",
            "object": "chat.completion.chunk",
            "created": 1,
            "model": "m",
            "choices": [
                {
                    "index": 3,
                    "delta": {},
                    "finish_reason": "error",
                    "error": {"code": 500, "message": "boom"},
                }
            ],
        }
    )
    assert sc.push_chunk(err) is None
    assert 3 not in sc.confidence and 3 in sc.failed


def test_streaming_consensus_grows_past_initial_capacity():
    """More candidates than the initial device-buffer capacity: the buffer
    doubles and the final distribution matches the one-shot vote."""
    import numpy as np

    pytest.importorskip("jax")
    from llm_weighted_consensus_tpu.clients.multichat import (
        StreamingSelfConsistency,
    )
    from llm_weighted_consensus_tpu.models.configs import TEST_TINY
    from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
    from llm_weighted_consensus_tpu.types.multichat_response import (
        ChatCompletionChunk,
    )

    def make_chunk(slot, content, finish):
        return ChatCompletionChunk.from_json_obj(
            {
                "id": "mc",
                "object": "chat.completion.chunk",
                "created": 1,
                "model": "m",
                "choices": [
                    {
                        "index": slot,
                        "delta": {"content": content},
                        "finish_reason": finish,
                    }
                ],
            }
        )

    emb = TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=3)
    sc = StreamingSelfConsistency(emb)
    sc.INITIAL_CAPACITY = 4
    n = 10
    texts = [f"candidate answer number {i % 3}" for i in range(n)]
    last = None
    for i, text in enumerate(texts):
        out = sc.push_chunk(make_chunk(i, text, finish="stop"))
        if out is not None:
            last = out
    assert last is not None and len(last) == n
    assert sum(last.values()) == pytest.approx(1.0, abs=1e-4)
    one_shot = np.asarray(emb.consensus_confidence(texts))
    np.testing.assert_allclose(
        [last[i] for i in range(n)], one_shot, atol=1e-4
    )


def test_streaming_consensus_failed_embed_leaves_no_phantom():
    """A raising embedder must not commit a phantom slot: the candidate
    retries on the next finish signal and the distribution stays honest."""
    pytest.importorskip("jax")
    from llm_weighted_consensus_tpu.clients.multichat import (
        StreamingSelfConsistency,
    )
    from llm_weighted_consensus_tpu.models.configs import TEST_TINY
    from llm_weighted_consensus_tpu.models.embedder import TpuEmbedder
    from llm_weighted_consensus_tpu.types.multichat_response import (
        ChatCompletionChunk,
    )

    def make_chunk(slot, content, finish):
        return ChatCompletionChunk.from_json_obj(
            {
                "id": "mc",
                "object": "chat.completion.chunk",
                "created": 1,
                "model": "m",
                "choices": [
                    {
                        "index": slot,
                        "delta": {"content": content},
                        "finish_reason": finish,
                    }
                ],
            }
        )

    emb = TpuEmbedder("test-tiny", config=TEST_TINY, max_tokens=32, seed=3)
    sc = StreamingSelfConsistency(emb)
    real = emb.stream_vote_update
    fail = {"next": True}

    def flaky(*args, **kwargs):
        if fail["next"]:
            fail["next"] = False
            raise RuntimeError("transient device OOM")
        return real(*args, **kwargs)

    emb.stream_vote_update = flaky
    with pytest.raises(RuntimeError):
        sc.push_chunk(make_chunk(0, "the answer", finish="stop"))
    assert sc.count == 0  # no phantom
    # the same slot retries (finish signal arrives again) and succeeds
    sc.push_chunk(make_chunk(0, "the answer", finish="stop"))
    conf = sc.push_chunk(make_chunk(1, "the answer", finish="stop"))
    assert set(conf) == {0, 1}
    assert sum(conf.values()) == pytest.approx(1.0, abs=1e-5)
    assert all(v > 0 for v in conf.values())
